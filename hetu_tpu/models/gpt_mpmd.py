"""Heterogeneous MPMD-pipelined GPT — real GPT-2 and LLaMA blocks.

The model-side counterpart of
:mod:`hetu_tpu.parallel.pipeline_mpmd`: builds per-stage pure forward
functions + parameter pytrees for *unequal* per-stage layer ranges
(Malleus ``Strategy.stage_layers``) and per-pipeline device submeshes.

Unlike the SPMD stacked-stage path (``models/gpt_pipeline.py``, which
requires homogeneous blocks), stages here are independent programs, so
the full GPT-2 architecture is supported: gelu+bias, LayerNorm with
bias, learned positions, GQA, dropout — plus the LLaMA variant
(swiglu/rmsnorm/rotary).  Embedding lives on stage 0 and the LM head +
loss on the last stage; with ``tie_embeddings`` the two stages carry the
same logical ``wte`` whose grads are summed by key (the reference's
shared-weight p2p handling, ``executable_graph.cc:2312-2453``).

Parameters are keyed per *global layer index* ("layer7") so the elastic
engine can re-partition stages and migrate state between layouts.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.pipeline_mpmd import MPMDPipelineRuntime, Stage
from .gpt import GPTConfig

# ---------------------------------------------------------------------------
# pure block functions (GPT-2 and LLaMA variants)


def _rotary_tables(seq_len: int, d: int):
    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float32) / d))
    ang = np.outer(np.arange(seq_len, dtype=np.float32), inv)
    emb = np.concatenate([ang, ang], axis=-1)
    return (jnp.asarray(np.cos(emb)[None, :, None, :]),
            jnp.asarray(np.sin(emb)[None, :, None, :]))


def _apply_rotary(x, cos, sin):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos.astype(x.dtype) + rot * sin.astype(x.dtype)


def _norm_apply(cfg: GPTConfig, p: Dict[str, Any], x):
    xf = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        out = xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)
        return (out * p["g"].astype(jnp.float32)).astype(x.dtype)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
    out = (xf - mu) * lax.rsqrt(var + 1e-5)
    return (out * p["g"].astype(jnp.float32)
            + p["b"].astype(jnp.float32)).astype(x.dtype)


def _dropout(x, rate: float, key):
    if not rate or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def _wsc(v, mesh: Optional[Mesh], spec: P):
    if mesh is None:
        return v
    return lax.with_sharding_constraint(v, NamedSharding(mesh, spec))


def block_apply(cfg: GPTConfig, p: Dict[str, Any], x, key=None,
                mesh: Optional[Mesh] = None):
    """One transformer block, pure.  x: [b, s, h].

    Honors cfg.norm / cfg.activation / cfg.position / GQA
    (cfg.num_kv_heads) / cfg.dropout (needs ``key``) / biases (gelu
    mode), i.e. actual GPT-2 as well as LLaMA blocks.
    """
    c = cfg
    b, s, hdim = x.shape
    nh, kvh, hd = c.num_heads, c.kv_heads, c.head_dim
    bias = c.activation == "gelu"
    k1 = k2 = k3 = None
    if key is not None and c.dropout:
        k1, k2, k3 = jax.random.split(key, 3)

    h = _norm_apply(c, p["ln1"], x)
    qkv = jnp.einsum("bsh,oh->bso", h, p["qkv"])
    if bias:
        qkv = qkv + p["qkv_b"]
    qkv = _wsc(qkv, mesh, P("dp", None, "tp"))
    q_size, kv_size = nh * hd, kvh * hd
    q = qkv[..., :q_size].reshape(b, s, nh, hd)
    k = qkv[..., q_size:q_size + kv_size].reshape(b, s, kvh, hd)
    v = qkv[..., q_size + kv_size:].reshape(b, s, kvh, hd)
    if c.position == "rotary":
        cos, sin = _rotary_tables(s, hd)
        q = _apply_rotary(q, cos, sin)
        k = _apply_rotary(k, cos, sin)
    if kvh != nh:  # GQA: broadcast kv heads over query groups
        rep = nh // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    q = _wsc(q, mesh, P("dp", None, "tp", None))
    k = _wsc(k, mesh, P("dp", None, "tp", None))
    v = _wsc(v, mesh, P("dp", None, "tp", None))
    # attention (causal), fp32 softmax
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((s, s), bool))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
    probs = _dropout(probs, c.dropout, k1)
    attn = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(b, s, nh * hd)
    attn = _wsc(attn, mesh, P("dp", None, "tp"))
    out = jnp.einsum("bso,ho->bsh", attn, p["attn_out"])
    if bias:
        out = out + p["attn_out_b"]
    out = _dropout(out, c.dropout, k2)
    x = x + _wsc(out, mesh, P("dp", None, None))

    h = _norm_apply(c, p["ln2"], x)
    up = jnp.einsum("bsh,oh->bso", h, p["mlp_up"])
    if bias:
        up = up + p["mlp_up_b"]
    up = _wsc(up, mesh, P("dp", None, "tp"))
    if c.activation == "swiglu":
        u1, u2 = jnp.split(up, 2, axis=-1)
        act = jax.nn.silu(u1) * u2
    elif c.activation == "gelu":
        act = jax.nn.gelu(up, approximate=True)
    elif c.activation == "relu":
        act = jax.nn.relu(up)
    else:
        act = jax.nn.silu(up)
    down = jnp.einsum("bso,ho->bsh", act, p["mlp_down"])
    if bias:
        down = down + p["mlp_down_b"]
    down = _dropout(down, c.dropout, k3)
    return x + _wsc(down, mesh, P("dp", None, None))


def init_block_params(cfg: GPTConfig, rng: np.random.RandomState
                      ) -> Dict[str, Any]:
    c = cfg
    h, f = c.hidden_size, c.ffn_size
    nh, kvh, hd = c.num_heads, c.kv_heads, c.head_dim
    bias = c.activation == "gelu"
    mult = 2 if c.activation == "swiglu" else 1
    depth_std = c.init_std / math.sqrt(2 * c.num_layers)
    qkv_out = (nh + 2 * kvh) * hd

    def w(shape, std):
        return rng.normal(0.0, std, shape).astype(np.float32)

    p: Dict[str, Any] = {
        "ln1": {"g": np.ones(h, np.float32)},
        "qkv": w((qkv_out, h), c.init_std),
        "attn_out": w((h, nh * hd), depth_std),
        "ln2": {"g": np.ones(h, np.float32)},
        "mlp_up": w((mult * f, h), c.init_std),
        "mlp_down": w((h, f), depth_std),
    }
    if c.norm == "layernorm":
        p["ln1"]["b"] = np.zeros(h, np.float32)
        p["ln2"]["b"] = np.zeros(h, np.float32)
    if bias:
        p["qkv_b"] = np.zeros(qkv_out, np.float32)
        p["attn_out_b"] = np.zeros(h, np.float32)
        p["mlp_up_b"] = np.zeros(mult * f, np.float32)
        p["mlp_down_b"] = np.zeros(h, np.float32)
    return p


BLOCK_SPECS = {
    "qkv": P("tp", None), "attn_out": P(None, "tp"),
    "mlp_up": P("tp", None), "mlp_down": P(None, "tp"),
    "qkv_b": P("tp"), "attn_out_b": P(), "mlp_up_b": P("tp"),
    "mlp_down_b": P(),
    "ln1": P(), "ln2": P(),
}


def stage_comm_edges(cfg: GPTConfig, lrange: Sequence[int], first: bool,
                     last: bool, batch: int, seq: int,
                     mesh_axes: Dict[str, int]) -> List[Dict[str, Any]]:
    """Declared DS-transition edges of one MPMD stage program, for the
    analyzer's per-edge attribution (``hetu_tpu/analysis/edges``).

    ``block_apply`` plants its sharding constraints below the graph
    layer (raw ``lax.with_sharding_constraint``), so the stage declares
    the same boundary list here — one edge per ``_wsc`` site, deduced
    exactly as the graph-level walk would: the tp-sharded qkv/mlp_up
    projections are local slices (``scatter``), the attn_out/mlp_down
    contractions leave tp-partial sums (``all_reduce``), the LM head
    re-slices the logits over tp and its log-softmax reduces them.
    """
    tp = int(mesh_axes.get("tp", 1))
    if tp <= 1:
        return []
    c = cfg
    act = batch * seq * c.hidden_size * 4
    edges: List[Dict[str, Any]] = []

    def e(kind, tensor, src, dst, payload):
        edges.append({"kind": kind, "tensor": tensor,
                      "producer": tensor, "consumer": f"{tensor}.wsc",
                      "src_spec": src, "dst_spec": dst, "axes": ("tp",),
                      "payload_bytes": int(payload)})

    for li in lrange:
        qkv_bytes = batch * seq * (c.num_heads + 2 * c.kv_heads) \
            * c.head_dim * 4
        e("scatter", f"layer{li}.qkv", "P(dp)", "P(dp,None,tp)",
          qkv_bytes)
        # q/k/v head split: [b,s,o] tp on the fused projection dim ->
        # [b,s,nh,hd] tp on the head dim — a genuine reshard (GSPMD
        # lowers the GQA repeat + head regrouping to collective-permutes
        # when nh/kvh tilings disagree)
        e("reshard", f"layer{li}.attn_heads", "P(dp,None,tp)",
          "P(dp,None,tp,None)", qkv_bytes)
        e("all_reduce", f"layer{li}.attn_out", "partial(tp)",
          "P(dp,None,None)", act)
        mult = 2 if c.activation == "swiglu" else 1
        e("scatter", f"layer{li}.mlp_up", "P(dp)", "P(dp,None,tp)",
          batch * seq * mult * c.ffn_size * 4)
        e("all_reduce", f"layer{li}.mlp_down", "partial(tp)",
          "P(dp,None,None)", act)
    if first:
        # vocab-sharded wte lookup: masked local gather + psum over tp
        e("all_reduce", "wte_lookup", "P(tp,None) table",
          "P(dp,None,None)", act)
    if last:
        e("scatter", "logits", "P(dp)", "P(dp,None,tp)",
          batch * seq * c.vocab_size * 4)
        e("all_reduce", "log_softmax", "partial(tp)", "replicated",
          batch * seq * 4)
    return edges


# ---------------------------------------------------------------------------
# stage builders


def _embed_apply(cfg: GPTConfig, p, ids, key):
    x = jnp.take(p["wte"], ids, axis=0)
    if cfg.position == "learned":
        x = x + p["wpe"][: ids.shape[1]][None]
    return _dropout(x, cfg.dropout, key)


def _head_loss_apply(cfg: GPTConfig, p, x, labels, mesh):
    x = _norm_apply(cfg, p["ln_f"], x)
    logits = jnp.einsum("bsh,vh->bsv", x, p["wte_head"])
    logits = _wsc(logits, mesh, P("dp", None, "tp"))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    valid = (labels >= 0).astype(jnp.float32)
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def _place_entry(v, mesh: Mesh, spec: P):
    if isinstance(v, dict):
        # norm params: small vectors, replicated
        return {k: jax.device_put(np.asarray(vv), NamedSharding(mesh, P()))
                for k, vv in v.items()}
    return jax.device_put(np.asarray(v), NamedSharding(mesh, spec))


def _place_stage(params: Dict[str, Any], mesh: Optional[Mesh],
                 specs: Dict[str, P]) -> Dict[str, Any]:
    """Put a stage's params on its submesh: block entries use
    BLOCK_SPECS per weight, others the given spec (default replicated)."""
    if mesh is None:
        return jax.tree_util.tree_map(jnp.asarray, params)
    out: Dict[str, Any] = {}
    for name, sub in params.items():
        if name.startswith("layer"):
            out[name] = {k: _place_entry(v, mesh, BLOCK_SPECS.get(k, P()))
                         for k, v in sub.items()}
        else:
            out[name] = _place_entry(sub, mesh, specs.get(name, P()))
    return out


class MPMDGPT:
    """GPT over the MPMD pipeline runtime with hetero stage layouts.

    ``stage_layers[p]`` — layers per stage for pipeline ``p`` (sums to
    cfg.num_layers); ``meshes[p][s]`` — submesh per stage (axes
    ("dp","tp"); None = default device).  Parameter entries are keyed
    "layerN" / "wte" / "wpe" / "ln_f" / "head" so grads reduce correctly
    across pipelines and (for the tied wte) across first/last stages.
    """

    def __init__(self, cfg: GPTConfig,
                 stage_layers: Sequence[Sequence[int]],
                 meshes: Optional[Sequence[Sequence[Optional[Mesh]]]] = None,
                 schedule: str = "1f1b",
                 num_chunks: int = 1,
                 seed: int = 0):
        # interleaved virtual stages: stage_layers has S*C entries per
        # pipeline, meshes repeating with period S (Megatron interleaved
        # 1F1B; pass schedule="interleaved", num_chunks=C)
        self.num_chunks = int(num_chunks)
        self.cfg = cfg
        self.stage_layers = [list(sl) for sl in stage_layers]
        P_n = len(self.stage_layers)
        S = len(self.stage_layers[0])
        assert all(len(sl) == S for sl in self.stage_layers)
        assert all(sum(sl) == cfg.num_layers for sl in self.stage_layers)
        assert all(all(n >= 1 for n in sl) for sl in self.stage_layers)
        if meshes is None:
            meshes = [[None] * S for _ in range(P_n)]
        self.meshes = meshes

        # one canonical init (shared across pipelines: DP replicas)
        rng = np.random.RandomState(seed)
        layer_params = [init_block_params(cfg, rng)
                        for _ in range(cfg.num_layers)]
        wte = rng.normal(0.0, cfg.init_std,
                         (cfg.vocab_size, cfg.hidden_size)).astype(np.float32)
        wpe = rng.normal(0.0, cfg.init_std,
                         (cfg.max_seq_len, cfg.hidden_size)).astype(np.float32)
        head = wte if cfg.tie_embeddings else \
            rng.normal(0.0, cfg.init_std,
                       (cfg.vocab_size, cfg.hidden_size)).astype(np.float32)
        ln_f = {"g": np.ones(cfg.hidden_size, np.float32)}
        if cfg.norm == "layernorm":
            ln_f["b"] = np.zeros(cfg.hidden_size, np.float32)

        pipes: List[List[Stage]] = []
        self.layer_keys: List[List[Dict[str, Any]]] = []
        for p in range(P_n):
            stages: List[Stage] = []
            keys_per_stage: List[Dict[str, Any]] = []
            lo = 0
            for s, n in enumerate(self.stage_layers[p]):
                mesh = self.meshes[p][s]
                lrange = list(range(lo, lo + n))
                lo += n
                params: Dict[str, Any] = {}
                keys: Dict[str, Any] = {}
                specs: Dict[str, P] = {}
                for li in lrange:
                    params[f"layer{li}"] = layer_params[li]
                    keys[f"layer{li}"] = f"layer{li}"
                if s == 0:
                    params["wte"] = wte
                    keys["wte"] = "wte"
                    specs["wte"] = P("tp", None)
                    if cfg.position == "learned":
                        params["wpe"] = wpe
                        keys["wpe"] = "wpe"
                last = s == S - 1
                if last:
                    params["ln_f"] = ln_f
                    keys["ln_f"] = "ln_f"
                    params["wte_head"] = head
                    keys["wte_head"] = "wte" if cfg.tie_embeddings \
                        else "head"
                    specs["wte_head"] = P("tp", None)
                placed = _place_stage(params, mesh, specs)
                fwd = self._make_stage_fwd(lrange, first=(s == 0),
                                           last=last, mesh=mesh)
                stages.append(Stage(
                    fwd, placed, mesh=mesh,
                    act_spec=P("dp", None, None) if s else P("dp", None),
                    is_last=last))
                keys_per_stage.append(keys)
            pipes.append(stages)
            self.layer_keys.append(keys_per_stage)
        self.runtime = MPMDPipelineRuntime(pipes, schedule=schedule,
                                           num_chunks=num_chunks)

    def _make_stage_fwd(self, lrange: List[int], first: bool, last: bool,
                        mesh: Optional[Mesh]):
        cfg = self.cfg

        if last:
            def fwd(params, x, labels, rng):
                if first:  # S == 1
                    x = _embed_apply(cfg, params, x,
                                     jax.random.fold_in(rng, 997)
                                     if cfg.dropout else None)
                for i, li in enumerate(lrange):
                    key = jax.random.fold_in(rng, li) if cfg.dropout \
                        else None
                    x = block_apply(cfg, params[f"layer{li}"], x, key, mesh)
                return _head_loss_apply(cfg, params, x, labels, mesh)
            return fwd

        def fwd(params, x, rng):
            if first:
                x = _embed_apply(cfg, params, x,
                                 jax.random.fold_in(rng, 997)
                                 if cfg.dropout else None)
            for li in lrange:
                key = jax.random.fold_in(rng, li) if cfg.dropout else None
                x = block_apply(cfg, params[f"layer{li}"], x, key, mesh)
            return x
        return fwd

    # -- static analysis -----------------------------------------------------

    def register_analysis(self, name: str, batch: int, seq: int
                          ) -> List[str]:
        """Register every stage program with the static analyzer
        (``python -m hetu_tpu.analysis``), declaring each stage's
        DS-transition edges (:func:`stage_comm_edges`) so the per-edge
        pass can explain the tp collectives GSPMD inserts inside stage
        programs.  Returns the registered executable names."""
        from ..parallel.pipeline_mpmd import register_stage_executables
        cfg = self.cfg
        ranges: List[List[int]] = []
        lo = 0
        for n in self.stage_layers[0]:
            ranges.append(list(range(lo, lo + n)))
            lo += n

        def _sds(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                               np.asarray(a).dtype)
                if not hasattr(a, "aval") else
                jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

        rng_sds = _sds(jax.random.PRNGKey(0))

        def stage_args(p, s, stage):
            params_sds = _sds(stage.params)
            if s == 0:
                x_sds = jax.ShapeDtypeStruct((batch, seq), np.int32)
            else:
                x_sds = jax.ShapeDtypeStruct(
                    (batch, seq, cfg.hidden_size), np.float32)
            if stage.is_last:
                y_sds = jax.ShapeDtypeStruct((batch, seq), np.int32)
                return (params_sds, x_sds, y_sds, rng_sds)
            return (params_sds, x_sds, rng_sds)

        def stage_meta(p, s, stage):
            mesh_axes = {str(a): int(sz)
                         for a, sz in stage.mesh.shape.items()} \
                if stage.mesh is not None else {}
            params = []
            for ename, sub in stage.params.items():
                leaves = sub.items() if isinstance(sub, dict) \
                    else [("", sub)]
                for lname, leaf in leaves:
                    spec = BLOCK_SPECS.get(lname) \
                        if ename.startswith("layer") \
                        else self._entry_spec(ename)
                    params.append({
                        "name": f"{ename}.{lname}" if lname else ename,
                        "shape": tuple(np.shape(leaf)),
                        "dtype": str(np.asarray(leaf).dtype)
                        if not hasattr(leaf, "dtype")
                        else np.dtype(leaf.dtype).name,
                        "pspec": spec})
            first, last = s == 0, stage.is_last
            return {
                "params": params,
                "declared_edges": stage_comm_edges(
                    cfg, ranges[s], first, last, batch, seq, mesh_axes),
                "pipeline": {"hops": 0,
                             "boundary_bytes": batch * seq
                             * cfg.hidden_size * 4},
            }

        return register_stage_executables(self.runtime, name,
                                          stage_args, stage_meta)

    # -- training ------------------------------------------------------------

    def split_micro_batches(self, ids: np.ndarray, labels: np.ndarray,
                            micro_batches: Sequence[int]
                            ) -> List[List[Tuple[Any, Any]]]:
        """Apportion the global batch into per-pipeline micro-batch lists
        (Malleus unequal counts); every micro-batch has equal size."""
        M_total = sum(micro_batches)
        assert ids.shape[0] % M_total == 0, \
            f"batch {ids.shape[0]} not divisible by {M_total} micro-batches"
        mb = ids.shape[0] // M_total
        data: List[List[Tuple[Any, Any]]] = []
        off = 0
        for p, m_p in enumerate(micro_batches):
            lst = []
            mesh = self.meshes[p][0]
            for _ in range(m_p):
                x = jnp.asarray(ids[off:off + mb])
                y = jnp.asarray(labels[off:off + mb])
                if mesh is not None:
                    sh = NamedSharding(mesh, P("dp", None))
                    x = jax.device_put(x, sh)
                ly_mesh = self.meshes[p][-1]
                if ly_mesh is not None:
                    y = jax.device_put(y, NamedSharding(ly_mesh,
                                                        P("dp", None)))
                lst.append((x, y))
                off += mb
            data.append(lst)
        return data

    def train_step(self, data, rng=None):
        from ..parallel.pipeline_mpmd import reduce_layer_grads
        loss, grads, stats = self.runtime.train_step(data, rng=rng)
        # sums across pipelines per layer key AND across first/last stage
        # for the tied wte (same "wte" key on both entries)
        grads = reduce_layer_grads(self.runtime, grads, self.layer_keys)
        return loss, grads, stats

    # -- state migration (elastic re-layout) ---------------------------------

    def _entry_spec(self, name: str) -> P:
        if name in ("wte", "wte_head"):
            return P("tp", None)
        return P()

    def gather_state(self, extra: Optional[List[List[Any]]] = None
                     ) -> Dict[str, Any]:
        """Host snapshot keyed by canonical parameter key (pipe 0 copy;
        all copies are kept identical).  ``extra`` optionally gathers a
        parallel structure (e.g. optimizer moments) with the same keys."""
        src = extra if extra is not None else \
            [[st.params for st in pipe] for pipe in self.runtime.pipes]
        out: Dict[str, Any] = {}
        for s, keys in enumerate(self.layer_keys[0]):
            for name, key in keys.items():
                if key is not None and key not in out:
                    out[key] = jax.device_get(src[0][s][name])
        return out

    def load_state(self, state: Dict[str, Any],
                   extra: Optional[List[List[Any]]] = None) -> None:
        """Place a :meth:`gather_state` snapshot onto every pipe/stage
        copy (the hot-switch migration: reference SwitchExecGraph's
        param resharding, switch_exec_graph.h:459)."""
        dst = extra if extra is not None else \
            [[st.params for st in pipe] for pipe in self.runtime.pipes]
        for p, pipe in enumerate(self.runtime.pipes):
            for s, stage in enumerate(pipe):
                keys = self.layer_keys[p][s]
                for name, key in keys.items():
                    if key is None or key not in state:
                        continue
                    val = state[key]
                    if stage.mesh is None:
                        placed = jax.tree_util.tree_map(jnp.asarray, val)
                    elif name.startswith("layer"):
                        placed = {k: _place_entry(v, stage.mesh,
                                                  BLOCK_SPECS.get(k, P()))
                                  for k, v in val.items()}
                    else:
                        placed = _place_entry(val, stage.mesh,
                                              self._entry_spec(name))
                    dst[p][s][name] = placed
