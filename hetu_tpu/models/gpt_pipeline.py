"""Pipeline-parallel GPT: transformer blocks as stacked pp-sharded stages.

The 3D/4D-parallel counterpart of ``models/gpt.py`` (reference: the same
LLaMA blocks placed across pipeline stages via per-op DeviceGroupUnion,
``examples/gpt/hetu_llama.py`` + GPipe/1F1B in ``executable_graph.cc``).
Embedding and LM head live outside the pipeline body (computed under plain
GSPMD, replicated over pp); the homogeneous block stack runs through
``pipeline_spmd``.  dp/tp shardings inside blocks are expressed with
``with_sharding_constraint`` on the auto axes.

Functional-style block (pure params pytree) because the pipeline body must
be a jax-transformable function of stacked parameters.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import ops as _ops
from ..graph.ctor import NormalInitializer, parallel_parameter
from ..nn import Module, VocabParallelEmbedding, vocab_parallel_cross_entropy
from ..nn.parallel import ParallelRMSNorm, sharded
from ..ops.attention import sdpa
from ..parallel.pipeline import pipeline_spmd
from .gpt import GPTConfig


def _rotary_tables(seq_len: int, d: int):
    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float32) / d))
    ang = np.outer(np.arange(seq_len, dtype=np.float32), inv)
    emb = np.concatenate([ang, ang], axis=-1)
    return (jnp.asarray(np.cos(emb)[None, :, None, :]),
            jnp.asarray(np.sin(emb)[None, :, None, :]))


def _apply_rotary(x, cos, sin):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos.astype(x.dtype) + rot * sin.astype(x.dtype)


def _rms(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    out = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (out * w.astype(jnp.float32)).astype(x.dtype)


def block_fn(params, x, *, cfg: GPTConfig, mesh=None):
    """One LLaMA-style block (rmsnorm/rotary/swiglu), pure function.

    params: dict of this layer's weights; x: [b, s, h].
    """
    from jax.sharding import NamedSharding
    c = cfg

    def _wsc(v, spec):
        if mesh is None:
            return v
        return lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
    b, s, hdim = x.shape
    cos, sin = _rotary_tables(s, c.head_dim)

    h = _rms(x, params["ln1"])
    qkv = jnp.einsum("bsh,oh->bso", h, params["qkv"])
    qkv = _wsc(qkv, P(c.dp_axis, None, c.tp_axis))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, c.num_heads, c.head_dim)
    k = k.reshape(b, s, c.num_heads, c.head_dim)
    v = v.reshape(b, s, c.num_heads, c.head_dim)
    q = _apply_rotary(q, cos, sin)
    k = _apply_rotary(k, cos, sin)
    spec4 = P(c.dp_axis, None, c.tp_axis, None)
    q = _wsc(q, spec4)
    k = _wsc(k, spec4)
    v = _wsc(v, spec4)
    attn = sdpa(q, k, v, causal=True)
    attn = attn.reshape(b, s, c.num_heads * c.head_dim)
    attn = _wsc(attn, P(c.dp_axis, None, c.tp_axis))
    attn_out = jnp.einsum("bso,ho->bsh", attn, params["attn_out"])
    attn_out = _wsc(attn_out, P(c.dp_axis, None, None))
    x = x + attn_out

    h = _rms(x, params["ln2"])
    up = jnp.einsum("bsh,oh->bso", h, params["mlp_up"])
    up = _wsc(up, P(c.dp_axis, None, c.tp_axis))
    u1, u2 = jnp.split(up, 2, axis=-1)
    act = jax.nn.silu(u1) * u2
    down = jnp.einsum("bso,ho->bsh", act, params["mlp_down"])
    down = _wsc(down, P(c.dp_axis, None, None))
    return x + down


class GPTPipelineModel(Module):
    """LLaMA-family LM with pp-stacked blocks + dp/tp inside stages.

    ``num_stages`` must equal the mesh's pp size; layers are split into
    equal ranges per stage (reference layer-range placement).
    """

    def __init__(self, config: GPTConfig, num_stages: int,
                 pp_axis: str = "pp"):
        super().__init__()
        assert config.num_layers % num_stages == 0
        # block_fn implements a dense swiglu/rotary/rmsnorm MHA block; fail
        # loudly on config fields it does not honor rather than silently
        # building the wrong architecture
        if config.num_kv_heads not in (None, config.num_heads):
            raise NotImplementedError("pipelined blocks are MHA-only "
                                      "(num_kv_heads must equal num_heads)")
        for fld, want in (("activation", "swiglu"), ("norm", "rmsnorm"),
                          ("position", "rotary")):
            if getattr(config, fld) != want:
                raise NotImplementedError(
                    f"pipelined blocks only support {fld}={want!r}, "
                    f"got {getattr(config, fld)!r}")
        if config.dropout:
            raise NotImplementedError("pipelined blocks do not support "
                                      "dropout")
        self.config = config
        self.num_stages = num_stages
        self.pp_axis = pp_axis
        self.layers_per_stage = config.num_layers // num_stages
        c = config

        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            dtype=c.dtype, init=NormalInitializer(0.0, c.init_std), name="wte")
        self.ln_f = ParallelRMSNorm(c.hidden_size, sp=False,
                                    dp_axis=c.dp_axis, tp_axis=c.tp_axis,
                                    dtype=c.dtype, name="ln_f")
        self.lm_head = parallel_parameter(
            NormalInitializer(0.0, c.init_std), (c.vocab_size, c.hidden_size),
            pspec=P(c.tp_axis, None), dtype=c.dtype, name="lm_head")

        # stacked per-stage block params: [S, L/S, ...] sharded over pp.
        # tp sharding of the per-layer weight dims composes via trailing
        # spec entries.
        S, L = num_stages, self.layers_per_stage
        h, f = c.hidden_size, c.ffn_size

        def stacked(name, shape, pspec_tail, std):
            return parallel_parameter(
                NormalInitializer(0.0, std), (S, L, *shape),
                pspec=P(pp_axis, None, *pspec_tail), dtype=c.dtype,
                name=f"blocks.{name}")

        depth_std = c.init_std / math.sqrt(2 * c.num_layers)
        self.blk_ln1 = stacked("ln1", (h,), (None,), 0.0)
        self.blk_qkv = stacked("qkv", (3 * h, h), (c.tp_axis, None),
                               c.init_std)
        self.blk_attn_out = stacked("attn_out", (h, h), (None, c.tp_axis),
                                    depth_std)
        self.blk_ln2 = stacked("ln2", (h,), (None,), 0.0)
        self.blk_mlp_up = stacked("mlp_up", (2 * f, h), (c.tp_axis, None),
                                  c.init_std)
        self.blk_mlp_down = stacked("mlp_down", (h, f), (None, c.tp_axis),
                                    depth_std)
        # norms init to 1
        g = self.blk_ln1.graph
        g.reset_variable(self.blk_ln1, np.ones((S, L, h), np.float32))
        g.reset_variable(self.blk_ln2, np.ones((S, L, h), np.float32))

    def forward(self, input_ids, labels=None,
                num_micro_batches: int = 1):
        c = self.config
        mesh = self.wte.weight.graph.mesh
        x = self.wte(input_ids)

        def _impl(x, ln1, qkv, attn_out, ln2, mlp_up, mlp_down,
                  num_micro_batches=1):
            stage_params = {"ln1": ln1, "qkv": qkv, "attn_out": attn_out,
                            "ln2": ln2, "mlp_up": mlp_up,
                            "mlp_down": mlp_down}

            def stage_fn(params, x_mb):
                # scan this stage's layer range (leading dim L/S)
                def layer(x, layer_params):
                    return block_fn(layer_params, x, cfg=c, mesh=mesh), None
                out, _ = lax.scan(layer, x_mb, params)
                return out

            return pipeline_spmd(stage_fn, stage_params, x,
                                 num_micro_batches, mesh, self.pp_axis)

        x = _ops.functional._op(
            "pipeline_transformer", _impl,
            [x, self.blk_ln1, self.blk_qkv, self.blk_attn_out,
             self.blk_ln2, self.blk_mlp_up, self.blk_mlp_down],
            {"num_micro_batches": num_micro_batches})

        x = self.ln_f(x)
        logits = _ops.matmul(x, self.lm_head, trans_b=True)
        logits = sharded(logits, P(c.dp_axis, None, c.tp_axis))
        if labels is None:
            return logits
        return vocab_parallel_cross_entropy(
            logits, labels, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            ignore_index=-100)
