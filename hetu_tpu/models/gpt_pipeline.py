"""Pipeline-parallel GPT: transformer blocks as stacked pp-sharded stages.

The 3D/4D-parallel counterpart of ``models/gpt.py`` (reference: the same
LLaMA blocks placed across pipeline stages via per-op DeviceGroupUnion,
``examples/gpt/hetu_llama.py`` + GPipe/1F1B in ``executable_graph.cc``).
Embedding and LM head live outside the pipeline body (computed under plain
GSPMD, replicated over pp); the homogeneous block stack runs through
``pipeline_spmd``.  dp/tp shardings inside blocks are expressed with
``with_sharding_constraint`` on the auto axes.

Functional-style block (pure params pytree) because the pipeline body must
be a jax-transformable function of stacked parameters.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import ops as _ops
from ..graph.ctor import NormalInitializer, parallel_parameter
from ..nn import Module, VocabParallelEmbedding, vocab_parallel_cross_entropy
from ..nn.parallel import ParallelLayerNorm, ParallelRMSNorm, sharded
from ..ops.attention import sdpa
from ..parallel.pipeline import pipeline_spmd
from .gpt import GPTConfig


def _rotary_tables(seq_len: int, d: int):
    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float32) / d))
    ang = np.outer(np.arange(seq_len, dtype=np.float32), inv)
    emb = np.concatenate([ang, ang], axis=-1)
    return (jnp.asarray(np.cos(emb)[None, :, None, :]),
            jnp.asarray(np.sin(emb)[None, :, None, :]))


def _apply_rotary(x, cos, sin):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos.astype(x.dtype) + rot * sin.astype(x.dtype)


def _rms(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    out = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (out * w.astype(jnp.float32)).astype(x.dtype)


def _layernorm(x, w, b, eps=1e-5):
    # mirrors ops.layer_norm (input-dtype math) so pipelined GPT-2 blocks
    # match the non-pipelined model numerically
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * w + b


def block_fn(params, x, *, cfg: GPTConfig, mesh=None):
    """One transformer block, pure function: LLaMA-style
    (rmsnorm/rotary/swiglu, bias-free) or GPT-2-style
    (layernorm/learned-positions/gelu, with biases) by ``cfg``; GQA via
    ``cfg.num_kv_heads`` and MoE MLPs via ``cfg.num_experts`` (params
    carry ``moe_*`` leaves instead of ``mlp_*``).

    With ``cfg.sp`` the residual stream stays SEQUENCE-sharded over the
    tp axis between sublayers (Megatron-SP, reference
    parallel_multi_ds.py:156-170 per-layer ``sp`` flag) — under GSPMD
    this is purely a constraint change; XLA places the all-gather /
    reduce-scatter pair at the column/row-parallel boundaries.

    params: dict of this layer's weights; x: [b, s, h].
    Returns ``(x, aux)`` — aux is the MoE balance loss (0 for dense).
    """
    from jax.sharding import NamedSharding
    c = cfg

    def _wsc(v, spec):
        if mesh is None:
            return v
        # drop axis names the mesh doesn't have (e.g. no tp axis on a
        # pp x dp x ep mesh) — same degradation rule as graph._pspec_for
        names = set(mesh.axis_names)
        spec = P(*[e if e in names else None for e in spec])
        return lax.with_sharding_constraint(v, NamedSharding(mesh, spec))

    def _norm(x, which):
        if c.norm == "rmsnorm":
            return _rms(x, params[which])
        return _layernorm(x, params[which], params[which + "_b"])

    b, s, hdim = x.shape
    # residual-stream layout between sublayers: seq-sharded under SP
    resid_spec = P(c.dp_axis, c.tp_axis, None) if c.sp \
        else P(c.dp_axis, None, None)
    nkv = c.num_kv_heads or c.num_heads
    q_size = c.num_heads * c.head_dim
    kv_size = nkv * c.head_dim

    h = _norm(x, "ln1")
    qkv = jnp.einsum("bsh,oh->bso", h, params["qkv"])
    if "qkv_b" in params:
        qkv = qkv + params["qkv_b"]
    qkv = _wsc(qkv, P(c.dp_axis, None, c.tp_axis))
    q = qkv[..., :q_size].reshape(b, s, c.num_heads, c.head_dim)
    k = qkv[..., q_size:q_size + kv_size].reshape(b, s, nkv, c.head_dim)
    v = qkv[..., q_size + kv_size:].reshape(b, s, nkv, c.head_dim)
    if c.position == "rotary":
        cos, sin = _rotary_tables(s, c.head_dim)
        q = _apply_rotary(q, cos, sin)
        k = _apply_rotary(k, cos, sin)
    if nkv != c.num_heads:
        # repeat BEFORE constraining (models/gpt.py:165: kv_heads may be
        # < tp size; a head-dim constraint there forces remat)
        rep = c.num_heads // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    spec4 = P(c.dp_axis, None, c.tp_axis, None)
    q = _wsc(q, spec4)
    k = _wsc(k, spec4)
    v = _wsc(v, spec4)
    attn = sdpa(q, k, v, causal=True)
    attn = attn.reshape(b, s, c.num_heads * c.head_dim)
    attn = _wsc(attn, P(c.dp_axis, None, c.tp_axis))
    attn_out = jnp.einsum("bso,ho->bsh", attn, params["attn_out"])
    if "attn_out_b" in params:
        attn_out = attn_out + params["attn_out_b"]
    attn_out = _wsc(attn_out, resid_spec)
    x = x + attn_out

    h = _norm(x, "ln2")
    if "moe_w1" in params:
        down, aux = _moe_mlp(params, h, cfg=c, wsc=_wsc)
    else:
        aux = jnp.zeros((), jnp.float32)
        up = jnp.einsum("bsh,oh->bso", h, params["mlp_up"])
        if "mlp_up_b" in params:
            up = up + params["mlp_up_b"]
        up = _wsc(up, P(c.dp_axis, None, c.tp_axis))
        if c.activation == "swiglu":
            u1, u2 = jnp.split(up, 2, axis=-1)
            act = jax.nn.silu(u1) * u2
        else:
            act = jax.nn.gelu(up, approximate=True)
        down = jnp.einsum("bso,ho->bsh", act, params["mlp_down"])
        if "mlp_down_b" in params:
            down = down + params["mlp_down_b"]
    down = _wsc(down, resid_spec)
    return x + down, aux


def _moe_mlp(params, h, *, cfg: GPTConfig, wsc):
    """MoE feed-forward inside a pipelined block (pure-params form of
    nn/moe.py MoELayer: GShard top-k gate + stacked-expert einsums; EP
    sharding over ``cfg.ep_axis`` via constraints)."""
    from ..nn.moe import topk_gating_impl
    c = cfg
    b, s, hdim = h.shape
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "silu": jax.nn.silu}["silu" if c.activation == "swiglu"
                                else c.activation]
    espec = P(c.ep_axis, None, None) if c.ep_axis else P()
    xt = h.reshape(-1, hdim)                                     # [T, d]
    logits = jnp.einsum("td,ed->te", xt, params["moe_gate"])
    l_aux, combine, dispatch = topk_gating_impl(
        logits, c.moe_top_k, c.moe_capacity_factor)
    dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(xt.dtype), xt)
    dispatched = wsc(dispatched, espec)
    h1 = act(jnp.einsum("ecd,edf->ecf", dispatched, params["moe_w1"])
             + params["moe_b1"])
    eout = jnp.einsum("ecf,efd->ecd", h1, params["moe_w2"]) \
        + params["moe_b2"]
    eout = wsc(eout, espec)
    out = jnp.einsum("tec,ecd->td", combine.astype(eout.dtype), eout)
    return out.reshape(b, s, hdim).astype(h.dtype), l_aux


class GPTPipelineModel(Module):
    """LLaMA-family LM with pp-stacked blocks + dp/tp inside stages.

    ``num_stages`` must equal the mesh's pp size; layers are split into
    equal ranges per stage (reference layer-range placement).
    """

    def __init__(self, config: GPTConfig, num_stages: int,
                 pp_axis: str = "pp"):
        super().__init__()
        assert config.num_layers % num_stages == 0
        # fail loudly on config fields block_fn does not honor rather than
        # silently building the wrong architecture
        if config.dropout:
            raise NotImplementedError("pipelined blocks do not support "
                                      "dropout")
        if config.num_experts > 0:
            # lax.scan over a stage needs homogeneous layers: every block
            # must be MoE (the reference stacks per-layer modules instead)
            if any(not config.is_moe_layer(i)
                   for i in range(config.num_layers)):
                raise NotImplementedError(
                    "pipelined MoE needs every layer MoE (moe_every=1); "
                    "mixed dense/MoE stacks use the MPMD path")
            moe_act = "silu" if config.activation == "swiglu" \
                else config.activation
            if moe_act not in ("relu", "gelu", "silu"):
                raise ValueError(f"MoE experts do not support activation "
                                 f"{config.activation!r}")
        self.config = config
        self.num_stages = num_stages
        self.pp_axis = pp_axis
        self.layers_per_stage = config.num_layers // num_stages
        c = config
        biased = c.activation == "gelu"   # GPT-2 convention (models/gpt.py)

        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            dtype=c.dtype, init=NormalInitializer(0.0, c.init_std), name="wte")
        if c.position == "learned":
            self.wpe = parallel_parameter(
                NormalInitializer(0.0, c.init_std),
                (c.max_seq_len, c.hidden_size), pspec=P(None, None),
                dtype=c.dtype, name="wpe")
        else:
            self.wpe = None
        norm_cls = ParallelRMSNorm if c.norm == "rmsnorm" \
            else ParallelLayerNorm
        self.ln_f = norm_cls(c.hidden_size, sp=c.sp,
                             dp_axis=c.dp_axis, tp_axis=c.tp_axis,
                             dtype=c.dtype, name="ln_f")
        self.lm_head = parallel_parameter(
            NormalInitializer(0.0, c.init_std), (c.vocab_size, c.hidden_size),
            pspec=P(c.tp_axis, None), dtype=c.dtype, name="lm_head")

        # stacked per-stage block params: [S, L/S, ...] sharded over pp.
        # tp sharding of the per-layer weight dims composes via trailing
        # spec entries.
        S, L = num_stages, self.layers_per_stage
        h, f = c.hidden_size, c.ffn_size
        self._stacked = {}

        def stacked(name, shape, pspec_tail, std):
            t = parallel_parameter(
                NormalInitializer(0.0, std), (S, L, *shape),
                pspec=P(pp_axis, None, *pspec_tail), dtype=c.dtype,
                name=f"blocks.{name}")
            self._stacked[name] = t
            setattr(self, f"blk_{name}", t)
            return t

        depth_std = c.init_std / math.sqrt(2 * c.num_layers)
        up_rows = (2 if c.activation == "swiglu" else 1) * f
        q_size = c.num_heads * c.head_dim
        kv_size = (c.num_kv_heads or c.num_heads) * c.head_dim
        stacked("ln1", (h,), (None,), 0.0)
        if c.norm == "layernorm":
            stacked("ln1_b", (h,), (None,), 0.0)
        stacked("qkv", (q_size + 2 * kv_size, h), (c.tp_axis, None),
                c.init_std)
        if biased:
            stacked("qkv_b", (q_size + 2 * kv_size,), (c.tp_axis,), 0.0)
        stacked("attn_out", (h, q_size), (None, c.tp_axis), depth_std)
        if biased:
            stacked("attn_out_b", (h,), (None,), 0.0)
        stacked("ln2", (h,), (None,), 0.0)
        if c.norm == "layernorm":
            stacked("ln2_b", (h,), (None,), 0.0)
        if c.num_experts > 0:
            E = c.num_experts
            ep = c.ep_axis
            stacked("moe_gate", (E, h), (None, None), c.init_std)
            stacked("moe_w1", (E, h, f), (ep, None, None), c.init_std)
            stacked("moe_b1", (E, 1, f), (ep, None, None), 0.0)
            stacked("moe_w2", (E, f, h), (ep, None, None), depth_std)
            stacked("moe_b2", (E, 1, h), (ep, None, None), 0.0)
        else:
            stacked("mlp_up", (up_rows, h), (c.tp_axis, None), c.init_std)
            if biased:
                stacked("mlp_up_b", (up_rows,), (c.tp_axis,), 0.0)
            stacked("mlp_down", (h, f), (None, c.tp_axis), depth_std)
            if biased:
                stacked("mlp_down_b", (h,), (None,), 0.0)
        # norm scales init to 1
        g = self.blk_ln1.graph
        g.reset_variable(self.blk_ln1, np.ones((S, L, h), np.float32))
        g.reset_variable(self.blk_ln2, np.ones((S, L, h), np.float32))

    def forward(self, input_ids, labels=None,
                num_micro_batches: int = 1):
        c = self.config
        mesh = self.wte.weight.graph.mesh
        use_moe = c.num_experts > 0
        x = self.wte(input_ids)
        if self.wpe is not None:
            seq_len = input_ids.shape[-1]
            pos = _ops.getitem(self.wpe, slice(0, seq_len))
            x = x + pos
        if c.sp:
            x = sharded(x, P(c.dp_axis, c.tp_axis, None))
        keys = list(self._stacked.keys())

        def _impl(x, *stacked_arrays, num_micro_batches=1):
            stage_params = dict(zip(keys, stacked_arrays))

            def stage_fn(params, x_mb):
                # scan this stage's layer range (leading dim L/S),
                # accumulating the MoE aux loss across layers
                def layer(carry, layer_params):
                    x, aux = carry
                    y, a = block_fn(layer_params, x, cfg=c, mesh=mesh)
                    return (y, aux + a), None
                (out, aux), _ = lax.scan(
                    layer, (x_mb, jnp.zeros((), jnp.float32)), params)
                return (out, aux) if use_moe else out

            return pipeline_spmd(stage_fn, stage_params, x,
                                 num_micro_batches, mesh, self.pp_axis,
                                 with_aux=use_moe)

        if use_moe:
            x, aux = _ops.functional._op(
                "pipeline_transformer", _impl,
                [x, *self._stacked.values()],
                {"num_micro_batches": num_micro_batches}, num_outputs=2)
        else:
            x = _ops.functional._op(
                "pipeline_transformer", _impl,
                [x, *self._stacked.values()],
                {"num_micro_batches": num_micro_batches})

        x = self.ln_f(x)
        logits = _ops.matmul(x, self.lm_head, trans_b=True)
        logits = sharded(logits, P(c.dp_axis, None, c.tp_axis))
        if labels is None:
            return logits
        loss = vocab_parallel_cross_entropy(
            logits, labels, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            ignore_index=-100)
        if use_moe and c.moe_aux_coef:
            loss = loss + c.moe_aux_coef * aux
        return loss
