"""Recurrent models: LSTM/GRU/vanilla RNN + a language-model wrapper.

Counterpart of the reference's RNN workloads (``tests/test_rnn.py``,
``v1`` sequence layers).  Recurrence is expressed with ``lax.scan`` —
the XLA-idiomatic loop (static trip count, no Python-level unrolling),
with all gate matmuls fused into one [h, 4h]/[h, 3h] projection per step
so the MXU sees large GEMMs.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import ops
from ..graph.ctor import (ConstantInitializer, XavierUniformInitializer,
                          parameter)
from ..nn import Embedding, Linear, Module


class _RecurrentBase(Module):
    """Shared scaffolding: fused input/hidden projections + lax.scan."""

    GATES = 1

    def __init__(self, input_size: int, hidden_size: int,
                 name: str = "rnn"):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        g = self.GATES
        self.w_ih = parameter(XavierUniformInitializer(),
                              (input_size, g * hidden_size),
                              name=f"{name}.w_ih")
        self.w_hh = parameter(XavierUniformInitializer(),
                              (hidden_size, g * hidden_size),
                              name=f"{name}.w_hh")
        self.bias = parameter(ConstantInitializer(0.0), (g * hidden_size,),
                              name=f"{name}.bias")

    def _cell(self, carry, gates):
        raise NotImplementedError

    def _init_carry(self, batch, dtype):
        raise NotImplementedError

    def forward(self, x, initial_state=None):
        """x: [batch, seq, input] -> (outputs [batch, seq, hidden],
        final hidden state).  ``initial_state``: [batch, hidden] hidden
        (RNN/GRU) or (h, c) tuple (LSTM); zeros when omitted."""
        H = self.hidden_size
        cell = self._cell
        init = self._init_carry
        init_inputs = []
        if initial_state is not None:
            init_inputs = list(initial_state) \
                if isinstance(initial_state, (tuple, list)) \
                else [initial_state]

        def _impl(x, w_ih, w_hh, b, *carry_in):
            # precompute all input projections in one big matmul
            xg = jnp.einsum("bsi,ig->bsg", x, w_ih) + b   # [b, s, g*H]

            def step(carry, xg_t):
                h = carry[0] if isinstance(carry, tuple) else carry
                gates = xg_t + h @ w_hh
                new_carry = cell(carry, gates)
                h_out = new_carry[0] if isinstance(new_carry, tuple) \
                    else new_carry
                return new_carry, h_out

            if carry_in:
                carry0 = carry_in[0] if len(carry_in) == 1 \
                    else tuple(carry_in)
            else:
                carry0 = init(x.shape[0], x.dtype)
            carry, ys = lax.scan(step, carry0,
                                 jnp.swapaxes(xg, 0, 1))   # scan over seq
            h_final = carry[0] if isinstance(carry, tuple) else carry
            return jnp.swapaxes(ys, 0, 1), h_final

        return ops.functional._op(f"{type(self).__name__}_scan", _impl,
                                  [x, self.w_ih, self.w_hh, self.bias,
                                   *init_inputs],
                                  num_outputs=2)


class RNN(_RecurrentBase):
    """Vanilla tanh RNN."""

    GATES = 1

    def _cell(self, h, gates):
        return jnp.tanh(gates)

    def _init_carry(self, batch, dtype):
        return jnp.zeros((batch, self.hidden_size), dtype)


class GRU(_RecurrentBase):
    """GRU needs the hidden projection per-gate (reset gates the
    candidate's hidden term), so it overrides the scan instead of
    _cell."""

    GATES = 3

    def forward(self, x, initial_state=None):
        H = self.hidden_size
        init_inputs = [initial_state] if initial_state is not None else []

        def _impl(x, w_ih, w_hh, b, *carry_in):
            xg = jnp.einsum("bsi,ig->bsg", x, w_ih) + b

            def step(h, xg_t):
                hg = h @ w_hh                       # [b, 3H]
                r = jax.nn.sigmoid(xg_t[:, :H] + hg[:, :H])
                z = jax.nn.sigmoid(xg_t[:, H:2 * H] + hg[:, H:2 * H])
                n = jnp.tanh(xg_t[:, 2 * H:] + r * hg[:, 2 * H:])
                h_new = (1 - z) * n + z * h
                return h_new, h_new

            h0 = carry_in[0] if carry_in \
                else jnp.zeros((x.shape[0], H), x.dtype)
            carry, ys = lax.scan(step, h0, jnp.swapaxes(xg, 0, 1))
            return jnp.swapaxes(ys, 0, 1), carry

        return ops.functional._op("gru_scan", _impl,
                                  [x, self.w_ih, self.w_hh, self.bias,
                                   *init_inputs],
                                  num_outputs=2)


class LSTM(_RecurrentBase):
    GATES = 4

    def _cell(self, carry, gates):
        h, c = carry
        H = self.hidden_size
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H] + 1.0)  # forget bias 1
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new)

    def _init_carry(self, batch, dtype):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)


class RNNLanguageModel(Module):
    """Embedding -> recurrent stack -> tied-ish LM head (the reference's
    test_rnn.py language-model shape)."""

    def __init__(self, vocab_size: int, hidden_size: int,
                 cell: str = "lstm", num_layers: int = 1,
                 name: str = "rnnlm"):
        super().__init__()
        cells = {"rnn": RNN, "gru": GRU, "lstm": LSTM}
        self.embed = Embedding(vocab_size, hidden_size)
        self.layers = []
        for li in range(num_layers):
            layer = cells[cell](hidden_size, hidden_size,
                                name=f"{name}.l{li}")
            self.add_module(f"l{li}", layer)
            self.layers.append(layer)
        self.head = Linear(hidden_size, vocab_size)

    def forward(self, input_ids, labels=None):
        x = self.embed(input_ids)
        for layer in self.layers:
            x, _ = layer(x)
        logits = self.head(x)
        if labels is None:
            return logits
        return ops.softmax_cross_entropy(logits, labels)
