"""CNN model family: CIFAR-style convnet + ResNet.

Counterpart of the reference's CNN workloads (``tests/test_cifar10.py``,
``v1/examples/cnn`` — LeNet/MLP/ResNet CIFAR recipes).  Convolutions use
NCHW layouts lowered by XLA onto the MXU; data parallelism comes from
batch-dim sharding annotations like every other model family.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .. import ops
from ..nn import (AvgPool2d, BatchNorm2d, Conv2d, Linear, MaxPool2d, Module,
                  ModuleList, ReLU, Sequential)


class SimpleCNN(Module):
    """LeNet-style CIFAR-10 net (reference test_cifar10.py)."""

    def __init__(self, num_classes: int = 10, in_channels: int = 3):
        super().__init__()
        self.features = Sequential(
            Conv2d(in_channels, 32, kernel_size=3, padding=1), ReLU(),
            Conv2d(32, 32, kernel_size=3, padding=1), ReLU(),
            MaxPool2d(2),
            Conv2d(32, 64, kernel_size=3, padding=1), ReLU(),
            Conv2d(64, 64, kernel_size=3, padding=1), ReLU(),
            MaxPool2d(2),
        )
        self.fc1 = Linear(64 * 8 * 8, 256)
        self.fc2 = Linear(256, num_classes)

    def forward(self, x, labels=None):
        h = self.features(x)
        h = ops.reshape(h, (h.shape[0], -1))
        logits = self.fc2(ops.relu(self.fc1(h)))
        if labels is None:
            return logits
        return ops.softmax_cross_entropy(logits, labels)


class BasicBlock(Module):
    """ResNet v1 basic block (3x3 + 3x3, identity/projection shortcut)."""

    def __init__(self, in_ch: int, out_ch: int, stride: int = 1):
        super().__init__()
        self.conv1 = Conv2d(in_ch, out_ch, kernel_size=3, stride=stride,
                            padding=1, bias=False)
        self.bn1 = BatchNorm2d(out_ch)
        self.conv2 = Conv2d(out_ch, out_ch, kernel_size=3, padding=1,
                            bias=False)
        self.bn2 = BatchNorm2d(out_ch)
        if stride != 1 or in_ch != out_ch:
            self.shortcut = Sequential(
                Conv2d(in_ch, out_ch, kernel_size=1, stride=stride,
                       bias=False),
                BatchNorm2d(out_ch))
        else:
            self.shortcut = None

    def forward(self, x):
        h = ops.relu(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        sc = self.shortcut(x) if self.shortcut is not None else x
        return ops.relu(h + sc)


class ResNet(Module):
    """CIFAR ResNet (18-layer default: stages (2, 2, 2, 2))."""

    def __init__(self, num_classes: int = 10,
                 stages: Sequence[int] = (2, 2, 2, 2),
                 widths: Sequence[int] = (64, 128, 256, 512),
                 in_channels: int = 3):
        super().__init__()
        assert len(stages) <= len(widths), \
            f"need a width per stage ({len(stages)} stages, " \
            f"{len(widths)} widths)"
        self.stem = Sequential(
            Conv2d(in_channels, widths[0], kernel_size=3, padding=1,
                   bias=False),
            BatchNorm2d(widths[0]), ReLU())
        blocks = []
        in_ch = widths[0]
        for si, (n, w) in enumerate(zip(stages, widths)):
            for bi in range(n):
                stride = 2 if (si > 0 and bi == 0) else 1
                blocks.append(BasicBlock(in_ch, w, stride))
                in_ch = w
        self.blocks = ModuleList(blocks)
        self.head = Linear(in_ch, num_classes)

    def forward(self, x, labels=None):
        h = self.stem(x)
        for blk in self.blocks:
            h = blk(h)
        h = ops.reduce_mean(h, axis=(2, 3))   # global average pool
        logits = self.head(h)
        if labels is None:
            return logits
        return ops.softmax_cross_entropy(logits, labels)


def resnet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(num_classes, stages=(2, 2, 2, 2), **kw)


def resnet34(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(num_classes, stages=(3, 4, 6, 3), **kw)
