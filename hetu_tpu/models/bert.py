"""BERT model family on parallel layers.

Counterpart of the reference's BERT workload (``tests/hetu_bert.py`` —
the v2 op-test model — and ``v1/examples/nlp``): bidirectional
transformer encoder with token/position/segment embeddings, MLM + NSP
pre-training heads, and a sequence-classification head.  Uses the same
column/row-parallel layers and sharding annotations as the GPT family.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from jax.sharding import PartitionSpec as P

from .. import ops
from ..graph.ctor import NormalInitializer, parallel_parameter
from ..nn import (ColumnParallelLinear, Module, ModuleList,
                  ParallelLayerNorm, RowParallelLinear,
                  VocabParallelEmbedding, vocab_parallel_cross_entropy)
from ..nn.parallel import sharded


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None   # None -> 4h
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    init_std: float = 0.02
    dtype: str = "float32"
    dp_axis: str = "dp"
    tp_axis: str = "tp"

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class BertSelfAttention(Module):
    """Bidirectional multi-head attention, TP head-split."""

    def __init__(self, cfg: BertConfig, idx: int):
        super().__init__()
        self.cfg = cfg
        self.qkv = ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std),
            name=f"bert.blocks{idx}.attn.qkv")
        self.dense = RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std),
            name=f"bert.blocks{idx}.attn.dense")

    def forward(self, x):
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x)                           # [b, s, 3h] (tp-split)
        qkv = ops.reshape(qkv, (b, s, 3, cfg.num_heads, cfg.head_dim))
        qkv = sharded(qkv, P(cfg.dp_axis, None, None, cfg.tp_axis, None))
        q = ops.getitem(qkv, (slice(None), slice(None), 0))
        k = ops.getitem(qkv, (slice(None), slice(None), 1))
        v = ops.getitem(qkv, (slice(None), slice(None), 2))
        out = ops.attention(q, k, v, causal=False)  # [b, s, nh, hd]
        out = ops.reshape(out, (b, s, cfg.hidden_size))
        out = sharded(out, P(cfg.dp_axis, None, cfg.tp_axis))
        return self.dense(out)


class BertLayer(Module):
    """Post-norm encoder block (original BERT ordering)."""

    def __init__(self, cfg: BertConfig, idx: int):
        super().__init__()
        self.attn = BertSelfAttention(cfg, idx)
        self.ln1 = ParallelLayerNorm(cfg.hidden_size, dp_axis=cfg.dp_axis,
                                     tp_axis=cfg.tp_axis,
                                     name=f"bert.blocks{idx}.ln1")
        self.fc1 = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_size, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std),
            name=f"bert.blocks{idx}.mlp.fc1")
        self.fc2 = RowParallelLinear(
            cfg.ffn_size, cfg.hidden_size, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std),
            name=f"bert.blocks{idx}.mlp.fc2")
        self.ln2 = ParallelLayerNorm(cfg.hidden_size, dp_axis=cfg.dp_axis,
                                     tp_axis=cfg.tp_axis,
                                     name=f"bert.blocks{idx}.ln2")

    def forward(self, x):
        x = self.ln1(x + self.attn(x))
        x = self.ln2(x + self.fc2(ops.gelu(self.fc1(x))))
        return x


class BertModel(Module):
    """Embeddings + encoder stack + pooler."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std), name="bert.wte")
        self.wpe = parallel_parameter(
            NormalInitializer(0.0, cfg.init_std),
            (cfg.max_seq_len, cfg.hidden_size), pspec=P(),
            name="bert.wpe")
        self.wse = parallel_parameter(
            NormalInitializer(0.0, cfg.init_std),
            (cfg.type_vocab_size, cfg.hidden_size), pspec=P(),
            name="bert.wse")
        self.ln = ParallelLayerNorm(cfg.hidden_size, dp_axis=cfg.dp_axis,
                                    tp_axis=cfg.tp_axis, name="bert.ln")
        self.blocks = ModuleList([BertLayer(cfg, i)
                                  for i in range(cfg.num_layers)])
        self.pooler = ColumnParallelLinear(
            cfg.hidden_size, cfg.hidden_size, gather_output=True,
            dp_axis=cfg.dp_axis, tp_axis=cfg.tp_axis,
            init=NormalInitializer(0.0, cfg.init_std), name="bert.pooler")

    def forward(self, input_ids, token_type_ids=None):
        cfg = self.cfg
        s = input_ids.shape[1]
        x = self.wte(input_ids)
        pos = ops.slice(self.wpe, (0, 0), (s, cfg.hidden_size))
        x = x + pos
        if token_type_ids is not None:
            x = x + ops.embedding_lookup(self.wse, token_type_ids)
        x = self.ln(x)
        for blk in self.blocks:
            x = blk(x)
        cls = ops.getitem(x, (slice(None), 0))     # [b, h]
        pooled = ops.tanh(self.pooler(cls))
        return x, pooled


class BertForPreTraining(Module):
    """MLM + NSP heads (the hetu_bert.py pre-training setup)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.nsp_head = ColumnParallelLinear(
            cfg.hidden_size, 2, gather_output=True, dp_axis=cfg.dp_axis,
            tp_axis=cfg.tp_axis, name="bert.nsp")

    def forward(self, input_ids, token_type_ids=None, mlm_labels=None,
                nsp_labels=None):
        cfg = self.cfg
        hidden, pooled = self.bert(input_ids, token_type_ids)
        # tied MLM head: hidden @ wte^T (vocab-parallel)
        logits = ops.linear(hidden, self.bert.wte.weight, trans_b=True)
        if mlm_labels is None:
            return logits
        mlm_loss = vocab_parallel_cross_entropy(
            logits, mlm_labels, dp_axis=cfg.dp_axis, tp_axis=cfg.tp_axis,
            ignore_index=-100)
        loss = mlm_loss
        if nsp_labels is not None:
            nsp_logits = self.nsp_head(pooled)
            loss = loss + ops.softmax_cross_entropy(nsp_logits, nsp_labels)
        return loss


class BertForSequenceClassification(Module):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.classifier = ColumnParallelLinear(
            cfg.hidden_size, num_classes, gather_output=True,
            dp_axis=cfg.dp_axis, tp_axis=cfg.tp_axis, name="bert.cls")

    def forward(self, input_ids, labels=None, token_type_ids=None):
        _, pooled = self.bert(input_ids, token_type_ids)
        logits = self.classifier(pooled)
        if labels is None:
            return logits
        return ops.softmax_cross_entropy(logits, labels)
