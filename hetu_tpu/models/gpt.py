"""GPT-2 / LLaMA model family on parallel layers.

TPU-native re-expression of the reference's canonical LLM workloads
(``examples/gpt/hetu_llama.py``, ``python/elastic/models/gpt/gpt_model.py``):
transformer blocks built from column/row-parallel linears, vocab-parallel
embedding + CE, parallel norms with SP, rotary or learned positions, and
flash attention (Pallas on TPU).  DP/TP/SP shardings are PartitionSpec
annotations over a named mesh; CP (ring attention over the ``cp_axis``)
dispatches to ``ops.parallel_attention`` when ``config.cp_axis`` is set.

Config mirrors the reference's argparse surface (examples/gpt/train_hetu.py
:479-588): hidden/layers/heads/seq/vocab, activation/norm variants.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import ops
from ..graph.ctor import NormalInitializer, parallel_parameter
from ..nn import (ColumnParallelLinear, Dropout, Module, ModuleList,
                  ParallelLayerNorm, ParallelRMSNorm, RowParallelLinear,
                  VocabParallelEmbedding, vocab_parallel_cross_entropy)
from ..nn.parallel import sharded
from jax.sharding import PartitionSpec as P


@dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None      # GQA; None -> = num_heads
    ffn_hidden_size: Optional[int] = None   # None -> 4h (gelu) or 8h/3 (swiglu)
    max_seq_len: int = 1024
    activation: str = "gelu"                # gelu (GPT) | swiglu (LLaMA)
    norm: str = "layernorm"                 # layernorm (GPT) | rmsnorm (LLaMA)
    position: str = "learned"               # learned (GPT) | rotary (LLaMA)
    dropout: float = 0.0
    sp: bool = True                         # Megatron sequence parallel
    tie_embeddings: bool = False
    init_std: float = 0.02
    dtype: str = "float32"
    dp_axis: str = "dp"
    tp_axis: str = "tp"
    cp_axis: Optional[str] = None   # context parallel axis
    cp_impl: str = "ring"           # "ring" (AttnCommRing) | "ulysses"
    # fuse lm_head matmul + CE so [B*S, V] logits are never stored
    # whole (HBM win; scratch/purejax.py "fusedce" variant)
    fused_lm_ce: bool = False
    # MoE (v1 MoELayer capability): >0 replaces the dense MLP with a
    # mixture of experts every `moe_every` blocks
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_every: int = 1
    moe_aux_coef: float = 0.01
    ep_axis: Optional[str] = None   # expert-parallel mesh axis
    # MLA (multi-head latent attention, FlashMLA-ETAP arxiv 2506.01969):
    # when set, the decode/serving stack stores ONE [T, kv_latent_dim]
    # compressed KV stream per layer instead of [T, kv_heads, head_dim]
    # k + v, and attention runs weight-absorbed against the latent.
    # kv_rope_dim is the decoupled-RoPE key width (rotary configs only;
    # None -> head_dim); learned-position configs carry no rope stream.
    kv_latent_dim: Optional[int] = None
    kv_rope_dim: Optional[int] = None

    def __post_init__(self):
        assert self.hidden_size % self.num_heads == 0, \
            f"hidden {self.hidden_size} not divisible by heads {self.num_heads}"
        kv = self.num_kv_heads or self.num_heads
        assert self.num_heads % kv == 0, \
            f"num_heads {self.num_heads} not divisible by kv_heads {kv}"
        if self.kv_latent_dim is not None:
            assert self.kv_latent_dim >= 1, \
                f"kv_latent_dim must be >= 1, got {self.kv_latent_dim}"
            if self.position == "rotary":
                r = self.rope_dim
                assert r > 0 and r % 2 == 0, \
                    f"MLA decoupled rope dim must be positive even, got {r}"
        elif self.kv_rope_dim is not None:
            raise ValueError("kv_rope_dim requires kv_latent_dim (MLA mode)")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def is_mla(self) -> bool:
        return self.kv_latent_dim is not None

    @property
    def rope_dim(self) -> int:
        """Decoupled-RoPE key width d_r: 0 for non-MLA and for
        learned-position MLA (no positional content in the cache)."""
        if self.kv_latent_dim is None or self.position != "rotary":
            return 0
        return self.kv_rope_dim if self.kv_rope_dim is not None \
            else self.head_dim

    def is_moe_layer(self, layer_idx: int) -> bool:
        """Single source of truth for MoE placement — used by both the
        training blocks (GPTBlock) and the decode engine (generate.py)."""
        return self.num_experts > 0 and \
            layer_idx % max(1, self.moe_every) == 0

    @property
    def ffn_size(self) -> int:
        if self.ffn_hidden_size:
            return self.ffn_hidden_size
        if self.activation == "swiglu":
            return int(8 * self.hidden_size / 3 / 64) * 64 or 64
        return 4 * self.hidden_size


def llama_config(**kw) -> GPTConfig:
    kw.setdefault("activation", "swiglu")
    kw.setdefault("norm", "rmsnorm")
    kw.setdefault("position", "rotary")
    return GPTConfig(**kw)


def draft_config(cfg: GPTConfig, num_layers: int) -> GPTConfig:
    """A shallow draft-model config for speculative decoding
    (serving/spec.py): identical tokenizer/embedding/head geometry —
    the draft and target MUST share the vocab so draft proposals are
    target token ids — with only the layer count reduced."""
    if not 1 <= num_layers <= cfg.num_layers:
        raise ValueError(
            f"draft num_layers must be in [1, {cfg.num_layers}] (the "
            f"target's layer count), got {num_layers}")
    import dataclasses
    return dataclasses.replace(cfg, num_layers=int(num_layers))


def draft_state_from(state, cfg: GPTConfig, num_layers: int):
    """Build a truncated draft ``(state, config)`` from a target
    checkpoint: the first ``num_layers`` transformer blocks plus the
    shared embeddings / final norm / lm head.  A self-distilled
    truncation like this shares the residual-stream geometry with its
    target, which is what makes its greedy proposals land — any
    separately-trained model with the same vocab works through the same
    ``SpecConfig`` entry point."""
    from .generate import _Params
    dcfg = draft_config(cfg, num_layers)
    keep = {}
    for k, v in state.items():
        nk = _Params._norm(k)
        if nk.startswith("h"):
            idx = nk[1:].split(".", 1)[0]
            if idx.isdigit() and int(idx) >= num_layers:
                continue
        keep[k] = v
    return keep, dcfg


def mla_config(cfg: GPTConfig, kv_latent_dim: int,
               kv_rope_dim: Optional[int] = None) -> GPTConfig:
    """The MLA twin of a full-head config: identical everywhere except
    the cache layout fields (decode-cache keys treat these as part of
    the config identity, so full-head and latent executables never
    collide)."""
    import dataclasses
    return dataclasses.replace(cfg, kv_latent_dim=int(kv_latent_dim),
                               kv_rope_dim=kv_rope_dim)


def mla_state_from(state, cfg: GPTConfig, kv_latent_dim: int,
                   kv_rope_dim: Optional[int] = None, seed: int = 0):
    """Convert a full-head checkpoint into an MLA ``(state, config)``.

    Per layer, the fused ``attn.qkv`` projection is split and re-factored
    into the weight-absorbed MLA schema:

    - ``attn.q.weight``  [nh*(hd+d_r), H] — per-head ``[q_nope | q_rope]``
      rows; the nope rows are the source query projection verbatim.
    - ``attn.kv_a.weight`` [d_c+d_r, H] — shared latent down-projection
      (plus the decoupled rope key rows when d_r > 0).
    - ``attn.k_up.weight`` / ``attn.v_up.weight`` [nh, hd, d_c] — the
      up-projections that decode ABSORBS into q / out (FlashMLA-ETAP):
      ``score_h = (q_h @ k_up_h) . c`` and ``out_h = (probs @ C) @
      v_up_h.T``, so no cached token is ever decompressed.

    The factorization is the truncated SVD of the stacked per-head
    ``[W_k; W_v]`` — EXACT (up to fp rounding) whenever that stack has
    rank <= d_c, which is how the bench accuracy gate builds its
    equivalence witness.  Learned-position configs convert losslessly;
    rotary sources are approximate by construction (full-head rope
    content cannot live in a position-free latent — the decoupled rope
    rows are freshly initialized) and are gated by measured accuracy,
    not bitwise claims.  K/V projection biases are least-squares-folded
    into ``kv_a.bias`` (exact when they lie in the latent column span).
    """
    from .generate import _Params
    d_c = int(kv_latent_dim)
    ncfg = mla_config(cfg, d_c, kv_rope_dim)
    d_r = ncfg.rope_dim
    nh, kvh, hd, H = (cfg.num_heads, cfg.kv_heads, cfg.head_dim,
                      cfg.hidden_size)
    g = nh // kvh
    q_size, kv_size = nh * hd, kvh * hd
    rng = np.random.RandomState(seed)
    flat = {_Params._norm(k): v for k, v in state.items()}
    out = {k: v for k, v in flat.items()
           if ".attn.qkv." not in k}
    for i in range(cfg.num_layers):
        w = np.asarray(flat[f"h{i}.attn.qkv.weight"], np.float32)
        b = flat.get(f"h{i}.attn.qkv.bias")
        b = None if b is None else np.asarray(b, np.float32)
        wq, wk, wv = (w[:q_size], w[q_size:q_size + kv_size],
                      w[q_size + kv_size:])
        # -- latent factorization: [W_k; W_v] = U @ (S Vt), keep d_c --
        m = np.concatenate([wk, wv], axis=0)          # [2*kv_size, H]
        u, s, vt = np.linalg.svd(m, full_matrices=False)
        r = min(d_c, s.shape[0])
        kv_a = np.zeros((d_c + d_r, H), np.float32)
        kv_a[:r] = s[:r, None] * vt[:r]
        up = np.zeros((2 * kv_size, d_c), np.float32)
        up[:, :r] = u[:, :r]
        k_up = up[:kv_size].reshape(kvh, hd, d_c)
        v_up = up[kv_size:].reshape(kvh, hd, d_c)
        # GQA: expand kv-head up-projections to query heads so decode
        # absorbs per query head against the single shared latent
        k_up = np.repeat(k_up, g, axis=0)
        v_up = np.repeat(v_up, g, axis=0)
        # -- query: source nope rows + fresh decoupled-rope rows --
        q_w = np.zeros((nh, hd + d_r, H), np.float32)
        q_w[:, :hd] = wq.reshape(nh, hd, H)
        if d_r:
            q_w[:, hd:] = rng.normal(
                0.0, cfg.init_std, (nh, d_r, H)).astype(np.float32)
            kv_a[d_c:] = rng.normal(
                0.0, cfg.init_std, (d_r, H)).astype(np.float32)
        out[f"h{i}.attn.q.weight"] = q_w.reshape(nh * (hd + d_r), H)
        out[f"h{i}.attn.kv_a.weight"] = kv_a
        out[f"h{i}.attn.k_up.weight"] = k_up
        out[f"h{i}.attn.v_up.weight"] = v_up
        if b is not None:
            q_b = np.zeros((nh, hd + d_r), np.float32)
            q_b[:, :hd] = b[:q_size].reshape(nh, hd)
            out[f"h{i}.attn.q.bias"] = q_b.reshape(-1)
            kv_b = np.zeros((d_c + d_r,), np.float32)
            kv_b[:d_c] = up.T @ b[q_size:]   # least-squares fold
            out[f"h{i}.attn.kv_a.bias"] = kv_b
    return out, ncfg


def _norm(config: GPTConfig, name: str):
    if config.norm == "rmsnorm":
        return ParallelRMSNorm(config.hidden_size, sp=config.sp,
                               dp_axis=config.dp_axis, tp_axis=config.tp_axis,
                               seq_axis=config.cp_axis,
                               dtype=config.dtype, name=name)
    return ParallelLayerNorm(config.hidden_size, sp=config.sp,
                             dp_axis=config.dp_axis, tp_axis=config.tp_axis,
                             seq_axis=config.cp_axis,
                             dtype=config.dtype, name=name)


class ParallelAttentionBlock(Module):
    """Self-attention with TP head split (reference ParallelAttention op +
    qkv column-parallel / out row-parallel layout)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.config = config
        c = config
        if c.kv_latent_dim is not None:
            raise NotImplementedError(
                "MLA (kv_latent_dim) is a decode/serving cache layout; "
                "train full-head and convert with models.gpt.mla_state_from")
        q_size = c.num_heads * c.head_dim
        kv_size = c.kv_heads * c.head_dim
        self.qkv = ColumnParallelLinear(
            c.hidden_size, q_size + 2 * kv_size, bias=(c.activation == "gelu"),
            dp_axis=c.dp_axis, tp_axis=c.tp_axis, seq_axis=c.cp_axis,
            dtype=c.dtype,
            init=NormalInitializer(0.0, c.init_std),
            name=f"h{layer_idx}.attn.qkv")
        self.out = RowParallelLinear(
            q_size, c.hidden_size, bias=(c.activation == "gelu"), sp=c.sp,
            dp_axis=c.dp_axis, tp_axis=c.tp_axis, seq_axis=c.cp_axis,
            dtype=c.dtype,
            init=NormalInitializer(0.0, c.init_std / math.sqrt(2 * c.num_layers)),
            name=f"h{layer_idx}.attn.out")
        self.dropout = Dropout(c.dropout) if c.dropout else None
        self._rotary_cache = {}

    def _rotary(self, seq_len: int):
        if seq_len not in self._rotary_cache:
            d = self.config.head_dim
            inv = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float32) / d))
            ang = np.outer(np.arange(seq_len, dtype=np.float32), inv)
            emb = np.concatenate([ang, ang], axis=-1)
            cos = np.cos(emb)[None, :, None, :].astype(np.float32)
            sin = np.sin(emb)[None, :, None, :].astype(np.float32)
            self._rotary_cache[seq_len] = (cos, sin)
        return self._rotary_cache[seq_len]

    def forward(self, x, seq_len: int, segment_ids=None):
        c = self.config
        qkv = self.qkv(x)  # [b, s, (nh + 2*nkv) * hd], tp-sharded on last dim
        b_spec = P(c.dp_axis, c.cp_axis, c.tp_axis, None)
        q_size = c.num_heads * c.head_dim
        kv_size = c.kv_heads * c.head_dim
        q = ops.getitem(qkv, (Ellipsis, slice(0, q_size)))
        k = ops.getitem(qkv, (Ellipsis, slice(q_size, q_size + kv_size)))
        v = ops.getitem(qkv, (Ellipsis, slice(q_size + kv_size, None)))
        q = sharded(q.reshape((-1, seq_len, c.num_heads, c.head_dim)), b_spec)
        k = k.reshape((-1, seq_len, c.kv_heads, c.head_dim))
        v = v.reshape((-1, seq_len, c.kv_heads, c.head_dim))
        if c.position == "rotary":
            cos, sin = self._rotary(seq_len)
            q = ops.rotary_embed(q, cos, sin)
            k = ops.rotary_embed(k, cos, sin)
        if c.kv_heads != c.num_heads:
            # repeat BEFORE constraining: kv_heads may be < tp size, and a
            # head-dim constraint there forces SPMD full rematerialization
            k = ops.repeat_kv(k, c.num_heads // c.kv_heads)
            v = ops.repeat_kv(v, c.num_heads // c.kv_heads)
        k = sharded(k, b_spec)
        v = sharded(v, b_spec)
        if c.cp_axis:
            attn = ops.parallel_attention(
                q, k, v, causal=True, cp_axis=c.cp_axis,
                batch_axis=c.dp_axis, head_axis=c.tp_axis,
                segment_ids=segment_ids, cp_impl=c.cp_impl)
        else:
            attn = ops.attention(q, k, v, causal=True,
                                 segment_ids=segment_ids)
        attn = sharded(attn, b_spec)
        attn = attn.reshape((-1, seq_len, q_size))
        attn = sharded(attn, P(c.dp_axis, c.cp_axis, c.tp_axis))
        out = self.out(attn)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class ParallelMLP(Module):
    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        c = config
        mult = 2 if c.activation == "swiglu" else 1
        self.up = ColumnParallelLinear(
            c.hidden_size, c.ffn_size * mult, bias=(c.activation == "gelu"),
            dp_axis=c.dp_axis, tp_axis=c.tp_axis, seq_axis=c.cp_axis,
            dtype=c.dtype,
            init=NormalInitializer(0.0, c.init_std),
            name=f"h{layer_idx}.mlp.up")
        self.down = RowParallelLinear(
            c.ffn_size, c.hidden_size, bias=(c.activation == "gelu"), sp=c.sp,
            dp_axis=c.dp_axis, tp_axis=c.tp_axis, seq_axis=c.cp_axis,
            dtype=c.dtype,
            init=NormalInitializer(0.0, c.init_std / math.sqrt(2 * c.num_layers)),
            name=f"h{layer_idx}.mlp.down")
        self.activation = c.activation
        self.dropout = Dropout(c.dropout) if c.dropout else None

    def forward(self, x):
        h = self.up(x)
        if self.activation == "swiglu":
            h = ops.swiglu(h)
        elif self.activation == "silu":
            h = ops.silu(h)
        elif self.activation == "relu":
            h = ops.relu(h)
        else:
            h = ops.gelu(h)
        out = self.down(h)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class MoEMLP(Module):
    """MoE feed-forward block (reference v1 MoELayer in a transformer,
    v1/examples/moe): token dispatch + stacked experts; the aux balance
    loss is accumulated on the module for the LM head to pick up."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        from ..nn.moe import make_moe_layer
        c = config
        # experts use the config activation directly; swiglu (gated, 2x
        # fc1 width) has no stacked-expert form here, so it maps to its
        # silu nonlinearity
        moe_act = "silu" if c.activation == "swiglu" else c.activation
        if moe_act not in ("relu", "gelu", "silu"):
            raise ValueError(
                f"MoE experts do not support activation {c.activation!r}")
        self.moe = make_moe_layer(
            c.hidden_size, c.ffn_size, num_experts=c.num_experts,
            gate_type="topk", k=c.moe_top_k,
            capacity_factor=c.moe_capacity_factor,
            activation=moe_act,
            ep_axis=c.ep_axis, dtype=c.dtype, name=f"h{layer_idx}.moe")
        self.last_aux = None

    def forward(self, x):
        out, aux = self.moe(x)
        self.last_aux = aux
        return out


class GPTBlock(Module):
    def __init__(self, config: GPTConfig, layer_idx: int):
        super().__init__()
        self.ln_1 = _norm(config, f"h{layer_idx}.ln_1")
        self.attn = ParallelAttentionBlock(config, layer_idx)
        self.ln_2 = _norm(config, f"h{layer_idx}.ln_2")
        use_moe = config.is_moe_layer(layer_idx)
        self.mlp = MoEMLP(config, layer_idx) if use_moe \
            else ParallelMLP(config, layer_idx)

    def forward(self, x, seq_len: int, segment_ids=None):
        x = x + self.attn(self.ln_1(x), seq_len, segment_ids=segment_ids)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(Module):
    """Backbone: embeddings + blocks + final norm
    (reference LLamaModel, examples/gpt/hetu_llama.py)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            seq_axis=c.cp_axis,
            dtype=c.dtype, init=NormalInitializer(0.0, c.init_std), name="wte")
        if c.position == "learned":
            self.wpe = parallel_parameter(
                NormalInitializer(0.0, c.init_std),
                (c.max_seq_len, c.hidden_size), pspec=P(), dtype=c.dtype,
                name="wpe")
        self.drop = Dropout(c.dropout) if c.dropout else None
        self.h = ModuleList([GPTBlock(c, i) for i in range(c.num_layers)])
        self.ln_f = _norm(config, "ln_f")

    def forward(self, input_ids, seq_len: Optional[int] = None,
                segment_ids=None):
        c = self.config
        if seq_len is None:
            seq_len = input_ids.shape[-1]
            if hasattr(seq_len, "get"):
                seq_len = seq_len.get()
        x = self.wte(input_ids)
        if c.position == "learned":
            pos = ops.getitem(self.wpe, slice(0, seq_len))
            x = x + pos
        if self.drop is not None:
            x = self.drop(x)
        for block in self.h:
            x = block(x, seq_len, segment_ids=segment_ids)
        return self.ln_f(x)


class GPTLMHeadModel(Module):
    """LM head + vocab-parallel CE loss (reference LLamaLMHeadModel)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        self.transformer = GPTModel(config)
        if c.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                c.hidden_size, c.vocab_size, bias=False,
                dp_axis=c.dp_axis, tp_axis=c.tp_axis, seq_axis=c.cp_axis,
                dtype=c.dtype,
                init=NormalInitializer(0.0, c.init_std), name="lm_head")

    def logits(self, input_ids, seq_len: Optional[int] = None,
               segment_ids=None):
        c = self.config
        x = self.transformer(input_ids, seq_len, segment_ids=segment_ids)
        if self.lm_head is None:
            logits = ops.matmul(x, self.transformer.wte.weight, trans_b=True)
            logits = sharded(logits, P(c.dp_axis, c.cp_axis, c.tp_axis))
        else:
            logits = self.lm_head(x)
        return logits

    def forward(self, input_ids, labels=None,
                seq_len: Optional[int] = None, segment_ids=None):
        """``segment_ids``: [b, s] packed doc ids (-1 pad) — the
        reference's cu_seqlens varlen path (ops/Attention.h:286),
        Hydraulis packed training."""
        c = self.config
        if labels is not None and c.fused_lm_ce and c.num_experts == 0:
            x = self.transformer(input_ids, seq_len,
                                 segment_ids=segment_ids)
            w = self.lm_head.weight if self.lm_head is not None \
                else self.transformer.wte.weight
            return ops.fused_lm_cross_entropy(x, w, labels,
                                              ignore_index=-100)
        logits = self.logits(input_ids, seq_len, segment_ids=segment_ids)
        if labels is None:
            return logits
        loss = vocab_parallel_cross_entropy(
            logits, labels, dp_axis=c.dp_axis, tp_axis=c.tp_axis,
            seq_axis=c.cp_axis, ignore_index=-100)
        if c.num_experts > 0 and c.moe_aux_coef:
            for block in self.transformer.h:
                if isinstance(block.mlp, MoEMLP) and \
                        block.mlp.last_aux is not None:
                    loss = loss + c.moe_aux_coef * block.mlp.last_aux
        return loss


# Reference-compatible aliases
LLamaLMHeadModel = GPTLMHeadModel
LLamaModel = GPTModel
