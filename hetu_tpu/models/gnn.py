"""Graph neural networks: GCN + 1.5-D distributed GCN.

Counterpart of the reference's GNN workload
(``hetu/v1/python/hetu/gpu_ops/DistGCN_15d.py`` — DistGCN with 1.5-D
adjacency/feature partitioning (CAGNET scheme: nodes row-partitioned
over p/c groups, features broadcast within replication groups) and
``v1/examples/gnn``).

TPU-first design: two aggregation paths —
- **dense**: normalized adjacency [N, N] x features, row-sharded over the
  ``dp`` mesh axis (P("dp", None)); GSPMD inserts the feature allgather
  that DistGCN_15d's ``broad_func`` issues by hand — this IS the 1.5-D
  scheme with replication factor c = 1 (c > 1 maps to replicating the
  feature allgather over a second mesh axis).
- **sparse**: static edge lists + ``segment_sum`` (TPU-friendly: static
  shapes, no scatter of dynamic size).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import ops
from ..graph.ctor import XavierUniformInitializer, parallel_parameter
from ..nn import Module, ModuleList
from ..nn.parallel import sharded


def normalize_adjacency(adj: np.ndarray, add_self_loops: bool = True
                        ) -> np.ndarray:
    """Symmetric GCN normalization D^-1/2 (A + I) D^-1/2 (host-side
    preprocessing, like the reference's scipy pipeline)."""
    a = np.asarray(adj, np.float32)
    if add_self_loops:
        a = a + np.eye(a.shape[0], dtype=np.float32)
    d = a.sum(1)
    dinv = np.where(d > 0, 1.0 / np.sqrt(d), 0.0)
    return a * dinv[:, None] * dinv[None, :]


class GCNLayer(Module):
    """H' = act(A_hat H W): one dense-aggregation GCN layer.

    With ``dp_axis`` set, A_hat rows and H rows are sharded over dp and
    the H-allgather for the A_hat @ H product is GSPMD-inserted (the
    1.5-D broad_func exchange)."""

    def __init__(self, in_dim: int, out_dim: int,
                 activation: Optional[str] = "relu",
                 dp_axis: Optional[str] = None, name: str = "gcn"):
        super().__init__()
        self.activation = activation
        self.dp_axis = dp_axis
        self.weight = parallel_parameter(
            XavierUniformInitializer(), (in_dim, out_dim), pspec=P(),
            name=f"{name}.weight")

    def forward(self, adj, h):
        if self.dp_axis:
            adj = sharded(adj, P(self.dp_axis, None))
            h = sharded(h, P(self.dp_axis, None))
        # aggregate then transform (A (H W) == (A H) W; HW first keeps the
        # big [N, N] product at the smaller feature width)
        hw = ops.matmul(h, self.weight)
        out = ops.matmul(adj, hw)
        if self.dp_axis:
            out = sharded(out, P(self.dp_axis, None))
        if self.activation == "relu":
            out = ops.relu(out)
        elif self.activation == "tanh":
            out = ops.tanh(out)
        return out


class SparseGCNLayer(Module):
    """Edge-list aggregation: out[i] = sum_{j->i} w_ij h[j] W via
    segment_sum (static edge count)."""

    def __init__(self, in_dim: int, out_dim: int, num_nodes: int,
                 activation: Optional[str] = "relu", name: str = "sgcn"):
        super().__init__()
        self.num_nodes = num_nodes
        self.activation = activation
        self.weight = parallel_parameter(
            XavierUniformInitializer(), (in_dim, out_dim), pspec=P(),
            name=f"{name}.weight")

    def forward(self, h, src, dst, edge_weight):
        N = self.num_nodes
        act = self.activation

        def _impl(h, w, src, dst, ew):
            hw = h @ w
            msgs = hw[src] * ew[:, None]
            out = jax.ops.segment_sum(msgs, dst, num_segments=N)
            if act == "relu":
                out = jax.nn.relu(out)
            elif act == "tanh":
                out = jnp.tanh(out)
            return out

        return ops.functional._op(
            "sparse_gcn", _impl, [h, self.weight, src, dst, edge_weight])


class GCN(Module):
    """Multi-layer GCN node classifier (v1/examples/gnn shape)."""

    def __init__(self, in_dim: int, hidden_dim: int, num_classes: int,
                 num_layers: int = 2, dp_axis: Optional[str] = None):
        super().__init__()
        dims = [in_dim] + [hidden_dim] * (num_layers - 1) + [num_classes]
        self.layers = ModuleList([
            GCNLayer(dims[i], dims[i + 1],
                     activation="relu" if i < num_layers - 1 else None,
                     dp_axis=dp_axis, name=f"gcn.l{i}")
            for i in range(num_layers)])

    def forward(self, adj, x, labels=None, train_mask=None):
        h = x
        for layer in self.layers:
            h = layer(adj, h)
        if labels is None:
            return h
        if train_mask is not None:
            # masked CE: ignore_index -100 outside the training mask
            labels = ops.where(train_mask, labels,
                               ops.full(labels.shape, -100, "int32"))
        return ops.softmax_cross_entropy(h, labels, ignore_index=-100)


class DistGCN15D(GCN):
    """1.5-D distributed GCN (DistGCN_15dOp): nodes row-partitioned over
    the dp mesh axis; each layer's feature exchange rides GSPMD
    collectives instead of the reference's explicit MPI broadcast rounds
    (broad_func, DistGCN_15d.py:19)."""

    def __init__(self, in_dim: int, hidden_dim: int, num_classes: int,
                 num_layers: int = 2, dp_axis: str = "dp"):
        super().__init__(in_dim, hidden_dim, num_classes, num_layers,
                         dp_axis=dp_axis)
