"""CTR model family: WDL, DeepFM, DCN.

Capability counterparts of the reference's CTR examples
(``hetu/v1/examples/ctr/models/{wdl_criteo.py,wdl_adult.py,
deepfm_criteo.py,dcn_criteo.py}`` — Criteo-style recommenders trained
with PS/hybrid embedding backends).  Sparse features go through a
pluggable embedding module (dense :class:`hetu_tpu.nn.Embedding`, the
HET-style :class:`hetu_tpu.embedding.CachedEmbedding`, or host-PS pulled
rows); dense features feed the MLP towers directly.

All towers are plain matmul stacks — XLA fuses them onto the MXU.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .. import ops
from ..nn import Embedding, Linear, Module, ModuleList, Sequential, ReLU


class MLP(Module):
    def __init__(self, dims: Sequence[int], activate_last: bool = False,
                 name: str = "mlp"):
        super().__init__()
        layers = []
        for i in range(len(dims) - 1):
            layers.append(Linear(dims[i], dims[i + 1]))
            if i < len(dims) - 2 or activate_last:
                layers.append(ReLU())
        self.net = Sequential(*layers)

    def forward(self, x):
        return self.net(x)


class _CTRBase(Module):
    """Shared wiring: sparse field embeddings + dense features."""

    def __init__(self, num_sparse_fields: int, vocab_size: int,
                 embedding_dim: int, num_dense: int,
                 embedding: Optional[Module] = None):
        super().__init__()
        self.num_sparse_fields = num_sparse_fields
        self.embedding_dim = embedding_dim
        self.num_dense = num_dense
        # one shared table over all fields (ids are globally offset), the
        # reference's Criteo layout
        self.embedding = embedding if embedding is not None else \
            Embedding(vocab_size, embedding_dim)

    def embed(self, sparse_ids):
        """[B, F] ids -> [B, F, D] embeddings."""
        return self.embedding(sparse_ids)


class WDL(_CTRBase):
    """Wide & Deep (reference wdl_criteo.py): linear 'wide' part over
    sparse embeddings + dense, MLP 'deep' part."""

    def __init__(self, num_sparse_fields: int, vocab_size: int,
                 embedding_dim: int = 16, num_dense: int = 13,
                 hidden: Sequence[int] = (256, 256, 256),
                 embedding: Optional[Module] = None):
        super().__init__(num_sparse_fields, vocab_size, embedding_dim,
                         num_dense, embedding)
        flat = num_sparse_fields * embedding_dim
        self.wide = Linear(flat + num_dense, 1)
        self.deep = MLP([flat + num_dense, *hidden, 1])

    def forward(self, sparse_ids, dense):
        e = self.embed(sparse_ids)
        flat = ops.reshape(e, (e.shape[0], -1))
        x = ops.concat([flat, dense], axis=1)
        return self.wide(x) + self.deep(x)


class DeepFM(_CTRBase):
    """DeepFM (reference deepfm_criteo.py): first-order linear term +
    second-order FM interactions + deep MLP."""

    def __init__(self, num_sparse_fields: int, vocab_size: int,
                 embedding_dim: int = 16, num_dense: int = 13,
                 hidden: Sequence[int] = (256, 256),
                 embedding: Optional[Module] = None):
        super().__init__(num_sparse_fields, vocab_size, embedding_dim,
                         num_dense, embedding)
        # first-order term is a projection of the SAME embedding output
        # (not a second id-indexed table) so pluggable backends that remap
        # ids — e.g. CachedEmbedding slots — stay consistent
        self.first_order = Linear(num_sparse_fields * embedding_dim, 1,
                                  bias=False)
        flat = num_sparse_fields * embedding_dim
        self.deep = MLP([flat + num_dense, *hidden, 1])
        self.dense_linear = Linear(num_dense, 1)

    def forward(self, sparse_ids, dense):
        e = self.embed(sparse_ids)                       # [B, F, D]
        # first order
        first = self.first_order(ops.reshape(e, (e.shape[0], -1)))
        first = first + self.dense_linear(dense)
        # second order FM: 0.5 * ((sum e)^2 - sum e^2)
        s = ops.reduce_sum(e, axis=1)                    # [B, D]
        fm = 0.5 * ops.reduce_sum(s * s - ops.reduce_sum(e * e, axis=1),
                                  axis=1, keepdims=True)
        # deep
        flat = ops.reshape(e, (e.shape[0], -1))
        deep = self.deep(ops.concat([flat, dense], axis=1))
        return first + fm + deep


class CrossLayer(Module):
    """One DCN cross layer: x_{l+1} = x0 * (w^T x_l) + b + x_l."""

    def __init__(self, dim: int):
        super().__init__()
        from ..graph.ctor import ConstantInitializer, parameter
        self.w = Linear(dim, 1, bias=False)
        self.b = parameter(ConstantInitializer(0.0), (dim,), name="cross.b")

    def forward(self, x0, xl):
        return x0 * self.w(xl) + (self.b + xl)


class DCN(_CTRBase):
    """Deep & Cross Network (reference dcn_criteo.py): explicit
    feature-cross tower + deep tower, concatenated into the head."""

    def __init__(self, num_sparse_fields: int, vocab_size: int,
                 embedding_dim: int = 16, num_dense: int = 13,
                 num_cross: int = 3, hidden: Sequence[int] = (256, 256),
                 embedding: Optional[Module] = None):
        super().__init__(num_sparse_fields, vocab_size, embedding_dim,
                         num_dense, embedding)
        dim = num_sparse_fields * embedding_dim + num_dense
        self.crosses = ModuleList([CrossLayer(dim) for _ in range(num_cross)])
        self.deep = MLP([dim, *hidden], activate_last=True)
        self.head = Linear(dim + hidden[-1], 1)

    def forward(self, sparse_ids, dense):
        e = self.embed(sparse_ids)
        x0 = ops.concat([ops.reshape(e, (e.shape[0], -1)), dense], axis=1)
        xl = x0
        for cross in self.crosses:
            xl = cross(x0, xl)
        deep = self.deep(x0)
        return self.head(ops.concat([xl, deep], axis=1))


def ctr_loss(logits, labels):
    """Binary cross entropy with logits (the reference trains all CTR
    models with BCE, examples/ctr/run_hetu.py)."""
    return ops.binary_cross_entropy(ops.reshape(logits, (-1,)), labels,
                                    with_logits=True)
