from .safetensors_io import (save_model, load_model, save_split, load_split,
                             save_split_async, AsyncSaveHandle,
                             save_checkpoint, load_checkpoint,
                             WriterDeathError, arm_kill_mid_write,
                             disarm_kill_mid_write, restore_records)
from .converters import (hf_gpt2_to_ht, ht_to_hf_gpt2,
                         megatron_qkv_to_interleaved,
                         interleaved_qkv_to_megatron)
