"""Checkpoint format converters (HF GPT-2 <-> hetu_tpu, Megatron qkv order).

Capability parity with the reference's converters
(``python/hetu/utils/checkpoint/ht_safetensors.py:100`` qkv-ordering
converters, ``examples/gpt/gpt_hf_to_ht.py`` HF mapping): HF GPT-2 stores
linear weights as Conv1D ``[in, out]`` and fuses qkv per-head interleaved;
Megatron fuses qkv as ``[q_all; k_all; v_all]`` concatenation.  Our layers
are torch-style ``[out, in]`` with Megatron-style concatenated qkv.
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def megatron_qkv_to_interleaved(w: np.ndarray, num_heads: int,
                                num_kv_heads: int = None) -> np.ndarray:
    """[q_all; k_all; v_all] rows -> per-head interleaved [q0;k0;v0;q1;...]."""
    num_kv_heads = num_kv_heads or num_heads
    assert num_heads == num_kv_heads, "interleave needs MHA (q==kv heads)"
    out = w.shape[0]
    hd = out // (3 * num_heads)
    q, k, v = np.split(w, 3, axis=0)
    qh = q.reshape(num_heads, hd, *w.shape[1:])
    kh = k.reshape(num_heads, hd, *w.shape[1:])
    vh = v.reshape(num_heads, hd, *w.shape[1:])
    inter = np.stack([qh, kh, vh], axis=1)  # [nh, 3, hd, ...]
    return inter.reshape(out, *w.shape[1:])


def interleaved_qkv_to_megatron(w: np.ndarray, num_heads: int,
                                num_kv_heads: int = None) -> np.ndarray:
    """Inverse of :func:`megatron_qkv_to_interleaved`."""
    num_kv_heads = num_kv_heads or num_heads
    assert num_heads == num_kv_heads
    out = w.shape[0]
    hd = out // (3 * num_heads)
    inter = w.reshape(num_heads, 3, hd, *w.shape[1:])
    q = inter[:, 0].reshape(num_heads * hd, *w.shape[1:])
    k = inter[:, 1].reshape(num_heads * hd, *w.shape[1:])
    v = inter[:, 2].reshape(num_heads * hd, *w.shape[1:])
    return np.concatenate([q, k, v], axis=0)


def hf_gpt2_to_ht(hf_state: Dict[str, np.ndarray],
                  tie_embeddings: bool = True) -> Dict[str, np.ndarray]:
    """Map a HuggingFace GPT-2 state dict onto hetu_tpu GPT names.

    HF Conv1D weights ``[in, out]`` are transposed to ``[out, in]``;
    ``c_attn`` is already Megatron-ordered ``[q;k;v]`` in HF GPT-2.
    """
    out: Dict[str, np.ndarray] = {}

    def _t(a):
        return np.ascontiguousarray(np.asarray(a).T)

    for key, val in hf_state.items():
        k = key[len("transformer."):] if key.startswith("transformer.") \
            else key
        v = np.asarray(val)
        if k == "wte.weight":
            out["transformer.wte.weight"] = v
        elif k == "wpe.weight":
            out["transformer.wpe"] = v
        elif k in ("ln_f.weight", "ln_f.bias"):
            out[f"transformer.{k}"] = v
        elif k == "lm_head.weight":
            out["lm_head.weight"] = v
        elif k.startswith("h."):
            parts = k.split(".")
            i, rest = parts[1], ".".join(parts[2:])
            pre = f"transformer.h.{i}"
            m = {
                "ln_1.weight": f"{pre}.ln_1.weight",
                "ln_1.bias": f"{pre}.ln_1.bias",
                "ln_2.weight": f"{pre}.ln_2.weight",
                "ln_2.bias": f"{pre}.ln_2.bias",
                "attn.c_attn.weight": f"{pre}.attn.qkv.weight",
                "attn.c_attn.bias": f"{pre}.attn.qkv.bias",
                "attn.c_proj.weight": f"{pre}.attn.out.weight",
                "attn.c_proj.bias": f"{pre}.attn.out.bias",
                "mlp.c_fc.weight": f"{pre}.mlp.up.weight",
                "mlp.c_fc.bias": f"{pre}.mlp.up.bias",
                "mlp.c_proj.weight": f"{pre}.mlp.down.weight",
                "mlp.c_proj.bias": f"{pre}.mlp.down.bias",
            }
            if rest not in m:
                continue  # attn.bias causal-mask buffers etc.
            tgt = m[rest]
            if rest.endswith("weight") and ("c_attn" in rest or
                                            "c_proj" in rest or
                                            "c_fc" in rest):
                v = _t(v)  # Conv1D [in,out] -> [out,in]
            out[tgt] = v
    if tie_embeddings and "lm_head.weight" not in out \
            and "transformer.wte.weight" in out:
        out["lm_head.weight"] = out["transformer.wte.weight"]
    return out


def ht_to_hf_gpt2(ht_state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Inverse mapping: hetu_tpu GPT names -> HF GPT-2 names/layouts."""
    out: Dict[str, np.ndarray] = {}

    def _t(a):
        return np.ascontiguousarray(np.asarray(a).T)

    for key, v in ht_state.items():
        v = np.asarray(v)
        if key == "transformer.wte.weight":
            out["transformer.wte.weight"] = v
        elif key == "transformer.wpe":
            out["transformer.wpe.weight"] = v
        elif key in ("transformer.ln_f.weight", "transformer.ln_f.bias"):
            out[key] = v
        elif key == "lm_head.weight":
            out["lm_head.weight"] = v
        elif key.startswith("transformer.h."):
            parts = key.split(".")
            i, rest = parts[2], ".".join(parts[3:])
            pre = f"transformer.h.{i}"
            m = {
                "ln_1.weight": f"{pre}.ln_1.weight",
                "ln_1.bias": f"{pre}.ln_1.bias",
                "ln_2.weight": f"{pre}.ln_2.weight",
                "ln_2.bias": f"{pre}.ln_2.bias",
                "attn.qkv.weight": f"{pre}.attn.c_attn.weight",
                "attn.qkv.bias": f"{pre}.attn.c_attn.bias",
                "attn.out.weight": f"{pre}.attn.c_proj.weight",
                "attn.out.bias": f"{pre}.attn.c_proj.bias",
                "mlp.up.weight": f"{pre}.mlp.c_fc.weight",
                "mlp.up.bias": f"{pre}.mlp.c_fc.bias",
                "mlp.down.weight": f"{pre}.mlp.c_proj.weight",
                "mlp.down.bias": f"{pre}.mlp.c_proj.bias",
            }
            if rest not in m:
                continue
            if rest.endswith("weight") and rest.split(".")[0] in ("attn",
                                                                  "mlp"):
                v = _t(v)
            out[m[rest]] = v
    return out
