"""ds-aware safetensors checkpointing.

TPU-native re-expression of the reference's distributed checkpoint layer
(``python/hetu/utils/checkpoint/ht_safetensors.py``):

* ``save_model`` / ``load_model`` — whole-model safetensors with optional
  dtype transfer and 4-bit quantized save (reference ``:18-35,234``).
* ``save_split`` / ``load_split`` — sharded save where each shard file
  carries *slices* of the global tensors plus an ``index.json``; load
  reassembles and the framework reshards to the *current* parallel config
  (reference ``temp_save_split``/``temp_load_split`` ``:446,913``).  Where
  the reference walks DistributedStates to decide who owns which slice, we
  read ``jax.Array.addressable_shards`` — the sharding itself says it.
* ``save_checkpoint`` / ``load_checkpoint`` — model + optimizer states +
  step counter (RunLevel-based save in the reference, ``graph.h:267-270``).

bfloat16/float16 tensors are stored bit-exactly (uint16 view) with the real
dtype recorded in the header metadata, so files round-trip without ml_dtypes
support in safetensors.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from safetensors.numpy import save_file

from ...ops.quantization import (dequantize_4bit, quantize_4bit)

_VIEW_DTYPES = {"bfloat16": np.uint16, "float16": np.uint16}


# ---------------------------------------------------------------------------
# durability seams (resilience plane, DESIGN.md §19)
# ---------------------------------------------------------------------------

#: audit log of every checkpoint restore: the ``unverified-restore``
#: lint rule reads these records — a restore that reached tensor bytes
#: without a digest check against a generation manifest (``verified``
#: False, no ``verify_exempt``) fails CI.  Bounded: a long-lived
#: process (serving host, full pytest session) keeps only the newest
#: records rather than growing without limit.
from collections import deque as _deque
RESTORE_LOG = _deque(maxlen=4096)


def restore_records(prefix: Optional[str] = None) -> list:
    """Copies of the restore audit records, optionally filtered to
    directories under ``prefix``."""
    if prefix is None:
        return [dict(r) for r in RESTORE_LOG]
    p = os.path.abspath(prefix)
    # path-component match, not a raw string prefix: /tmp/run1 must not
    # claim /tmp/run10's records
    return [dict(r) for r in RESTORE_LOG
            if r["dir"] == p or r["dir"].startswith(p + os.sep)]


class WriterDeathError(RuntimeError):
    """Simulated checkpoint-writer death (the ``kill_mid_write`` chaos
    verdict): raised between shard files so the save never commits."""


# chaos hook consulted before every shard/index write; fault injection
# arms it, normal operation leaves it None
_WRITE_CHAOS: list = [None]


def arm_kill_mid_write(after_files: int = 1) -> None:
    """Arm the ``kill_mid_write`` chaos verdict: the NEXT split write
    dies (WriterDeathError) after ``after_files`` files have reached
    disk — a half-written checkpoint with no index and no manifest,
    exactly what a killed process leaves.  One-shot: disarms on fire."""
    box = [int(after_files)]

    def hook(fname: str) -> None:
        if box[0] <= 0:
            _WRITE_CHAOS[0] = None
            raise WriterDeathError(
                f"chaos kill_mid_write: writer died before {fname}")
        box[0] -= 1

    _WRITE_CHAOS[0] = hook


def disarm_kill_mid_write() -> None:
    _WRITE_CHAOS[0] = None


def _chaos_gate(fname: str) -> None:
    if _WRITE_CHAOS[0] is not None:
        _WRITE_CHAOS[0](fname)


def _prune_stale_shards(dirpath: str, keep) -> None:
    """Remove shard files a PREVIOUS save into this directory left
    behind (a re-save with fewer shards/processes): ``load_split``
    reads only ``index.json``, but stale ``model_*.safetensors`` files
    poison any consumer that globs the directory — and make the
    checksummed-generation manifest reject the save wholesale."""
    for fn in os.listdir(dirpath):
        if fn.startswith("model_") and fn.endswith(".safetensors") \
                and fn not in keep:
            try:
                os.remove(os.path.join(dirpath, fn))
            except OSError:
                pass


def _to_numpy(arr) -> np.ndarray:
    if isinstance(arr, np.ndarray):
        return arr
    return np.asarray(jax.device_get(arr))


def _encode(name: str, a: np.ndarray, meta: Dict[str, str]):
    """Return a safetensors-storable array, recording true dtype in meta."""
    dt = str(a.dtype)
    if dt in _VIEW_DTYPES:
        meta[f"{name}.dtype"] = dt
        return a.view(np.uint16)
    return a


def _decode(name: str, a: np.ndarray, meta: Dict[str, str]) -> np.ndarray:
    dt = meta.get(f"{name}.dtype")
    if dt is not None:
        import ml_dtypes
        np_dt = {"bfloat16": ml_dtypes.bfloat16,
                 "float16": np.float16}[dt]
        return a.view(np_dt)
    return a


# ---------------------------------------------------------------------------
# whole-model save/load
# ---------------------------------------------------------------------------

def save_model(model, path: str, dtype: Optional[str] = None,
               quantize: Optional[str] = None, blocksize: int = 64) -> None:
    """Save ``model.state_dict()`` to a single safetensors file.

    ``dtype`` casts on save (fp32->bf16 transfer save); ``quantize`` in
    {"fp4","nf4"} writes packed-4bit + per-block absmax sidecars.
    """
    state = model.state_dict() if hasattr(model, "state_dict") else dict(model)
    meta: Dict[str, str] = {"format": "hetu_tpu"}
    out: Dict[str, np.ndarray] = {}
    for name, arr in state.items():
        a = _to_numpy(arr)
        if dtype is not None and np.issubdtype(a.dtype, np.floating):
            import ml_dtypes
            a = a.astype({"bfloat16": ml_dtypes.bfloat16,
                          "float16": np.float16,
                          "float32": np.float32}[dtype])
        if quantize is not None and np.issubdtype(a.dtype, np.floating) \
                and a.ndim >= 2:
            packed, absmax = quantize_4bit(np.asarray(a, np.float32),
                                           quant_type=quantize,
                                           blocksize=blocksize)
            meta[f"{name}.quant"] = json.dumps(
                {"type": quantize, "blocksize": blocksize,
                 "shape": list(a.shape), "dtype": str(a.dtype)})
            out[name] = _to_numpy(packed)
            out[f"{name}.absmax"] = _to_numpy(absmax)
            continue
        out[name] = _encode(name, np.ascontiguousarray(a), meta)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    save_file(out, path, metadata=meta)


def _read_file(path: str) -> Dict[str, np.ndarray]:
    from safetensors import safe_open
    state: Dict[str, np.ndarray] = {}
    with safe_open(path, framework="np") as f:
        meta = f.metadata() or {}
        names = list(f.keys())
        for name in names:
            if name.endswith(".absmax"):
                continue
            a = f.get_tensor(name)
            q = meta.get(f"{name}.quant")
            if q is not None:
                info = json.loads(q)
                absmax = f.get_tensor(f"{name}.absmax")
                a = _to_numpy(dequantize_4bit(
                    a, absmax, tuple(info["shape"]),
                    quant_type=info["type"], blocksize=info["blocksize"]))
            else:
                a = _decode(name, a, meta)
            state[name] = a
    return state


def load_model(model, path: str, strict: bool = True):
    """Load a safetensors file into ``model`` — parameters are resharded
    to the model's *current* parallel config on assignment."""
    state = _read_file(path)
    return model.load_state_dict(state, strict=strict)


# ---------------------------------------------------------------------------
# sharded (split) save/load — the ds-aware path
# ---------------------------------------------------------------------------

def _addressable_slices(arr):
    """Deduplicated (index, data) pairs for a jax.Array's local shards;
    replicas collapse to one owner."""
    seen = set()
    for sh in arr.addressable_shards:
        key = tuple((s.start or 0, s.stop) for s in sh.index)
        if key in seen:
            continue
        seen.add(key)
        yield sh.index, np.asarray(sh.data)


def save_split(state: Dict[str, Any], dirpath: str,
               num_shards: Optional[int] = None,
               process_index: Optional[int] = None,
               num_processes: Optional[int] = None) -> None:
    """Sharded save of a name->array state dict.

    If values are sharded ``jax.Array``s, each process writes exactly its
    addressable slices (one file per process; multi-host safe).  Otherwise
    tensors are split along dim 0 into ``num_shards`` slice files.
    ``index.json`` records global shape/dtype and every slice's offsets.
    """
    os.makedirs(dirpath, exist_ok=True)
    pidx = jax.process_index() if process_index is None else process_index
    pcount = jax.process_count() if num_processes is None else num_processes

    snap = _snapshot_slices(state) if num_shards is None else None
    _write_split(state, snap, dirpath, pidx, pcount, num_shards)


def _snapshot_slices(state: Dict[str, Any]) -> Dict[str, Any]:
    """Device->host snapshot of every value's addressable slices.

    Runs synchronously so a subsequent training step cannot invalidate
    donated buffers under an async writer; jax.Arrays are immutable, but
    donation reuses their buffers."""
    snap: Dict[str, Any] = {}
    for name, arr in state.items():
        gshape = list(np.shape(arr))
        dtype = str(arr.dtype) if hasattr(arr, "dtype") \
            else str(np.asarray(arr).dtype)
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 0:
            slices = [(idx, np.asarray(data))
                      for idx, data in _addressable_slices(arr)]
        else:
            a = _to_numpy(arr)
            slices = [(tuple(slice(0, s) for s in a.shape), a)]
        snap[name] = (gshape, dtype, slices)
    return snap


def _write_split(state, snap, dirpath, pidx, pcount, num_shards,
                 barrier_fn=None) -> None:
    index: Dict[str, Any] = {"tensors": {}, "num_files": 0}
    files: Dict[str, Dict[str, np.ndarray]] = {}
    metas: Dict[str, Dict[str, str]] = {}

    def _file(i, n):
        return f"model_{i:05d}-of-{n:05d}.safetensors"

    if num_shards is None:
        fname = _file(pidx, pcount)
        files[fname] = {}
        metas[fname] = {}
        for name, (gshape, dtype, slices) in snap.items():
            ent = {"shape": gshape, "dtype": dtype, "slices": []}
            for k, (idx, data) in enumerate(slices):
                offs = [[s.start or 0, s.stop if s.stop is not None else dim]
                        for s, dim in zip(idx, gshape)]
                key = f"{name}@@{k}"
                files[fname][key] = _encode(
                    key, np.ascontiguousarray(data), metas[fname])
                ent["slices"].append({"file": fname, "key": key,
                                      "offsets": offs})
            index["tensors"][name] = ent
        index["num_files"] = pcount
    else:
        for i in range(num_shards):
            files[_file(i, num_shards)] = {}
            metas[_file(i, num_shards)] = {}
        for name, arr in state.items():
            a = _to_numpy(arr)
            ent = {"shape": list(a.shape), "dtype": str(a.dtype),
                   "slices": []}
            if a.ndim == 0 or a.shape[0] < num_shards:
                fname = _file(0, num_shards)
                key = f"{name}@@0"
                files[fname][key] = _encode(key, np.ascontiguousarray(a),
                                            metas[fname])
                ent["slices"].append(
                    {"file": fname, "key": key,
                     "offsets": [[0, d] for d in a.shape]})
            else:
                bounds = np.linspace(0, a.shape[0], num_shards + 1,
                                     dtype=np.int64)
                for i in range(num_shards):
                    lo, hi = int(bounds[i]), int(bounds[i + 1])
                    if lo == hi:
                        continue
                    fname = _file(i, num_shards)
                    key = f"{name}@@{i}"
                    piece = np.ascontiguousarray(a[lo:hi])
                    files[fname][key] = _encode(key, piece, metas[fname])
                    offs = [[lo, hi]] + [[0, d] for d in a.shape[1:]]
                    ent["slices"].append({"file": fname, "key": key,
                                          "offsets": offs})
            index["tensors"][name] = ent
        index["num_files"] = num_shards

    if num_shards is not None:
        # single-writer path: every process computes identical content, so
        # only process 0 touches the filesystem
        if pidx == 0:
            for fname, tensors in files.items():
                _chaos_gate(fname)
                save_file(tensors, os.path.join(dirpath, fname),
                          metadata={"format": "hetu_tpu_split",
                                    **metas[fname]})
            _chaos_gate("index.json")
            _atomic_json(os.path.join(dirpath, "index.json"), index)
            # a re-save with fewer shards must not leave the old save's
            # extra shard files for a directory consumer to mix in
            _prune_stale_shards(dirpath, set(files))
        return

    # per-process path: each process owns exactly its shard file + index
    for fname, tensors in files.items():
        _chaos_gate(fname)
        save_file(tensors, os.path.join(dirpath, fname),
                  metadata={"format": "hetu_tpu_split", **metas[fname]})
    _chaos_gate(f"index.{pidx}.json")
    _atomic_json(os.path.join(dirpath, f"index.{pidx}.json"), index)
    barrier = _barrier if barrier_fn is None else barrier_fn
    barrier()
    if pidx == 0:
        # drop stale per-process indices from a previous save with a
        # different process count, then merge exactly this save's set
        for fn in os.listdir(dirpath):
            if fn.startswith("index.") and fn.endswith(".json") \
                    and fn != "index.json":
                try:
                    i = int(fn.split(".")[1])
                except ValueError:
                    continue
                if i >= pcount:
                    os.remove(os.path.join(dirpath, fn))
        merged = _merge_indices(dirpath, pcount)
        # shard files no slice of the merged index references are a
        # previous save's leftovers — drop them with the stale indices
        referenced = {sl["file"] for ent in merged["tensors"].values()
                      for sl in ent["slices"]}
        _prune_stale_shards(dirpath, referenced)
    barrier()


class AsyncSaveHandle:
    """Handle for a background checkpoint write (reference
    ``temp_save_split``'s background archiving thread,
    ``ht_safetensors.py:446``)."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._errbox = errbox

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the write finishes; re-raise any writer error."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in progress")
        if self._errbox:
            raise self._errbox[0]


def save_split_async(state: Dict[str, Any], dirpath: str,
                     num_shards: Optional[int] = None,
                     process_index: Optional[int] = None,
                     num_processes: Optional[int] = None,
                     on_complete=None) -> AsyncSaveHandle:
    """:func:`save_split` with the file writing on a background thread.

    The device->host snapshot happens synchronously BEFORE returning
    (training may donate/reuse the parameter buffers on the very next
    step), so only serialization + disk IO overlap with compute — the
    same split the reference makes (write tensors, archive in
    background).  Call :meth:`AsyncSaveHandle.wait` before reading the
    checkpoint or exiting.  ``on_complete`` runs in the writer thread
    after a successful write (commit markers belong there, not before
    the data).

    Multi-process: the synchronous path's cross-process barrier is a
    device collective, which must NEVER run on a side thread (it would
    interleave with the main thread's training collectives in different
    orders on different hosts — deadlock).  Here the barrier routes
    through the registered host-level coordinator
    (:func:`hetu_tpu.parallel.comm.set_coordinator`); without one,
    multi-process background saves are refused loudly.
    """
    import threading

    os.makedirs(dirpath, exist_ok=True)
    pidx = jax.process_index() if process_index is None else process_index
    pcount = jax.process_count() if num_processes is None else num_processes
    if pcount > 1 and num_shards is None:
        from ...parallel import comm as _comm
        coord = _comm._COORDINATOR[0]
        if coord is None:
            raise RuntimeError(
                "background save with multiple processes needs a "
                "registered CoordinatorClient (comm.set_coordinator): "
                "the device-collective barrier cannot run on the writer "
                "thread")
        # checkpoint-sized timeout: a slow peer disk must not fail the
        # whole save (default coordinator barrier timeout is 60s)
        barrier_fn = lambda: _comm.barrier(  # noqa: E731 (host-level TCP)
            coordinator=coord, name=f"ckpt:{os.path.abspath(dirpath)}",
            timeout=1800.0)
    else:
        barrier_fn = lambda: None  # noqa: E731
    if num_shards is None:
        snap, host_state = _snapshot_slices(state), None
    else:
        snap = None
        host_state = {k: _to_numpy(v) for k, v in state.items()}
    errbox: list = []

    def _run():
        try:
            _write_split(host_state, snap, dirpath, pidx, pcount,
                         num_shards, barrier_fn=barrier_fn)
            if on_complete is not None:
                on_complete()
        except BaseException as e:  # surfaced by wait()
            errbox.append(e)

    t = threading.Thread(target=_run, name="hetu-ckpt-writer", daemon=True)
    t.start()
    return AsyncSaveHandle(t, errbox)


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _barrier() -> None:
    """Cross-process sync point for multi-host saves; no-op single-host."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("hetu_tpu_ckpt")


def _merge_indices(dirpath: str, pcount: int) -> Dict[str, Any]:
    merged: Dict[str, Any] = {"tensors": {}, "num_files": 0}
    for i in range(pcount):
        with open(os.path.join(dirpath, f"index.{i}.json")) as f:
            part = json.load(f)
        merged["num_files"] = max(merged["num_files"], part["num_files"])
        for name, ent in part["tensors"].items():
            if name not in merged["tensors"]:
                merged["tensors"][name] = {"shape": ent["shape"],
                                           "dtype": ent["dtype"],
                                           "slices": []}
            merged["tensors"][name]["slices"].extend(ent["slices"])
    _atomic_json(os.path.join(dirpath, "index.json"), merged)
    return merged


def load_split(dirpath: str, names: Optional[list] = None
               ) -> Dict[str, np.ndarray]:
    """Reassemble global tensors from a split checkpoint directory.

    Works regardless of the parallel config that *wrote* the checkpoint —
    this is the reshard-on-load capability of the reference's
    ``temp_load_split`` (ht_safetensors.py:913): the caller hands the
    result to ``Module.load_state_dict`` and each param lands with the
    current sharding.
    """
    with open(os.path.join(dirpath, "index.json")) as f:
        index = json.load(f)
    from safetensors import safe_open
    handles: Dict[str, Any] = {}
    file_meta: Dict[str, Dict[str, str]] = {}

    def _handle(fname):
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(dirpath, fname),
                                       framework="np")
            file_meta[fname] = handles[fname].metadata() or {}
        return handles[fname]

    out: Dict[str, np.ndarray] = {}
    try:
        for name, ent in index["tensors"].items():
            if names is not None and name not in names:
                continue
            import ml_dtypes
            np_dt = dict(bfloat16=ml_dtypes.bfloat16)\
                .get(ent["dtype"], None) or np.dtype(ent["dtype"])
            full = np.zeros(tuple(ent["shape"]), dtype=np_dt)
            for sl in ent["slices"]:
                h = _handle(sl["file"])
                piece = _decode(sl["key"], h.get_tensor(sl["key"]),
                                file_meta[sl["file"]])
                sel = tuple(slice(lo, hi) for lo, hi in sl["offsets"])
                full[sel] = piece.reshape(full[sel].shape)
            out[name] = full
    finally:
        handles.clear()
    return out


# ---------------------------------------------------------------------------
# full checkpoint (model + optimizer + step)
# ---------------------------------------------------------------------------

def _opt_state_items(optimizer, tid_to_name):
    # restored-but-ungrafted structured state supersedes whatever is in
    # _state (a load_checkpoint after training leaves the LOADED leaves
    # in _pending_tree_state while _state still holds pre-load values)
    pending = getattr(optimizer, "_pending_tree_state", None) or {}
    lay = getattr(optimizer, "_flat_layout", None)
    state = optimizer._state or {}
    if lay is not None and any(k.startswith("flat_") for k in state):
        # flat dp-sharded state (optim/flat_state.py): decompose the
        # per-bucket buffers through the param->(offset, length) index
        # so the checkpoint stays per-parameter keyed — it loads into
        # flat_state=True/False alike, at any dp size (the flat load
        # path repacks under the reader's geometry).  The fp32 master
        # copy rides as "opt.master.<name>"; per-param readers drop it
        # at first use (_ensure_state) so a stale copy can never
        # survive per-param training into a later flat restore.
        for key, val in state.items():
            if not key.startswith("flat_"):
                if isinstance(val, (list, tuple)):
                    # per-bucket replicated extras (Adafactor's factored
                    # row/col EMAs): leaves-by-index, regrafted through
                    # _pending_tree_state at the reader's next flat
                    # state rebuild (shape-matched, else reset)
                    for i, leaf in enumerate(val):
                        yield f"opt.{key}@@leaf{i:04d}", leaf, key, None
                else:
                    yield f"opt.{key}", val, key, None
                continue
            slot = key[len("flat_"):]
            # slice the LIVE buffers through the index (device-side) and
            # fetch one parameter at a time — never materializing every
            # flat buffer on the host at once the way an up-front
            # _to_numpy of master+m+v would
            per = lay.unpack(val)
            for tid, arr in per.items():
                name = tid_to_name.get(tid, str(tid))
                yield f"opt.{slot}.{name}", _to_numpy(arr), slot, tid
        return
    for key, tree in state.items():
        if key in pending:
            continue
        if isinstance(tree, dict):
            for tid, arr in tree.items():
                name = tid_to_name.get(tid, str(tid))
                yield f"opt.{key}.{name}", arr, key, tid
        elif hasattr(tree, "shape"):
            yield f"opt.{key}", tree, key, None
        else:
            # structured state (e.g. Adafactor's optax pytree): store the
            # array leaves in flattening order; the optimizer rebuilds the
            # structure from a fresh _init_state at restore
            leaves = jax.tree_util.tree_leaves(tree)
            for i, leaf in enumerate(leaves):
                yield f"opt.{key}@@leaf{i:04d}", leaf, key, None
    # load->save with no training step in between: restored structured
    # state still sits un-grafted in _pending_tree_state — pass it
    # through so a checkpoint copy/reshard can't silently drop it
    for slot, leaves in pending.items():
        for i, leaf in enumerate(leaves):
            yield f"opt.{slot}@@leaf{i:04d}", leaf, slot, None


def save_checkpoint(model, optimizer, dirpath: str, step: int = 0,
                    num_shards: Optional[int] = None,
                    extra: Optional[Dict[str, Any]] = None,
                    background: bool = False
                    ) -> Optional["AsyncSaveHandle"]:
    """Save model params + optimizer states + step to ``dirpath``.

    ``background=True`` snapshots device state synchronously, then
    writes files on a daemon thread and returns an
    :class:`AsyncSaveHandle` (reference temp_save_split background
    archiving); call ``.wait()`` before relying on the checkpoint."""
    os.makedirs(dirpath, exist_ok=True)
    tid_to_name = {p.id: n for n, p in model.named_parameters()}
    # params as live (possibly sharded) arrays so save_split can use shards
    state: Dict[str, Any] = {}
    for name, p in model.named_parameters():
        state[name] = p.graph.get_tensor_value(p)
    for name, b in model.named_buffers():
        state[name] = np.asarray(b)
    if optimizer is not None:
        for sname, arr, _k, _tid in _opt_state_items(optimizer, tid_to_name):
            state[sname] = arr if hasattr(arr, "shape") \
                else np.asarray(arr)
    marker = os.path.join(dirpath, "trainer_state.json")
    if jax.process_index() == 0 and os.path.exists(marker):
        # re-saving into an existing checkpoint dir: drop the stale
        # marker FIRST — otherwise a crash mid-write leaves a directory
        # whose marker claims the old step over mixed-step tensor files
        os.remove(marker)

    def _write_marker():
        # commit marker: written only AFTER the tensor data is on disk,
        # so a crash mid-write never leaves a directory that claims to
        # be a valid step-N checkpoint
        if jax.process_index() == 0:
            _atomic_json(marker, {"step": int(step), "extra": extra or {}})

    if background:
        return save_split_async(state, dirpath, num_shards=num_shards,
                                on_complete=_write_marker)
    save_split(state, dirpath, num_shards=num_shards)
    _write_marker()
    return None


def load_checkpoint(model, optimizer, dirpath: str,
                    verified: bool = False,
                    verify_exempt: bool = False) -> Dict[str, Any]:
    """Load a checkpoint saved by :func:`save_checkpoint`; reshards params
    and optimizer states to the current config.  Returns trainer state.

    Every call lands in :data:`RESTORE_LOG` for the
    ``unverified-restore`` lint rule: ``verified=True`` is stamped by
    the digest-checking generation loader
    (:func:`hetu_tpu.resilience.load_latest_generation`) — raw loads
    that deliberately skip verification must say so with
    ``verify_exempt=True`` or they fail CI."""
    state = load_split(dirpath)
    model_state = {k: v for k, v in state.items()
                   if not k.startswith("opt.")}
    model.load_state_dict(model_state, strict=False)
    if optimizer is not None:
        name_to_p = dict(model.named_parameters())
        new_state: Dict[str, Any] = {}
        pending_trees: Dict[str, Dict[int, Any]] = {}
        for key, val in state.items():
            if not key.startswith("opt."):
                continue
            rest = key[len("opt."):]
            if "@@leaf" in rest:
                slot, idx = rest.split("@@leaf", 1)
                pending_trees.setdefault(slot, {})[int(idx)] = \
                    jax.numpy.asarray(val)
                continue
            if "." in rest:
                slot, pname = rest.split(".", 1)
                p = name_to_p.get(pname)
                if p is None:
                    continue
                tree = new_state.setdefault(slot, {})
                arr = jax.numpy.asarray(val)
                g = p.graph
                sh = optimizer._state_sharding(p, arr, g) if g is not None \
                    else None
                if sh is not None:
                    arr = jax.device_put(arr, sh)
                    optimizer._shardings[p.id] = sh
                tree[p.id] = arr
            else:
                new_state[rest] = jax.numpy.asarray(val)
        if new_state:
            optimizer._state = new_state
        if pending_trees:
            # leaves-by-index, reassembled into the structure the
            # optimizer builds at its next _ensure_state
            optimizer._pending_tree_state = {
                slot: [leaves[i] for i in sorted(leaves)]
                for slot, leaves in pending_trees.items()}
    ts_path = os.path.join(dirpath, "trainer_state.json")
    ts = {"step": 0, "extra": {}}
    if os.path.exists(ts_path):
        with open(ts_path) as f:
            ts = json.load(f)
    RESTORE_LOG.append({"dir": os.path.abspath(dirpath),
                        "verified": bool(verified),
                        "verify_exempt": bool(verify_exempt),
                        "step": int(ts.get("step", 0))})
    return ts
