from . import checkpoint  # noqa: F401
from .logging_utils import TIK, TOK, Timer, get_logger, set_log_level
from .profiler import (MemoryProfiler, OpProfiler, StepProfiler,
                       device_memory_stats)

__all__ = [
    "checkpoint", "TIK", "TOK", "Timer", "get_logger", "set_log_level",
    "MemoryProfiler", "OpProfiler", "StepProfiler", "device_memory_stats",
]
