"""Graph/memory profiler subsystem.

Counterpart of the reference's profiling stack (SURVEY.md §5a-c):
- op-level ``TimeCost`` per micro-batch + impl-level op profiler
  (``hetu/graph/profiler.h:40``, ``hetu/impl/profiler/profiler.h:16-25``)
  -> :class:`OpProfiler` (eager replay timing each op) and
  :class:`StepProfiler` (whole-step wall times with warmup discard);
- subgraph fwd/bwd/update aggregation (``SubGraphProfiling``,
  ``graph.h:445``) -> :meth:`OpProfiler.by_group`;
- memory info (``CUDAProfiler::GetCurrMemoryInfo``, ``MicroBatchMemoryInfo``
  ``graph/profiler.h:20-47``) -> :func:`device_memory_stats` +
  :class:`MemoryProfiler` with the env-file protocol
  (``HETU_TPU_MEMORY_PROFILE`` / ``HETU_TPU_MEMORY_LOG_FILE``, mirroring
  the reference's ``HETU_MEMORY_PROFILE`` envs,
  ``executable_graph.cc:1738-1761``).

On TPU the per-op path uses eager replay (each op dispatched and
synchronized individually) — inside a jitted step XLA fuses ops, so
per-op attribution is only meaningful un-fused, exactly like the
reference's impl-level profiler which times raw kernel launches.
"""
from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

ENV_MEMORY_PROFILE = "HETU_TPU_MEMORY_PROFILE"
ENV_MEMORY_LOG_FILE = "HETU_TPU_MEMORY_LOG_FILE"


def device_memory_stats(device=None) -> Dict[str, int]:
    """Per-device memory counters (bytes).  On TPU backends this reads
    the allocator's live/peak stats (the analogue of the reference's
    mempool reserved/peak/allocated); platforms without stats (CPU sim)
    return zeros."""
    import jax
    d = device or jax.devices()[0]
    stats = None
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                "bytes_limit": 0}
    return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0))}


class OpProfiler:
    """Eager-replay op profiler: walks the graph topologically, running
    and synchronizing each op to attribute wall time per op / op type /
    name group (the reference's per-op TimeCost + SubGraph profile)."""

    def __init__(self, graph):
        self.graph = graph
        self.records: List[Dict[str, Any]] = []

    def profile(self, targets: Sequence, feed_dict: Dict,
                warmup: int = 1, iters: int = 3) -> List[Dict[str, Any]]:
        import jax
        g = self.graph
        targets = list(targets)
        env: Dict[int, Any] = {}
        for t, v in feed_dict.items():
            env[t.id] = np.asarray(v)
        topo = g._topo_from(targets)
        records = []
        for node in topo:
            if node.op_type == "placeholder":
                continue
            if node.op_type == "constant":
                env[node.outputs[0].id] = node.attrs["value"]
                continue
            if node.op_type == "variable":
                for out in node.outputs:
                    env[out.id] = g._materialize_var(out)
                continue
            if node.impl is None:
                continue  # structural nodes (update/gradients handled by run)
            in_vals = [env[inp.id] for inp in node.inputs if inp.id in env]
            if len(in_vals) != len(node.inputs):
                continue
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("_")}

            def run_once():
                out = node.impl(*in_vals, **attrs)
                jax.block_until_ready(out)
                return out

            out = run_once()
            for _ in range(warmup):
                run_once()
            t0 = time.perf_counter()
            for _ in range(iters):
                run_once()
            dt = (time.perf_counter() - t0) / iters
            for t, o in zip(node.outputs, jax.tree_util.tree_leaves(out)):
                env[t.id] = o
            records.append({
                "name": node.name or node.op_type,
                "op_type": node.op_type,
                "time": dt,
                "out_shapes": [tuple(t.shape) for t in node.outputs],
            })
        self.records = records
        return records

    # -- aggregations (reference SubGraph::profile) ------------------------

    def by_type(self) -> Dict[str, float]:
        agg: Dict[str, float] = defaultdict(float)
        for r in self.records:
            agg[r["op_type"]] += r["time"]
        return dict(sorted(agg.items(), key=lambda kv: -kv[1]))

    def by_group(self, depth: int = 1) -> Dict[str, float]:
        """Aggregate by name prefix (module path), e.g. 'blocks0' for
        'blocks0.attn.qkv'."""
        agg: Dict[str, float] = defaultdict(float)
        for r in self.records:
            parts = r["name"].split(".")
            agg[".".join(parts[:depth])] += r["time"]
        return dict(sorted(agg.items(), key=lambda kv: -kv[1]))

    def total(self) -> float:
        return sum(r["time"] for r in self.records)

    def summary(self, top: int = 10) -> str:
        lines = [f"{'op':<28}{'type':<22}{'ms':>8}"]
        for r in sorted(self.records, key=lambda r: -r["time"])[:top]:
            lines.append(f"{r['name'][:27]:<28}{r['op_type'][:21]:<22}"
                         f"{r['time'] * 1e3:>8.3f}")
        lines.append(f"total {self.total() * 1e3:.3f} ms over "
                     f"{len(self.records)} ops")
        return "\n".join(lines)


class StepProfiler:
    """Whole-step timing: wraps ``graph.run`` calls, discarding compile/
    warmup steps, reporting mean/p50/p90 (the e2e analogue of the
    reference's TIK/TOK + per-micro-batch TimeCost)."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times: List[float] = []
        self._count = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._count += 1
        if self._count > self.warmup:
            self.times.append(dt)

    def stats(self) -> Dict[str, float]:
        if not self.times:
            return {"mean": 0.0, "p50": 0.0, "p90": 0.0, "steps": 0}
        a = np.asarray(self.times)
        return {"mean": float(a.mean()), "p50": float(np.percentile(a, 50)),
                "p90": float(np.percentile(a, 90)), "steps": len(a)}


class MemoryProfiler:
    """Per-step memory snapshots appended to a JSONL log when enabled via
    env (reference: ``HETU_MEMORY_PROFILE=MICRO_BATCH`` +
    ``HETU_MEMORY_LOG_FILE``)."""

    def __init__(self, log_file: Optional[str] = None,
                 enabled: Optional[bool] = None):
        env_mode = os.environ.get(ENV_MEMORY_PROFILE, "")
        self.enabled = enabled if enabled is not None else bool(env_mode)
        self.log_file = log_file or os.environ.get(ENV_MEMORY_LOG_FILE)
        self.snapshots: List[Dict[str, Any]] = []

    def snapshot(self, tag: str, micro_batch_id: int = -1) -> Dict:
        if not self.enabled:
            return {}
        rec = {"tag": tag, "micro_batch_id": micro_batch_id,
               "ts": time.time(), **device_memory_stats()}
        self.snapshots.append(rec)
        if self.log_file:
            with open(self.log_file, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec

    def peak(self) -> int:
        return max((s["peak_bytes_in_use"] for s in self.snapshots),
                   default=0)
