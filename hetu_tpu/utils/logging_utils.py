"""Leveled logging + wall-clock timing macros.

Counterpart of the reference's ``hetu/common/logging.h`` (TRACE..FATAL
streams gated by ``HETU_INTERNAL_LOG_LEVEL``) and ``timing.h`` (TIK/TOK
wall timing).  Level env: ``HETU_TPU_LOG_LEVEL`` in
TRACE/DEBUG/INFO/WARN/ERROR/FATAL.
"""
from __future__ import annotations

import logging
import os
import sys
import time
from typing import Dict, Optional

ENV_LOG_LEVEL = "HETU_TPU_LOG_LEVEL"

_LEVELS = {"TRACE": 5, "DEBUG": logging.DEBUG, "INFO": logging.INFO,
           "WARN": logging.WARNING, "ERROR": logging.ERROR,
           "FATAL": logging.CRITICAL}

logging.addLevelName(5, "TRACE")

_loggers: Dict[str, logging.Logger] = {}


def get_logger(name: str = "hetu_tpu") -> logging.Logger:
    if name in _loggers:
        return _loggers[name]
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "[%(levelname)s %(asctime)s %(name)s] %(message)s",
            datefmt="%H:%M:%S"))
        logger.addHandler(h)
        logger.propagate = False
    level_name = os.environ.get(ENV_LOG_LEVEL, "WARN").upper()
    logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
    _loggers[name] = logger
    return logger


def set_log_level(level: str, name: str = "hetu_tpu") -> None:
    get_logger(name).setLevel(_LEVELS[level.upper()])


# -- TIK/TOK (reference hetu/common/timing.h) -------------------------------

_timers: Dict[str, float] = {}


def TIK(tag: str = "default") -> None:
    _timers[tag] = time.perf_counter()


def TOK(tag: str = "default", log: bool = False) -> float:
    """Seconds since the matching TIK; optionally logs at INFO."""
    if tag not in _timers:
        raise KeyError(f"TOK({tag!r}) without TIK")
    dt = time.perf_counter() - _timers[tag]
    if log:
        get_logger().info("%s: %.3f ms", tag, dt * 1e3)
    return dt


class Timer:
    """Context-manager timer: ``with Timer() as t: ...; t.seconds``."""

    def __init__(self, tag: str = ""):
        self.tag = tag
        self.seconds = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
