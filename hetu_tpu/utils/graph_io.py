"""Graph import/export.

Counterpart of the reference's ONNX interop (``hetu/v1/python/hetu/onnx/``
import/export).  Two formats:

- **JSON structure export** (always available): ops, tensors, shapes,
  attrs — enough for visualization, diffing, and re-importing the graph
  *structure* (impl lambdas are re-bound by op_type through the op
  registry).
- **ONNX export** (gated on the ``onnx`` package, which is not baked into
  every image): maps the common op subset to ONNX nodes.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

# op_type -> ONNX operator name for the subset we translate EXACTLY
# (elementwise ops are attr-free; matmul/linear get their trans flags
# lowered to Transpose nodes; reduce_* use opset-13 axes-as-input; gelu
# maps its `approximate` flag).  Ops with unhandled required attributes
# (conv/pool/slice/one_hot/batch_norm/...) are deliberately NOT listed —
# exporting them raises "ops without ONNX mapping" instead of silently
# emitting a model that computes something else.
_ONNX_OPS = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "neg": "Neg", "abs": "Abs", "exp": "Exp", "log": "Log",
    "sqrt": "Sqrt", "tanh": "Tanh", "sigmoid": "Sigmoid",
    "relu": "Relu", "gelu": "Gelu", "softmax": "Softmax",
    "log_softmax": "LogSoftmax",
    "matmul": "MatMul", "linear": "MatMul", "reshape": "Reshape",
    "transpose": "Transpose", "concat": "Concat",
    "reduce_sum": "ReduceSum", "reduce_mean": "ReduceMean",
    "reduce_max": "ReduceMax", "embedding_lookup": "Gather",
    "where": "Where", "pow": "Pow",
}


def _is_function(v: Any) -> bool:
    """True only for real function objects (impl lambdas, init_fns) — NOT
    for callable classes like jnp.float32, which are legitimate attr
    values (cast dtypes)."""
    import functools
    import types
    return isinstance(v, (types.FunctionType, types.MethodType,
                          types.BuiltinFunctionType, functools.partial))


def _jsonable(v: Any):
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if hasattr(v, "__array__") and hasattr(v, "dtype"):  # jax.Array etc.
        a = np.asarray(v)
        return {"__ndarray__": a.tolist(), "dtype": str(a.dtype)}
    return repr(v)


def export_graph_json(graph, targets=None, path: Optional[str] = None
                      ) -> Dict:
    """Serialize the graph structure (ops/tensors/shapes/attrs)."""
    nodes = graph._topo_from(list(targets)) if targets is not None \
        else list(graph.ops)
    out: Dict = {"format": "hetu_tpu.graph.v1", "ops": []}
    for node in nodes:
        out["ops"].append({
            "id": node.id,
            "op_type": node.op_type,
            "name": node.name,
            "inputs": [t.id for t in node.inputs],
            "outputs": [
                {"id": t.id, "name": t.name,
                 "shape": [int(d) for d in t.concrete_shape()],
                 # canonical short string ("float32"), importable by the
                 # dtype parser (str(DataType.X) is 'DataType.X')
                 "dtype": t.dtype.value if hasattr(t.dtype, "value")
                 else str(t.dtype)}
                for t in node.outputs],
            "attrs": {k: _jsonable(v) for k, v in node.attrs.items()
                      if not k.startswith("_") and not _is_function(v)},
            "onnx_op": _ONNX_OPS.get(node.op_type),
        })
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def graph_summary(graph, targets=None) -> str:
    """Human-readable op listing (netron-lite)."""
    spec = export_graph_json(graph, targets)
    lines = []
    for op in spec["ops"]:
        outs = ", ".join(f"{o['name']}:{o['shape']}" for o in op["outputs"])
        ins = ", ".join(str(i) for i in op["inputs"])
        lines.append(f"[{op['id']:>4}] {op['op_type']:<22} ({ins}) -> {outs}")
    return "\n".join(lines)


def _onnx_attrs(op_type: str, attrs: Dict) -> Dict:
    """Map our op attrs to the ONNX node's required attributes (the
    opset-13+ reduce ``axes`` input is handled separately)."""
    out: Dict = {}
    if op_type in ("concat", "stack", "softmax", "log_softmax"):
        out["axis"] = int(attrs.get("axis", -1))
    elif op_type == "transpose" and attrs.get("perm") is not None:
        out["perm"] = [int(p) for p in attrs["perm"]]
    elif op_type in ("reduce_sum", "reduce_mean", "reduce_max"):
        out["keepdims"] = int(bool(attrs.get("keepdims", False)))
    elif op_type == "gelu":
        out["approximate"] = "tanh" if attrs.get("approximate", True) \
            else "none"
    return out


def export_onnx(graph, targets, path: str):
    """Export the subset of the graph mappable to ONNX: placeholders
    become graph inputs, materialized variables become initializers,
    targets become graph outputs.  Requires the ``onnx`` package (not
    bundled in all images — install separately)."""
    try:
        import onnx
        from onnx import helper, numpy_helper
    except ImportError as e:
        raise ImportError(
            "ONNX export needs the `onnx` package; it is not installed in "
            "this environment. Use export_graph_json() for the native "
            "JSON graph format instead.") from e

    _NP2ONNX = {"float32": onnx.TensorProto.FLOAT,
                "float16": onnx.TensorProto.FLOAT16,
                "bfloat16": onnx.TensorProto.BFLOAT16,
                "int32": onnx.TensorProto.INT32,
                "int64": onnx.TensorProto.INT64,
                "bool": onnx.TensorProto.BOOL}

    def vi(t):
        dt = _NP2ONNX.get(str(np.dtype(t.dtype.to_jnp()))
                          if hasattr(t.dtype, "to_jnp") else str(t.dtype),
                          onnx.TensorProto.FLOAT)
        return helper.make_tensor_value_info(
            f"t{t.id}", dt, [int(d) for d in t.concrete_shape()])

    nodes = graph._topo_from(list(targets))
    onnx_nodes, inputs, initializers = [], [], []
    unmapped = []
    for node in nodes:
        if node.op_type == "placeholder":
            inputs.append(vi(node.outputs[0]))
            continue
        if node.op_type == "variable":
            t = node.outputs[0]
            arr = np.asarray(graph._materialize_var(t))
            initializers.append(
                numpy_helper.from_array(arr, name=f"t{t.id}"))
            continue
        if node.op_type == "constant":
            arr = np.asarray(node.attrs["value"])
            initializers.append(
                numpy_helper.from_array(arr,
                                        name=f"t{node.outputs[0].id}"))
            continue
        op_name = _ONNX_OPS.get(node.op_type)
        if op_name is None:
            unmapped.append(node.op_type)
            continue
        in_names = [f"t{t.id}" for t in node.inputs]
        out_name = f"t{node.outputs[0].id}"
        nname = node.name or f"op{node.id}"

        def transposed(name, tag, rank):
            tname = f"{name}_{tag}_T"
            perm = list(range(rank))
            perm[-1], perm[-2] = perm[-2], perm[-1]
            onnx_nodes.append(helper.make_node(
                "Transpose", [name], [tname], perm=perm,
                name=f"{nname}.{tag}_T"))
            return tname

        if node.op_type in ("matmul", "linear"):
            # lower trans flags to explicit (last-two-dims) Transpose
            # nodes; `linear` additionally adds the bias
            a, b = in_names[0], in_names[1]
            if node.attrs.get("trans_a"):
                a = transposed(a, "a", len(node.inputs[0].shape))
            if node.attrs.get("trans_b", node.op_type == "linear"):
                b = transposed(b, "b", len(node.inputs[1].shape))
            if node.op_type == "linear":
                mm = f"{out_name}_mm"
                onnx_nodes.append(helper.make_node(
                    "MatMul", [a, b], [mm], name=f"{nname}.mm"))
                onnx_nodes.append(helper.make_node(
                    "Add", [mm, in_names[2]], [out_name],
                    name=f"{nname}.bias"))
            else:
                onnx_nodes.append(helper.make_node(
                    "MatMul", [a, b], [out_name], name=nname))
            continue
        extra_inputs = []
        if node.op_type == "reshape":
            # ONNX Reshape takes the target shape as a tensor input
            shp = np.asarray([int(d) for d in
                              node.outputs[0].concrete_shape()], np.int64)
            sname = f"{out_name}_shape"
            initializers.append(numpy_helper.from_array(shp, name=sname))
            extra_inputs = [sname]
        elif node.op_type in ("reduce_sum", "reduce_mean", "reduce_max"):
            # opset 13+: axes is an input, not an attribute
            ax = node.attrs.get("axis")
            if ax is not None:
                axes = np.asarray(np.atleast_1d(ax), np.int64)
                aname = f"{out_name}_axes"
                initializers.append(
                    numpy_helper.from_array(axes, name=aname))
                extra_inputs = [aname]
        onnx_nodes.append(helper.make_node(
            op_name,
            inputs=in_names + extra_inputs,
            outputs=[f"t{t.id}" for t in node.outputs],
            name=nname,
            **_onnx_attrs(node.op_type, node.attrs)))
    if unmapped:
        raise ValueError(f"ops without ONNX mapping: {sorted(set(unmapped))}")
    outputs = [vi(t) for t in targets]
    g = helper.make_graph(onnx_nodes, "hetu_tpu", inputs, outputs,
                          initializer=initializers)
    model = helper.make_model(g)
    onnx.checker.check_model(model)
    onnx.save(model, path)
    return model


# ---------------------------------------------------------------------------
# import (counterpart of the reference's hetu/v1/python/hetu/onnx importers)
# ---------------------------------------------------------------------------

def _unjsonable(v: Any):
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=v["dtype"])
    if isinstance(v, list):
        return [_unjsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _unjsonable(x) for k, x in v.items()}
    return v


def import_graph_json(spec, graph=None):
    """Rebuild a graph from :func:`export_graph_json` output.

    Ops are re-bound by op_type through the public op surface
    (``hetu_tpu.ops.<op_type>``), placeholders/variables through the
    graph constructors; attrs become keyword arguments.  Returns
    ``(graph, tensors)`` where ``tensors`` maps exported tensor ids to
    the rebuilt Tensor objects (variables are created zero-initialized —
    load real values with the checkpoint machinery).

    Counterpart of the reference's ONNX importer
    (``hetu/v1/python/hetu/onnx/onnx_opset/``) for the native format.
    """
    import hetu_tpu as ht
    from .. import ops as ops_mod
    from ..graph.ctor import parameter

    if isinstance(spec, (str, bytes)):
        with open(spec) as f:
            spec = json.load(f)
    if spec.get("format") != "hetu_tpu.graph.v1":
        raise ValueError(f"not a hetu_tpu graph export: "
                         f"{spec.get('format')!r}")
    if graph is None:
        from ..graph.graph import get_default_graph
        graph = get_default_graph()

    tensors: Dict[int, Any] = {}
    for op in spec["ops"]:
        op_type = op["op_type"]
        outs = op["outputs"]
        attrs = _unjsonable(op.get("attrs", {}))
        if op_type == "placeholder":
            o = outs[0]
            tensors[o["id"]] = ht.placeholder(
                o["dtype"], tuple(o["shape"]), name=o["name"])
            continue
        if op_type == "variable":
            o = outs[0]
            t = parameter(np.zeros(o["shape"],
                                   np.dtype(o["dtype"])
                                   if o["dtype"] != "bfloat16"
                                   else np.float32),
                          shape=tuple(o["shape"]), dtype=o["dtype"],
                          name=o["name"])
            tensors[o["id"]] = t
            continue
        if op_type == "constant":
            o = outs[0]
            val = attrs.get("value", np.zeros(o["shape"]))
            tensors[o["id"]] = ops_mod.constant(
                np.asarray(val), dtype=o["dtype"], name=o["name"]) \
                if hasattr(ops_mod, "constant") else parameter(
                    np.asarray(val), shape=tuple(o["shape"]),
                    dtype=o["dtype"], name=o["name"])
            continue
        fn = getattr(ops_mod, op_type, None)
        if fn is None:
            raise ValueError(
                f"cannot re-bind op_type {op_type!r}: no public "
                f"hetu_tpu.ops function of that name")
        ins = [tensors[i] for i in op["inputs"]]
        try:
            result = fn(*ins, **attrs)
        except TypeError:
            # some attrs are derived (not ctor kwargs); strip only the
            # UNKNOWN kwargs — dropping all attrs would silently rebuild
            # a semantically different op
            import inspect
            try:
                sig = inspect.signature(fn)
                known = {k: v for k, v in attrs.items()
                         if k in sig.parameters}
            except (TypeError, ValueError):
                known = {}
            if known == attrs:
                raise
            result = fn(*ins, **known)
        rs = result if isinstance(result, (tuple, list)) else [result]
        for o, r in zip(outs, rs):
            tensors[o["id"]] = r
    return graph, tensors


_ONNX_TO_OP = {v: k for k, v in _ONNX_OPS.items() if v != "MatMul"}
_ONNX_TO_OP["MatMul"] = "matmul"


def import_onnx(path, graph=None):
    """Import an ONNX model (the op subset of ``_ONNX_OPS``):
    graph inputs -> placeholders, initializers -> variables (with their
    values), nodes -> ops.  Returns (graph, outputs) with ``outputs`` the
    list of target tensors.  Requires the ``onnx`` package.

    Counterpart of the reference's v1 ONNX import
    (``hetu/v1/python/hetu/onnx/``).
    """
    try:
        import onnx
        from onnx import numpy_helper
    except ImportError as e:
        raise ImportError(
            "ONNX import needs the `onnx` package; it is not installed "
            "in this environment. Use import_graph_json() for the native "
            "JSON graph format instead.") from e
    import hetu_tpu as ht
    from .. import ops as ops_mod
    from ..graph.ctor import parameter

    if graph is None:
        from ..graph.graph import get_default_graph
        graph = get_default_graph()
    model = onnx.load(path) if isinstance(path, (str, bytes)) else path
    g = model.graph
    tensors: Dict[str, Any] = {}
    for init in g.initializer:
        arr = numpy_helper.to_array(init)
        tensors[init.name] = parameter(arr, shape=arr.shape,
                                       dtype=str(arr.dtype),
                                       name=init.name)
    for vi_ in g.input:
        if vi_.name in tensors:
            continue
        shape = [d.dim_value for d in vi_.type.tensor_type.shape.dim]
        dt = onnx.helper.tensor_dtype_to_np_dtype(
            vi_.type.tensor_type.elem_type)
        tensors[vi_.name] = ht.placeholder(str(dt), tuple(shape),
                                           name=vi_.name)

    for node in g.node:
        op_type = _ONNX_TO_OP.get(node.op_type)
        attrs = {a.name: onnx.helper.get_attribute_value(a)
                 for a in node.attribute}
        ins = [tensors[n] for n in node.input if n in tensors]
        if node.op_type == "Transpose":
            out = ops_mod.transpose(ins[0], perm=list(attrs.get(
                "perm", range(len(ins[0].shape))[::-1])))
        elif node.op_type == "MatMul":
            out = ops_mod.matmul(ins[0], ins[1])
        elif node.op_type == "Reshape":
            # shape arrives as an initializer input; read its value
            shp_t = tensors[node.input[1]]
            shp = [int(x) for x in
                   np.asarray(graph._materialize_var(shp_t)).ravel()]
            out = ops_mod.reshape(ins[0], tuple(shp))
        elif node.op_type in ("ReduceSum", "ReduceMean", "ReduceMax"):
            kw = {"keepdims": bool(attrs.get("keepdims", 0))}
            if len(node.input) > 1 and node.input[1] in tensors:
                ax_t = tensors[node.input[1]]
                ax = [int(x) for x in
                      np.asarray(graph._materialize_var(ax_t)).ravel()]
                kw["axis"] = ax[0] if len(ax) == 1 else tuple(ax)
            out = getattr(ops_mod, op_type)(ins[0], **kw)
        elif op_type == "gelu":
            # ONNX spec default for Gelu.approximate is "none" (exact)
            approx = attrs.get("approximate", b"none")
            if isinstance(approx, bytes):
                approx = approx.decode()
            out = ops_mod.gelu(ins[0], approximate=approx != "none")
        elif op_type in ("softmax", "log_softmax", "concat"):
            out = getattr(ops_mod, op_type)(
                *ins, axis=int(attrs.get("axis", -1)))
        elif op_type == "embedding_lookup":
            out = ops_mod.embedding_lookup(ins[0], ins[1])
        elif op_type is not None and hasattr(ops_mod, op_type):
            out = getattr(ops_mod, op_type)(*ins)
        else:
            raise ValueError(f"unsupported ONNX op {node.op_type!r}")
        outs = out if isinstance(out, (tuple, list)) else [out]
        for name, t in zip(node.output, outs):
            tensors[name] = t
    outputs = [tensors[o.name] for o in g.output if o.name in tensors]
    return graph, outputs
