"""Graph import/export.

Counterpart of the reference's ONNX interop (``hetu/v1/python/hetu/onnx/``
import/export).  Two formats:

- **JSON structure export** (always available): ops, tensors, shapes,
  attrs — enough for visualization, diffing, and re-importing the graph
  *structure* (impl lambdas are re-bound by op_type through the op
  registry).
- **ONNX export** (gated on the ``onnx`` package, which is not baked into
  every image): maps the common op subset to ONNX nodes.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

# op_type -> ONNX operator name for the subset we translate EXACTLY
# (elementwise ops are attr-free; matmul/linear get their trans flags
# lowered to Transpose nodes; reduce_* use opset-13 axes-as-input; gelu
# maps its `approximate` flag).  Ops with unhandled required attributes
# (conv/pool/slice/one_hot/batch_norm/...) are deliberately NOT listed —
# exporting them raises "ops without ONNX mapping" instead of silently
# emitting a model that computes something else.
_ONNX_OPS = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "neg": "Neg", "abs": "Abs", "exp": "Exp", "log": "Log",
    "sqrt": "Sqrt", "tanh": "Tanh", "sigmoid": "Sigmoid",
    "relu": "Relu", "gelu": "Gelu", "softmax": "Softmax",
    "log_softmax": "LogSoftmax",
    "matmul": "MatMul", "linear": "MatMul", "reshape": "Reshape",
    "transpose": "Transpose", "concat": "Concat",
    "reduce_sum": "ReduceSum", "reduce_mean": "ReduceMean",
    "reduce_max": "ReduceMax", "embedding_lookup": "Gather",
    "where": "Where", "pow": "Pow",
}


def _is_function(v: Any) -> bool:
    """True only for real function objects (impl lambdas, init_fns) — NOT
    for callable classes like jnp.float32, which are legitimate attr
    values (cast dtypes)."""
    import functools
    import types
    return isinstance(v, (types.FunctionType, types.MethodType,
                          types.BuiltinFunctionType, functools.partial))


def _jsonable(v: Any):
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if hasattr(v, "__array__") and hasattr(v, "dtype"):  # jax.Array etc.
        a = np.asarray(v)
        return {"__ndarray__": a.tolist(), "dtype": str(a.dtype)}
    return repr(v)


def export_graph_json(graph, targets=None, path: Optional[str] = None
                      ) -> Dict:
    """Serialize the graph structure (ops/tensors/shapes/attrs)."""
    nodes = graph._topo_from(list(targets)) if targets is not None \
        else list(graph.ops)
    out: Dict = {"format": "hetu_tpu.graph.v1", "ops": []}
    for node in nodes:
        out["ops"].append({
            "id": node.id,
            "op_type": node.op_type,
            "name": node.name,
            "inputs": [t.id for t in node.inputs],
            "outputs": [
                {"id": t.id, "name": t.name,
                 "shape": [int(d) for d in t.concrete_shape()],
                 "dtype": str(t.dtype)}
                for t in node.outputs],
            "attrs": {k: _jsonable(v) for k, v in node.attrs.items()
                      if not k.startswith("_") and not _is_function(v)},
            "onnx_op": _ONNX_OPS.get(node.op_type),
        })
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def graph_summary(graph, targets=None) -> str:
    """Human-readable op listing (netron-lite)."""
    spec = export_graph_json(graph, targets)
    lines = []
    for op in spec["ops"]:
        outs = ", ".join(f"{o['name']}:{o['shape']}" for o in op["outputs"])
        ins = ", ".join(str(i) for i in op["inputs"])
        lines.append(f"[{op['id']:>4}] {op['op_type']:<22} ({ins}) -> {outs}")
    return "\n".join(lines)


def _onnx_attrs(op_type: str, attrs: Dict) -> Dict:
    """Map our op attrs to the ONNX node's required attributes (the
    opset-13+ reduce ``axes`` input is handled separately)."""
    out: Dict = {}
    if op_type in ("concat", "stack", "softmax", "log_softmax"):
        out["axis"] = int(attrs.get("axis", -1))
    elif op_type == "transpose" and attrs.get("perm") is not None:
        out["perm"] = [int(p) for p in attrs["perm"]]
    elif op_type in ("reduce_sum", "reduce_mean", "reduce_max"):
        out["keepdims"] = int(bool(attrs.get("keepdims", False)))
    elif op_type == "gelu":
        out["approximate"] = "tanh" if attrs.get("approximate", True) \
            else "none"
    return out


def export_onnx(graph, targets, path: str):
    """Export the subset of the graph mappable to ONNX: placeholders
    become graph inputs, materialized variables become initializers,
    targets become graph outputs.  Requires the ``onnx`` package (not
    bundled in all images — install separately)."""
    try:
        import onnx
        from onnx import helper, numpy_helper
    except ImportError as e:
        raise ImportError(
            "ONNX export needs the `onnx` package; it is not installed in "
            "this environment. Use export_graph_json() for the native "
            "JSON graph format instead.") from e

    _NP2ONNX = {"float32": onnx.TensorProto.FLOAT,
                "float16": onnx.TensorProto.FLOAT16,
                "bfloat16": onnx.TensorProto.BFLOAT16,
                "int32": onnx.TensorProto.INT32,
                "int64": onnx.TensorProto.INT64,
                "bool": onnx.TensorProto.BOOL}

    def vi(t):
        dt = _NP2ONNX.get(str(np.dtype(t.dtype.to_jnp()))
                          if hasattr(t.dtype, "to_jnp") else str(t.dtype),
                          onnx.TensorProto.FLOAT)
        return helper.make_tensor_value_info(
            f"t{t.id}", dt, [int(d) for d in t.concrete_shape()])

    nodes = graph._topo_from(list(targets))
    onnx_nodes, inputs, initializers = [], [], []
    unmapped = []
    for node in nodes:
        if node.op_type == "placeholder":
            inputs.append(vi(node.outputs[0]))
            continue
        if node.op_type == "variable":
            t = node.outputs[0]
            arr = np.asarray(graph._materialize_var(t))
            initializers.append(
                numpy_helper.from_array(arr, name=f"t{t.id}"))
            continue
        if node.op_type == "constant":
            arr = np.asarray(node.attrs["value"])
            initializers.append(
                numpy_helper.from_array(arr,
                                        name=f"t{node.outputs[0].id}"))
            continue
        op_name = _ONNX_OPS.get(node.op_type)
        if op_name is None:
            unmapped.append(node.op_type)
            continue
        in_names = [f"t{t.id}" for t in node.inputs]
        out_name = f"t{node.outputs[0].id}"
        nname = node.name or f"op{node.id}"

        def transposed(name, tag, rank):
            tname = f"{name}_{tag}_T"
            perm = list(range(rank))
            perm[-1], perm[-2] = perm[-2], perm[-1]
            onnx_nodes.append(helper.make_node(
                "Transpose", [name], [tname], perm=perm,
                name=f"{nname}.{tag}_T"))
            return tname

        if node.op_type in ("matmul", "linear"):
            # lower trans flags to explicit (last-two-dims) Transpose
            # nodes; `linear` additionally adds the bias
            a, b = in_names[0], in_names[1]
            if node.attrs.get("trans_a"):
                a = transposed(a, "a", len(node.inputs[0].shape))
            if node.attrs.get("trans_b", node.op_type == "linear"):
                b = transposed(b, "b", len(node.inputs[1].shape))
            if node.op_type == "linear":
                mm = f"{out_name}_mm"
                onnx_nodes.append(helper.make_node(
                    "MatMul", [a, b], [mm], name=f"{nname}.mm"))
                onnx_nodes.append(helper.make_node(
                    "Add", [mm, in_names[2]], [out_name],
                    name=f"{nname}.bias"))
            else:
                onnx_nodes.append(helper.make_node(
                    "MatMul", [a, b], [out_name], name=nname))
            continue
        extra_inputs = []
        if node.op_type == "reshape":
            # ONNX Reshape takes the target shape as a tensor input
            shp = np.asarray([int(d) for d in
                              node.outputs[0].concrete_shape()], np.int64)
            sname = f"{out_name}_shape"
            initializers.append(numpy_helper.from_array(shp, name=sname))
            extra_inputs = [sname]
        elif node.op_type in ("reduce_sum", "reduce_mean", "reduce_max"):
            # opset 13+: axes is an input, not an attribute
            ax = node.attrs.get("axis")
            if ax is not None:
                axes = np.asarray(np.atleast_1d(ax), np.int64)
                aname = f"{out_name}_axes"
                initializers.append(
                    numpy_helper.from_array(axes, name=aname))
                extra_inputs = [aname]
        onnx_nodes.append(helper.make_node(
            op_name,
            inputs=in_names + extra_inputs,
            outputs=[f"t{t.id}" for t in node.outputs],
            name=nname,
            **_onnx_attrs(node.op_type, node.attrs)))
    if unmapped:
        raise ValueError(f"ops without ONNX mapping: {sorted(set(unmapped))}")
    outputs = [vi(t) for t in targets]
    g = helper.make_graph(onnx_nodes, "hetu_tpu", inputs, outputs,
                          initializer=initializers)
    model = helper.make_model(g)
    onnx.checker.check_model(model)
    onnx.save(model, path)
    return model
