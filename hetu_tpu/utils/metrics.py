"""Training metrics recorder (v1 ``python/hetu/metrics.py`` capability).

Scalar time series with windowed smoothing, JSONL persistence, and a
CSV export — the observability layer between raw logging (TIK/TOK,
``logging_utils``) and external dashboards.  No TensorBoard/W&B
dependency (none is baked into the image); the JSONL stream is the
interchange format.

    rec = Metrics(log_file="run.jsonl")
    rec.log(step, loss=2.31, lr=3e-4, tokens_per_sec=1.1e5)
    rec.smoothed("loss")        # windowed mean
    rec.summary()               # per-key count/mean/min/max/last
    rec.to_csv("run.csv")
"""
from __future__ import annotations

import json
import os
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional


class Metrics:
    def __init__(self, log_file: Optional[str] = None, window: int = 20):
        self.window = int(window)
        self._series: Dict[str, List[tuple]] = defaultdict(list)
        self._recent: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=self.window))
        self._log_file = log_file
        self._fh = None
        if log_file:
            os.makedirs(os.path.dirname(os.path.abspath(log_file)),
                        exist_ok=True)
            self._fh = open(log_file, "a")

    # -- recording -----------------------------------------------------------

    def log(self, step: int, **values: Any) -> None:
        """Record scalar values at ``step`` (jax/np scalars accepted)."""
        clean = {}
        for k, v in values.items():
            v = float(v)
            self._series[k].append((int(step), v))
            self._recent[k].append(v)
            clean[k] = v
        if self._fh is not None:
            self._fh.write(json.dumps({"step": int(step), **clean}) + "\n")
            self._fh.flush()

    # -- reading -------------------------------------------------------------

    def last(self, key: str) -> Optional[float]:
        s = self._series.get(key)
        return s[-1][1] if s else None

    def smoothed(self, key: str) -> Optional[float]:
        """Mean over the most recent ``window`` values."""
        r = self._recent.get(key)
        return sum(r) / len(r) if r else None

    def series(self, key: str) -> List[tuple]:
        return list(self._series.get(key, ()))

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for k, s in self._series.items():
            vals = [v for _, v in s]
            out[k] = {"count": len(vals), "mean": sum(vals) / len(vals),
                      "min": min(vals), "max": max(vals), "last": vals[-1]}
        return out

    # -- export --------------------------------------------------------------

    def to_csv(self, path: str) -> None:
        """One row per step, one column per key (blank when missing)."""
        keys = sorted(self._series)
        by_step: Dict[int, Dict[str, float]] = defaultdict(dict)
        for k in keys:
            for step, v in self._series[k]:
                by_step[step][k] = v
        with open(path, "w") as f:
            f.write(",".join(["step"] + keys) + "\n")
            for step in sorted(by_step):
                row = [str(step)] + [
                    (f"{by_step[step][k]!r}" if k in by_step[step] else "")
                    for k in keys]
                f.write(",".join(row) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- serving instruments -----------------------------------------------------
#
# The Metrics recorder above is a step-keyed time series (training loops
# log once per step).  Serving needs instantaneous instruments instead:
# monotonically increasing counters (tokens out), point-in-time gauges
# (batch occupancy, page-pool utilization), and latency distributions
# (TTFT/TPOT percentiles).  All three share a no-op fallback so the
# engine's hot loop pays nothing when observability is disabled.


def percentile_of(xs_sorted, p: float) -> float:
    """Linear-interpolated percentile of an ascending-sorted sequence
    (numpy's default estimator; ``p`` in [0, 100]) — shared by
    :meth:`Histogram.percentile` and the trace-plane reconciliation so
    no consumer re-grows the old nearest-index tail bias."""
    if not xs_sorted:
        return 0.0
    rank = max(0.0, min(100.0, float(p))) / 100.0 * (len(xs_sorted) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(xs_sorted) - 1)
    return xs_sorted[lo] + (xs_sorted[hi] - xs_sorted[lo]) * (rank - lo)


class Counter:
    """Monotonically increasing count (tokens generated, preemptions)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Point-in-time value (queue depth, pool utilization)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Latency/size distribution with exact percentiles.

    Serving cares about tails over bounded windows (a few thousand
    requests), so observations are kept raw (capped deque) and
    percentiles computed exactly — no bucket-boundary error, no bucket
    schema to choose per deployment.

    Optional Prometheus-style export: pass ``buckets`` (sorted upper
    bounds) and :meth:`bucket_counts` returns cumulative
    ``{le: count}`` with an implicit ``+Inf`` bucket.  Observations
    above the last finite bound still count toward ``+Inf``, ``count``
    and ``total`` — dropping the overflow tail silently under-reports
    exactly the latencies a histogram exists to expose.
    """

    __slots__ = ("name", "_obs", "count", "total", "buckets",
                 "_bucket_counts")

    def __init__(self, name: str = "", max_observations: int = 4096,
                 buckets: Optional[List[float]] = None):
        self.name = name
        self._obs = deque(maxlen=int(max_observations))
        self.count = 0
        self.total = 0.0
        self.buckets = tuple(sorted(float(b) for b in buckets)) \
            if buckets else ()
        # per-bucket (non-cumulative) tallies; slot -1 is +Inf overflow
        self._bucket_counts = [0] * (len(self.buckets) + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        self._obs.append(v)
        self.count += 1
        self.total += v
        for i, bound in enumerate(self.buckets):
            if v <= bound:
                self._bucket_counts[i] += 1
                break
        else:
            # above every finite bound (or no buckets): +Inf slot, so
            # cumulative counts always sum to self.count
            self._bucket_counts[-1] += 1

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative Prometheus-style ``{le: count}`` incl. ``+Inf``."""
        out: Dict[str, int] = {}
        cum = 0
        for bound, c in zip(self.buckets, self._bucket_counts):
            cum += c
            out[repr(bound)] = cum
        out["+Inf"] = cum + self._bucket_counts[-1]
        return out

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Exact percentile over the retained window (p in [0, 100]),
        linearly interpolated between ranks (numpy's default).  The old
        nearest-index rounding biased small-window tails — p90 of
        [1..10] snapped to a sample instead of 9.1 — which made
        BENCH_SERVING TTFT/TBT tails jumpy run-to-run."""
        return percentile_of(sorted(self._obs), p)

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean,
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}


class _NullInstrument:
    """No-op stand-in for any instrument when metrics are disabled: every
    method swallows its arguments, every read returns zero."""

    name = ""
    value = 0.0
    count = 0
    total = 0.0
    mean = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def percentile(self, p: float) -> float:
        return 0.0

    def bucket_counts(self) -> Dict[str, int]:
        return {"+Inf": 0}

    def summary(self) -> Dict[str, float]:
        # zeroed, same keys as Histogram.summary: consumers indexing
        # e.g. ["p90"] must not crash when metrics are disabled
        return {"count": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0,
                "p99": 0.0}


NULL_INSTRUMENT = _NullInstrument()


def make_instrument(kind: str, name: str = "", enabled: bool = True,
                    **kwargs):
    """Factory with the disabled fallback: ``make_instrument("gauge",
    "occupancy", enabled=False)`` returns the shared no-op instrument.
    Extra kwargs flow to the instrument constructor (e.g.
    ``make_instrument("histogram", "ttft", buckets=[0.1, 1.0])`` for
    Prometheus-style bucketed latency histograms)."""
    if not enabled:
        return NULL_INSTRUMENT
    cls = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}.get(
        kind.lower())
    if cls is None:
        raise ValueError(f"unknown instrument kind {kind!r}")
    return cls(name, **kwargs)


def _prom_name(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"                  # exposition-format spellings:
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(instruments) -> str:
    """Prometheus text exposition (v0.0.4) for a set of instruments.

    ``instruments``: a ``{name: instrument}`` dict (e.g. the engine's
    ``counters``/``gauges``/``histograms`` merged) or an iterable of
    instruments (named by their ``name`` attribute).  Counters and
    gauges render as-is; histograms render the standard
    ``_bucket``/``_sum``/``_count`` triple via :meth:`bucket_counts`
    (cumulative, ``+Inf`` included, so ``_bucket{le="+Inf"} == _count``
    by construction).  No-op instruments are skipped — disabled metrics
    expose nothing rather than fake zeros.
    """
    if isinstance(instruments, dict):
        items = list(instruments.items())
    else:
        items = [(getattr(inst, "name", "") or f"metric_{i}", inst)
                 for i, inst in enumerate(instruments)]
    lines: List[str] = []
    for name, inst in items:
        if isinstance(inst, _NullInstrument):
            continue
        name = _prom_name(name)
        if isinstance(inst, Counter):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {_prom_value(inst.value)}")
        elif isinstance(inst, Gauge):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_prom_value(inst.value)}")
        elif isinstance(inst, Histogram):
            lines.append(f"# TYPE {name} histogram")
            for le, c in inst.bucket_counts().items():
                # bounds keep the float form ("1.0", not "1") so the
                # series identity is stable as buckets are retuned
                le_txt = le if le == "+Inf" else repr(float(le))
                lines.append(f'{name}_bucket{{le="{le_txt}"}} {int(c)}')
            lines.append(f"{name}_sum {_prom_value(inst.total)}")
            lines.append(f"{name}_count {int(inst.count)}")
    return "\n".join(lines) + "\n" if lines else ""


def merge_prometheus_texts(texts: Dict[str, str],
                           label: str = "replica") -> str:
    """Merge several Prometheus expositions into one, tagging every
    sample with ``label="<key>"`` — the cluster's ``metrics_text()``
    merges per-replica ``Engine.metrics_text()`` outputs this way, so
    one scrape endpoint serves the whole replica fleet and dashboards
    slice by the ``replica`` label.

    Samples are regrouped per metric (one ``# TYPE`` line per metric
    name, first-seen kind wins, then every labeled sample), which keeps
    the output a valid exposition: Prometheus requires all samples of a
    metric to be contiguous under its single TYPE header."""
    import re
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(.*)$")
    kinds: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []
    for key, text in texts.items():
        tag = f'{_prom_name(label)}="{key}"'
        for line in (text or "").splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split()
                if len(parts) >= 4 and parts[1] == "TYPE":
                    kinds.setdefault(parts[2], parts[3])
                continue
            m = sample_re.match(line)
            if m is None:
                continue
            name, labels, value = m.groups()
            inner = (labels or "{}")[1:-1]
            labels = "{" + (f"{inner},{tag}" if inner else tag) + "}"
            # histogram series (_bucket/_sum/_count) group under the
            # base metric's TYPE header, like the scrape format expects
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[:-len(suffix)] in kinds:
                    base = name[:-len(suffix)]
                    break
            if base not in samples:
                samples[base] = []
                order.append(base)
            samples[base].append(f"{name}{labels} {value}")
    lines: List[str] = []
    for base in order:
        if base in kinds:
            lines.append(f"# TYPE {base} {kinds[base]}")
        lines.extend(samples[base])
    return "\n".join(lines) + "\n" if lines else ""


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read back a Metrics JSONL stream."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
