"""ds_parallel_config generators — the JSON parallel-layout IR.

Counterpart of the reference's config generators
(``examples/gpt/ds_parallel_config/generate_gpt_3d_config.py`` and
``generate_gpt_hetero_3d_config.py``): given (dp, tp, pp[, hetero
layout]) over an ordered chip list, emit the per-module JSON spec
(``split``/``dup``/``device_group_union``/``type``/``zero``) parsed by
:func:`hetu_tpu.nn.parallel.config2ds`.  Entries always use the union
form (one group per pipeline stage), which covers both the homogeneous
``device_group`` and heterogeneous ``device_group_union`` schemas of the
reference.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


def _entry(split: Dict[str, List[int]], dup: List[int],
           groups: List[List[int]], kind: str = "variable",
           zero: bool = False) -> Dict:
    e = {"split": split, "dup": dup, "device_group_union": groups,
         "type": kind}
    if kind == "variable":
        e["zero"] = zero
    return e


def generate_gpt_3d_config(num_layers: int, dp: int, tp: int, pp: int,
                           num_devices: Optional[int] = None,
                           zero: bool = True,
                           devices: Optional[Sequence[int]] = None) -> Dict:
    """Homogeneous 3-D (dp x tp x pp) layout for a GPT stack.

    Layers are split evenly into pp stages; each stage occupies dp*tp
    chips (dp-major, tp-minor — the reference's device ordering).
    """
    n = num_devices or dp * tp * pp
    assert dp * tp * pp == n, f"dp*tp*pp != num_devices ({dp}*{tp}*{pp} != {n})"
    devices = list(devices) if devices is not None else list(range(n))
    per_stage = dp * tp
    stage_groups = [devices[s * per_stage:(s + 1) * per_stage]
                    for s in range(pp)]
    layers_per_stage = (num_layers + pp - 1) // pp

    cfg: Dict = {
        "zero": zero,
        "devices": devices,
        "input": _entry({"0": [dp]}, [tp], [stage_groups[0]],
                        kind="placeholder"),
        "gpt": {
            "wte": _entry({"0": [tp]}, [dp], [stage_groups[0]], zero=zero),
            "wpe": _entry({}, [per_stage], [stage_groups[0]], zero=zero),
            "blocks": {},
            "layernorm_final": _entry({}, [per_stage], [stage_groups[-1]],
                                      zero=zero),
        },
        "lm_head": _entry({"1": [tp]}, [dp], [stage_groups[-1]], zero=zero),
        "label": _entry({"0": [dp]}, [tp], [stage_groups[-1]],
                        kind="placeholder"),
    }
    blocks = cfg["gpt"]["blocks"]
    for s in range(pp):
        lo = s * layers_per_stage
        hi = min(num_layers - 1, (s + 1) * layers_per_stage - 1)
        if lo > hi:
            continue
        g = [stage_groups[s]]
        blocks[f"blocks{lo}-{hi}"] = {
            "range": [lo, hi],
            "layernorm1": _entry({}, [per_stage], g, zero=zero),
            "attn": {
                "qkv": _entry({"1": [tp]}, [dp], g, zero=zero),
                "dense": _entry({"0": [tp]}, [dp], g, zero=zero),
            },
            "layernorm2": _entry({}, [per_stage], g, zero=zero),
            "mlp": {
                "dense_h_to_4h": _entry({"1": [tp]}, [dp], g, zero=zero),
                "dense_4h_to_h": _entry({"0": [tp]}, [dp], g, zero=zero),
            },
        }
    return cfg


def generate_gpt_hetero_3d_config(num_layers: int,
                                  stage_layouts: Sequence[Dict],
                                  zero: bool = True) -> Dict:
    """Heterogeneous layout (Malleus): per-pipeline-stage dicts
    ``{"dp": int, "tp": int, "devices": [ids], "layers": [lo, hi]}`` with
    possibly unequal shapes per stage (reference
    generate_gpt_hetero_3d_config.py; hetero_stages in
    examples/gpt/train_hetu.py:256-335)."""
    devices: List[int] = []
    for st in stage_layouts:
        assert st["dp"] * st["tp"] == len(st["devices"]), \
            f"stage {st}: dp*tp != len(devices)"
        devices.extend(st["devices"])
    first, last = stage_layouts[0], stage_layouts[-1]

    def single(st, key_split, kind="variable"):
        g = [list(st["devices"])]
        if key_split == "col":
            split, dup = {"1": [st["tp"]]}, [st["dp"]]
        elif key_split == "row":
            split, dup = {"0": [st["tp"]]}, [st["dp"]]
        elif key_split == "vocab":
            split, dup = {"0": [st["tp"]]}, [st["dp"]]
        else:
            split, dup = {}, [len(st["devices"])]
        return _entry(split, dup, g, kind=kind,
                      zero=zero if kind == "variable" else False)

    cfg: Dict = {
        "zero": zero,
        "hetero": True,
        "devices": devices,
        "input": single(first, None, kind="placeholder"),
        "gpt": {
            "wte": single(first, "vocab"),
            "wpe": single(first, None),
            "blocks": {},
            "layernorm_final": single(last, None),
        },
        "lm_head": single(last, "col"),
        "label": single(last, None, kind="placeholder"),
    }
    blocks = cfg["gpt"]["blocks"]
    for st in stage_layouts:
        lo, hi = st["layers"]
        blocks[f"blocks{lo}-{hi}"] = {
            "range": [lo, hi],
            "layernorm1": single(st, None),
            "attn": {"qkv": single(st, "col"),
                     "dense": single(st, "row")},
            "layernorm2": single(st, None),
            "mlp": {"dense_h_to_4h": single(st, "col"),
                    "dense_4h_to_h": single(st, "row")},
        }
    return cfg


def save_ds_config(cfg: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(cfg, f, indent=2)


def parse_layout(cfg: Dict):
    """Derive the (dp, tp, pp, zero) layout from a ds_parallel_config —
    the entry-path inverse of :func:`generate_gpt_3d_config` (reference
    reads the same fields in ``examples/gpt/train_hetu.py:256-335``).

    ``pp`` = number of distinct block device groups, in layer order
    (each stage's blocks share a DeviceGroupUnion).
    """
    first = cfg["input"]
    dp = first["split"].get("0", [1])[0]
    tp = first["dup"][0]
    seen: List[tuple] = []
    blocks = sorted(cfg["gpt"]["blocks"].items(),
                    key=lambda kv: kv[1].get("range", [0])[0])
    for _, block in blocks:
        grp = tuple(block["attn"]["qkv"]["device_group_union"][0])
        if grp not in seen:
            seen.append(grp)
    pp = max(1, len(seen))
    # "zero" is the reference-schema bool ds flag; planner-emitted configs
    # also carry "zero_stage" (0-3) — surface the strongest level found
    levels = [int(e.get("zero_stage", 1 if e.get("zero") else 0))
              for _, _, e in iter_block_entries(cfg)]
    zero = int(cfg.get("zero_stage", 1 if cfg.get("zero") else 0))
    zero = max([zero] + levels)
    return dp, tp, pp, zero


def parse_hetero_layout(cfg: Dict) -> List[Dict]:
    """Inverse of :func:`generate_gpt_hetero_3d_config`: recover the
    per-stage ``{"dp", "tp", "devices", "layers"}`` dicts from a hetero
    ds_parallel_config so the MPMD runtime can be built straight from the
    JSON (reference train_hetu.py:256-335 reads hetero configs the same
    way)."""
    stages: List[Dict] = []
    blocks = sorted(cfg["gpt"]["blocks"].items(),
                    key=lambda kv: kv[1].get("range", [0])[0])
    for _, block in blocks:
        qkv = block["attn"]["qkv"]
        devices = list(qkv["device_group_union"][0])
        tp = qkv["split"].get("1", [1])[0]
        dp = qkv["dup"][0]
        st = {"dp": dp, "tp": tp, "devices": devices,
              "layers": list(block["range"])}
        if stages and stages[-1]["devices"] == devices:
            stages[-1]["layers"][1] = st["layers"][1]
        else:
            stages.append(st)
    return stages


def iter_block_entries(cfg: Dict):
    """Yield (block_range, sub_name, entry) for every leaf block entry."""
    for bname, block in cfg["gpt"]["blocks"].items():
        for key, val in block.items():
            if key == "range":
                continue
            if "type" in val:
                yield block["range"], key, val
            else:
                for sub, leaf in val.items():
                    yield block["range"], f"{key}.{sub}", leaf
