"""Cluster coordination: rendezvous/KV/barrier/heartbeat service + launcher.

TPU-native re-expression of the reference's gRPC DeviceController control
plane (``hetu/impl/communication/protos/heturpc.proto:11-64``, Python
servers ``python/hetu/rpc/heturpc_polling_server.py``) and the
parallel-SSH launcher (``python/hetu/rpc/pssh_start.py``).
"""
from .coordinator import CoordinatorClient, CoordinatorServer
from .launcher import HostSpec, Launcher, load_hostfile

__all__ = ["CoordinatorServer", "CoordinatorClient", "Launcher", "HostSpec",
           "load_hostfile"]
