"""Multi-process launcher with heartbeat monitoring + restart policy.

TPU-native re-expression of the reference's parallel-SSH launcher
(``python/hetu/rpc/pssh_start.py:16``): read a YAML hostfile (addrs,
workers per host, ``max_restart_times``, ``heartbeat_interval``), start the
coordinator, spawn workers locally via subprocess or remotely via ssh, and
monitor heartbeats — restarting dead workers up to the restart budget
(failure detection; the reference kills the process group on worker
exceptions, ``examples/gpt/train_hetu.py:421-426``).

Hostfile format (mirrors ``examples/hydraulis/scripts/host_example.yaml``)::

    hosts:
      - addr: localhost
        initial_workers: 4
      - addr: 10.0.0.2
        initial_workers: 4
    max_restart_times: 2
    heartbeat_interval: 2.0
"""
from __future__ import annotations

import os
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .coordinator import CoordinatorServer

ENV_COORD = "HETU_TPU_COORDINATOR"
ENV_RANK = "HETU_TPU_WORKER_RANK"
ENV_NUM_WORKERS = "HETU_TPU_NUM_WORKERS"


@dataclass
class HostSpec:
    addr: str = "localhost"
    initial_workers: int = 1
    min_workers: int = 0
    max_workers: int = 8


def load_hostfile(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f)
    hosts = [HostSpec(**h) for h in cfg.get("hosts", [])]
    return {"hosts": hosts,
            "max_restart_times": int(cfg.get("max_restart_times", 0)),
            "heartbeat_interval": float(cfg.get("heartbeat_interval", 2.0))}


@dataclass
class _Worker:
    rank: int
    host: str
    proc: subprocess.Popen
    restarts: int = 0


class Launcher:
    """Spawn N workers running ``cmd`` and babysit them.

    ``cmd`` is a list (argv) executed with env vars ``HETU_TPU_COORDINATOR``
    (host:port of the coordinator), ``HETU_TPU_WORKER_RANK`` and
    ``HETU_TPU_NUM_WORKERS`` — the worker connects back via
    :class:`CoordinatorClient` and heartbeats.
    """

    def __init__(self, cmd: Sequence[str],
                 hosts: Optional[Sequence[HostSpec]] = None,
                 num_workers: Optional[int] = None,
                 max_restart_times: int = 0,
                 heartbeat_interval: float = 2.0,
                 heartbeat_ttl: float = 10.0,
                 env: Optional[Dict[str, str]] = None):
        if hosts is None:
            hosts = [HostSpec(addr="localhost",
                              initial_workers=num_workers or 1)]
        self.cmd = list(cmd)
        self.hosts = list(hosts)
        self.num_workers = sum(h.initial_workers for h in self.hosts)
        self.max_restart_times = max_restart_times
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_ttl = heartbeat_ttl
        self.extra_env = dict(env or {})
        self.server = CoordinatorServer(world_size=self.num_workers)
        self.workers: List[_Worker] = []
        self.events: List[Dict[str, Any]] = []   # monitor log (tests/obs)

    # -- spawning ------------------------------------------------------------

    def _worker_env(self, rank: int) -> Dict[str, str]:
        return {**self.extra_env,
                ENV_COORD: self.server.address,
                ENV_RANK: str(rank),
                ENV_NUM_WORKERS: str(self.num_workers)}

    def _spawn(self, rank: int, host: str) -> subprocess.Popen:
        wenv = self._worker_env(rank)
        if host in ("localhost", "127.0.0.1"):
            return subprocess.Popen(self.cmd, env={**os.environ, **wenv})
        # remote: ssh with env inlined (reference pssh path)
        env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in wenv.items())
        remote = f"{env_str} {' '.join(shlex.quote(c) for c in self.cmd)}"
        return subprocess.Popen(["ssh", "-o", "BatchMode=yes", host, remote])

    def start(self) -> "Launcher":
        self.server.start()
        rank = 0
        for h in self.hosts:
            for _ in range(h.initial_workers):
                self.workers.append(
                    _Worker(rank, h.addr, self._spawn(rank, h.addr)))
                rank += 1
        return self

    # -- monitoring (reference heartbeat monitor + max_restart_times) -------

    def monitor(self, poll: float = 0.5,
                timeout: Optional[float] = None) -> int:
        """Babysit until all workers exit (or timeout).  Dead processes are
        restarted while restart budget remains; returns the number of
        workers that completed cleanly."""
        t0 = time.time()
        done: Dict[int, int] = {}
        while len(done) < len(self.workers):
            for w in self.workers:
                if w.rank in done:
                    continue
                rc = w.proc.poll()
                if rc is None:
                    continue
                if rc == 0:
                    done[w.rank] = 0
                    continue
                if w.restarts < self.max_restart_times:
                    w.restarts += 1
                    self.events.append({"event": "restart", "rank": w.rank,
                                        "attempt": w.restarts, "rc": rc})
                    w.proc = self._spawn(w.rank, w.host)
                else:
                    done[w.rank] = rc
                    self.events.append({"event": "gave_up", "rank": w.rank,
                                        "rc": rc})
            # a hung worker (heartbeat-dead but process alive) must be
            # killed so the rc-based restart logic above engages
            dead = set(self.server.dead_ranks(ttl=self.heartbeat_ttl))
            for w in self.workers:
                if w.rank in dead and w.rank not in done \
                        and w.proc.poll() is None:
                    self.events.append({"event": "heartbeat_lost",
                                        "rank": w.rank})
                    w.proc.terminate()
            if timeout is not None and time.time() - t0 > timeout:
                self.terminate()
                raise TimeoutError("launcher monitor timed out")
            time.sleep(poll)
        return sum(1 for rc in done.values() if rc == 0)

    def terminate(self) -> None:
        for w in self.workers:
            if w.proc.poll() is None:
                w.proc.terminate()
        for w in self.workers:
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                w.proc.kill()

    def shutdown(self) -> None:
        self.terminate()
        self.server.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()


def worker_client():
    """Inside a launched worker: connect back to the coordinator using the
    env the launcher set (reference worker-side CommGroup_Init path)."""
    from .coordinator import CoordinatorClient
    addr = os.environ[ENV_COORD]
    rank = os.environ.get(ENV_RANK, "0")
    c = CoordinatorClient(addr, uid=f"worker-{rank}")
    c.connect()
    c.start_heartbeat_thread()
    return c
