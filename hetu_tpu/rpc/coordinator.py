"""Coordinator service: rendezvous, typed KV store, barrier, heartbeat.

TPU-native re-expression of the reference's ``DeviceController`` gRPC
service (``hetu/impl/communication/protos/heturpc.proto:11-64``):
Connect/GetRank, CommitHostName/GetHostName, CommitDeviceInfo/
GetDeviceInfo, Barrier, HeartBeat, Exit, and the typed KV store
(double/int/string/bytes/json).  The reference additionally exchanges
NCCL unique ids (CommitNcclId/GetNcclId); the TPU analogue is exchanging
the ``jax.distributed`` coordinator address + process ids, served by the
same KV surface (:meth:`CoordinatorClient.commit_jax_coordinator`).

Wire format is length-free JSON lines over TCP (stdlib-only, no proto
codegen); the service surface — not the encoding — is the parity target.
The server is the single central process of a multi-host run, exactly like
``heturpc_polling_server.py:17``; worker liveness is tracked by heartbeat
timestamps (``last_heartbeat`` in the reference server).
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _State:
    def __init__(self, world_size: Optional[int]):
        self.lock = threading.Condition()
        self.world_size = world_size
        self.ranks: Dict[str, int] = {}           # worker uid -> rank
        self.hostnames: Dict[int, str] = {}
        self.device_info: Dict[int, Any] = {}
        self.kv: Dict[str, Any] = {}
        self.barriers: Dict[str, set] = {}
        self.barrier_gen: Dict[str, int] = {}
        self.last_heartbeat: Dict[int, float] = {}
        self.exited: set = set()


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        self._conn_ranks: set = set()
        try:
            for line in self.rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line.decode())
                    # chaos seam: an installed fault injector may refuse
                    # any op BEFORE dispatch — a refused op proves
                    # nothing (no heartbeat refresh), exactly like a
                    # connection the real coordinator never accepted
                    inj = getattr(self.server, "fault_injector", None)
                    err = inj(req.get("op"), req) if inj else None
                    resp = {"ok": False, "error": err} if err \
                        else self._dispatch(st, req)
                except Exception as e:  # noqa: BLE001 — report, keep serving
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()
        finally:
            # connection died: pull this worker's pending barrier entries so
            # a crashed participant can't satisfy (or wedge) a barrier
            with st.lock:
                for group in st.barriers.values():
                    group.difference_update(self._conn_ranks)
                st.lock.notify_all()

    # -- ops ----------------------------------------------------------------

    def _dispatch(self, st: _State, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req["op"]
        with st.lock:
            # any authenticated request proves liveness: refresh the
            # heartbeat so long blocking calls (barrier) on the shared
            # client socket can't starve the heartbeat thread into a
            # false-dead verdict
            if "rank" in req and req["rank"] is not None:
                r = int(req["rank"])
                self._conn_ranks.add(r)
                if r in st.last_heartbeat:
                    st.last_heartbeat[r] = time.time()
            if op == "connect":          # Connect + GetRank
                uid = req["uid"]
                if uid not in st.ranks:
                    if st.world_size is not None \
                            and len(st.ranks) >= st.world_size:
                        # full world: recycle the rank of an exited worker
                        # (restart with a fresh uid); otherwise refuse a
                        # rank >= world_size that would corrupt barriers
                        recyclable = sorted(st.exited)
                        if not recyclable:
                            raise ValueError(
                                f"world is full ({st.world_size}) and no "
                                f"exited rank to recycle for uid {uid!r}")
                        rank = recyclable[0]
                        for old_uid, old_rank in list(st.ranks.items()):
                            if old_rank == rank:
                                del st.ranks[old_uid]
                        st.ranks[uid] = rank
                    else:
                        st.ranks[uid] = len(st.ranks)
                rank = st.ranks[uid]
                st.exited.discard(rank)   # a reconnect revives the rank
                self._conn_ranks.add(rank)
                st.hostnames[rank] = req.get("hostname", uid)
                st.last_heartbeat[rank] = time.time()
                st.lock.notify_all()
                return {"ok": True, "rank": rank,
                        "world_size": st.world_size}
            if op == "get_hostname":     # GetHostName(rank)
                r = int(req["rank"])
                return {"ok": True, "hostname": st.hostnames.get(r)}
            if op == "commit_device_info":
                st.device_info[int(req["rank"])] = req["info"]
                st.lock.notify_all()
                return {"ok": True}
            if op == "get_device_info":
                return {"ok": True,
                        "info": st.device_info.get(int(req["rank"]))}
            if op == "put":              # typed KV Commit*
                st.kv[req["key"]] = req["value"]
                st.lock.notify_all()
                return {"ok": True}
            if op == "get":              # typed KV Get* (optionally blocking)
                deadline = time.time() + float(req.get("timeout", 0.0))
                while req["key"] not in st.kv and time.time() < deadline:
                    st.lock.wait(timeout=min(0.1, deadline - time.time()))
                return {"ok": True, "value": st.kv.get(req["key"])}
            if op == "remove":
                st.kv.pop(req["key"], None)
                return {"ok": True}
            if op == "barrier":          # Barrier(name) over world_size
                name = req.get("name", "default")
                n = int(req.get("world_size") or st.world_size or 0)
                gen = st.barrier_gen.get(name, 0)
                group = st.barriers.setdefault(name, set())
                group.add(int(req["rank"]))
                if len(group) >= n:
                    st.barrier_gen[name] = gen + 1
                    st.barriers[name] = set()
                    st.lock.notify_all()
                    return {"ok": True}
                deadline = time.time() + float(req.get("timeout", 60.0))
                while st.barrier_gen.get(name, 0) == gen:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        group.discard(int(req["rank"]))
                        return {"ok": False, "error": "barrier timeout"}
                    st.lock.wait(timeout=min(0.1, remaining))
                    # waiting at a barrier is liveness too
                    st.last_heartbeat[int(req["rank"])] = time.time()
                return {"ok": True}
            if op == "heartbeat":        # HeartBeat(rank)
                st.last_heartbeat[int(req["rank"])] = time.time()
                return {"ok": True}
            if op == "alive":            # liveness snapshot (monitor use)
                ttl = float(req.get("ttl", 10.0))
                now = time.time()
                alive = [r for r, t in st.last_heartbeat.items()
                         if now - t <= ttl and r not in st.exited]
                dead = [r for r, t in st.last_heartbeat.items()
                        if now - t > ttl and r not in st.exited]
                return {"ok": True, "alive": sorted(alive),
                        "dead": sorted(dead)}
            if op == "exit":             # Exit(rank)
                st.exited.add(int(req["rank"]))
                st.lock.notify_all()
                return {"ok": True}
            if op == "num_connected":
                return {"ok": True, "n": len(st.ranks),
                        "n_exited": len(st.exited)}
            raise ValueError(f"unknown op {op!r}")


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class CoordinatorServer:
    """The central control-plane process (reference polling server).

    ``with CoordinatorServer(port=0) as srv: addr = srv.address`` — or call
    ``start()``/``stop()`` explicitly.  ``port=0`` picks a free port.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 world_size: Optional[int] = None, ttl: float = 10.0):
        # default liveness TTL for dead_ranks() — serving clusters run
        # much tighter failure-detection windows than training jobs, so
        # the server (and each client, see CoordinatorClient(ttl=))
        # carries its own default instead of one hard-coded 10 s
        self.ttl = float(ttl)
        self.state = _State(world_size)
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.state = self.state  # type: ignore[attr-defined]
        self._srv.fault_injector = None  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    def start(self) -> "CoordinatorServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- fault injection (chaos harness seam) --------------------------------

    def set_fault_injector(self, injector) -> None:
        """Install ``injector(op, req) -> Optional[str]``: a non-None
        return refuses the request with that error string, before
        dispatch (no liveness refresh).  ``None`` uninstalls."""
        self._srv.fault_injector = injector  # type: ignore[attr-defined]

    def refuse_for(self, seconds: float, ops: Optional[set] = None
                   ) -> None:
        """Refuse every op (or just ``ops``) for the next ``seconds``
        of wall time — the ``coord_refuse`` chaos event.  Clients see
        ``RuntimeError: coordinator error: refused (fault injection)``;
        their heartbeat threads must survive it by backing off and
        retrying (``start_heartbeat_thread``)."""
        until = time.time() + float(seconds)

        def injector(op, req):
            if time.time() >= until:
                self.set_fault_injector(None)   # window over: heal
                return None
            if ops is not None and op not in ops:
                return None
            return "refused (fault injection)"
        self.set_fault_injector(injector)

    # -- monitor-side helpers ------------------------------------------------

    def dead_ranks(self, ttl: Optional[float] = None) -> List[int]:
        ttl = self.ttl if ttl is None else float(ttl)
        now = time.time()
        with self.state.lock:
            return sorted(r for r, t in self.state.last_heartbeat.items()
                          if now - t > ttl and r not in self.state.exited)


class CoordinatorClient:
    """Worker-side client (reference C++ ``rpc_client.cc`` surface)."""

    def __init__(self, address: str, uid: Optional[str] = None,
                 hostname: Optional[str] = None,
                 connect_timeout: float = 30.0, ttl: float = 10.0):
        # per-client liveness TTL: alive() calls without an explicit ttl
        # use this, so a monitor tuned for fast failover (serving
        # router) and one tuned for slow links (multi-host training)
        # can share a coordinator without renegotiating every call
        self.ttl = float(ttl)
        host, port = address.rsplit(":", 1)
        deadline = time.time() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection((host, int(port)),
                                                      timeout=connect_timeout)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        # the connect timeout must NOT become the read timeout: a blocking
        # barrier/get longer than it would raise mid-readline and desync
        # the request/response stream
        self._sock.settimeout(None)
        self._f = self._sock.makefile("rwb")
        self._lock = threading.Lock()
        self.uid = uid or f"{socket.gethostname()}:{id(self)}"
        self.hostname = hostname or socket.gethostname()
        self.rank: Optional[int] = None
        self.world_size: Optional[int] = None

    def _call(self, **req) -> Dict[str, Any]:
        with self._lock:
            self._f.write((json.dumps(req) + "\n").encode())
            self._f.flush()
            line = self._f.readline()
        if not line:
            raise ConnectionError("coordinator closed connection")
        resp = json.loads(line.decode())
        if not resp.get("ok"):
            raise RuntimeError(f"coordinator error: {resp.get('error')}")
        return resp

    # -- rendezvous ---------------------------------------------------------

    def connect(self) -> int:
        r = self._call(op="connect", uid=self.uid, hostname=self.hostname)
        self.rank = r["rank"]
        self.world_size = r.get("world_size")
        return self.rank

    def get_hostname(self, rank: int) -> Optional[str]:
        return self._call(op="get_hostname", rank=rank)["hostname"]

    def commit_device_info(self, info: Any) -> None:
        self._call(op="commit_device_info", rank=self.rank, info=info)

    def get_device_info(self, rank: int) -> Any:
        return self._call(op="get_device_info", rank=rank)["info"]

    # -- KV (typed Commit*/Get* in the proto; JSON carries all types) -------

    def put(self, key: str, value: Any) -> None:
        self._call(op="put", key=key, value=value)

    def get(self, key: str, timeout: float = 0.0) -> Any:
        return self._call(op="get", key=key, timeout=timeout)["value"]

    def remove(self, key: str) -> None:
        self._call(op="remove", key=key)

    # -- barrier / heartbeat / exit -----------------------------------------

    def barrier(self, name: str = "default",
                world_size: Optional[int] = None,
                timeout: float = 60.0) -> None:
        self._call(op="barrier", name=name, rank=self.rank,
                   world_size=world_size, timeout=timeout)

    def heartbeat(self) -> None:
        self._call(op="heartbeat", rank=self.rank)

    def alive(self, ttl: Optional[float] = None
              ) -> Tuple[List[int], List[int]]:
        r = self._call(op="alive",
                       ttl=self.ttl if ttl is None else float(ttl))
        return r["alive"], r["dead"]

    def exit(self) -> None:
        self._call(op="exit", rank=self.rank)

    def close(self) -> None:
        try:
            self._f.close()
            self._sock.close()
        except OSError:
            pass

    # -- jax.distributed bootstrap (NCCL-id exchange analogue) --------------

    def commit_jax_coordinator(self, coordinator_address: str) -> None:
        """Rank 0 publishes the jax.distributed coordinator address
        (reference CommitNcclId)."""
        self.put("jax/coordinator", coordinator_address)

    def get_jax_coordinator(self, timeout: float = 60.0) -> str:
        addr = self.get("jax/coordinator", timeout=timeout)
        if addr is None:
            raise TimeoutError("jax coordinator address not published")
        return addr

    def start_heartbeat_thread(self, interval: float = 2.0
                               ) -> threading.Event:
        """Background heartbeat (the reference workers ping inside their
        poll loop).  Returns an Event; set it to stop.

        A refused heartbeat (coordinator fault window, transient server
        error) no longer kills the thread: it backs off with the capped
        exponential :class:`~hetu_tpu.fault.backoff.RetryPolicy` and
        keeps trying, so an outage shorter than the liveness TTL never
        turns into a false-dead verdict.  Only a dead transport (the
        socket itself gone) ends the loop — there is nothing left to
        retry onto."""
        from ..fault.backoff import RetryPolicy
        stop = threading.Event()
        policy = RetryPolicy(base=interval, cap=max(4 * interval, 0.5),
                             jitter=0.25)

        def loop():
            failures = 0
            while True:
                delay = interval if failures == 0 \
                    else policy.delay(failures - 1, key=self.rank or 0)
                if stop.wait(delay):
                    return
                try:
                    self.heartbeat()
                    failures = 0
                except (ConnectionError, OSError, ValueError):
                    return            # transport dead / socket closed
                except Exception:
                    failures += 1     # refused: back off, retry
        threading.Thread(target=loop, daemon=True).start()
        return stop


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def distributed_init(server_address: str, num_hosts: int,
                     local_device_count: Optional[int] = None,
                     uid: Optional[str] = None,
                     jax_coord_port: Optional[int] = None
                     ) -> CoordinatorClient:
    """Multi-host bootstrap (reference ``ht.init_comm_group``, SURVEY §3.1):
    rendezvous via the coordinator, then initialize ``jax.distributed`` with
    rank 0 as the jax coordinator.  Single-host callers get a connected
    client without touching jax.distributed."""
    client = CoordinatorClient(server_address, uid=uid)
    rank = client.connect()
    client.start_heartbeat_thread()
    if num_hosts > 1:
        import jax
        if rank == 0:
            host = socket.gethostname()
            port = jax_coord_port or _free_port()  # avoid cross-job clashes
            client.commit_jax_coordinator(f"{host}:{port}")
        coord = client.get_jax_coordinator()
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=num_hosts,
                                   process_id=rank,
                                   local_device_ids=None)
    client.barrier("init", world_size=num_hosts)
    # route host-level comm.barrier() through the coordinator from now on;
    # the server may have been started without world_size, so pin the one
    # we were given (plain comm.barrier() relies on it)
    if client.world_size is None:
        client.world_size = num_hosts
    from ..parallel.comm import set_coordinator
    set_coordinator(client)
    return client
