"""hetu_tpu — a TPU-native distributed deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of Hetu
(PKU DAIR Lab; reference mounted at /root/reference): define-and-run graphs
with a compiled-plan pool, DistributedStates sharding annotations lowered to
GSPMD, DP/TP/SP/PP/CP/EP parallelism, ZeRO, elastic hot-switching,
ds-aware safetensors checkpointing, and Hetu-style nn/optim Python APIs.
"""
from . import core
from .core import (DataType, uint8, int8, int16, int32, int64, float16,
                   float32, float64, bfloat16, bool_, float4, nfloat4,
                   Device, DeviceGroup, DeviceGroupUnion)
from . import parallel
from .parallel import (DistributedStates, DistributedStatesUnion,
                       DistributedStatesHierarchy, create_mesh)
from .graph import (Tensor, SymbolicDim, Graph, EagerGraph,
                    DefineAndRunGraph, DefineByRunGraph, RunLevel, graph, run_level,
                    get_default_graph, placeholder, parameter, variable,
                    parallel_placeholder, parallel_parameter)
from .graph.amp import autocast, GradScaler
from .graph.recompute import recompute, cpu_offload
from .graph.ctor import (ConstantInitializer, UniformInitializer,
                         NormalInitializer, TruncatedNormalInitializer,
                         XavierUniformInitializer, XavierNormalInitializer,
                         HeUniformInitializer, HeNormalInitializer,
                         ProvidedInitializer)
from . import ops
from .ops.functional import *  # noqa: F401,F403

from . import nn   # noqa: E402
from . import optim  # noqa: E402
from . import serving  # noqa: E402
from . import analysis  # noqa: E402
from . import obs  # noqa: E402
from . import resilience  # noqa: E402

__version__ = "0.1.0"


def gradients(loss, xs):
    """Reverse-mode autodiff entry (reference hetu.gradients -> Graph::Gradients)."""
    g = loss.graph or get_default_graph()
    return g.make_gradients(loss, list(xs))


def set_seed(seed: int) -> None:
    """Reset the parameter-init and dropout RNG streams (reference
    per-device seeded RNG state, ``hetu/impl/random/``).  Subsequent
    variable initializers draw keys derived from ``seed`` in creation
    order, and graphs built afterwards draw deterministic dropout seeds
    from a dedicated stream — so two models built after identical
    ``set_seed`` calls get identical weights AND identical dropout masks.
    numpy's process-global RNG is left untouched."""
    import numpy as _np
    import importlib
    from .graph import ctor
    # hetu_tpu.graph re-exports a `graph` context manager that shadows the
    # graph.py submodule — resolve the MODULE explicitly
    _graph_module = importlib.import_module("hetu_tpu.graph.graph")
    ctor._seed_counter[0] = int(seed)
    _graph_module._GRAPH_SEED_STREAM[0] = _np.random.RandomState(
        int(seed) & 0x7FFFFFFF)
