"""On-device numeric sentry: silent-failure detection fused into the
train step.

The failures that actually corrupt long LLM runs are *silent*: a
NaN/Inf gradient or a loss spike that poisons the optimizer state and
only shows up thousands of steps later as a diverged curve.  The sentry
closes that gap on-device:

* **verdict** — every UPDATE-level step computes a packed float32
  verdict vector (:data:`VERDICT_SLOTS` lanes, see the ``V_*`` indices)
  from signals the step already produces: the fp32 global gradient
  norm (the same sum-of-squares :meth:`Optimizer._grad_sq_norm` feeds
  the global-norm clip — XLA CSE makes the reuse literal), finiteness
  of the loss and of that norm (NaN/Inf propagate through the
  sum-of-squares, so ``isfinite(norm)`` IS the all-gradients finite
  check at zero extra reduction cost), and a relative loss-spike test
  against an on-device EMA of the clean-step loss.
* **skip** — an anomalous verdict selects the OLD params, optimizer
  state and step counter through ``jnp.where`` inside the same compiled
  program: a skipped step leaves bitwise-zero residue, so the loss
  curve of clean steps is bit-for-bit the anomaly-free run's.
  Scope note: the residue contract covers params / optimizer core
  state / step counter.  Under a dynamic AMP loss scaler the scaler's
  own overflow backoff still applies on a nonfinite step — that
  backoff IS the recovery mechanism for a too-high scale (freezing it
  would make every retry overflow identically), so with a scaler
  active the clean-step curve is bitwise vs a reference applying the
  same scale sequence, not vs a run that never saw the overflow.
* **zero host cost** — the verdict rides the existing step outputs
  (it lives in the optimizer-state pytree the step already returns,
  exactly like the AMP scaler state); no extra device->host fetch, no
  second executable, no recompile across clean/anomalous steps (the
  chaos injection code is a plain int32 feed).

The policy *ladder* on top (skip -> rewind to the last good checkpoint
generation) lives host-side in
:class:`hetu_tpu.elastic.FaultTolerantTrainer`; this module is the
on-device half plus the seeded injection seam the chaos plane
(``fault/``) drives: ``grad_nan`` / ``grad_spike`` / ``loss_spike``
verdicts multiply the already-computed gradients/loss by a poison
factor selected by the fed code, at the same point in the program where
a real silent corruption would surface.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: verdict vector layout (float32 lanes)
VERDICT_SLOTS = 7
(V_ANOMALY, V_LOSS_NONFINITE, V_GRAD_NONFINITE, V_GRAD_SPIKE,
 V_LOSS_SPIKE, V_CONSECUTIVE, V_GRAD_NORM) = range(VERDICT_SLOTS)

#: chaos injection codes (the int32 the graph auto-feeds each step;
#: 0 = clean).  Keyed by the FaultPlan event kinds the trainer injects.
INJECT_CODES: Dict[str, int] = {"grad_nan": 1, "grad_spike": 2,
                                "loss_spike": 3}


@dataclass(frozen=True)
class SentryConfig:
    """Thresholds of the numeric sentry (all checked on-device)."""
    #: global grad norm above this is a spike even when finite
    grad_norm_max: float = 1e4
    #: loss > factor * EMA(clean losses) is a spike (after warmup)
    loss_spike_factor: float = 8.0
    #: EMA decay of the clean-step loss
    loss_ema_decay: float = 0.9
    #: spike verdicts need this many clean steps of EMA history first
    warmup_steps: int = 2
    #: chaos seam: what grad_spike injection multiplies gradients by
    inject_grad_scale: float = 1e6
    #: chaos seam: what loss_spike injection multiplies the loss by
    inject_loss_scale: float = 64.0


class NumericSentry:
    """Runtime half of the sentry: persistent device-side state (loss
    EMA, consecutive-anomaly count, last verdict) plus the trace-time
    check/inject functions the graph executor fuses into the step.

    Lives on the :class:`~hetu_tpu.optim.optimizer.Optimizer`
    (``Optimizer(sentry=...)``) and rides the optimizer-state pytree
    through the jitted step exactly like the AMP scaler state: the
    graph adds ``opt_state["_sentry"]`` on the way in and stores the
    updated dict back here on commit — the verdict is a step OUTPUT,
    never a separate fetch.
    """

    def __init__(self, config: Optional[SentryConfig] = None):
        self.config = config or SentryConfig()
        self._state: Optional[Dict[str, Any]] = None
        # honesty counter: device->host reads of the verdict (the
        # trainer reads it once per step, alongside the loss fetch)
        self.host_reads = 0

    # -- persistent state (mirrors the scaler's init/store contract) ---------

    def init_state(self) -> Dict[str, Any]:
        if self._state is None:
            self._state = {
                "ema": jnp.zeros((), jnp.float32),
                "seen": jnp.zeros((), jnp.int32),
                "consecutive": jnp.zeros((), jnp.int32),
                "verdict": jnp.zeros((VERDICT_SLOTS,), jnp.float32),
            }
        return self._state

    def store_state(self, state: Dict[str, Any]) -> None:
        self._state = dict(state)

    def reset(self) -> None:
        """Forget EMA/consecutive history (called after a rewind: the
        restored state predates the anomaly streak)."""
        self._state = None

    def last_verdict(self) -> Optional[Dict[str, Any]]:
        """Decode the most recent step's verdict (one small host read,
        counted in :attr:`host_reads`); ``None`` before the first
        UPDATE step."""
        if self._state is None:
            return None
        self.host_reads += 1
        return decode_verdict(np.asarray(self._state["verdict"]))

    # -- trace-time: chaos injection seam ------------------------------------

    def inject_grads(self, grads, code):
        """Multiply every gradient leaf by the poison factor the fed
        ``code`` selects (1.0 when clean — a bitwise identity for the
        finite values a clean step carries)."""
        cfg = self.config
        factor = jnp.where(
            code == INJECT_CODES["grad_nan"], jnp.float32(jnp.nan),
            jnp.where(code == INJECT_CODES["grad_spike"],
                      jnp.float32(cfg.inject_grad_scale),
                      jnp.float32(1.0)))
        return jax.tree_util.tree_map(
            lambda g: g * factor.astype(g.dtype), grads)

    def inject_loss(self, loss, code):
        cfg = self.config
        factor = jnp.where(code == INJECT_CODES["loss_spike"],
                           jnp.float32(cfg.inject_loss_scale),
                           jnp.float32(1.0))
        return loss * factor.astype(loss.dtype)

    # -- trace-time: the verdict ---------------------------------------------

    def update(self, loss, grad_sq_norm, state):
        """Compute the step verdict and the updated sentry state.

        ``grad_sq_norm`` is the fp32 global sum of squared gradients
        (pre-clip) — nonfinite iff ANY gradient lane is nonfinite, so
        one scalar carries the whole finite check.  Returns
        ``(ok, new_state)``; ``ok`` is the bool the caller selects
        new-vs-old params/opt-state/step-counter with."""
        cfg = self.config
        loss32 = loss.astype(jnp.float32)
        gnorm = jnp.sqrt(grad_sq_norm.astype(jnp.float32))
        loss_fin = jnp.isfinite(loss32)
        grad_fin = jnp.isfinite(gnorm)
        grad_spike = jnp.logical_and(grad_fin,
                                     gnorm > cfg.grad_norm_max)
        warm = state["seen"] >= cfg.warmup_steps
        loss_spike = jnp.logical_and(
            jnp.logical_and(loss_fin, warm),
            loss32 > cfg.loss_spike_factor * state["ema"])
        anomaly = (~loss_fin) | (~grad_fin) | grad_spike | loss_spike
        ok = ~anomaly
        d = jnp.float32(cfg.loss_ema_decay)
        ema_next = jnp.where(state["seen"] > 0,
                             d * state["ema"] + (1.0 - d) * loss32,
                             loss32)
        consecutive = jnp.where(ok, 0, state["consecutive"] + 1)
        verdict = jnp.stack([
            anomaly.astype(jnp.float32),
            (~loss_fin).astype(jnp.float32),
            (~grad_fin).astype(jnp.float32),
            grad_spike.astype(jnp.float32),
            loss_spike.astype(jnp.float32),
            consecutive.astype(jnp.float32),
            gnorm,
        ])
        new_state = {
            "ema": jnp.where(ok, ema_next, state["ema"]),
            "seen": state["seen"] + jnp.where(ok, 1, 0),
            "consecutive": consecutive,
            "verdict": verdict,
        }
        return ok, new_state

    def meta(self) -> Dict[str, Any]:
        """Registration meta (graph plan meta ``sentry`` key): the
        thresholds the compiled verdict enforces, for the analysis
        plane."""
        cfg = self.config
        return {"grad_norm_max": cfg.grad_norm_max,
                "loss_spike_factor": cfg.loss_spike_factor,
                "warmup_steps": cfg.warmup_steps,
                "slots": VERDICT_SLOTS}


def decode_verdict(arr) -> Dict[str, Any]:
    """Unpack a verdict vector into named fields."""
    a = np.asarray(arr, np.float32)
    return {
        "anomaly": bool(a[V_ANOMALY]),
        "loss_nonfinite": bool(a[V_LOSS_NONFINITE]),
        "grad_nonfinite": bool(a[V_GRAD_NONFINITE]),
        "grad_spike": bool(a[V_GRAD_SPIKE]),
        "loss_spike": bool(a[V_LOSS_SPIKE]),
        "consecutive": int(a[V_CONSECUTIVE]),
        "grad_norm": float(a[V_GRAD_NORM]),
    }
