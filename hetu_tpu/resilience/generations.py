"""Durable checkpoint plane: checksummed generations with verified
restore.

``save_checkpoint`` alone leaves two silent-corruption holes the fault
plane (PR 12) cannot see: a bit-rotted or half-written shard restores
garbage without complaint, and a re-save into an existing directory can
leave stale shard files a later ``load_split`` happily mixes in.  This
module closes both:

* **generations** — every save lands in its own fresh
  ``gen-<step>/`` directory under the checkpoint root (no re-save can
  ever mix files from two saves), with retention of the last N
  *committed* generations.
* **manifest** — after the tensor data is on disk, a ``manifest.json``
  is committed atomically carrying a blake2b digest + byte size for
  EVERY file in the generation.  No manifest = not a checkpoint (a
  writer killed mid-write — the ``kill_mid_write`` chaos verdict —
  leaves a partial directory that verification rejects wholesale).
* **verified restore** — :func:`load_latest_generation` walks
  generations newest-first, re-digests every shard against the
  manifest (rejecting unmanifested stragglers too), and loads the
  newest generation that verifies — falling back past corrupted ones
  (the ``shard_corrupt`` chaos verdict) with a ``fallbacks`` record the
  trainer surfaces as the ``restore_fallbacks`` counter.

The digest check is the ``unverified-restore`` lint rule's contract:
every restore that reaches tensor bytes must either go through
:func:`load_latest_generation` (recorded ``verified``) or be explicitly
flagged ``verify_exempt`` (see ``analysis/rules.py``).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ..utils.checkpoint.safetensors_io import (_atomic_json,
                                               load_checkpoint,
                                               save_checkpoint)

MANIFEST = "manifest.json"
_GEN_RE = re.compile(r"^gen-(\d+)$")


def generation_dir(root: str, step: int) -> str:
    return os.path.join(root, f"gen-{int(step)}")


def list_generations(root: str) -> List[int]:
    """Steps of every generation directory under ``root`` (committed or
    not), ascending."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for name in names:
        m = _GEN_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            out.append(int(m.group(1)))
    return sorted(out)


def _digest_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def write_manifest(gen_dir: str, step: int,
                   emergency: bool = False) -> Dict[str, Any]:
    """Digest every file in ``gen_dir`` and commit the manifest
    atomically — the LAST write, so a crash at any earlier point leaves
    a directory that simply is not a checkpoint."""
    shards: Dict[str, Dict[str, Any]] = {}
    for fn in sorted(os.listdir(gen_dir)):
        if fn == MANIFEST or fn.endswith(".tmp"):
            continue
        p = os.path.join(gen_dir, fn)
        if not os.path.isfile(p):
            continue
        shards[fn] = {"blake2b": _digest_file(p),
                      "bytes": os.path.getsize(p)}
    manifest = {"step": int(step), "emergency": bool(emergency),
                "shards": shards}
    _atomic_json(os.path.join(gen_dir, MANIFEST), manifest)
    return manifest


def verify_generation(gen_dir: str) -> Tuple[bool, List[str]]:
    """Re-digest a generation against its manifest.

    Rejects: a missing manifest (uncommitted / killed mid-write), a
    missing or size-changed or digest-mismatched shard (bit rot,
    truncation), and any unmanifested tensor file (a stale straggler
    from another save that a naive loader would mix in)."""
    mpath = os.path.join(gen_dir, MANIFEST)
    if not os.path.isfile(mpath):
        return False, ["no manifest (uncommitted or partial write)"]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f"unreadable manifest: {e}"]
    problems: List[str] = []
    shards = manifest.get("shards", {})
    for fn, ent in shards.items():
        p = os.path.join(gen_dir, fn)
        if not os.path.isfile(p):
            problems.append(f"missing shard {fn}")
            continue
        size = os.path.getsize(p)
        if size != int(ent.get("bytes", -1)):
            problems.append(f"shard {fn} is {size} B, manifest says "
                            f"{ent.get('bytes')} B")
            continue
        if _digest_file(p) != ent.get("blake2b"):
            problems.append(f"shard {fn} digest mismatch (bit rot or "
                            f"torn write)")
    for fn in sorted(os.listdir(gen_dir)):
        if fn == MANIFEST or fn.endswith(".tmp"):
            continue
        if os.path.isfile(os.path.join(gen_dir, fn)) \
                and fn not in shards:
            problems.append(f"unmanifested file {fn} (stale shard from "
                            f"another save?)")
    return (not problems), problems


def prune_generations(root: str, keep: int) -> List[int]:
    """Remove the oldest generations beyond the newest ``keep``
    COMMITTED ones (uncommitted partials older than the oldest keeper
    go too).  Returns the steps kept."""
    steps = list_generations(root)
    committed = [s for s in steps
                 if os.path.isfile(os.path.join(generation_dir(root, s),
                                                MANIFEST))]
    keepers = set(committed[-int(keep):]) if keep > 0 else set(committed)
    floor = min(keepers) if keepers else None
    for s in steps:
        if s in keepers or (floor is not None and s >= floor):
            continue
        shutil.rmtree(generation_dir(root, s), ignore_errors=True)
    return sorted(keepers)


def save_generation(model, optimizer, root: str, step: int,
                    keep: int = 2, extra: Optional[Dict[str, Any]] = None,
                    emergency: bool = False,
                    num_shards: Optional[int] = None) -> str:
    """Save one checkpoint generation: fresh ``gen-<step>/`` directory,
    tensor data via :func:`save_checkpoint`, then the digest manifest,
    then retention pruning.  A writer death mid-save (simulated by the
    ``kill_mid_write`` chaos hook) propagates BEFORE the manifest is
    written and before anything is pruned — previous generations stay
    intact and verified."""
    d = generation_dir(root, step)
    aside = None
    if os.path.isdir(d):
        # a rewind replay or an emergency flush can re-save a step that
        # already has a generation.  The save must be FRESH (never a
        # mix with the old files), but a committed generation must not
        # be destroyed before its replacement exists: rename it aside
        # (invisible to list_generations) and restore it if this save
        # dies mid-write — only a completed fresh save retires it.
        if os.path.isfile(os.path.join(d, MANIFEST)):
            aside = d + ".prev"
            shutil.rmtree(aside, ignore_errors=True)
            os.rename(d, aside)
        else:
            shutil.rmtree(d)
    os.makedirs(d, exist_ok=True)
    try:
        save_checkpoint(model, optimizer, d, step=int(step),
                        num_shards=num_shards, extra=extra)
        write_manifest(d, step=int(step), emergency=emergency)
    except BaseException:
        if aside is not None:
            shutil.rmtree(d, ignore_errors=True)
            os.rename(aside, d)
        raise
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)
    prune_generations(root, keep)
    return d


def load_latest_generation(model, optimizer, root: str,
                           steps: Optional[List[int]] = None
                           ) -> Dict[str, Any]:
    """Restore the newest generation that VERIFIES, falling back past
    corrupted/partial ones.

    ``steps`` restricts the candidate set (the trainer passes the
    generations it wrote this run, so a stale directory from an earlier
    process can never be restored by accident).  Returns
    ``{"step", "generation", "fallbacks", "dir", "extra"}``;
    ``fallbacks`` lists every newer generation that failed verification
    with its problems.  Raises ``RuntimeError`` when nothing verifies.
    """
    cands = sorted(steps) if steps is not None else list_generations(root)
    fallbacks: List[Dict[str, Any]] = []
    for s in reversed(cands):
        d = generation_dir(root, s)
        if not os.path.isdir(d):
            continue
        ok, problems = verify_generation(d)
        if not ok:
            fallbacks.append({"generation": int(s), "problems": problems})
            continue
        ts = load_checkpoint(model, optimizer, d, verified=True)
        return {"step": int(ts.get("step", s)), "generation": int(s),
                "fallbacks": fallbacks, "dir": d,
                "extra": ts.get("extra", {})}
    raise RuntimeError(
        f"no checkpoint generation under {root} verifies; "
        f"rejected: {fallbacks}")


def corrupt_generation(root: str, step: Optional[int] = None,
                       nbytes: int = 16, seed: int = 0) -> str:
    """Chaos seam for the ``shard_corrupt`` verdict: flip ``nbytes``
    seeded-deterministic bytes inside a tensor shard of the newest
    (or given) committed generation.  Returns the corrupted path."""
    import numpy as np
    steps = [s for s in list_generations(root)
             if os.path.isfile(os.path.join(generation_dir(root, s),
                                            MANIFEST))]
    if not steps:
        raise RuntimeError(f"no committed generation under {root}")
    s = int(step) if step is not None else steps[-1]
    d = generation_dir(root, s)
    shard = next((fn for fn in sorted(os.listdir(d))
                  if fn.endswith(".safetensors")), None)
    if shard is None:
        raise RuntimeError(f"generation {d} has no tensor shard")
    path = os.path.join(d, shard)
    size = os.path.getsize(path)
    rng = np.random.RandomState(seed)
    with open(path, "r+b") as f:
        for _ in range(int(nbytes)):
            off = int(rng.randint(0, max(1, size)))
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([(b[0] if b else 0) ^ 0xFF]))
    return path
