"""Resilience plane: silent-failure detection + durable checkpoints.

PR 12's fault plane survives *loud* failures (crashes, zombies, dropped
handoffs); this package covers the silent ones — the failures that
corrupt long LLM runs without raising anything:

* :mod:`.sentry` — an on-device numeric sentry fused into the train
  step: finite-check of loss + gradients and a grad-norm/loss-spike
  ladder, packed into one verdict vector riding the existing step
  outputs; anomalous steps skip the update with bitwise-zero residue
  (``lax.select``-style ``where`` over params/opt-state/step-counter).
* :mod:`.generations` — checksummed checkpoint *generations*
  (``gen-<step>/`` + blake2b manifest, atomic commit, retention) with
  verified restore that falls back past corrupted or half-written
  generations.

The policy ladder (skip -> rewind) and the chaos-plane integration
(``grad_nan`` / ``grad_spike`` / ``loss_spike`` / ``shard_corrupt`` /
``kill_mid_write`` FaultPlan verdicts) are driven end-to-end by
:class:`hetu_tpu.elastic.FaultTolerantTrainer`.  DESIGN.md §19.
"""
from .generations import (corrupt_generation, generation_dir,
                          list_generations, load_latest_generation,
                          prune_generations, save_generation,
                          verify_generation, write_manifest)
from .sentry import (INJECT_CODES, VERDICT_SLOTS, NumericSentry,
                     SentryConfig, decode_verdict)

__all__ = [
    "INJECT_CODES", "NumericSentry", "SentryConfig", "VERDICT_SLOTS",
    "corrupt_generation", "decode_verdict", "generation_dir",
    "list_generations", "load_latest_generation", "prune_generations",
    "save_generation", "verify_generation", "write_manifest",
]
