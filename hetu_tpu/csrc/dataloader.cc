// Native prefetching dataloader core.
//
// TPU-native counterpart of the reference's C++ batched prefetching loader
// (hetu/graph/data/dataloader.h:18 — background batch assembly with a
// worker queue, shuffle, drop_last, and dp-rank sharding via set_dp_rank,
// dataloader.h:116).  Host-side only: assembles contiguous batch buffers
// from fixed-stride sample rows on background threads so the accelerator
// step never waits on Python-side indexing.
//
// C ABI, loaded via ctypes (see hetu_tpu/csrc/build.py).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<uint8_t> buf;
  int32_t rows = 0;
};

struct Loader {
  const uint8_t* data = nullptr;
  int64_t num_samples = 0;
  int64_t row_bytes = 0;
  int32_t batch_size = 0;
  bool shuffle = false;
  bool drop_last = true;
  // dp sharding: this loader yields the dp_rank-th of dp_nrank disjoint
  // sample shards (reference Dataloader::set_dp_rank)
  int32_t dp_rank = 0;
  int32_t dp_nrank = 1;

  std::vector<int64_t> order;   // local (sharded) sample indices
  int64_t cursor = 0;           // next sample in `order`

  // prefetch machinery
  size_t queue_cap = 2;
  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::thread worker;
  std::atomic<bool> stop{false};
  bool epoch_done = false;

  int64_t num_batches() const {
    const int64_t n = static_cast<int64_t>(order.size());
    if (drop_last) return n / batch_size;
    return (n + batch_size - 1) / batch_size;
  }

  void build_order(uint64_t seed) {
    order.clear();
    for (int64_t i = dp_rank; i < num_samples; i += dp_nrank)
      order.push_back(i);
    if (shuffle) {
      std::mt19937_64 rng(seed);
      std::shuffle(order.begin(), order.end(), rng);
    }
    cursor = 0;
  }

  bool assemble(Batch& out) {
    const int64_t n = static_cast<int64_t>(order.size());
    if (cursor >= n) return false;
    int64_t take = std::min<int64_t>(batch_size, n - cursor);
    if (take < batch_size && drop_last) return false;
    out.rows = static_cast<int32_t>(take);
    out.buf.resize(static_cast<size_t>(batch_size) * row_bytes);
    for (int64_t r = 0; r < take; ++r) {
      std::memcpy(out.buf.data() + r * row_bytes,
                  data + order[cursor + r] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
    cursor += take;
    return true;
  }

  void run() {
    while (true) {
      Batch b;
      const bool ok = assemble(b);
      std::unique_lock<std::mutex> lk(mu);
      if (!ok) {
        epoch_done = true;
        cv_pop.notify_all();
        return;
      }
      cv_push.wait(lk, [&] {
        return stop.load() || queue.size() < queue_cap;
      });
      if (stop.load()) return;
      queue.push_back(std::move(b));
      cv_pop.notify_one();
    }
  }

  void start() {
    epoch_done = false;
    stop.store(false);
    worker = std::thread([this] { run(); });
  }

  void join() {
    stop.store(true);
    cv_push.notify_all();
    if (worker.joinable()) worker.join();
  }
};

}  // namespace

extern "C" {

void* hetu_loader_create(const void* data, int64_t num_samples,
                         int64_t row_bytes, int32_t batch_size,
                         int32_t queue_size, int32_t shuffle, uint64_t seed,
                         int32_t drop_last, int32_t dp_rank,
                         int32_t dp_nrank) {
  auto* l = new Loader();
  l->data = static_cast<const uint8_t*>(data);
  l->num_samples = num_samples;
  l->row_bytes = row_bytes;
  l->batch_size = batch_size;
  l->queue_cap = queue_size > 0 ? static_cast<size_t>(queue_size) : 2;
  l->shuffle = shuffle != 0;
  l->drop_last = drop_last != 0;
  l->dp_rank = dp_nrank > 1 ? dp_rank : 0;
  l->dp_nrank = dp_nrank > 1 ? dp_nrank : 1;
  l->build_order(seed);
  l->start();
  return l;
}

int64_t hetu_loader_num_batches(void* handle) {
  return static_cast<Loader*>(handle)->num_batches();
}

// Blocks until the next prefetched batch is ready and copies it into
// `out` (batch_size*row_bytes).  Returns the number of valid rows, or 0
// at epoch end.
int32_t hetu_loader_next(void* handle, void* out) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_pop.wait(lk, [&] { return !l->queue.empty() || l->epoch_done; });
  if (l->queue.empty()) return 0;
  Batch b = std::move(l->queue.front());
  l->queue.pop_front();
  l->cv_push.notify_one();
  lk.unlock();
  std::memcpy(out, b.buf.data(), b.buf.size());
  return b.rows;
}

// Restart an epoch (optionally reshuffled with a new seed).
void hetu_loader_reset(void* handle, uint64_t seed) {
  auto* l = static_cast<Loader*>(handle);
  l->join();
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->queue.clear();
  }
  l->build_order(seed);
  l->start();
}

void hetu_loader_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  l->join();
  delete l;
}

}  // extern "C"
