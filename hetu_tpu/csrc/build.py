"""Build + load the native planner core (ctypes, cached .so).

The reference ships its solver as a pybind11 extension
(``tools/Galvatron/csrc/dp_core.cpp``); here we compile a plain C-ABI
shared library with g++ at first use (cached by source mtime) and bind it
with ctypes — no pybind11 needed.  All callers must tolerate ``None``
(compiler missing) and fall back to the pure-Python implementations.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_LOCK = threading.Lock()
_CACHE: dict = {}


def _compile(name: str, sources) -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"lib{name}.so")
    srcs = [os.path.join(_SRC_DIR, s) for s in sources]
    if os.path.exists(so_path) and all(
            os.path.getmtime(so_path) >= os.path.getmtime(s) for s in srcs):
        return so_path
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", so_path, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return so_path


def load_native(name: str, sources) -> Optional[ctypes.CDLL]:
    """Compile-if-stale and dlopen ``lib<name>.so``; None on any failure."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        lib = None
        so = _compile(name, sources)
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                lib = None
        _CACHE[name] = lib
        return lib


def load_dataloader_core() -> Optional[ctypes.CDLL]:
    lib = load_native("hetu_dataloader", ["dataloader.cc"])
    if lib is not None and not getattr(lib, "_hetu_sigs_set", False):
        lib.hetu_loader_create.restype = ctypes.c_void_p
        lib.hetu_loader_create.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32]
        lib.hetu_loader_num_batches.restype = ctypes.c_int64
        lib.hetu_loader_num_batches.argtypes = [ctypes.c_void_p]
        lib.hetu_loader_next.restype = ctypes.c_int32
        lib.hetu_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.hetu_loader_reset.restype = None
        lib.hetu_loader_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.hetu_loader_destroy.restype = None
        lib.hetu_loader_destroy.argtypes = [ctypes.c_void_p]
        lib._hetu_sigs_set = True
    return lib


def load_embed_cache_core() -> Optional[ctypes.CDLL]:
    lib = load_native("hetu_embed_cache", ["embed_cache.cc"])
    if lib is not None and not getattr(lib, "_hetu_sigs_set", False):
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.hetu_cache_create.restype = ctypes.c_void_p
        lib.hetu_cache_create.argtypes = [ctypes.c_int32, ctypes.c_int64]
        lib.hetu_cache_destroy.restype = None
        lib.hetu_cache_destroy.argtypes = [ctypes.c_void_p]
        lib.hetu_cache_size.restype = ctypes.c_int64
        lib.hetu_cache_size.argtypes = [ctypes.c_void_p]
        lib.hetu_cache_lookup.restype = ctypes.c_int64
        lib.hetu_cache_lookup.argtypes = [
            ctypes.c_void_p, i64p, ctypes.c_int64, i64p, u8p, i64p, i64p]
        lib._hetu_sigs_set = True
    return lib


def load_dp_core() -> Optional[ctypes.CDLL]:
    lib = load_native("hetu_dp_core", ["dp_core.cc"])
    if lib is not None and not getattr(lib, "_hetu_sigs_set", False):
        i32p = ctypes.POINTER(ctypes.c_int32)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.hetu_dp_strategy_solve.restype = ctypes.c_double
        lib.hetu_dp_strategy_solve.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i32p, f64p, f64p, i32p]
        lib.hetu_dp_pipeline_partition.restype = ctypes.c_double
        lib.hetu_dp_pipeline_partition.argtypes = [
            ctypes.c_int32, ctypes.c_int32, f64p, f64p, i32p]
        lib._hetu_sigs_set = True
    return lib
