// Native dynamic-programming cores for the auto-parallel planner.
//
// TPU-native counterpart of the reference's Galvatron C++ DP solver
// (tools/Galvatron/csrc/dp_core.cpp:23 dynamic_programming_core) and the
// v1 pipeline partitioners (v1/python/hetu/distributed_strategies/
// {gpipe.py,pipedream.py}).  Exposed through a plain C ABI and loaded via
// ctypes (no pybind11 in this environment).
//
// Build: see hetu_tpu/csrc/build.py (g++ -O2 -shared -fPIC).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {
constexpr double kInf = std::numeric_limits<double>::infinity();
}

extern "C" {

// Per-layer strategy selection under a memory budget with inter-layer
// transition costs.
//
//   layer_num   L
//   max_mem     discretized memory budget, INCLUSIVE (a plan whose total
//               memory equals max_mem units is feasible)
//   strategy_num S
//   mem_cost    [L*S]   int   memory units consumed by layer i under s
//   intra_cost  [L*S]   double per-layer execution cost under s
//   inter_cost  [L*S*S] double transition cost from strategy si (layer i-1)
//                       to strategy s (layer i)
//   res_list    [L]     int   chosen strategy per layer (output)
//
// Returns the minimal total cost, or +inf if the budget is infeasible.
double hetu_dp_strategy_solve(int32_t layer_num, int32_t max_mem,
                              int32_t strategy_num, const int32_t* mem_cost,
                              const double* intra_cost,
                              const double* inter_cost, int32_t* res_list) {
  const int32_t L = layer_num, S = strategy_num;
  const int32_t M = max_mem + 1;  // states 0..max_mem inclusive
  // f[v][s]: best cost of layers processed so far using v memory units,
  // with the last layer running strategy s.  Double-buffered per layer so
  // zero-memory strategies don't read partially-updated rows.
  std::vector<double> f(static_cast<size_t>(M) * S, 0.0);
  std::vector<double> nf(static_cast<size_t>(M) * S, kInf);
  // choice[i][v][s]: argmin predecessor strategy.
  std::vector<int32_t> choice(static_cast<size_t>(L) * M * S, -1);

  for (int32_t i = 0; i < L; ++i) {
    std::fill(nf.begin(), nf.end(), kInf);
    for (int32_t v = M - 1; v >= 0; --v) {
      for (int32_t s = 0; s < S; ++s) {
        const int32_t need = mem_cost[i * S + s];
        if (v < need) continue;
        const double* fprev = &f[static_cast<size_t>(v - need) * S];
        const double* trans = &inter_cost[(static_cast<size_t>(i) * S) * S];
        double best = kInf;
        int32_t best_si = -1;
        for (int32_t si = 0; si < S; ++si) {
          const double c = fprev[si] + trans[si * S + s];
          if (c < best) {
            best = c;
            best_si = si;
          }
        }
        choice[(static_cast<size_t>(i) * M + v) * S + s] = best_si;
        if (best_si >= 0)
          nf[static_cast<size_t>(v) * S + s] = best + intra_cost[i * S + s];
      }
    }
    f.swap(nf);
  }

  const double* last = &f[static_cast<size_t>(M - 1) * S];
  int32_t s = static_cast<int32_t>(
      std::min_element(last, last + S) - last);
  double total = last[s];
  if (!(total < kInf)) return kInf;

  int32_t v = M - 1;
  res_list[L - 1] = s;
  for (int32_t i = L - 1; i > 0; --i) {
    const int32_t prev = choice[(static_cast<size_t>(i) * M + v) * S + s];
    v -= mem_cost[i * S + s];
    s = prev;
    res_list[i - 1] = s;
  }
  return total;
}

// Balanced contiguous pipeline partition: split L layers into P stages
// minimizing the maximum stage cost (layer costs + per-boundary comm cost).
// DP over (first t layers, k stages).  Mirrors the v1 GPipe/PipeDream
// partition searching capability.
//
//   costs     [L] per-layer time
//   comm      [L] cost of cutting AFTER layer i (activation send)
//   boundaries[P-1] output: last layer index of stages 0..P-2
//
// Returns the bottleneck (max) stage cost.
double hetu_dp_pipeline_partition(int32_t layer_num, int32_t num_stages,
                                  const double* costs, const double* comm,
                                  int32_t* boundaries) {
  const int32_t L = layer_num, P = num_stages;
  std::vector<double> prefix(L + 1, 0.0);
  for (int32_t i = 0; i < L; ++i) prefix[i + 1] = prefix[i] + costs[i];

  auto seg = [&](int32_t a, int32_t b) {  // layers [a, b)
    double c = prefix[b] - prefix[a];
    if (b < L) c += comm[b - 1];  // boundary after layer b-1
    return c;
  };

  // g[t][k]: min over partitions of first t layers into k stages of the
  // bottleneck cost.
  std::vector<double> g(static_cast<size_t>(L + 1) * (P + 1), kInf);
  std::vector<int32_t> cut(static_cast<size_t>(L + 1) * (P + 1), -1);
  g[0] = 0.0;
  for (int32_t k = 1; k <= P; ++k) {
    for (int32_t t = k; t <= L - (P - k); ++t) {
      double best = kInf;
      int32_t best_j = -1;
      for (int32_t j = k - 1; j < t; ++j) {
        const double c =
            std::max(g[static_cast<size_t>(j) * (P + 1) + (k - 1)],
                     seg(j, t));
        if (c < best) {
          best = c;
          best_j = j;
        }
      }
      g[static_cast<size_t>(t) * (P + 1) + k] = best;
      cut[static_cast<size_t>(t) * (P + 1) + k] = best_j;
    }
  }

  double total = g[static_cast<size_t>(L) * (P + 1) + P];
  int32_t t = L;
  for (int32_t k = P; k > 1; --k) {
    const int32_t j = cut[static_cast<size_t>(t) * (P + 1) + k];
    boundaries[k - 2] = j - 1;  // stage k-2 ends at layer j-1
    t = j;
  }
  return total;
}

}  // extern "C"
