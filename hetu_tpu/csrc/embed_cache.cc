// Native embedding-cache policy core (HET-style).
//
// Counterpart of the reference's hetu_cache
// (hetu/v1/src/hetu_cache/include/{cache.h,lru_cache.h,lfu_cache.h,
// lfuopt_cache.h} — the VLDB'22 HET cache-enabled embedding system).
// The policy bookkeeping (key -> slot map, recency/frequency eviction)
// runs on the host in C++; the actual embedding rows live in a fixed
// [limit, dim] device array indexed by the slots this core hands out, so
// the TPU side is a static-shape gather/scatter.
//
// Eviction rule: victim = min (priority, tiebreak) where
//   LRU    — priority 0,        tiebreak last-access time
//   LFU    — priority frequency, tiebreak first-insertion time
//   LFUOpt — priority frequency, tiebreak last-access time
//            (frequency + recency, approximating lfuopt_cache.h's
//            offline-optimal refinement)
//
// C ABI, loaded via ctypes (hetu_tpu/csrc/build.py).

#include <cstdint>
#include <set>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

enum Policy : int32_t { kLRU = 0, kLFU = 1, kLFUOpt = 2 };

struct Entry {
  int64_t slot;
  int64_t freq;
  int64_t tie;
  int64_t batch;  // last lookup batch that touched this key (pinning)
};

using Rank = std::tuple<int64_t, int64_t, int64_t>;  // (prio, tie, key)

struct Cache {
  Policy policy;
  int64_t limit;
  int64_t clock = 0;
  int64_t batch_id = 0;
  std::unordered_map<int64_t, Entry> map;  // key -> entry
  std::set<Rank> ranks;                    // eviction order (begin = victim)
  std::vector<int64_t> free_slots;

  explicit Cache(Policy p, int64_t lim) : policy(p), limit(lim) {
    free_slots.reserve(lim);
    for (int64_t s = lim - 1; s >= 0; --s) free_slots.push_back(s);
  }

  int64_t prio(const Entry& e) const {
    return policy == kLRU ? 0 : e.freq;
  }

  void touch(int64_t key, Entry& e) {
    ranks.erase({prio(e), e.tie, key});
    e.freq += 1;
    if (policy != kLFU) e.tie = ++clock;  // LFU keeps insertion time
    e.batch = batch_id;
    ranks.insert({prio(e), e.tie, key});
  }

  void insert(int64_t key, int64_t slot) {
    Entry e{slot, 1, ++clock, batch_id};
    map.emplace(key, e);
    ranks.insert({prio(e), e.tie, key});
  }

  // Returns (victim key, victim slot), skipping keys pinned by the
  // current batch (their returned slots must stay valid); (-1, -1) if
  // everything is pinned.
  std::pair<int64_t, int64_t> evict() {
    for (auto it = ranks.begin(); it != ranks.end(); ++it) {
      const int64_t key = std::get<2>(*it);
      Entry& e = map[key];
      if (e.batch == batch_id) continue;  // pinned
      const int64_t slot = e.slot;
      ranks.erase(it);
      map.erase(key);
      free_slots.push_back(slot);
      return {key, slot};
    }
    return {-1, -1};
  }
};

}  // namespace

extern "C" {

void* hetu_cache_create(int32_t policy, int64_t limit) {
  return new Cache(static_cast<Policy>(policy), limit);
}

void hetu_cache_destroy(void* h) { delete static_cast<Cache*>(h); }

int64_t hetu_cache_size(void* h) {
  return static_cast<int64_t>(static_cast<Cache*>(h)->map.size());
}

// Process a batch of keys.  For each key, return its cache slot
// (allocating/evicting on miss) and whether it missed.  Keys of the
// current batch are pinned: they are never evicted within the call, so
// every returned slot stays valid.  Evicted (key, slot) pairs are
// reported so the host can write those rows back to the master table
// before they are overwritten.  Returns the number of evictions
// (evicted_* arrays must hold >= n entries), or -1 if the batch has more
// unique keys than the cache limit.
int64_t hetu_cache_lookup(void* h, const int64_t* keys, int64_t n,
                          int64_t* slots, uint8_t* is_miss,
                          int64_t* evicted_keys, int64_t* evicted_slots) {
  auto* c = static_cast<Cache*>(h);
  c->batch_id += 1;
  int64_t num_evicted = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t key = keys[i];
    auto it = c->map.find(key);
    if (it != c->map.end()) {
      slots[i] = it->second.slot;
      is_miss[i] = 0;
      c->touch(key, it->second);
      continue;
    }
    if (c->free_slots.empty()) {
      const auto [vk, vs] = c->evict();
      if (vk < 0) return -1;  // batch exceeds cache capacity
      evicted_keys[num_evicted] = vk;
      evicted_slots[num_evicted] = vs;
      ++num_evicted;
    }
    const int64_t slot = c->free_slots.back();
    c->free_slots.pop_back();
    c->insert(key, slot);
    slots[i] = slot;
    is_miss[i] = 1;
  }
  return num_evicted;
}

}  // extern "C"
