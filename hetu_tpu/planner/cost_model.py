"""TPU cluster cost model for the auto-parallel planner.

Counterpart of the reference's profiling-driven cost estimation
(``tools/Galvatron/galvatron/profile_hardware/profile_hardware.py``,
``galvatron/core/profiler.py``; v1 ``HetuSimulator``,
``v1/python/hetu/profiler.py``) re-derived for TPU hardware: roofline
per-layer compute (MXU peak vs HBM bandwidth) and alpha-beta collective
costs over ICI (intra-slice) and DCN (cross-slice), matching the mental
model of the scaling-book recipe (pick mesh -> annotate -> collectives
ride ICI).

All sizes in bytes, times in seconds.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple


@dataclasses.dataclass
class ChipSpec:
    """Per-chip hardware parameters."""
    name: str = "v5p"
    peak_flops: float = 459e12      # bf16 FLOP/s
    hbm_bytes: float = 95e9
    hbm_bw: float = 2765e9          # bytes/s
    ici_bw: float = 90e9            # bytes/s per link direction
    ici_links: int = 6              # 3D torus: 2 per dim
    ici_latency: float = 1e-6
    dcn_bw: float = 25e9            # bytes/s per host
    dcn_latency: float = 10e-6
    mxu_efficiency: float = 0.55    # achievable fraction of peak on matmuls


CHIPS: Dict[str, ChipSpec] = {
    "v4": ChipSpec("v4", 275e12, 32e9, 1228e9, 45e9),
    "v5e": ChipSpec("v5e", 197e12, 16e9, 819e9, 45e9),
    "v5p": ChipSpec("v5p"),
    "v6e": ChipSpec("v6e", 918e12, 32e9, 1640e9, 90e9),
}


@dataclasses.dataclass
class ClusterSpec:
    """A (possibly multi-slice) TPU cluster: ``num_chips`` per slice
    connected by ICI, slices connected by DCN.

    ``link_alpha_beta`` optionally carries MEASURED per-collective
    ``(alpha, beta)`` fits (``profile_hardware.profile_collectives``
    keys: all_reduce / all_gather / reduce_scatter / p2p) — when a kind
    has a fit, the collective-time formulas below price it as
    ``alpha + beta * bytes`` instead of the datasheet ring model, so one
    measured link speed feeds the planner's solver AND the analysis
    plane's step-time linter identically
    (:meth:`hetu_tpu.planner.profile_hardware.Calibration.to_cluster_spec`).
    """
    chip: ChipSpec = dataclasses.field(default_factory=ChipSpec)
    num_chips: int = 8
    num_slices: int = 1
    link_alpha_beta: Optional[Dict[str, Tuple[float, float]]] = None

    @property
    def total_chips(self) -> int:
        return self.num_chips * self.num_slices

    def bw_for_group(self, group_size: int) -> Tuple[float, float]:
        """(bandwidth, latency) of the slowest hop a collective over
        ``group_size`` chips crosses: ICI if it fits in one slice else DCN."""
        if group_size <= self.num_chips:
            return self.chip.ici_bw, self.chip.ici_latency
        return self.chip.dcn_bw, self.chip.dcn_latency

    def measured(self, kind: str,
                 group_size: int = 1) -> Optional[Tuple[float, float]]:
        """The measured (alpha, beta) fit for ``kind``, or None when
        there is no fit OR the group spans slices — the fit was taken
        on one slice's ICI, so a DCN-crossing collective must fall back
        to the ring/DCN model rather than be underpriced ~10-100x."""
        if not self.link_alpha_beta or group_size > self.num_chips:
            return None
        return self.link_alpha_beta.get(kind)


# ---------------------------------------------------------------------------
# collective costs (alpha-beta / ring models) — THE one implementation
# ---------------------------------------------------------------------------
# Both consumers price communication through these four functions (via
# :func:`collective_time`): the planner's DP solver (layer_time /
# grad_sync_time below) and the static step-time pass
# (``hetu_tpu.analysis.cost``).  Keeping a single implementation is a
# correctness property — the linter and the solver can never disagree on
# what a collective costs.  Payload bytes are WIRE bytes: a quantized
# (bf16/int8) transport passes its narrow payload here, so EQuARX-style
# transports are priced at their real wire cost, not the fp32 width.

def all_reduce_time(bytes_: float, n: int, cluster: ClusterSpec) -> float:
    if n <= 1:
        return 0.0
    m = cluster.measured("all_reduce", n)
    if m is not None:
        return m[0] + m[1] * bytes_
    bw, lat = cluster.bw_for_group(n)
    return 2.0 * (n - 1) / n * bytes_ / bw + 2 * (n - 1) * lat


def all_gather_time(bytes_: float, n: int, cluster: ClusterSpec,
                    _kind: str = "all_gather") -> float:
    """bytes_ = full (gathered) size."""
    if n <= 1:
        return 0.0
    m = cluster.measured(_kind, n)
    if m is not None:
        return m[0] + m[1] * bytes_
    bw, lat = cluster.bw_for_group(n)
    return (n - 1) / n * bytes_ / bw + (n - 1) * lat


def reduce_scatter_time(bytes_: float, n: int,
                        cluster: ClusterSpec) -> float:
    """bytes_ = full (pre-scatter) size."""
    return all_gather_time(bytes_, n, cluster, _kind="reduce_scatter")


def all_to_all_time(bytes_: float, n: int, cluster: ClusterSpec) -> float:
    if n <= 1:
        return 0.0
    m = cluster.measured("all_to_all", n)
    if m is not None:
        return m[0] + m[1] * bytes_
    bw, lat = cluster.bw_for_group(n)
    return (n - 1) / n * bytes_ / bw / max(1, cluster.chip.ici_links // 2) \
        + (n - 1) * lat


def p2p_time(bytes_: float, cluster: ClusterSpec,
             cross_slice: bool = False) -> float:
    m = cluster.measured("p2p", 2)
    if m is not None and not cross_slice:
        return m[0] + m[1] * bytes_
    bw = cluster.chip.dcn_bw if cross_slice else cluster.chip.ici_bw
    lat = cluster.chip.dcn_latency if cross_slice else cluster.chip.ici_latency
    return bytes_ / bw + lat


#: collective kind (analysis/edges vocabulary) -> pricing function.
#: ``reshard`` lowers to all-to-all / gather chains — priced at the
#: all-to-all rate; ``scatter`` / ``identity`` move nothing.
def collective_time(kind: str, bytes_: float, n: int,
                    cluster: ClusterSpec) -> float:
    """Alpha-beta time of ONE collective of ``kind`` moving ``bytes_``
    payload over a group of ``n`` chips — the single entry point the
    analysis step-time pass uses, dispatching to the same four formulas
    the planner's solver prices plans with."""
    if kind in ("all_reduce", "broadcast", "reduce"):
        return all_reduce_time(bytes_, n, cluster)
    if kind == "all_gather":
        return all_gather_time(bytes_, n, cluster)
    if kind == "reduce_scatter":
        return reduce_scatter_time(bytes_, n, cluster)
    if kind in ("all_to_all", "reshard"):
        return all_to_all_time(bytes_, n, cluster)
    if kind == "ppermute":
        return p2p_time(bytes_, cluster)
    return 0.0


# ---------------------------------------------------------------------------
# layer specs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LayerSpec:
    """Per-layer workload description (one transformer block, an embedding,
    ...) — the planner's unit of placement."""
    name: str = "layer"
    flops: float = 0.0              # fwd FLOPs per micro-batch
    param_bytes: float = 0.0
    act_bytes: float = 0.0          # saved activations per micro-batch
    act_io_bytes: float = 0.0       # HBM traffic per micro-batch (roofline)
    boundary_bytes: float = 0.0     # activation size crossing to next layer
    tp_shardable: bool = True       # params/flops divide by tp

    def scaled(self, tp: int, dp: int = 1) -> "LayerSpec":
        """Per-device costs under a (tp, dp) layout: tp shards params and
        their compute; dp splits the batch (flops/activations, not
        params)."""
        t = tp if self.tp_shardable else 1
        return dataclasses.replace(
            self, flops=self.flops / t / dp,
            param_bytes=self.param_bytes / t,
            act_bytes=self.act_bytes / t / dp,
            act_io_bytes=self.act_io_bytes / t / dp,
            boundary_bytes=self.boundary_bytes / dp)


def transformer_layer_spec(batch: int, seq: int, hidden: int,
                           ffn: int, dtype_bytes: int = 2,
                           name: str = "block") -> LayerSpec:
    """Analytic cost of one pre-norm transformer block (attention + MLP),
    per micro-batch of ``batch`` sequences.  (Head count doesn't change
    flops/bytes at fixed hidden, so it is not a parameter.)"""
    b, s, h, f = batch, seq, hidden, ffn
    attn_flops = 2 * b * s * h * (3 * h) + 2 * b * s * s * h * 2 \
        + 2 * b * s * h * h
    mlp_flops = 2 * b * s * h * f * 2
    params = (4 * h * h + 2 * h * f + 4 * h) * dtype_bytes
    acts = b * s * (10 * h + 2 * f) * dtype_bytes  # checkpointable set
    io = acts + 3 * params
    return LayerSpec(name=name, flops=attn_flops + mlp_flops,
                     param_bytes=params, act_bytes=acts, act_io_bytes=io,
                     boundary_bytes=b * s * h * dtype_bytes)


def embedding_layer_spec(batch: int, seq: int, hidden: int, vocab: int,
                         dtype_bytes: int = 2,
                         name: str = "embed") -> LayerSpec:
    return LayerSpec(name=name, flops=2.0 * batch * seq * hidden,
                     param_bytes=vocab * hidden * dtype_bytes,
                     act_bytes=batch * seq * hidden * dtype_bytes,
                     act_io_bytes=batch * seq * hidden * dtype_bytes,
                     boundary_bytes=batch * seq * hidden * dtype_bytes)


# ---------------------------------------------------------------------------
# per-layer execution time + memory under a strategy
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Strategy:
    """One per-layer parallel strategy candidate: (dp, tp, zero stage,
    recompute flag).  pp is a global decision (layer->stage assignment)."""
    dp: int = 1
    tp: int = 1
    zero: int = 0          # 0: none, 1: optimizer states, 2: +grads, 3: +params
    recompute: bool = False

    def __str__(self):
        z = f"-z{self.zero}" if self.zero else ""
        c = "-ckpt" if self.recompute else ""
        return f"dp{self.dp}tp{self.tp}{z}{c}"


def layer_time(layer: LayerSpec, st: Strategy, cluster: ClusterSpec,
               include_grad_sync: bool = True,
               dp_splits_batch: bool = True,
               calibration: Optional["TimeCalibration"] = None) -> float:
    """fwd+bwd time of one layer under strategy st, the roofline max of
    MXU time and HBM time, plus TP/DP collectives.

    ``dp_splits_batch``: the layer's costs describe a fixed GLOBAL batch
    that dp divides (v1-searcher semantics).  Pass False when the costs
    already describe one per-replica micro-batch (SearchEngine).

    ``calibration`` scales the roofline (compute/IO) term by the ratio
    the static step-time pass (``analysis/cost.predict_cost``) measured
    on a lowered single-layer probe (:func:`calibrate_layer_time`) —
    the collective terms are added AFTER scaling because the probe is a
    single-device program (no comm to calibrate against)."""
    chip = cluster.chip
    sc = layer.scaled(st.tp, st.dp if dp_splits_batch else 1)
    # fwd + bwd ~ 3x fwd flops; recompute adds one extra fwd
    total_flops = sc.flops * (4.0 if st.recompute else 3.0)
    compute = total_flops / (chip.peak_flops * chip.mxu_efficiency)
    io = 3.0 * sc.act_io_bytes / chip.hbm_bw
    t = max(compute, io)
    if calibration is not None:
        t = calibration.apply(t)
    if st.tp > 1 and layer.tp_shardable:
        # Megatron TP: 2 allreduce fwd + 2 bwd on the boundary activation
        t += 4 * all_reduce_time(sc.boundary_bytes, st.tp, cluster)
    if include_grad_sync and st.dp > 1:
        t += grad_sync_time(layer, st, cluster)
    return t


def grad_sync_time(layer: LayerSpec, st: Strategy,
                   cluster: ClusterSpec) -> float:
    """Once-per-step gradient synchronization cost across the DP group
    (allreduce, or reduce-scatter + param allgather under ZeRO)."""
    if st.dp <= 1:
        return 0.0
    sc = layer.scaled(st.tp)
    gb = sc.param_bytes * 2  # fp32 grads of bf16 params
    if st.zero >= 1:
        return reduce_scatter_time(gb, st.dp, cluster) \
            + all_gather_time(sc.param_bytes, st.dp, cluster)
    return all_reduce_time(gb, st.dp, cluster)


def layer_memory(layer: LayerSpec, st: Strategy, cluster: ClusterSpec,
                 num_microbatches: int = 1,
                 optimizer_mult: float = 6.0,
                 dp_splits_batch: bool = True,
                 calibration: Optional["MemoryCalibration"] = None
                 ) -> float:
    """HBM bytes for one layer under strategy st: params + grads +
    optimizer states (Adam: 2 fp32 moments + fp32 master = ~6x bf16 param
    bytes) + live activations.

    ``calibration`` scales the closed form by the ratio the static
    peak-HBM pass (``analysis/memory.predict_memory``) measured on a
    lowered single-layer probe (:func:`calibrate_layer_memory`) — the
    planner's budget check then runs on the same numbers the analysis
    gate pins, not an unvalidated heuristic.
    """
    sc = layer.scaled(st.tp, st.dp if dp_splits_batch else 1)
    p = sc.param_bytes
    opt = p * optimizer_mult
    grads = p
    if st.zero >= 1:
        opt /= st.dp
    if st.zero >= 2:
        grads /= st.dp
    if st.zero >= 3:
        p /= st.dp
    act = sc.boundary_bytes if st.recompute else sc.act_bytes
    total = p + grads + opt + act * num_microbatches
    if calibration is not None:
        total = calibration.apply(total)
    return total


# ---------------------------------------------------------------------------
# calibration of layer_memory against the static peak-HBM pass
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MemoryCalibration:
    """Validation of :func:`layer_memory` against the static pass.

    ``static_bytes`` is the analysis-side prediction
    (``analysis/memory.predict_memory``) for a lowered single-layer
    train-step probe; ``model_bytes`` the closed-form estimate for the
    same workload; ``scale`` their ratio.  Feeding the calibration into
    :func:`layer_memory` / :class:`~hetu_tpu.planner.search.SearchEngine`
    constrains the planner by the analysis-backed numbers — the same
    model the CI gate cross-checks against XLA to ±10%.
    """
    scale: float = 1.0
    static_bytes: int = 0          # predict_memory peak on the probe
    model_bytes: float = 0.0       # closed-form layer_memory estimate
    xla_bytes: Optional[int] = None    # XLA's own total, when compiled
    probe: str = ""                # probe description (shapes/dtype)

    def apply(self, bytes_: float) -> float:
        return bytes_ * self.scale


def _layer_probe_handle(batch: int, seq: int, hidden: int, ffn: int,
                        dtype: str, name: str):
    """The calibration probe both :func:`calibrate_layer_memory` and
    :func:`calibrate_layer_time` lower: one pre-norm attention+MLP
    block with Adam state, fwd+bwd+update in one donated jit — the
    planner's unit of placement (:func:`transformer_layer_spec`) made
    real, registered as an :class:`~hetu_tpu.graph.graph.ExecutableHandle`
    so the analysis passes walk it exactly as they walk the gate
    families."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..graph.graph import ExecutableHandle

    f = ffn
    h = hidden
    dt = np.dtype(dtype)

    def _params():
        return {
            "ln1": jnp.ones((h,), dt), "ln2": jnp.ones((h,), dt),
            "qkv": jnp.zeros((h, 3 * h), dt), "proj": jnp.zeros((h, h), dt),
            "fc1": jnp.zeros((h, f), dt), "fc2": jnp.zeros((f, h), dt),
        }

    def _block(p, x):
        # pre-norm attention + MLP, the shape transformer_layer_spec
        # prices (single head: head count doesn't change bytes/flops)
        xn = x * p["ln1"]
        qkv = xn @ p["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = jax.nn.softmax(q @ k.transpose(0, 2, 1)
                           / np.sqrt(h), axis=-1)
        x = x + (a @ v) @ p["proj"]
        xn = x * p["ln2"]
        return x + jax.nn.gelu(xn @ p["fc1"]) @ p["fc2"]

    def _step(params, m, v, x):
        def loss_fn(p):
            return jnp.mean(_block(p, x) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_m = jax.tree_util.tree_map(
            lambda mi, g: 0.9 * mi + 0.1 * g.astype(jnp.float32), m, grads)
        new_v = jax.tree_util.tree_map(
            lambda vi, g: 0.99 * vi + 0.01
            * jnp.square(g.astype(jnp.float32)), v, grads)
        new_p = jax.tree_util.tree_map(
            lambda p, mi, vi: (p - 1e-3 * mi
                               / (jnp.sqrt(vi) + 1e-8)).astype(p.dtype),
            params, new_m, new_v)
        return loss, new_p, new_m, new_v

    params = _params()
    fp32 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    x = jnp.zeros((batch, seq, h), dt)
    fn = jax.jit(_step, donate_argnums=(0, 1, 2))
    return ExecutableHandle(
        name, fn, (params, fp32, fp32, x),
        meta={"kind": "train_step",
              "params": [{"name": k, "shape": tuple(v.shape),
                          "dtype": str(v.dtype), "pspec": None}
                         for k, v in params.items()]})


def calibrate_layer_memory(batch: int = 4, seq: int = 64,
                           hidden: int = 64, ffn: Optional[int] = None,
                           dtype: str = "float32",
                           xla_check: bool = False,
                           probe_handle=None) -> MemoryCalibration:
    """Lower a single-transformer-layer train-step probe and measure the
    ratio of the static peak-HBM pass over the closed-form
    :func:`layer_memory` estimate.

    The probe (:func:`_layer_probe_handle`) is the planner's unit of
    placement made real; ``predict_memory`` walks its jaxpr exactly as
    the CI gate does for the gate families, so the returned scale
    carries the model's validated liveness rules into the planner's
    budget check.  With ``xla_check=True`` the probe is also compiled
    and XLA's ``memory_analysis()`` total recorded (CPU-priced; slower).
    """
    import numpy as np

    from ..analysis.memory import predict_memory

    f = ffn if ffn is not None else 4 * hidden
    h = hidden
    dt = np.dtype(dtype)
    # probe_handle: reuse an already-traced probe (plan_for_gpt shares
    # ONE lowering between the memory and time calibrations — tracing
    # the probe is the dominant cost of calibrating)
    handle = probe_handle or _layer_probe_handle(
        batch, seq, h, f, dtype, "planner_probe/layer_mem")
    static = predict_memory(handle, xla=xla_check)

    spec = transformer_layer_spec(batch, seq, h, f,
                                  dtype_bytes=dt.itemsize)
    # the probe's optimizer state: fp32 m + v (+ no separate master —
    # params update in place), grads transient fp32
    opt_mult = 2 * 4 / dt.itemsize
    model = layer_memory(spec, Strategy(), ClusterSpec(),
                         optimizer_mult=opt_mult)
    xla_total = static.xla_total if xla_check else None
    return MemoryCalibration(
        scale=float(static.peak_bytes) / max(model, 1.0),
        static_bytes=int(static.peak_bytes),
        model_bytes=float(model),
        xla_bytes=int(xla_total) if xla_total is not None else None,
        probe=f"block b{batch} s{seq} h{h} f{f} {dt.name}")


# ---------------------------------------------------------------------------
# calibration of layer_time against the static step-time pass
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TimeCalibration:
    """Validation of :func:`layer_time` against the static step-time
    pass — the time-plane twin of :class:`MemoryCalibration`.

    ``static_s`` is the analysis-side prediction
    (``analysis/cost.predict_cost`` — the FLOP/HBM roofline the CI gate
    cross-checks against ``compiled.cost_analysis()`` to ±10%) for a
    lowered single-layer train-step probe; ``model_s`` the closed-form
    estimate for the same workload; ``scale`` their ratio.  Feeding the
    calibration into :func:`layer_time` /
    :class:`~hetu_tpu.planner.search.SearchEngine` makes the DP solver
    score candidates with the same counted-FLOP model the analysis gate
    pins, instead of an unvalidated closed form."""
    scale: float = 1.0
    static_s: float = 0.0          # predict_cost step time on the probe
    model_s: float = 0.0           # closed-form layer_time estimate
    static_flops: float = 0.0      # counted probe FLOPs (evidence)
    model_flops: float = 0.0       # closed-form probe FLOPs
    xla_flops: Optional[float] = None  # XLA's own count, when compiled
    probe: str = ""                # probe description (shapes/dtype)

    def apply(self, seconds: float) -> float:
        return seconds * self.scale


def calibrate_layer_time(batch: int = 4, seq: int = 64,
                         hidden: int = 64, ffn: Optional[int] = None,
                         dtype: str = "float32",
                         cluster: Optional[ClusterSpec] = None,
                         xla_check: bool = False,
                         probe_handle=None) -> TimeCalibration:
    """Lower a single-transformer-layer train-step probe, run the static
    step-time pass on it, and measure the ratio over the closed-form
    :func:`layer_time` estimate — exactly as
    :func:`calibrate_layer_memory` does for bytes.

    The ratio carries the counted-FLOP/HBM roofline (what the program
    *actually* computes and moves, per the jaxpr walk the CI gate
    cross-checks against XLA) into the planner's scoring, correcting
    the closed form's analytic flop/io estimates.  With
    ``xla_check=True`` the probe is compiled and XLA's own
    ``cost_analysis()`` FLOP count recorded (slower)."""
    import numpy as np

    from ..analysis.cost import predict_cost

    f = ffn if ffn is not None else 4 * hidden
    h = hidden
    dt = np.dtype(dtype)
    cluster = cluster or ClusterSpec(num_chips=1)
    handle = probe_handle or _layer_probe_handle(
        batch, seq, h, f, dtype, "planner_probe/layer_time")
    static = predict_cost(handle, cluster=cluster, xla=xla_check)

    spec = transformer_layer_spec(batch, seq, h, f,
                                  dtype_bytes=dt.itemsize)
    model = layer_time(spec, Strategy(), cluster,
                       include_grad_sync=False)
    xla_flops = None
    if xla_check and static.xla is not None:
        xla_flops = float(static.xla.get("flops", 0.0))
    return TimeCalibration(
        scale=float(static.step_time_s) / max(model, 1e-12),
        static_s=float(static.step_time_s),
        model_s=float(model),
        static_flops=float(static.flops),
        model_flops=3.0 * float(spec.flops),
        xla_flops=xla_flops,
        probe=f"block b{batch} s{seq} h{h} f{f} {dt.name}")


def pipeline_time(stage_times: Sequence[float], num_microbatches: int,
                  boundary_bytes: float, cluster: ClusterSpec) -> float:
    """1F1B / GPipe steady-state estimate: bottleneck stage dominates,
    plus the pipeline fill of (P-1) slots and stage-boundary p2p."""
    p = len(stage_times)
    if p == 0:
        return 0.0
    bottleneck = max(stage_times)
    fill = sum(stage_times) - bottleneck
    hop = p2p_time(boundary_bytes, cluster)
    return num_microbatches * bottleneck + fill + 2 * (p - 1) * hop
