"""TPU cluster cost model for the auto-parallel planner.

Counterpart of the reference's profiling-driven cost estimation
(``tools/Galvatron/galvatron/profile_hardware/profile_hardware.py``,
``galvatron/core/profiler.py``; v1 ``HetuSimulator``,
``v1/python/hetu/profiler.py``) re-derived for TPU hardware: roofline
per-layer compute (MXU peak vs HBM bandwidth) and alpha-beta collective
costs over ICI (intra-slice) and DCN (cross-slice), matching the mental
model of the scaling-book recipe (pick mesh -> annotate -> collectives
ride ICI).

All sizes in bytes, times in seconds.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple


@dataclasses.dataclass
class ChipSpec:
    """Per-chip hardware parameters."""
    name: str = "v5p"
    peak_flops: float = 459e12      # bf16 FLOP/s
    hbm_bytes: float = 95e9
    hbm_bw: float = 2765e9          # bytes/s
    ici_bw: float = 90e9            # bytes/s per link direction
    ici_links: int = 6              # 3D torus: 2 per dim
    ici_latency: float = 1e-6
    dcn_bw: float = 25e9            # bytes/s per host
    dcn_latency: float = 10e-6
    mxu_efficiency: float = 0.55    # achievable fraction of peak on matmuls


CHIPS: Dict[str, ChipSpec] = {
    "v4": ChipSpec("v4", 275e12, 32e9, 1228e9, 45e9),
    "v5e": ChipSpec("v5e", 197e12, 16e9, 819e9, 45e9),
    "v5p": ChipSpec("v5p"),
    "v6e": ChipSpec("v6e", 918e12, 32e9, 1640e9, 90e9),
}


@dataclasses.dataclass
class ClusterSpec:
    """A (possibly multi-slice) TPU cluster: ``num_chips`` per slice
    connected by ICI, slices connected by DCN."""
    chip: ChipSpec = dataclasses.field(default_factory=ChipSpec)
    num_chips: int = 8
    num_slices: int = 1

    @property
    def total_chips(self) -> int:
        return self.num_chips * self.num_slices

    def bw_for_group(self, group_size: int) -> Tuple[float, float]:
        """(bandwidth, latency) of the slowest hop a collective over
        ``group_size`` chips crosses: ICI if it fits in one slice else DCN."""
        if group_size <= self.num_chips:
            return self.chip.ici_bw, self.chip.ici_latency
        return self.chip.dcn_bw, self.chip.dcn_latency


# ---------------------------------------------------------------------------
# collective costs (alpha-beta / ring models)
# ---------------------------------------------------------------------------

def all_reduce_time(bytes_: float, n: int, cluster: ClusterSpec) -> float:
    if n <= 1:
        return 0.0
    bw, lat = cluster.bw_for_group(n)
    return 2.0 * (n - 1) / n * bytes_ / bw + 2 * (n - 1) * lat


def all_gather_time(bytes_: float, n: int, cluster: ClusterSpec) -> float:
    """bytes_ = full (gathered) size."""
    if n <= 1:
        return 0.0
    bw, lat = cluster.bw_for_group(n)
    return (n - 1) / n * bytes_ / bw + (n - 1) * lat


reduce_scatter_time = all_gather_time


def all_to_all_time(bytes_: float, n: int, cluster: ClusterSpec) -> float:
    if n <= 1:
        return 0.0
    bw, lat = cluster.bw_for_group(n)
    return (n - 1) / n * bytes_ / bw / max(1, cluster.chip.ici_links // 2) \
        + (n - 1) * lat


def p2p_time(bytes_: float, cluster: ClusterSpec,
             cross_slice: bool = False) -> float:
    bw = cluster.chip.dcn_bw if cross_slice else cluster.chip.ici_bw
    lat = cluster.chip.dcn_latency if cross_slice else cluster.chip.ici_latency
    return bytes_ / bw + lat


# ---------------------------------------------------------------------------
# layer specs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LayerSpec:
    """Per-layer workload description (one transformer block, an embedding,
    ...) — the planner's unit of placement."""
    name: str = "layer"
    flops: float = 0.0              # fwd FLOPs per micro-batch
    param_bytes: float = 0.0
    act_bytes: float = 0.0          # saved activations per micro-batch
    act_io_bytes: float = 0.0       # HBM traffic per micro-batch (roofline)
    boundary_bytes: float = 0.0     # activation size crossing to next layer
    tp_shardable: bool = True       # params/flops divide by tp

    def scaled(self, tp: int, dp: int = 1) -> "LayerSpec":
        """Per-device costs under a (tp, dp) layout: tp shards params and
        their compute; dp splits the batch (flops/activations, not
        params)."""
        t = tp if self.tp_shardable else 1
        return dataclasses.replace(
            self, flops=self.flops / t / dp,
            param_bytes=self.param_bytes / t,
            act_bytes=self.act_bytes / t / dp,
            act_io_bytes=self.act_io_bytes / t / dp,
            boundary_bytes=self.boundary_bytes / dp)


def transformer_layer_spec(batch: int, seq: int, hidden: int,
                           ffn: int, dtype_bytes: int = 2,
                           name: str = "block") -> LayerSpec:
    """Analytic cost of one pre-norm transformer block (attention + MLP),
    per micro-batch of ``batch`` sequences.  (Head count doesn't change
    flops/bytes at fixed hidden, so it is not a parameter.)"""
    b, s, h, f = batch, seq, hidden, ffn
    attn_flops = 2 * b * s * h * (3 * h) + 2 * b * s * s * h * 2 \
        + 2 * b * s * h * h
    mlp_flops = 2 * b * s * h * f * 2
    params = (4 * h * h + 2 * h * f + 4 * h) * dtype_bytes
    acts = b * s * (10 * h + 2 * f) * dtype_bytes  # checkpointable set
    io = acts + 3 * params
    return LayerSpec(name=name, flops=attn_flops + mlp_flops,
                     param_bytes=params, act_bytes=acts, act_io_bytes=io,
                     boundary_bytes=b * s * h * dtype_bytes)


def embedding_layer_spec(batch: int, seq: int, hidden: int, vocab: int,
                         dtype_bytes: int = 2,
                         name: str = "embed") -> LayerSpec:
    return LayerSpec(name=name, flops=2.0 * batch * seq * hidden,
                     param_bytes=vocab * hidden * dtype_bytes,
                     act_bytes=batch * seq * hidden * dtype_bytes,
                     act_io_bytes=batch * seq * hidden * dtype_bytes,
                     boundary_bytes=batch * seq * hidden * dtype_bytes)


# ---------------------------------------------------------------------------
# per-layer execution time + memory under a strategy
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Strategy:
    """One per-layer parallel strategy candidate: (dp, tp, zero stage,
    recompute flag).  pp is a global decision (layer->stage assignment)."""
    dp: int = 1
    tp: int = 1
    zero: int = 0          # 0: none, 1: optimizer states, 2: +grads, 3: +params
    recompute: bool = False

    def __str__(self):
        z = f"-z{self.zero}" if self.zero else ""
        c = "-ckpt" if self.recompute else ""
        return f"dp{self.dp}tp{self.tp}{z}{c}"


def layer_time(layer: LayerSpec, st: Strategy, cluster: ClusterSpec,
               include_grad_sync: bool = True,
               dp_splits_batch: bool = True) -> float:
    """fwd+bwd time of one layer under strategy st, the roofline max of
    MXU time and HBM time, plus TP/DP collectives.

    ``dp_splits_batch``: the layer's costs describe a fixed GLOBAL batch
    that dp divides (v1-searcher semantics).  Pass False when the costs
    already describe one per-replica micro-batch (SearchEngine)."""
    chip = cluster.chip
    sc = layer.scaled(st.tp, st.dp if dp_splits_batch else 1)
    # fwd + bwd ~ 3x fwd flops; recompute adds one extra fwd
    total_flops = sc.flops * (4.0 if st.recompute else 3.0)
    compute = total_flops / (chip.peak_flops * chip.mxu_efficiency)
    io = 3.0 * sc.act_io_bytes / chip.hbm_bw
    t = max(compute, io)
    if st.tp > 1 and layer.tp_shardable:
        # Megatron TP: 2 allreduce fwd + 2 bwd on the boundary activation
        t += 4 * all_reduce_time(sc.boundary_bytes, st.tp, cluster)
    if include_grad_sync and st.dp > 1:
        t += grad_sync_time(layer, st, cluster)
    return t


def grad_sync_time(layer: LayerSpec, st: Strategy,
                   cluster: ClusterSpec) -> float:
    """Once-per-step gradient synchronization cost across the DP group
    (allreduce, or reduce-scatter + param allgather under ZeRO)."""
    if st.dp <= 1:
        return 0.0
    sc = layer.scaled(st.tp)
    gb = sc.param_bytes * 2  # fp32 grads of bf16 params
    if st.zero >= 1:
        return reduce_scatter_time(gb, st.dp, cluster) \
            + all_gather_time(sc.param_bytes, st.dp, cluster)
    return all_reduce_time(gb, st.dp, cluster)


def layer_memory(layer: LayerSpec, st: Strategy, cluster: ClusterSpec,
                 num_microbatches: int = 1,
                 optimizer_mult: float = 6.0,
                 dp_splits_batch: bool = True,
                 calibration: Optional["MemoryCalibration"] = None
                 ) -> float:
    """HBM bytes for one layer under strategy st: params + grads +
    optimizer states (Adam: 2 fp32 moments + fp32 master = ~6x bf16 param
    bytes) + live activations.

    ``calibration`` scales the closed form by the ratio the static
    peak-HBM pass (``analysis/memory.predict_memory``) measured on a
    lowered single-layer probe (:func:`calibrate_layer_memory`) — the
    planner's budget check then runs on the same numbers the analysis
    gate pins, not an unvalidated heuristic.
    """
    sc = layer.scaled(st.tp, st.dp if dp_splits_batch else 1)
    p = sc.param_bytes
    opt = p * optimizer_mult
    grads = p
    if st.zero >= 1:
        opt /= st.dp
    if st.zero >= 2:
        grads /= st.dp
    if st.zero >= 3:
        p /= st.dp
    act = sc.boundary_bytes if st.recompute else sc.act_bytes
    total = p + grads + opt + act * num_microbatches
    if calibration is not None:
        total = calibration.apply(total)
    return total


# ---------------------------------------------------------------------------
# calibration of layer_memory against the static peak-HBM pass
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MemoryCalibration:
    """Validation of :func:`layer_memory` against the static pass.

    ``static_bytes`` is the analysis-side prediction
    (``analysis/memory.predict_memory``) for a lowered single-layer
    train-step probe; ``model_bytes`` the closed-form estimate for the
    same workload; ``scale`` their ratio.  Feeding the calibration into
    :func:`layer_memory` / :class:`~hetu_tpu.planner.search.SearchEngine`
    constrains the planner by the analysis-backed numbers — the same
    model the CI gate cross-checks against XLA to ±10%.
    """
    scale: float = 1.0
    static_bytes: int = 0          # predict_memory peak on the probe
    model_bytes: float = 0.0       # closed-form layer_memory estimate
    xla_bytes: Optional[int] = None    # XLA's own total, when compiled
    probe: str = ""                # probe description (shapes/dtype)

    def apply(self, bytes_: float) -> float:
        return bytes_ * self.scale


def calibrate_layer_memory(batch: int = 4, seq: int = 64,
                           hidden: int = 64, ffn: Optional[int] = None,
                           dtype: str = "float32",
                           xla_check: bool = False) -> MemoryCalibration:
    """Lower a single-transformer-layer train-step probe and measure the
    ratio of the static peak-HBM pass over the closed-form
    :func:`layer_memory` estimate.

    The probe is the planner's unit of placement made real: one
    pre-norm attention+MLP block with Adam state, fwd+bwd+update in one
    donated jit — the same program shape :func:`transformer_layer_spec`
    prices.  ``predict_memory`` walks its jaxpr exactly as the CI gate
    does for the gate families, so the returned scale carries the
    model's validated liveness rules into the planner's budget check.
    With ``xla_check=True`` the probe is also compiled and XLA's
    ``memory_analysis()`` total recorded (CPU-priced; slower).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..analysis.memory import predict_memory
    from ..graph.graph import ExecutableHandle

    f = ffn if ffn is not None else 4 * hidden
    h = hidden
    dt = np.dtype(dtype)

    def _params():
        return {
            "ln1": jnp.ones((h,), dt), "ln2": jnp.ones((h,), dt),
            "qkv": jnp.zeros((h, 3 * h), dt), "proj": jnp.zeros((h, h), dt),
            "fc1": jnp.zeros((h, f), dt), "fc2": jnp.zeros((f, h), dt),
        }

    def _block(p, x):
        # pre-norm attention + MLP, the shape transformer_layer_spec
        # prices (single head: head count doesn't change bytes/flops)
        xn = x * p["ln1"]
        qkv = xn @ p["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = jax.nn.softmax(q @ k.transpose(0, 2, 1)
                           / np.sqrt(h), axis=-1)
        x = x + (a @ v) @ p["proj"]
        xn = x * p["ln2"]
        return x + jax.nn.gelu(xn @ p["fc1"]) @ p["fc2"]

    def _step(params, m, v, x):
        def loss_fn(p):
            return jnp.mean(_block(p, x) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_m = jax.tree_util.tree_map(
            lambda mi, g: 0.9 * mi + 0.1 * g.astype(jnp.float32), m, grads)
        new_v = jax.tree_util.tree_map(
            lambda vi, g: 0.99 * vi + 0.01
            * jnp.square(g.astype(jnp.float32)), v, grads)
        new_p = jax.tree_util.tree_map(
            lambda p, mi, vi: (p - 1e-3 * mi
                               / (jnp.sqrt(vi) + 1e-8)).astype(p.dtype),
            params, new_m, new_v)
        return loss, new_p, new_m, new_v

    params = _params()
    fp32 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    x = jnp.zeros((batch, seq, h), dt)
    fn = jax.jit(_step, donate_argnums=(0, 1, 2))
    handle = ExecutableHandle(
        "planner_probe/layer_mem", fn, (params, fp32, fp32, x),
        meta={"kind": "train_step",
              "params": [{"name": k, "shape": tuple(v.shape),
                          "dtype": str(v.dtype), "pspec": None}
                         for k, v in params.items()]})
    static = predict_memory(handle, xla=xla_check)

    spec = transformer_layer_spec(batch, seq, h, f,
                                  dtype_bytes=dt.itemsize)
    # the probe's optimizer state: fp32 m + v (+ no separate master —
    # params update in place), grads transient fp32
    opt_mult = 2 * 4 / dt.itemsize
    model = layer_memory(spec, Strategy(), ClusterSpec(),
                         optimizer_mult=opt_mult)
    xla_total = static.xla_total if xla_check else None
    return MemoryCalibration(
        scale=float(static.peak_bytes) / max(model, 1.0),
        static_bytes=int(static.peak_bytes),
        model_bytes=float(model),
        xla_bytes=int(xla_total) if xla_total is not None else None,
        probe=f"block b{batch} s{seq} h{h} f{f} {dt.name}")


def pipeline_time(stage_times: Sequence[float], num_microbatches: int,
                  boundary_bytes: float, cluster: ClusterSpec) -> float:
    """1F1B / GPipe steady-state estimate: bottleneck stage dominates,
    plus the pipeline fill of (P-1) slots and stage-boundary p2p."""
    p = len(stage_times)
    if p == 0:
        return 0.0
    bottleneck = max(stage_times)
    fill = sum(stage_times) - bottleneck
    hop = p2p_time(boundary_bytes, cluster)
    return num_microbatches * bottleneck + fill + 2 * (p - 1) * hop
