"""Hydraulis-style variable-sequence-length dispatch.

Capability counterpart of the reference's Hydraulis strategy package
(``examples/hydraulis/strategy/``): quadratic attention cost model fit
(``cost_model.py:12-20``), per-iteration ILP dispatch of sequences onto
heterogeneous dp/cp groups (``dynamic_pulp.py:11`` — PuLP there, here
``scipy.optimize.milp`` with a greedy LPT fallback), micro-batch
splitting (``dynamic_pulp.py:97`` ``solve_v_micro_batches``), per-group
packing (``dynamic_pulp.py:124`` ``batching_strategy``) and strategy-pool
generation (``generate_strategy.py``).

The flow per training iteration:
  1. a global batch of sequences with heterogeneous lengths arrives;
  2. :func:`dynamic_dispatch` assigns each sequence to one of the DP
     groups (each running a different tp/pp/cp layout with its own
     max-seqlen bound) minimizing the makespan estimate;
  3. per group, :func:`solve_micro_batches` splits its sequences into
     balanced micro-batches and :func:`batching_strategy` packs them into
     fixed-shape rows (consumed by :class:`hetu_tpu.data.Bucket`).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import ClusterSpec
from .dp_solver import solve_pipeline_partition


# ---------------------------------------------------------------------------
# quadratic cost model (attention makes per-seq time quadratic in length)
# ---------------------------------------------------------------------------

def quadratic_predict(s, a: float, b: float, c: float):
    return a * np.square(np.asarray(s, np.float64)) + b * np.asarray(s) + c


def fit_cost_model(seqlens: Sequence[int], times: Sequence[float]
                   ) -> Tuple[float, float, float]:
    """Least-squares fit t(s) = a s^2 + b s + c from profiled (seqlen,
    time) points (reference cost_model.py quadratic fit)."""
    s = np.asarray(seqlens, np.float64)
    t = np.asarray(times, np.float64)
    A = np.stack([s * s, s, np.ones_like(s)], axis=1)
    coef, *_ = np.linalg.lstsq(A, t, rcond=None)
    return float(coef[0]), float(coef[1]), float(coef[2])


@dataclasses.dataclass
class DispatchStrategy:
    """One heterogeneous group's layout + fitted cost coefficients.

    Coefficients (a, b, c) describe the layout at cp=1; ring-attention
    context parallelism divides the per-rank work by cp."""
    tp: int = 1
    pp: int = 1
    cp: int = 1
    a: float = 0.0          # quadratic coeff (attention)
    b: float = 1.0          # linear coeff
    c: float = 0.0          # constant per-seq overhead
    max_seqlen: int = 1 << 30

    def seq_time(self, s) -> np.ndarray:
        """FULL-model time for one sequence (cp shards the ring-attention
        work; pp gets its credit in :meth:`batch_time`)."""
        return quadratic_predict(s, self.a / self.cp, self.b / self.cp,
                                 self.c)

    def steady_time(self, s) -> np.ndarray:
        """Steady-state 1F1B contribution of one sequence: with pp stages
        in flight, each new micro-batch occupies the pipeline for ~t/pp."""
        return self.seq_time(s) / self.pp

    def batch_time(self, seqlens: Sequence[int]) -> float:
        """1F1B estimate: steady-state contributions + warmup/cooldown of
        (pp-1) stage-slots of the longest sequence (reference
        static_strategy_time_cost)."""
        if len(seqlens) == 0:
            return 0.0
        t = float(np.sum(self.steady_time(seqlens)))
        return t + float(self.seq_time(max(seqlens))) \
            * (self.pp - 1) / self.pp


# ---------------------------------------------------------------------------
# dynamic dispatch: sequences -> groups
# ---------------------------------------------------------------------------

def dynamic_dispatch(strategies: Sequence[DispatchStrategy],
                     batch_seqlens: np.ndarray,
                     use_ilp: Optional[bool] = None,
                     time_limit: float = 5.0) -> List[List[int]]:
    """Assign every sequence to a strategy group minimizing the makespan.

    Returns per-strategy lists of sequence indices.  Sequences may only go
    to groups whose ``max_seqlen`` admits them (reference
    dynamic_strategy's J bound).  Exact path: scipy MILP; fallback: LPT
    greedy (longest sequence first onto the least-loaded eligible group).
    """
    seqlens = np.asarray(batch_seqlens).reshape(-1)
    B, G = len(seqlens), len(strategies)
    eligible = [[j for j, st in enumerate(strategies)
                 if seqlens[i] <= st.max_seqlen] for i in range(B)]
    for i, e in enumerate(eligible):
        if not e:
            raise ValueError(f"sequence {i} of length {seqlens[i]} exceeds "
                             f"every strategy's max_seqlen")
    if use_ilp is not False:
        res = _dispatch_milp(strategies, seqlens, eligible, time_limit)
        if res is not None:
            return res
        if use_ilp is True:
            raise RuntimeError("MILP dispatch unavailable or infeasible")
    return _dispatch_greedy(strategies, seqlens, eligible)


def _dispatch_greedy(strategies, seqlens, eligible) -> List[List[int]]:
    """LPT onto the group whose batch_time grows least (objective
    identical to batch_time: steady-state + pipeline warmup)."""
    G = len(strategies)
    steady = np.zeros(G)
    max_t = np.zeros(G)
    out: List[List[int]] = [[] for _ in range(G)]

    def group_time(j, extra_steady=0.0, extra_t=0.0):
        st = strategies[j]
        mt = max(max_t[j], extra_t)
        return steady[j] + extra_steady + mt * (st.pp - 1) / st.pp

    order = np.argsort(-seqlens)
    for i in order:
        costs = []
        for j in eligible[i]:
            t = float(strategies[j].seq_time(seqlens[i]))
            costs.append(group_time(j, t / strategies[j].pp, t))
        j = eligible[i][int(np.argmin(costs))]
        t = float(strategies[j].seq_time(seqlens[i]))
        out[j].append(int(i))
        steady[j] += t / strategies[j].pp
        max_t[j] = max(max_t[j], t)
    for g in out:
        g.sort()
    return out


def _dispatch_milp(strategies, seqlens, eligible, time_limit
                   ) -> Optional[List[List[int]]]:
    """Exact makespan minimization over the batch_time objective
    (mirrors the reference's PuLP formulation with its Y_j max-seqlen
    auxiliaries, dynamic_pulp.py:50-60):

        min Z
        s.t. sum_j m_ij = 1                                    (assign)
             Y_j >= t_ij m_ij                                  (group max)
             sum_i (t_ij/pp_j) m_ij + ((pp_j-1)/pp_j) Y_j <= Z (load)
    """
    try:
        from scipy.optimize import Bounds, LinearConstraint, milp
        from scipy.sparse import lil_matrix
    except ImportError:  # pragma: no cover - scipy is baked in
        return None
    B, G = len(seqlens), len(strategies)
    # variables: m_ij (B*G binary), Y_j (G continuous), Z
    nv = B * G + G + 1
    iY = B * G
    iZ = B * G + G
    t = np.zeros((B, G))
    for i in range(B):
        for j in eligible[i]:
            t[i, j] = float(strategies[j].seq_time(seqlens[i]))
    cost = np.zeros(nv)
    cost[iZ] = 1.0  # minimize Z
    nc = B + B * G + G
    A = lil_matrix((nc, nv))
    lb = np.zeros(nc)
    ub = np.zeros(nc)
    row = 0
    for i in range(B):  # assignment: sum_j m_ij == 1 over eligible j
        for j in eligible[i]:
            A[row, i * G + j] = 1.0
        lb[row] = ub[row] = 1.0
        row += 1
    for i in range(B):  # group max: t_ij m_ij - Y_j <= 0
        for j in range(G):
            if t[i, j] > 0:
                A[row, i * G + j] = t[i, j]
                A[row, iY + j] = -1.0
                lb[row] = -np.inf
                ub[row] = 0.0
            row += 1
    for j in range(G):  # load: sum_i (t_ij/pp) m_ij + ((pp-1)/pp) Y_j <= Z
        pp = strategies[j].pp
        for i in range(B):
            if t[i, j] > 0:
                A[row, i * G + j] = t[i, j] / pp
        A[row, iY + j] = (pp - 1) / pp
        A[row, iZ] = -1.0
        lb[row] = -np.inf
        ub[row] = 0.0
        row += 1
    integrality = np.zeros(nv)
    integrality[:B * G] = 1
    bounds_lb = np.zeros(nv)
    bounds_ub = np.full(nv, np.inf)
    bounds_ub[:B * G] = 1.0
    # forbid ineligible assignments
    for i in range(B):
        for j in range(G):
            if j not in eligible[i]:
                bounds_ub[i * G + j] = 0.0
    try:
        res = milp(c=cost,
                   constraints=LinearConstraint(A.tocsr(), lb, ub),
                   integrality=integrality,
                   bounds=Bounds(bounds_lb, bounds_ub),
                   options={"time_limit": time_limit})
    except Exception:
        return None
    if res is None or not res.success or res.x is None:
        return None
    m = np.round(res.x[:B * G]).reshape(B, G)
    out: List[List[int]] = [[] for _ in range(G)]
    for i in range(B):
        out[int(np.argmax(m[i]))].append(i)
    return out


def static_dispatch(strategies: Sequence[DispatchStrategy],
                    length_counts: Sequence[Tuple[int, int]]
                    ) -> List[Tuple[int, int]]:
    """Offline (static) dispatch (reference ``strategy/static.py``):
    given the dataset's seqlen histogram ``[(length, count), ...]``,
    assign contiguous length RANGES to strategies once, instead of
    re-solving per iteration.

    Strategies are ordered by ``max_seqlen`` ascending; a bottleneck DP
    picks the range boundaries minimizing the max per-strategy load.
    Returns per-strategy (lo, hi] length bounds (lo == hi for unused
    strategies).
    """
    order = sorted(range(len(strategies)),
                   key=lambda j: strategies[j].max_seqlen)
    G = len(order)
    buckets = sorted(length_counts)
    L = len(buckets)
    if buckets and buckets[-1][0] > strategies[order[-1]].max_seqlen:
        raise ValueError(
            f"longest sequence {buckets[-1][0]} exceeds every strategy's "
            f"max_seqlen")
    INF = float("inf")

    def load(j, a, b):  # strategy j handles buckets [a, b)
        st = strategies[order[j]]
        if b > a and buckets[b - 1][0] > st.max_seqlen:
            return INF
        return sum(float(st.steady_time(s)) * c for s, c in buckets[a:b])

    # f[a][j]: min bottleneck covering buckets [a:] with strategies j..G-1
    f = np.full((L + 1, G + 1), INF)
    cut = np.full((L + 1, G + 1), -1, np.int64)
    f[L, :] = 0.0
    for j in range(G - 1, -1, -1):
        for a in range(L, -1, -1):
            for b in range(a, L + 1):
                c = max(load(j, a, b), f[b, j + 1])
                if c < f[a, j]:
                    f[a, j] = c
                    cut[a, j] = b
    if not np.isfinite(f[0, 0]):
        raise ValueError("no feasible static assignment")
    ranges = []
    a = 0
    for j in range(G):
        b = int(cut[a, j]) if np.isfinite(f[a, j]) and cut[a, j] >= 0 else a
        lo = buckets[a - 1][0] if a > 0 else 0
        hi = buckets[b - 1][0] if b > a else lo
        ranges.append((lo, hi))
        a = b
    # un-sort back to the caller's strategy order
    out: List[Tuple[int, int]] = [None] * G  # type: ignore
    for pos, j in enumerate(order):
        out[j] = ranges[pos]
    return out


# ---------------------------------------------------------------------------
# per-group micro-batching + packing
# ---------------------------------------------------------------------------

def solve_micro_batches(seqlens: Sequence[int], strategy: DispatchStrategy,
                        num_micro_batches: int) -> List[List[int]]:
    """Split a group's sequences into v balanced micro-batches (reference
    solve_v_micro_batches): sort by length, contiguous bottleneck-DP
    partition on the per-seq cost."""
    if not seqlens:
        return [[] for _ in range(num_micro_batches)]
    idx = sorted(range(len(seqlens)), key=lambda i: seqlens[i])
    costs = [float(strategy.seq_time(seqlens[i])) for i in idx]
    v = min(num_micro_batches, len(idx))
    _, parts = solve_pipeline_partition(costs, v)
    out = [[idx[i] for i in part] for part in parts]
    # fixed arity: always exactly num_micro_batches lists (1F1B schedules
    # expect the same v across all dp groups)
    out += [[] for _ in range(num_micro_batches - len(out))]
    return out


def batching_strategy(seqlens: Sequence[int], max_seqlen: int,
                      alignment: int = 128) -> np.ndarray:
    """Pack a group's sequences into rows of ``max_seqlen`` (first-fit
    decreasing); returns the 0/1 batching-option matrix [rows, seqs]
    consumed by :meth:`hetu_tpu.data.Bucket.pack_data` (reference
    batching_strategy, dynamic_pulp.py:124)."""
    from ..data.bucket import ffd_pack
    rows = ffd_pack(seqlens, max_seqlen, alignment)
    mat = np.zeros((len(rows), len(seqlens)), np.int8)
    for ri, r in enumerate(rows):
        for i in r:
            mat[ri, i] = 1
    return mat


# ---------------------------------------------------------------------------
# strategy pool generation
# ---------------------------------------------------------------------------

def max_seqlen_for(tp: int, pp: int, cluster: ClusterSpec,
                   hidden: int, num_layers: int, cp: int = 1,
                   bytes_per_token_act: Optional[float] = None,
                   mem_fraction: float = 0.9,
                   alignment: int = 128) -> int:
    """Longest admissible sequence under a (tp, pp, cp) layout: activation
    memory per token is linear in s (reference strategy_max_seqlen's
    linear memory regression), params take the rest of HBM; ring-attention
    CP shards the per-token activations across cp ranks.  The bound is
    aligned DOWN so every admitted length survives aligned packing."""
    chip = cluster.chip
    budget = chip.hbm_bytes * mem_fraction
    layers_here = max(1, num_layers // pp)
    param_bytes = layers_here * (12 * hidden * hidden) * 2 / tp
    opt_bytes = param_bytes * 7  # grads + adam states
    act_per_token = bytes_per_token_act if bytes_per_token_act is not None \
        else layers_here * 18 * hidden * 2 / tp
    act_per_token /= cp
    free = budget - param_bytes - opt_bytes
    if free <= 0:
        return 0
    return int(free / act_per_token) // alignment * alignment


def generate_strategy_pool(cluster: ClusterSpec, hidden: int,
                           num_layers: int,
                           layouts: Optional[Sequence[Sequence[int]]]
                           = None,
                           flops_coeff: Optional[Tuple[float, float, float]]
                           = None) -> List[DispatchStrategy]:
    """Candidate (tp, pp[, cp]) layouts with cost coefficients and
    memory-bounded max seqlens (reference generate_strategy.py).

    ``flops_coeff``, when given, is the (a, b, c) fit of a tp=1 profile;
    it is rescaled by each layout's tp (cp scaling happens in
    ``seq_time``)."""
    n = cluster.total_chips
    if layouts is None:
        layouts = []
        tp = 1
        while tp <= min(8, n):
            pp = 1
            while tp * pp <= n:
                layouts.append((tp, pp))
                pp *= 2
            tp *= 2
    pool = []
    for layout in layouts:
        tp, pp = layout[0], layout[1]
        cp = layout[2] if len(layout) > 2 else 1
        ms = max_seqlen_for(tp, pp, cluster, hidden, num_layers, cp=cp)
        if ms <= 0:
            continue
        if flops_coeff is not None:
            a0, b0, c = flops_coeff
            a, b = a0 / tp, b0 / tp
        else:
            # analytic: attention quadratic term + matmul linear term,
            # scaled down by tp (sharded) and unchanged by pp (per-stage
            # work overlaps in 1F1B steady state)
            chip = cluster.chip
            eff = chip.peak_flops * chip.mxu_efficiency * tp
            a = 12.0 * hidden * num_layers / eff
            b = 72.0 * hidden * hidden * num_layers / eff
            c = 1e-4
        pool.append(DispatchStrategy(tp=tp, pp=pp, cp=cp, a=a, b=b, c=c,
                                     max_seqlen=ms))
    return pool
