"""Galvatron-style auto-parallel search over a TPU mesh.

Capability counterpart of the reference's Galvatron search engine
(``tools/Galvatron/galvatron/core/hybrid_parallel_config.py:13``
``get_hybrid_parallel_configs_api`` + the C++ DP core): enumerate global
(pp, tp, dp) decompositions of the chip grid, partition layers into
pipeline stages, then per-layer DP over (dp, tp, zero, recompute)
strategy candidates under the per-chip HBM budget — emitting a
reference-style ``ds_parallel_config`` JSON
(``examples/gpt/ds_parallel_config/generate_gpt_3d_config.py``).
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import (ClusterSpec, LayerSpec, Strategy, grad_sync_time,
                         layer_memory, layer_time, pipeline_time)
from .dp_solver import solve_layer_strategies, solve_pipeline_partition

MEM_UNITS = 64  # memory discretization granularity for the DP


@dataclasses.dataclass
class PlanResult:
    """The chosen hybrid-parallel plan."""
    time: float
    pp: int
    stages: List[List[int]]              # layer indices per stage
    layer_strategies: List[Strategy]     # one per layer
    num_microbatches: int
    cluster: ClusterSpec

    def describe(self) -> str:
        lines = [f"pp={self.pp} m={self.num_microbatches} "
                 f"est_step_time={self.time * 1e3:.2f}ms"]
        for si, stage in enumerate(self.stages):
            sts = {str(self.layer_strategies[i]) for i in stage}
            lines.append(f"  stage{si}: layers {stage[0]}..{stage[-1]} "
                         f"{sorted(sts)}")
        return "\n".join(lines)

    def to_ds_parallel_config(self, layer_names: Optional[Sequence[str]]
                              = None) -> Dict:
        """Reference-style JSON ds_parallel_config (per-layer split/dup/
        device_group_union/zero/recompute keys, parseable by
        :func:`hetu_tpu.nn.parallel.config2ds`)."""
        chips = list(range(self.cluster.total_chips))
        per_stage = len(chips) // self.pp
        out: Dict = {"pp": self.pp, "num_layers": {}, "layers": {}}
        for si, stage in enumerate(self.stages):
            group = [chips[si * per_stage:(si + 1) * per_stage]]
            for li in stage:
                st = self.layer_strategies[li]
                name = (layer_names[li] if layer_names is not None
                        else f"blocks{li}")

                def _w(split):
                    # matches generate_gpt_3d_config's schema: column-
                    # parallel weights split dim 1, row-parallel dim 0,
                    # norms duplicated over the whole stage group
                    return {
                        "type": "variable",
                        "split": split,
                        "dup": ([st.dp] if split else [st.dp * st.tp]),
                        "device_group_union": group,
                        "zero": st.zero > 0,
                        # full searched level (0-3), recorded for
                        # downstream tooling (ds_config.parse_layout
                        # surfaces it); the bool "zero" stays the
                        # reference-schema ds flag
                        "zero_stage": int(st.zero),
                        "recompute": st.recompute,
                    }

                out["layers"][name] = {
                    "layernorm1": _w({}),
                    "attn": {"qkv": _w({"1": [st.tp]}),
                             "dense": _w({"0": [st.tp]})},
                    "layernorm2": _w({}),
                    "mlp": {"dense_h_to_4h": _w({"1": [st.tp]}),
                            "dense_4h_to_h": _w({"0": [st.tp]})},
                }
        return out


class SearchEngine:
    """Search (pp, per-layer dp/tp/zero/ckpt) for a layer chain.

    ``layers`` describe per-micro-batch costs; ``global_batch`` /
    ``micro_batch`` set the schedule length per DP shard.
    """

    def __init__(self, cluster: ClusterSpec, layers: Sequence[LayerSpec],
                 global_batch: int, micro_batch: int,
                 mem_fraction: float = 0.9,
                 allow_recompute: bool = True,
                 allow_zero: bool = True,
                 max_tp: Optional[int] = None):
        self.cluster = cluster
        self.layers = list(layers)
        self.global_batch = global_batch
        self.micro_batch = micro_batch
        self.mem_cap = cluster.chip.hbm_bytes * mem_fraction
        self.allow_recompute = allow_recompute
        self.allow_zero = allow_zero
        self.max_tp = max_tp or cluster.num_chips

    # -- candidate (dp, tp) decompositions of a stage's chips --------------

    def _layouts(self, chips: int) -> List[Tuple[int, int]]:
        out = []
        tp = 1
        while tp <= min(chips, self.max_tp):
            if chips % tp == 0:
                out.append((chips // tp, tp))
            tp *= 2
        return out

    def _mem_variants(self, dp: int, tp: int) -> List[Strategy]:
        """Per-layer choices for a fixed (dp, tp) layout: ZeRO stage and
        recompute flag — the per-layer degrees of freedom Galvatron's DP
        optimizes (sdp/ckpt columns of its strategy table)."""
        zeros = [0, 1, 2] if (self.allow_zero and dp > 1) else [0]
        ckpts = [False, True] if self.allow_recompute else [False]
        return [Strategy(dp=dp, tp=tp, zero=z, recompute=ck)
                for z, ck in itertools.product(zeros, ckpts)]

    # -- main search -------------------------------------------------------

    def search(self, pp_options: Optional[Sequence[int]] = None
               ) -> PlanResult:
        total = self.cluster.total_chips
        if pp_options is None:
            pp_options = [p for p in (1, 2, 4, 8, 16, 32)
                          if p <= min(total, len(self.layers))
                          and total % p == 0]
        best: Optional[PlanResult] = None
        for pp in pp_options:
            plan = self._search_pp(pp)
            if plan is not None and (best is None or plan.time < best.time):
                best = plan
        if best is None:
            raise RuntimeError(
                "no feasible plan found: model does not fit in HBM under "
                "any searched configuration")
        return best

    def _search_pp(self, pp: int) -> Optional[PlanResult]:
        chips_per_stage = self.cluster.total_chips // pp
        best: Optional[PlanResult] = None
        for dp, tp in self._layouts(chips_per_stage):
            plan = self._search_layout(pp, dp, tp)
            if plan is not None and (best is None or plan.time < best.time):
                best = plan
        return best

    def _search_layout(self, pp: int, dp: int, tp: int
                       ) -> Optional[PlanResult]:
        """Evaluate one global (pp, dp, tp) decomposition; per-layer DP
        chooses the ZeRO stage + recompute flag under the HBM budget."""
        cands = self._mem_variants(dp, tp)
        L, S = len(self.layers), len(cands)
        if self.global_batch < self.micro_batch * dp:
            return None
        m = max(1, self.global_batch // (self.micro_batch * dp))

        # stage partition on per-micro-batch costs for this layout
        base = [layer_time(l, Strategy(dp=dp, tp=tp), self.cluster,
                           include_grad_sync=False, dp_splits_batch=False)
                for l in self.layers]
        comm = [l.boundary_bytes / self.cluster.chip.ici_bw
                for l in self.layers]
        try:
            _, stages = solve_pipeline_partition(base, pp, comm)
        except AssertionError:
            return None

        # per-stage DP over memory-saving variants under the HBM budget
        unit = self.mem_cap / MEM_UNITS
        strategies: List[Strategy] = [None] * L  # type: ignore
        stage_times = []
        for stage in stages:
            mem = np.zeros((len(stage), S), np.int32)
            intra = np.zeros((len(stage), S))
            inter = np.zeros((len(stage), S, S))  # same layout: no reshard
            for i, li in enumerate(stage):
                lay = self.layers[li]
                for s, st in enumerate(cands):
                    need = layer_memory(lay, st, self.cluster,
                                        num_microbatches=min(m, pp),
                                        dp_splits_batch=False)
                    # over-budget layers stay infeasible (> inclusive cap)
                    mem[i, s] = min(MEM_UNITS + 1,
                                    int(math.ceil(need / unit)))
                    # per-micro-batch compute + the once-per-step grad
                    # sync amortized over the schedule length
                    intra[i, s] = layer_time(lay, st, self.cluster,
                                             include_grad_sync=False,
                                             dp_splits_batch=False) \
                        + grad_sync_time(lay, st, self.cluster) / m
            cost, picks = solve_layer_strategies(mem, intra, inter,
                                                 MEM_UNITS)
            if picks is None:
                return None
            for i, li in enumerate(stage):
                strategies[li] = cands[picks[i]]
            stage_times.append(cost)

        boundary = max(l.boundary_bytes for l in self.layers)
        t = pipeline_time(stage_times, m, boundary, self.cluster)
        return PlanResult(time=t, pp=pp, stages=stages,
                          layer_strategies=strategies, num_microbatches=m,
                          cluster=self.cluster)
