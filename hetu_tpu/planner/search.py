"""Galvatron-style auto-parallel search over a TPU mesh.

Capability counterpart of the reference's Galvatron search engine
(``tools/Galvatron/galvatron/core/hybrid_parallel_config.py:13``
``get_hybrid_parallel_configs_api`` + the C++ DP core): enumerate global
(pp, tp, dp) decompositions of the chip grid, partition layers into
pipeline stages, then per-layer DP over (dp, tp, zero, recompute)
strategy candidates under the per-chip HBM budget — emitting a
reference-style ``ds_parallel_config`` JSON
(``examples/gpt/ds_parallel_config/generate_gpt_3d_config.py``).
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import (ClusterSpec, LayerSpec, Strategy,
                         embedding_layer_spec, grad_sync_time, layer_memory,
                         layer_time, pipeline_time, transformer_layer_spec)
from .dp_solver import solve_layer_strategies, solve_pipeline_partition

MEM_UNITS = 64  # memory discretization granularity for the DP


@dataclasses.dataclass
class PlanResult:
    """The chosen hybrid-parallel plan."""
    time: float
    pp: int
    stages: List[List[int]]              # layer indices per stage
    layer_strategies: List[Strategy]     # one per layer
    num_microbatches: int
    cluster: ClusterSpec
    micro_batch: Optional[int] = None    # set by plan_for_gpt's mb sweep

    def describe(self) -> str:
        lines = [f"pp={self.pp} m={self.num_microbatches} "
                 f"est_step_time={self.time * 1e3:.2f}ms"]
        for si, stage in enumerate(self.stages):
            sts = {str(self.layer_strategies[i]) for i in stage}
            lines.append(f"  stage{si}: layers {stage[0]}..{stage[-1]} "
                         f"{sorted(sts)}")
        return "\n".join(lines)

    def to_ds_parallel_config(self, layer_names: Optional[Sequence[str]]
                              = None) -> Dict:
        """Reference-style JSON ds_parallel_config (per-layer split/dup/
        device_group_union/zero/recompute keys, parseable by
        :func:`hetu_tpu.nn.parallel.config2ds`)."""
        chips = list(range(self.cluster.total_chips))
        per_stage = len(chips) // self.pp
        out: Dict = {"pp": self.pp, "num_layers": {}, "layers": {}}
        for si, stage in enumerate(self.stages):
            group = [chips[si * per_stage:(si + 1) * per_stage]]
            for li in stage:
                st = self.layer_strategies[li]
                name = (layer_names[li] if layer_names is not None
                        else f"blocks{li}")

                def _w(split):
                    # matches generate_gpt_3d_config's schema: column-
                    # parallel weights split dim 1, row-parallel dim 0,
                    # norms duplicated over the whole stage group
                    return {
                        "type": "variable",
                        "split": split,
                        "dup": ([st.dp] if split else [st.dp * st.tp]),
                        "device_group_union": group,
                        "zero": st.zero > 0,
                        # full searched level (0-3), recorded for
                        # downstream tooling (ds_config.parse_layout
                        # surfaces it); the bool "zero" stays the
                        # reference-schema ds flag
                        "zero_stage": int(st.zero),
                        "recompute": st.recompute,
                    }

                out["layers"][name] = {
                    "layernorm1": _w({}),
                    "attn": {"qkv": _w({"1": [st.tp]}),
                             "dense": _w({"0": [st.tp]})},
                    "layernorm2": _w({}),
                    "mlp": {"dense_h_to_4h": _w({"1": [st.tp]}),
                            "dense_4h_to_h": _w({"0": [st.tp]})},
                }
        return out


def gpt_layer_chain(cfg, global_batch: int, seq: int,
                    dtype_bytes: int) -> List[LayerSpec]:
    """The GPT model as the planner's layer chain: embedding +
    transformer blocks + untied LM head ([h, V] matmul per token)."""
    layers = [embedding_layer_spec(global_batch, seq, cfg.hidden_size,
                                   cfg.vocab_size, dtype_bytes, name="wte")]
    layers += [transformer_layer_spec(global_batch, seq, cfg.hidden_size,
                                      cfg.ffn_size, dtype_bytes,
                                      name=f"block{i}")
               for i in range(cfg.num_layers)]
    layers.append(LayerSpec(
        name="lm_head", flops=2.0 * global_batch * seq * cfg.hidden_size
        * cfg.vocab_size,
        param_bytes=cfg.vocab_size * cfg.hidden_size * dtype_bytes,
        act_bytes=global_batch * seq * cfg.hidden_size * dtype_bytes,
        act_io_bytes=global_batch * seq * cfg.hidden_size * dtype_bytes,
        boundary_bytes=global_batch * seq * cfg.hidden_size * dtype_bytes))
    return layers


#: the hand-written gate-family layouts (pp, dp, tp) of the analysis
#: CI gate, expressed on an 8-chip grid — what an engineer would write
#: down without the search.  hand_plan_times scores them with the SAME
#: calibrated cost model the search ranks candidates with, so "the
#: planner beats every hand plan" is a like-for-like comparison.
HAND_PLANS = {
    "dp8_zero2_flat": (1, 8, 1),        # gate_train: pure-dp ZeRO-2
    "dp2_tp4_sp": (1, 2, 4),            # gate_tp: Megatron-SP
    "pp4_dp2": (4, 2, 1),               # gate_pipe: 4-stage pipeline
    "pp2_dp2_tp2": (2, 2, 2),           # gate_pipe_mpmd submesh shape
}


def hand_plan_times(cfg, global_batch: int, seq: int, n_chips: int,
                    plans: Optional[Dict[str, Tuple[int, int, int]]]
                    = None,
                    cluster: Optional[ClusterSpec] = None,
                    micro_batch_options=None,
                    mem_fraction: float = 0.9,
                    memory_calibration=None,
                    time_calibration="auto") -> Dict[str, float]:
    """Best predicted step time of each hand-written (pp, dp, tp)
    layout, scored with the calibrated cost model — each hand plan
    still gets the per-layer ZeRO/recompute DP and the micro-batch
    sweep (its best possible showing), so beating it means beating the
    layout, not a strawman.  Infeasible layouts (don't fit HBM, don't
    divide the chip grid) are omitted from the result."""
    import jax
    from .cost_model import (CHIPS, ChipSpec, calibrate_layer_time)
    from .profile_hardware import _kind_key

    if cluster is None:
        kind = getattr(jax.devices()[0], "device_kind", "")
        cluster = ClusterSpec(chip=CHIPS.get(_kind_key(kind), ChipSpec()),
                              num_chips=n_chips)
    dtype_bytes = 2 if "bf16" in str(cfg.dtype) or "bfloat16" in \
        str(cfg.dtype) else 4
    if time_calibration == "auto":
        try:
            time_calibration = calibrate_layer_time(
                dtype="bfloat16" if dtype_bytes == 2 else "float32",
                cluster=ClusterSpec(chip=cluster.chip, num_chips=1))
        except Exception:
            time_calibration = None
    layers = gpt_layer_chain(cfg, global_batch, seq, dtype_bytes)
    if micro_batch_options is None:
        micro_batch_options = sorted({
            mb for mb in (1, 2, 4, 8, 16, 32, 64)
            if mb <= global_batch and global_batch % mb == 0},
            reverse=True)
    out: Dict[str, float] = {}
    for name, (pp, dp, tp) in (plans or HAND_PLANS).items():
        if dp * tp * pp != n_chips or cfg.num_layers % pp:
            continue
        best = None
        for mb in micro_batch_options:
            eng = SearchEngine(cluster, layers, global_batch, mb,
                               mem_fraction=mem_fraction,
                               memory_calibration=memory_calibration,
                               time_calibration=time_calibration)
            if global_batch < mb * dp:
                continue
            plan = eng._search_layout(pp, dp, tp)
            if plan is not None and (best is None or plan.time < best):
                best = plan.time
        if best is not None:
            out[name] = float(best)
    return out


def plan_for_gpt(cfg, global_batch: int, seq: int, n_chips: int,
                 calibration=None, micro_batch_options=None,
                 num_slices: int = 1, mem_fraction: float = 0.9,
                 max_tp: Optional[int] = None,
                 memory_calibration="auto",
                 time_calibration="auto") -> PlanResult:
    """Close the planner loop for a GPT model: build the layer chain from
    a ``models.gpt.GPTConfig``, fold a live-hardware
    :class:`~hetu_tpu.planner.profile_hardware.Calibration` into the chip
    spec when given, and return the searched plan — the reference's
    ``get_hybrid_parallel_configs_api`` entry point
    (``tools/Galvatron/galvatron/core/hybrid_parallel_config.py:13``),
    consumed by ``bench.py`` and ``examples/train_gpt.py --auto-parallel``.

    The search covers (pp, dp, tp, zero, recompute) jointly with the
    micro-batch size (``micro_batch_options`` defaults to the powers of
    two ≤ global_batch/dp candidates the schedule allows).

    ``memory_calibration`` feeds the HBM budget check: ``"auto"``
    (default) lowers a single-layer probe in the model's dtype and
    scales the closed-form ``layer_memory`` by the static peak-HBM
    pass's measurement (``cost_model.calibrate_layer_memory``), a
    :class:`~hetu_tpu.planner.cost_model.MemoryCalibration` is used as
    given, and ``None`` keeps the uncalibrated closed form.

    ``time_calibration`` feeds the step-time scoring the same way:
    ``"auto"`` (default) runs ``cost_model.calibrate_layer_time`` on
    the same probe shape (the static FLOP/HBM roofline pass over a
    lowered single-layer train step), so the DP search ranks candidate
    plans on the counted-cost model the analysis gate cross-checks
    against ``compiled.cost_analysis()``; pass a
    :class:`~hetu_tpu.planner.cost_model.TimeCalibration` to reuse a
    measurement, or ``None`` for the uncalibrated closed form.
    """
    import jax
    from .cost_model import (CHIPS, ChipSpec, calibrate_layer_memory,
                             calibrate_layer_time)
    from .profile_hardware import _kind_key

    if calibration is not None:
        chip = calibration.to_chip_spec()
    else:
        kind = getattr(jax.devices()[0], "device_kind", "")
        chip = CHIPS.get(_kind_key(kind), ChipSpec())
    cluster = ClusterSpec(chip=chip, num_chips=max(1, n_chips // num_slices),
                          num_slices=num_slices)
    if calibration is not None and getattr(calibration, "collectives",
                                           None):
        # measured per-link alpha-beta fits feed the SAME formulas the
        # solver and the analysis step-time pass share (cost_model)
        cluster = calibration.to_cluster_spec(
            num_chips=cluster.num_chips, num_slices=num_slices)
    dtype_bytes = 2 if "bf16" in str(cfg.dtype) or "bfloat16" in \
        str(cfg.dtype) else 4
    probe_dtype = "bfloat16" if dtype_bytes == 2 else "float32"
    probe = None
    if memory_calibration == "auto" or time_calibration == "auto":
        # ONE probe trace shared by both calibrations — tracing it is
        # the dominant cost of calibrating
        from .cost_model import _layer_probe_handle
        try:
            probe = _layer_probe_handle(4, 64, 64, 256, probe_dtype,
                                        "planner_probe/layer")
        except Exception:
            probe = None
    if memory_calibration == "auto":
        # probe in the model's compute dtype so the scale carries the
        # right activation widths; failures (no jax, walk error) fall
        # back to the uncalibrated closed form rather than blocking
        try:
            memory_calibration = calibrate_layer_memory(
                dtype=probe_dtype, probe_handle=probe)
        except Exception:
            memory_calibration = None
    if time_calibration == "auto":
        try:
            time_calibration = calibrate_layer_time(
                dtype=probe_dtype,
                cluster=ClusterSpec(chip=cluster.chip, num_chips=1),
                probe_handle=probe)
        except Exception:
            time_calibration = None
    layers = gpt_layer_chain(cfg, global_batch, seq, dtype_bytes)

    if micro_batch_options is None:
        # descending so predicted-time ties keep the LARGEST micro-batch
        # (fewest micro-batches = least per-dispatch overhead on chip)
        micro_batch_options = sorted({
            mb for mb in (1, 2, 4, 8, 16, 32, 64)
            if mb <= global_batch and global_batch % mb == 0},
            reverse=True)
    # pp must divide the transformer stack (the pipelined model places
    # equal layer ranges; embed/head live outside the pipeline body)
    total = cluster.total_chips
    pp_options = [p for p in (1, 2, 4, 8, 16, 32)
                  if p <= min(total, cfg.num_layers)
                  and total % p == 0 and cfg.num_layers % p == 0]
    best: Optional[PlanResult] = None
    for mb in micro_batch_options:
        eng = SearchEngine(cluster, layers, global_batch, mb,
                           mem_fraction=mem_fraction, max_tp=max_tp,
                           memory_calibration=memory_calibration,
                           time_calibration=time_calibration)
        try:
            plan = eng.search(pp_options=pp_options)
        except RuntimeError:
            continue
        if best is None or plan.time < best.time:
            best = plan
            best.micro_batch = mb
    if best is None:
        raise RuntimeError(
            "no feasible plan found for any micro-batch size: model does "
            "not fit in HBM under any searched configuration")
    return best


def verify_plan_schedule(plan: PlanResult):
    """Cross-rank schedule verdict for a searched plan: build the
    symbolic :class:`~hetu_tpu.analysis.schedule.ProgramSpec` the plan
    implies (pp stages x dp x tp, ZeRO level, 1F1B micro-batching) and
    run the collective-schedule verifier over all its ranks.  Returns
    the violation list — empty means the plan's multi-rank program is
    hang-free BEFORE anyone commits a pod to it, which is the planner's
    side of the DESIGN.md §25 contract (a searched plan that deadlocks
    on hardware is worse than a slow one)."""
    from ..analysis.schedule import (ProgramSpec, extract_schedules,
                                     verify_schedules)
    first = plan.layer_strategies[0]
    zero = max(s.zero for s in plan.layer_strategies)
    spec = ProgramSpec(
        dp=int(first.dp), tp=int(first.tp), pp=int(plan.pp),
        zero=int(zero), flat=zero >= 2,
        num_micro_batches=max(1, int(plan.num_microbatches)),
        pipeline_mode="mpmd" if plan.pp > 1 else "none",
        layers=len(plan.layer_strategies))
    return verify_schedules(extract_schedules(spec))


def plan_summary(plan: PlanResult) -> Dict:
    """Flat JSON-able description of a plan (bench `extra` reporting)."""
    from collections import Counter
    sts = Counter(str(s) for s in plan.layer_strategies)
    first = plan.layer_strategies[0]
    return {
        "pp": plan.pp,
        "dp": first.dp,
        "tp": first.tp,
        "zero": max(s.zero for s in plan.layer_strategies),
        "recompute_layers": sum(bool(s.recompute)
                                for s in plan.layer_strategies),
        "num_layers": len(plan.layer_strategies),
        "num_microbatches": plan.num_microbatches,
        "micro_batch": getattr(plan, "micro_batch", None),
        "est_step_time_ms": round(plan.time * 1e3, 3),
        "layer_strategy_counts": dict(sts),
        "schedule_hang_free": not verify_plan_schedule(plan),
    }


class SearchEngine:
    """Search (pp, per-layer dp/tp/zero/ckpt) for a layer chain.

    ``layers`` describe per-micro-batch costs; ``global_batch`` /
    ``micro_batch`` set the schedule length per DP shard.
    """

    def __init__(self, cluster: ClusterSpec, layers: Sequence[LayerSpec],
                 global_batch: int, micro_batch: int,
                 mem_fraction: float = 0.9,
                 allow_recompute: bool = True,
                 allow_zero: bool = True,
                 max_tp: Optional[int] = None,
                 memory_calibration=None,
                 time_calibration=None):
        self.cluster = cluster
        self.layers = list(layers)
        self.global_batch = global_batch
        self.micro_batch = micro_batch
        self.mem_cap = cluster.chip.hbm_bytes * mem_fraction
        self.allow_recompute = allow_recompute
        self.allow_zero = allow_zero
        self.max_tp = max_tp or cluster.num_chips
        # analysis-backed memory model: a MemoryCalibration from
        # cost_model.calibrate_layer_memory scales every layer_memory
        # number the DP budget check sees, so the planner is constrained
        # by the same statically-validated model the CI gate pins
        self.memory_calibration = memory_calibration
        # analysis-backed time model, same stance: a TimeCalibration
        # from cost_model.calibrate_layer_time scales every layer_time
        # roofline the DP search scores, so candidate plans compete on
        # the counted-FLOP/HBM numbers the CI gate cross-checks against
        # XLA — not on an unvalidated closed form
        self.time_calibration = time_calibration

    def _layer_time(self, layer: LayerSpec, st: Strategy,
                    include_grad_sync: bool = False) -> float:
        return layer_time(layer, st, self.cluster,
                          include_grad_sync=include_grad_sync,
                          dp_splits_batch=False,
                          calibration=self.time_calibration)

    # -- candidate (dp, tp) decompositions of a stage's chips --------------

    def _layouts(self, chips: int) -> List[Tuple[int, int]]:
        out = []
        tp = 1
        while tp <= min(chips, self.max_tp):
            if chips % tp == 0:
                out.append((chips // tp, tp))
            tp *= 2
        return out

    def _mem_variants(self, dp: int, tp: int) -> List[Strategy]:
        """Per-layer choices for a fixed (dp, tp) layout: ZeRO stage and
        recompute flag — the per-layer degrees of freedom Galvatron's DP
        optimizes (sdp/ckpt columns of its strategy table)."""
        zeros = [0, 1, 2, 3] if (self.allow_zero and dp > 1) else [0]
        ckpts = [False, True] if self.allow_recompute else [False]
        return [Strategy(dp=dp, tp=tp, zero=z, recompute=ck)
                for z, ck in itertools.product(zeros, ckpts)]

    # -- main search -------------------------------------------------------

    def search(self, pp_options: Optional[Sequence[int]] = None
               ) -> PlanResult:
        total = self.cluster.total_chips
        if pp_options is None:
            pp_options = [p for p in (1, 2, 4, 8, 16, 32)
                          if p <= min(total, len(self.layers))
                          and total % p == 0]
        best: Optional[PlanResult] = None
        for pp in pp_options:
            plan = self._search_pp(pp)
            if plan is not None and (best is None or plan.time < best.time):
                best = plan
        if best is None:
            raise RuntimeError(
                "no feasible plan found: model does not fit in HBM under "
                "any searched configuration")
        return best

    def _search_pp(self, pp: int) -> Optional[PlanResult]:
        chips_per_stage = self.cluster.total_chips // pp
        best: Optional[PlanResult] = None
        for dp, tp in self._layouts(chips_per_stage):
            plan = self._search_layout(pp, dp, tp)
            if plan is not None and (best is None or plan.time < best.time):
                best = plan
        return best

    def _search_layout(self, pp: int, dp: int, tp: int
                       ) -> Optional[PlanResult]:
        """Evaluate one global (pp, dp, tp) decomposition; per-layer DP
        chooses the ZeRO stage + recompute flag under the HBM budget."""
        cands = self._mem_variants(dp, tp)
        L, S = len(self.layers), len(cands)
        if self.global_batch < self.micro_batch * dp:
            return None
        m = max(1, self.global_batch // (self.micro_batch * dp))

        # stage partition on per-micro-batch costs for this layout
        base = [self._layer_time(l, Strategy(dp=dp, tp=tp))
                for l in self.layers]
        comm = [l.boundary_bytes / self.cluster.chip.ici_bw
                for l in self.layers]
        try:
            _, stages = solve_pipeline_partition(base, pp, comm)
        except AssertionError:
            return None

        # per-stage DP over memory-saving variants under the HBM budget
        unit = self.mem_cap / MEM_UNITS
        strategies: List[Strategy] = [None] * L  # type: ignore
        stage_times = []
        for stage in stages:
            mem = np.zeros((len(stage), S), np.int32)
            intra = np.zeros((len(stage), S))
            inter = np.zeros((len(stage), S, S))  # same layout: no reshard
            for i, li in enumerate(stage):
                lay = self.layers[li]
                for s, st in enumerate(cands):
                    need = layer_memory(lay, st, self.cluster,
                                        num_microbatches=min(m, pp),
                                        dp_splits_batch=False,
                                        calibration=self.memory_calibration)
                    # over-budget layers stay infeasible (> inclusive cap)
                    mem[i, s] = min(MEM_UNITS + 1,
                                    int(math.ceil(need / unit)))
                    # per-micro-batch compute + the once-per-step grad
                    # sync amortized over the schedule length
                    intra[i, s] = self._layer_time(lay, st) \
                        + grad_sync_time(lay, st, self.cluster) / m
            cost, picks = solve_layer_strategies(mem, intra, inter,
                                                 MEM_UNITS)
            if picks is None:
                return None
            for i, li in enumerate(stage):
                strategies[li] = cands[picks[i]]
            stage_times.append(cost)

        boundary = max(l.boundary_bytes for l in self.layers)
        t = pipeline_time(stage_times, m, boundary, self.cluster)
        return PlanResult(time=t, pp=pp, stages=stages,
                          layer_strategies=strategies, num_microbatches=m,
                          cluster=self.cluster)
