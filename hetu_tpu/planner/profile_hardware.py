"""Measured hardware profiling feeding the planner / elastic cost models.

TPU-native counterpart of the reference's profiling pass
(``tools/Galvatron/galvatron/profile_hardware/profile_hardware.py``, which
shells out to nccl-tests + matmul benchmarks and writes the fitted
constants consumed by ``galvatron/core/profiler.py``).  Here the same
measurements run through jax on the live backend:

- ``profile_matmul``     — achievable matmul FLOP/s (MXU roofline point)
- ``profile_hbm``        — HBM read+write bandwidth (elementwise saxpy)
- ``profile_collectives``— alpha-beta (latency, 1/bw) fits per collective
                           over a mesh axis, via least squares on message
                           -size sweeps
- ``calibrate``          — folds the measurements into a ``ChipSpec`` /
                           ``ClusterSpec`` (replacing the datasheet
                           constants) and into the elastic
                           ``StrategyModel`` constants
                           (``layer_comm_cost``, ``pipeline_p2p_cost``)
- ``validate_step_prediction`` — predicted-vs-measured wall time of a
                           real training step (the reference validates its
                           cost model the same way before trusting the
                           search)

Results serialize to JSON so a one-off profile feeds later planner runs.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import CHIPS, ChipSpec, ClusterSpec


def _sync(x) -> None:
    import jax
    jax.block_until_ready(x)
    # remote-relay PJRT backends can no-op block_until_ready; force a
    # host fetch of one element (same trick as bench.py)
    leaf = jax.tree.leaves(x)[0]
    np.asarray(leaf.ravel()[0])


def _time_fn(fn, *args, reps: int = 5, warmup: int = 2) -> float:
    """Median wall time of fn(*args) (jitted by the caller)."""
    for _ in range(warmup):
        _sync(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


# ---------------------------------------------------------------------------
# compute / memory
# ---------------------------------------------------------------------------

def profile_matmul(sizes: Sequence[int] = (1024, 2048, 4096),
                   dtype: str = "bfloat16",
                   reps: int = 5) -> Dict[int, float]:
    """Measured FLOP/s of square matmuls (datasheet check of
    peak_flops * mxu_efficiency)."""
    import jax
    import jax.numpy as jnp
    out = {}
    for n in sizes:
        a = jnp.asarray(np.random.RandomState(0).randn(n, n), dtype)
        b = jnp.asarray(np.random.RandomState(1).randn(n, n), dtype)
        f = jax.jit(lambda a, b: a @ b)
        t = _time_fn(f, a, b, reps=reps)
        out[int(n)] = 2.0 * n ** 3 / t
    return out


def profile_hbm(nbytes: int = 1 << 28, dtype: str = "float32",
                reps: int = 5) -> float:
    """Measured HBM bandwidth (bytes/s) via y = 2*x + 1 (read + write)."""
    import jax
    import jax.numpy as jnp
    n = nbytes // np.dtype(np.float32).itemsize
    x = jnp.arange(n, dtype=dtype)
    f = jax.jit(lambda x: 2.0 * x + 1.0)
    t = _time_fn(f, x, reps=reps)
    itemsize = np.dtype(dtype).itemsize if dtype != "bfloat16" else 2
    return 2.0 * n * itemsize / t   # one read + one write


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def _fit_alpha_beta(sizes_bytes: Sequence[float],
                    times: Sequence[float]) -> Tuple[float, float]:
    """Least-squares fit t = alpha + beta * bytes; clamped to >= 0."""
    A = np.stack([np.ones(len(sizes_bytes)), np.asarray(sizes_bytes)], 1)
    (alpha, beta), *_ = np.linalg.lstsq(A, np.asarray(times), rcond=None)
    return max(0.0, float(alpha)), max(0.0, float(beta))


def profile_collectives(mesh, axis: str,
                        sizes: Sequence[int] = (1 << 16, 1 << 20, 1 << 23),
                        dtype: str = "float32",
                        reps: int = 5) -> Dict[str, Tuple[float, float]]:
    """(alpha, beta) per collective over ``axis`` of ``mesh``:
    't = alpha + beta * message_bytes'.  Keys: all_reduce, all_gather,
    reduce_scatter, p2p (ring ppermute).  ``beta`` is seconds/byte —
    1/beta is the achieved bus bandwidth the planner's
    ``ClusterSpec.bw_for_group`` should report."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.comm import shard_map

    n = mesh.shape[axis]
    itemsize = 2 if dtype == "bfloat16" else np.dtype(dtype).itemsize

    def timed(make_fn, elems) -> float:
        x = jnp.asarray(np.random.RandomState(0).randn(n * elems)
                        .reshape(n, elems), dtype)
        f = jax.jit(shard_map(make_fn, mesh, (P(axis, None),), P(axis, None)))
        return _time_fn(f, x, reps=reps)

    perm = [(i, (i + 1) % n) for i in range(n)]
    builders = {
        "all_reduce": lambda v: lax.psum(v, axis),
        "all_gather": lambda v: lax.all_gather(
            v, axis, axis=1, tiled=True)[:, :v.shape[1]],
        "reduce_scatter": lambda v: jnp.tile(
            lax.psum_scatter(v, axis, scatter_dimension=1, tiled=True),
            (1, n)) if v.shape[1] % n == 0 else v,
        "p2p": lambda v: lax.ppermute(v, axis, perm),
    }
    out = {}
    for name, builder in builders.items():
        ts, szs = [], []
        for nb in sizes:
            elems = max(n, nb // itemsize // max(1, n) * max(1, n))
            ts.append(timed(builder, elems))
            szs.append(elems * itemsize)
        out[name] = _fit_alpha_beta(szs, ts)
    return out


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Calibration:
    """Everything the cost models consume, measured on the live backend."""
    matmul_flops: Dict[int, float] = dataclasses.field(default_factory=dict)
    hbm_bw: float = 0.0
    collectives: Dict[str, Tuple[float, float]] = \
        dataclasses.field(default_factory=dict)
    device_kind: str = "?"
    platform: str = "?"

    @property
    def best_matmul_flops(self) -> float:
        return max(self.matmul_flops.values()) if self.matmul_flops else 0.0

    def to_chip_spec(self, base: Optional[ChipSpec] = None) -> ChipSpec:
        """Fold measurements into a ChipSpec: measured matmul throughput
        replaces peak*efficiency, measured HBM bandwidth replaces the
        datasheet number, collective beta-fit replaces ici_bw."""
        base = base or CHIPS.get(_kind_key(self.device_kind), ChipSpec())
        kw: Dict = {}
        if self.best_matmul_flops:
            # keep nominal peak when it is plausible; fold the measurement
            # into mxu_efficiency (the planner multiplies them)
            if self.best_matmul_flops <= base.peak_flops:
                kw["mxu_efficiency"] = \
                    self.best_matmul_flops / base.peak_flops
            else:
                kw["peak_flops"] = self.best_matmul_flops
                kw["mxu_efficiency"] = 1.0
        if self.hbm_bw:
            kw["hbm_bw"] = self.hbm_bw
        ar = self.collectives.get("all_reduce")
        if ar:
            alpha, beta = ar
            if beta > 0:
                kw["ici_bw"] = 1.0 / beta
            kw["ici_latency"] = max(alpha, 1e-9)
        return dataclasses.replace(base, **kw)

    def to_cluster_spec(self, num_chips: int = 8, num_slices: int = 1,
                        base: Optional[ChipSpec] = None) -> ClusterSpec:
        """Fold the measurements into a full :class:`ClusterSpec`: the
        calibrated chip (:meth:`to_chip_spec`) PLUS the per-collective
        ``(alpha, beta)`` link fits, fed straight into the shared
        alpha-beta formulas (``cost_model.collective_time``) — so the
        planner's DP solver and the analysis step-time linter price
        every collective from the same measured link speeds instead of
        the datasheet ring model."""
        return ClusterSpec(
            chip=self.to_chip_spec(base),
            num_chips=max(1, int(num_chips)),
            num_slices=max(1, int(num_slices)),
            link_alpha_beta={k: (float(a), float(b))
                             for k, (a, b) in self.collectives.items()}
            if self.collectives else None)

    def elastic_constants(self, batch: int, seq: int, hidden: int,
                          ffn: int, tp: int = 2,
                          dtype_bytes: int = 2) -> Dict[str, float]:
        """Measured replacements for StrategyModel's invented
        layer_comm_cost / pipeline_p2p_cost: per-layer TP-collective and
        stage-boundary p2p time expressed in units of per-layer compute
        time at tp=1 (the solver's layer unit)."""
        from .cost_model import transformer_layer_spec
        spec = transformer_layer_spec(batch, seq, hidden, ffn, dtype_bytes)
        flops = self.best_matmul_flops or ChipSpec().peak_flops * 0.5
        layer_t = 3.0 * spec.flops / flops
        ar = self.collectives.get("all_reduce", (1e-6, 1e-11))
        p2p = self.collectives.get("p2p", (1e-6, 1e-11))
        ar_t = 4 * (ar[0] + ar[1] * spec.boundary_bytes)  # Megatron 2f+2b
        p2p_t = p2p[0] + p2p[1] * spec.boundary_bytes
        return {
            "layer_comm_cost": ar_t / max(layer_t, 1e-12),
            "pipeline_p2p_cost": p2p_t / max(layer_t, 1e-12),
        }

    def save(self, path: str) -> None:
        d = dataclasses.asdict(self)
        d["matmul_flops"] = {str(k): v for k, v in d["matmul_flops"].items()}
        with open(path, "w") as f:
            json.dump(d, f, indent=2)

    @classmethod
    def load(cls, path: str) -> "Calibration":
        with open(path) as f:
            d = json.load(f)
        d["matmul_flops"] = {int(k): v for k, v in d["matmul_flops"].items()}
        d["collectives"] = {k: tuple(v) for k, v in d["collectives"].items()}
        return cls(**d)


def _kind_key(device_kind: str) -> str:
    k = device_kind.lower()
    if "v5 lite" in k or "v5e" in k:
        return "v5e"
    if "v5p" in k or "v5" in k:
        return "v5p"
    if "v4" in k:
        return "v4"
    if "v6" in k or "trillium" in k:
        return "v6e"
    return "v5p"


def profile_and_calibrate(mesh=None, axis: Optional[str] = None,
                          matmul_sizes: Sequence[int] = (512, 1024, 2048),
                          hbm_bytes: int = 1 << 26,
                          coll_sizes: Sequence[int] = (1 << 14, 1 << 17,
                                                       1 << 20),
                          reps: int = 5) -> Calibration:
    """One-shot profiling pass (the profile_hardware entry point)."""
    import jax
    d = jax.devices()[0]
    cal = Calibration(
        matmul_flops=profile_matmul(matmul_sizes, reps=reps),
        hbm_bw=profile_hbm(hbm_bytes, reps=reps),
        device_kind=getattr(d, "device_kind", "?"),
        platform=d.platform,
    )
    if mesh is not None:
        ax = axis or mesh.axis_names[0]
        if mesh.shape[ax] > 1:
            cal.collectives = profile_collectives(mesh, ax, coll_sizes,
                                                  reps=reps)
    return cal


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def validate_step_prediction(cal: Calibration, batch: int = 4,
                             seq: int = 128, hidden: int = 128,
                             ffn: Optional[int] = None,
                             num_layers: int = 2,
                             vocab: int = 256) -> Dict[str, float]:
    """Predict a small GPT train step with the calibrated cost model, then
    measure it; returns {"predicted_s", "measured_s", "ratio"}.  The
    reference runs the same closed loop before trusting its search."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel
    from .cost_model import (Strategy, layer_time, transformer_layer_spec)

    ffn = ffn or 4 * hidden
    chip = cal.to_chip_spec()
    cluster = ClusterSpec(chip=chip, num_chips=1)
    spec = transformer_layer_spec(batch, seq, hidden, ffn, dtype_bytes=4)
    pred = num_layers * layer_time(spec, Strategy(), cluster) \
        + 3.0 * (2.0 * batch * seq * hidden * vocab) \
        / (chip.peak_flops * chip.mxu_efficiency)

    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=num_layers, num_heads=max(1, hidden // 64),
                    max_seq_len=seq, sp=False, dtype="float32")
    with ht.graph("define_and_run", create_new=True) as g:
        ids = ht.placeholder("int32", (batch, seq), name="ids")
        lbl = ht.placeholder("int32", (batch, seq), name="lbl")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, lbl)
        op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        I = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
        L = np.roll(I, -1, 1)

        def step():
            out = g.run(loss, [loss, op], {ids: I, lbl: L})
            return out[0]

        step()  # compile
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            v = step()
            np.asarray(v)
            ts.append(time.perf_counter() - t0)
    measured = float(np.median(ts))
    return {"predicted_s": float(pred), "measured_s": measured,
            "ratio": float(pred / measured) if measured else float("inf")}
