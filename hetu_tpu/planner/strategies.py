"""v1-style auto-parallel searching strategies.

Capability counterparts of the reference's
``hetu/v1/python/hetu/distributed_strategies/``: FlexFlow MCMC search
(``flexflow.py:12``), OptCNN per-layer partition DP (``optcnn.py:9``),
GPipe/PipeDream pipeline partitioners (``gpipe.py:6``, ``pipedream.py:7``)
and PipeOpt joint search (``pipeopt.py:9``) — re-expressed over the TPU
cost model (LayerSpec chains + ClusterSpec) instead of a CUDA op graph.

Every searcher returns a plain result object with the chosen layout and
its estimated cost, so callers can hand the layout to the mesh/sharding
layer (``hetu_tpu.parallel``).
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import (ClusterSpec, LayerSpec, Strategy, all_reduce_time,
                         layer_time, pipeline_time)
from .dp_solver import solve_layer_strategies, solve_pipeline_partition


@dataclasses.dataclass
class SearchResult:
    cost: float
    strategies: List[Strategy]                 # per layer
    stages: Optional[List[List[int]]] = None   # pipeline partition if any
    meta: Optional[Dict] = None


class BaseSearching:
    """Shared scaffolding (v1 BaseSearchingStrategy, base.py:230)."""

    def __init__(self, layers: Sequence[LayerSpec], cluster: ClusterSpec):
        self.layers = list(layers)
        self.cluster = cluster
        self.num_devices = cluster.total_chips

    def _device_factor_candidates(self) -> List[Strategy]:
        """All (dp, tp) factorizations of the device count."""
        n = self.num_devices
        out = []
        d = 1
        while d <= n:
            if n % d == 0:
                out.append(Strategy(dp=d, tp=n // d))
            d *= 2
        return out

    def simulate(self, strategies: Sequence[Strategy]) -> float:
        """Step-time estimate for a per-layer strategy assignment (the
        analogue of v1's HetuSimulator cost evaluation)."""
        t = 0.0
        for lay, st in zip(self.layers, strategies):
            t += layer_time(lay, st, self.cluster)
        return t

    def searching(self) -> SearchResult:
        raise NotImplementedError


class OptCNNSearching(BaseSearching):
    """OptCNN: per-layer parallelization chosen by DP with resharding
    transition costs (optcnn.py:9)."""

    def searching(self) -> SearchResult:
        cands = self._device_factor_candidates()
        L, S = len(self.layers), len(cands)
        mem = np.zeros((L, S), np.int32)  # no memory constraint here
        intra = np.zeros((L, S))
        inter = np.zeros((L, S, S))
        for i, lay in enumerate(self.layers):
            for s, st in enumerate(cands):
                intra[i, s] = layer_time(lay, st, self.cluster)
            if i > 0:
                prev = self.layers[i - 1]
                for a, sa in enumerate(cands):
                    for b, sb in enumerate(cands):
                        if sa.tp != sb.tp:
                            inter[i, a, b] = all_reduce_time(
                                prev.boundary_bytes, max(sa.tp, sb.tp),
                                self.cluster)
        cost, picks = solve_layer_strategies(mem, intra, inter, max_mem=1)
        assert picks is not None
        return SearchResult(cost, [cands[p] for p in picks])


class FlexFlowSearching(BaseSearching):
    """FlexFlow: MCMC over per-layer strategies with a simulator in the
    accept/reject loop (flexflow.py:12)."""

    def __init__(self, layers, cluster, alpha: float = 0.05,
                 round_budget: int = 500, seed: int = 0):
        super().__init__(layers, cluster)
        self.alpha = alpha
        self.round_budget = round_budget
        self.rng = random.Random(seed)

    def searching(self) -> SearchResult:
        cands = self._device_factor_candidates()
        cur = [self.rng.choice(cands) for _ in self.layers]
        cur_cost = self.simulate(cur)
        best, best_cost = list(cur), cur_cost
        for _ in range(self.round_budget):
            i = self.rng.randrange(len(self.layers))
            prop = list(cur)
            prop[i] = self.rng.choice(cands)
            c = self.simulate(prop)
            # Metropolis acceptance (minimization): alpha acts as the
            # temperature — a move that worsens cost by alpha*cur is
            # accepted with p = 1/e, larger regressions exponentially less
            if c < cur_cost or \
                    self.rng.random() < math.exp(
                        -(c - cur_cost) / (self.alpha *
                                           max(cur_cost, 1e-12))):
                cur, cur_cost = prop, c
                if c < best_cost:
                    best, best_cost = list(prop), c
        return SearchResult(best_cost, best,
                            meta={"rounds": self.round_budget})


class GPipeSearching(BaseSearching):
    """GPipe: balanced contiguous stage partition, devices split evenly
    across stages (gpipe.py:6)."""

    def __init__(self, layers, cluster, num_stages: int,
                 num_microbatches: int = 4):
        super().__init__(layers, cluster)
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches

    def searching(self) -> SearchResult:
        per_stage = max(1, self.num_devices // self.num_stages)
        st = Strategy(dp=1, tp=per_stage)
        costs = [layer_time(l, st, self.cluster) for l in self.layers]
        comm = [l.boundary_bytes / self.cluster.chip.ici_bw
                for l in self.layers]
        bottleneck, stages = solve_pipeline_partition(
            costs, self.num_stages, comm)
        boundary = max(l.boundary_bytes for l in self.layers)
        total = pipeline_time([sum(costs[i] for i in sg) for sg in stages],
                              self.num_microbatches, boundary, self.cluster)
        return SearchResult(total, [st] * len(self.layers), stages=stages)


class PipeDreamSearching(BaseSearching):
    """PipeDream: stage partition with per-stage replication — each stage
    may be replicated across several devices with the weight-sync
    (allreduce) cost folded in (pipedream.py:7).  Classic interval DP."""

    def __init__(self, layers, cluster, num_microbatches: int = 4):
        super().__init__(layers, cluster)
        self.num_microbatches = num_microbatches

    def searching(self) -> SearchResult:
        L, N = len(self.layers), self.num_devices
        base = [layer_time(l, Strategy(), self.cluster)
                for l in self.layers]
        prefix = np.concatenate([[0.0], np.cumsum(base)])
        params = [l.param_bytes for l in self.layers]
        pparam = np.concatenate([[0.0], np.cumsum(params)])

        def stage_cost(a, b, m):  # layers [a,b) replicated on m devices
            t = (prefix[b] - prefix[a]) / m
            if m > 1:
                t += all_reduce_time((pparam[b] - pparam[a]) * 2, m,
                                     self.cluster)
            return t

        INF = float("inf")
        # replication counts restricted to powers of two (keeps the DP at
        # O(L^2 N log N) instead of O(L^2 N^2) for big clusters)
        repl_opts = []
        m = 1
        while m <= N:
            repl_opts.append(m)
            m *= 2
        # f[t][n]: min bottleneck using first t layers on n devices
        f = np.full((L + 1, N + 1), INF)
        back: Dict[Tuple[int, int], Tuple[int, int]] = {}
        f[0, 0] = 0.0
        for t in range(1, L + 1):
            for n in range(1, N + 1):
                for j in range(t):
                    for m in repl_opts:
                        if m > n or not np.isfinite(f[j, n - m]):
                            continue
                        c = max(f[j, n - m], stage_cost(j, t, m))
                        if c < f[t, n]:
                            f[t, n] = c
                            back[(t, n)] = (j, m)
        # allow using <= N devices
        n_best = int(np.argmin(f[L, 1:])) + 1
        bottleneck = float(f[L, n_best])
        # reconstruct stages + replication
        stages, repl = [], []
        t, n = L, n_best
        while t > 0:
            j, m = back[(t, n)]
            stages.append(list(range(j, t)))
            repl.append(m)
            t, n = j, n - m
        stages.reverse()
        repl.reverse()
        strategies = [None] * L
        for sg, m in zip(stages, repl):
            for i in sg:
                strategies[i] = Strategy(dp=m, tp=1)
        boundary = max(l.boundary_bytes for l in self.layers)
        total = pipeline_time(
            [stage_cost(sg[0], sg[-1] + 1, m)
             for sg, m in zip(stages, repl)],
            self.num_microbatches, boundary, self.cluster)
        return SearchResult(total, strategies, stages=stages,
                            meta={"replication": repl,
                                  "bottleneck": bottleneck,
                                  "devices_used": n_best})


class PipeOptSearching(BaseSearching):
    """PipeOpt: jointly search the stage count and partition, picking the
    best end-to-end pipeline estimate (pipeopt.py:9)."""

    def __init__(self, layers, cluster, num_microbatches: int = 4,
                 stage_options: Optional[Sequence[int]] = None):
        super().__init__(layers, cluster)
        self.num_microbatches = num_microbatches
        self.stage_options = stage_options

    def searching(self) -> SearchResult:
        opts = self.stage_options or [
            p for p in (1, 2, 4, 8, 16)
            if p <= min(self.num_devices, len(self.layers))
            and self.num_devices % p == 0]
        best: Optional[SearchResult] = None
        for p in opts:
            r = GPipeSearching(self.layers, self.cluster, p,
                               self.num_microbatches).searching()
            r.meta = {"num_stages": p}
            if best is None or r.cost < best.cost:
                best = r
        assert best is not None
        return best
