"""Dynamic-programming solvers for the auto-parallel planner.

Wraps the native core (``hetu_tpu/csrc/dp_core.cc``, the TPU counterpart
of the reference's ``tools/Galvatron/csrc/dp_core.cpp:23``
``dynamic_programming_core``) with ctypes, falling back to equivalent
pure-numpy implementations when no compiler is available.
"""
from __future__ import annotations

import ctypes
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..csrc.build import load_dp_core


def _as_c(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# per-layer strategy selection under memory budget (knapsack-style DP)
# ---------------------------------------------------------------------------

def solve_layer_strategies(mem_cost: np.ndarray, intra_cost: np.ndarray,
                           inter_cost: np.ndarray, max_mem: int,
                           use_native: Optional[bool] = None
                           ) -> Tuple[float, Optional[List[int]]]:
    """Choose one strategy per layer minimizing total time subject to the
    discretized memory budget (inclusive: total memory == max_mem fits).

    mem_cost   [L, S] int    memory units per layer/strategy
    intra_cost [L, S] float  per-layer time
    inter_cost [L, S, S]     transition (resharding) time between layers
    Returns (total_cost, per-layer strategy indices) or (inf, None).
    """
    L, S = mem_cost.shape
    mem_cost = np.ascontiguousarray(mem_cost, np.int32)
    intra_cost = np.ascontiguousarray(intra_cost, np.float64)
    inter_cost = np.ascontiguousarray(inter_cost, np.float64)
    assert intra_cost.shape == (L, S) and inter_cost.shape == (L, S, S)

    lib = load_dp_core() if use_native is not False else None
    if lib is not None:
        res = np.zeros(L, np.int32)
        total = lib.hetu_dp_strategy_solve(
            L, int(max_mem), S, _as_c(mem_cost, ctypes.c_int32),
            _as_c(intra_cost, ctypes.c_double),
            _as_c(inter_cost, ctypes.c_double), _as_c(res, ctypes.c_int32))
        if math.isinf(total):
            return float("inf"), None
        return float(total), res.tolist()
    return _solve_layer_strategies_py(mem_cost, intra_cost, inter_cost,
                                      int(max_mem))


def _solve_layer_strategies_py(mem_cost, intra_cost, inter_cost, max_mem):
    L, S = mem_cost.shape
    INF = float("inf")
    M = max_mem + 1  # states 0..max_mem inclusive
    f = np.zeros((M, S))
    choice = np.full((L, M, S), -1, np.int32)
    for i in range(L):
        nf = np.full((M, S), INF)
        for v in range(M - 1, -1, -1):
            for s in range(S):
                need = mem_cost[i, s]
                if v < need:
                    continue
                cand = f[v - need, :] + inter_cost[i, :, s]
                si = int(np.argmin(cand))
                if np.isfinite(cand[si]):
                    choice[i, v, s] = si
                    nf[v, s] = cand[si] + intra_cost[i, s]
        f = nf
    s = int(np.argmin(f[M - 1]))
    total = f[M - 1, s]
    if not np.isfinite(total):
        return INF, None
    res = [0] * L
    v = M - 1
    res[L - 1] = s
    for i in range(L - 1, 0, -1):
        prev = int(choice[i, v, s])
        v -= mem_cost[i, s]
        s = prev
        res[i - 1] = s
    return float(total), res


# ---------------------------------------------------------------------------
# balanced contiguous pipeline partition (bottleneck DP)
# ---------------------------------------------------------------------------

def solve_pipeline_partition(costs: Sequence[float],
                             num_stages: int,
                             comm: Optional[Sequence[float]] = None,
                             use_native: Optional[bool] = None
                             ) -> Tuple[float, List[List[int]]]:
    """Split layers into ``num_stages`` contiguous stages minimizing the
    bottleneck stage cost (+ cut comm cost).  Returns (bottleneck,
    [[layer idxs] per stage]).  Capability parity with the v1 GPipe /
    PipeDream partition search (v1/python/hetu/distributed_strategies/)."""
    L = len(costs)
    P = int(num_stages)
    assert 1 <= P <= L, f"need 1 <= stages ({P}) <= layers ({L})"
    costs_a = np.ascontiguousarray(costs, np.float64)
    comm_a = np.ascontiguousarray(
        comm if comm is not None else np.zeros(L), np.float64)

    if P == 1:
        return float(costs_a.sum()), [list(range(L))]

    lib = load_dp_core() if use_native is not False else None
    if lib is not None:
        bounds = np.zeros(P - 1, np.int32)
        bottleneck = lib.hetu_dp_pipeline_partition(
            L, P, _as_c(costs_a, ctypes.c_double),
            _as_c(comm_a, ctypes.c_double), _as_c(bounds, ctypes.c_int32))
        ends = bounds.tolist() + [L - 1]
    else:
        bottleneck, ends = _partition_py(costs_a, comm_a, P)
    stages, start = [], 0
    for e in ends:
        stages.append(list(range(start, e + 1)))
        start = e + 1
    return float(bottleneck), stages


def _partition_py(costs, comm, P):
    L = len(costs)
    INF = float("inf")
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    def seg(a, b):  # [a, b)
        c = prefix[b] - prefix[a]
        if b < L:
            c += comm[b - 1]
        return c

    g = np.full((L + 1, P + 1), INF)
    cut = np.full((L + 1, P + 1), -1, np.int32)
    g[0, 0] = 0.0
    for k in range(1, P + 1):
        for t in range(k, L - (P - k) + 1):
            for j in range(k - 1, t):
                c = max(g[j, k - 1], seg(j, t))
                if c < g[t, k]:
                    g[t, k] = c
                    cut[t, k] = j
    ends, t = [], L
    for k in range(P, 1, -1):
        j = int(cut[t, k])
        ends.append(j - 1)
        t = j
    ends.reverse()
    return float(g[L, P]), ends + [L - 1]
