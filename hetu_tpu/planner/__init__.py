"""Auto-parallel planner: TPU cost model + DP solvers + search engines.

Covers the reference's Galvatron tool (``tools/Galvatron``) and v1
auto-parallel strategies (``hetu/v1/python/hetu/distributed_strategies/``)
as first-class framework components.
"""
from .cost_model import (CHIPS, ChipSpec, ClusterSpec, LayerSpec,
                         MemoryCalibration, Strategy, TimeCalibration,
                         all_gather_time, all_reduce_time,
                         all_to_all_time, calibrate_layer_memory,
                         calibrate_layer_time, collective_time,
                         embedding_layer_spec, grad_sync_time,
                         layer_memory, layer_time, p2p_time,
                         pipeline_time, reduce_scatter_time,
                         transformer_layer_spec)
from .dispatch import (DispatchStrategy, batching_strategy, dynamic_dispatch,
                       fit_cost_model, generate_strategy_pool,
                       max_seqlen_for, quadratic_predict,
                       solve_micro_batches, static_dispatch)
from .dp_solver import solve_layer_strategies, solve_pipeline_partition
from .profile_hardware import (Calibration, profile_and_calibrate,
                               profile_collectives, profile_hbm,
                               profile_matmul, validate_step_prediction)
from .search import (HAND_PLANS, PlanResult, SearchEngine,
                     gpt_layer_chain, hand_plan_times, plan_for_gpt,
                     plan_summary, verify_plan_schedule)
from .strategies import (BaseSearching, FlexFlowSearching, GPipeSearching,
                         OptCNNSearching, PipeDreamSearching,
                         PipeOptSearching, SearchResult)

__all__ = [
    "CHIPS", "ChipSpec", "ClusterSpec", "LayerSpec", "MemoryCalibration",
    "Strategy", "TimeCalibration", "all_gather_time", "all_reduce_time",
    "all_to_all_time", "calibrate_layer_memory", "calibrate_layer_time",
    "collective_time", "embedding_layer_spec", "layer_memory",
    "layer_time", "p2p_time", "pipeline_time", "reduce_scatter_time",
    "transformer_layer_spec", "HAND_PLANS", "gpt_layer_chain",
    "hand_plan_times",
    "solve_layer_strategies", "solve_pipeline_partition",
    "DispatchStrategy", "batching_strategy", "dynamic_dispatch",
    "fit_cost_model", "generate_strategy_pool", "max_seqlen_for",
    "quadratic_predict", "solve_micro_batches", "static_dispatch",
    "Calibration", "profile_and_calibrate", "profile_collectives",
    "profile_hbm", "profile_matmul", "validate_step_prediction",
    "PlanResult", "SearchEngine", "plan_for_gpt", "plan_summary",
    "verify_plan_schedule",
    "BaseSearching", "FlexFlowSearching", "GPipeSearching",
    "OptCNNSearching", "PipeDreamSearching", "PipeOptSearching",
    "SearchResult",
]
