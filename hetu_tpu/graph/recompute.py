"""Activation recompute + host offload contexts.

TPU-native re-expression of the reference's graph passes:

* ``Recompute::InsertRecomputedOps`` (``hetu/graph/recompute/recompute.h:27``)
  clones max recompute-subgraphs and rewires backward inputs — on TPU the
  same FLOPs-for-HBM trade is XLA rematerialization: ``ht.recompute()``
  records a ``jax.checkpoint`` policy on the current graph, and the traced
  step function wraps its fwd/bwd closure with that policy.  Policies map
  Hetu's "recompute everything in the marked subgraph" to XLA's
  checkpoint-policy vocabulary.
* ``ActivationCPUOffload::OffloadToCPU``
  (``hetu/graph/offload/activation_cpu_offload.h:25``) inserts D2H/H2D
  transfer ops on a dedicated offload stream — on TPU ``ht.cpu_offload()``
  selects an offloading checkpoint policy that parks saved residuals in
  ``pinned_host`` memory (XLA schedules the HBM<->host DMAs asynchronously,
  playing the role of ``kOffloadStream``).
"""
from __future__ import annotations

from typing import Optional

import jax

from .graph import get_default_graph

_POLICIES = {
    # recompute everything (Hetu's marked-subgraph recompute, maximal)
    "nothing_saveable": lambda: jax.checkpoint_policies.nothing_saveable,
    # keep matmul outputs, recompute elementwise (cheap default on TPU:
    # MXU results are expensive to recompute, VPU chains are free)
    "dots_saveable": lambda: jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "everything_saveable": lambda: jax.checkpoint_policies.everything_saveable,
}


def resolve_policy(name: Optional[str]):
    if name is None:
        return None
    if callable(name):
        return name
    return _POLICIES[name]()


class recompute:
    """``with ht.recompute():`` — enable rematerialization for step
    functions built from the current graph (reference
    ``python/hetu/__init__.py:232``).

    ``policy`` picks what is saved across fwd->bwd:
    ``"nothing_saveable"`` (default; recompute all activations) |
    ``"dots_saveable"`` | ``"dots_with_no_batch_dims_saveable"`` |
    ``"everything_saveable"`` | any jax checkpoint policy callable.
    """

    def __init__(self, policy: str = "nothing_saveable", graph=None,
                 multi_recompute=None):
        # multi_recompute accepted for reference API parity (per-strategy
        # enable flags); a falsy entry disables recompute entirely.
        if multi_recompute is not None and not any(
                bool(x) for x in jax.tree_util.tree_leaves(multi_recompute)):
            policy = None
        self.policy_name = policy
        self.graph = graph

    def __enter__(self):
        g = self.graph or get_default_graph()
        self._g = g
        self._prev = getattr(g, "_recompute_policy", None)
        g._recompute_policy = self.policy_name
        return self

    def __exit__(self, *exc):
        self._g._recompute_policy = self._prev


class cpu_offload:
    """``with ht.cpu_offload():`` — offload saved activations to host
    memory instead of recomputing (reference
    ``python/hetu/__init__.py:243``).  Requires a backend with
    ``pinned_host`` memory space (real TPU); on backends without it the
    step builder falls back to plain recompute."""

    def __init__(self, graph=None, multi_cpu_offload=None):
        enabled = True
        if multi_cpu_offload is not None and not any(
                bool(x) for x in jax.tree_util.tree_leaves(multi_cpu_offload)):
            enabled = False
        self.enabled = enabled
        self.graph = graph

    def __enter__(self):
        g = self.graph or get_default_graph()
        self._g = g
        self._prev = getattr(g, "_offload", False)
        g._offload = self.enabled
        return self

    def __exit__(self, *exc):
        self._g._offload = self._prev


def offload_policy():
    """Checkpoint policy parking dot outputs in host memory; None when the
    running jax has no offload-policy support."""
    try:
        return jax.checkpoint_policies.offload_dot_products_to_host(
            "device", "pinned_host")
    except Exception:
        return None
