"""Graph layer: eager + define-and-run graphs with a compiled-plan pool.

TPU-native re-expression of the reference's graph stack
(``hetu/graph/graph.h:21-27`` graph types, ``define_and_run_graph.cc:912``
plan matching, ``executable_graph.cc:1788`` CrucialRun):

* ``EagerGraph``     — ops execute immediately on jax arrays
  (reference ``eager_graph.h:8``).
* ``DefineAndRunGraph`` — user builds a symbolic op DAG once;
  ``run(fetches, feed_dict, ...)`` matches (strategy_id, fetches,
  feed shapes) against an **executable-plan pool** and on miss traces the
  DAG into a pure jax function, jit-compiles it with sharding annotations,
  and caches it — the exact analogue of Hetu's ExecGraphPlan + shape-plan
  pools (``define_and_run_graph.h:23``, ``.cc:912-1068``), with XLA playing
  the role of the ExecutableGraph runtime.

Autodiff is reverse-mode via ``jax.grad`` over the traced DAG rather than
per-op DoGradient (``graph.cc:117``); grad-reduce insertion for partial(-2)
grads is subsumed by GSPMD once activations/params carry shardings.

Run levels mirror ``graph.h:29-35``: TOPO / ALLOC / COMPUTE_ONLY / GRAD /
UPDATE — GRAD accumulates gradients across ``run`` calls into persistent
device buffers; UPDATE folds them into the parameter update.
"""
from __future__ import annotations

import enum
import itertools
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.dtype import canonicalize_dtype
from ..obs.tracer import get_tracer
from .tensor import SymbolicDim, Tensor, concrete_shape

_op_ids = itertools.count()

# dedicated stream for per-graph dropout seeds: ht.set_seed reseeds THIS
# (not numpy's process-global RNG), so framework reproducibility and user
# np.random usage never interfere with each other
_GRAPH_SEED_STREAM = [np.random.RandomState()]


class RunLevel(enum.Enum):
    TOPO = "topo"
    ALLOC = "alloc"
    COMPUTE_ONLY = "compute_only"
    GRAD = "grad"
    UPDATE = "update"


# ---------------------------------------------------------------------------
# executable registry (static-analysis hook, hetu_tpu/analysis)
# ---------------------------------------------------------------------------


class ExecutableHandle:
    """A lowerable reference to a compiled plan, registered for analysis.

    Wraps a jitted function plus the abstract argument specs it was (or
    will be) compiled for, so ``hetu_tpu.analysis`` can obtain the closed
    jaxpr / StableHLO / compiled HLO of any executable — train steps,
    serving prefill/decode, pipeline stages — WITHOUT running it.
    ``meta`` carries graph-level facts the jaxpr cannot express (param
    shardings, mesh axes, grad-comm plan, serving pool snapshot hooks).
    """

    def __init__(self, name: str, jit_fn, abstract_args: Tuple,
                 meta: Optional[Dict[str, Any]] = None):
        self.name = name
        self.jit_fn = jit_fn
        self.abstract_args = tuple(abstract_args)
        self.meta: Dict[str, Any] = dict(meta or {})
        self._traced = None
        self._lowered = None
        self._compiled = None
        self._compiled_text = None

    def trace(self):
        if self._traced is None:
            self._traced = self.jit_fn.trace(*self.abstract_args)
        return self._traced

    @property
    def jaxpr(self):
        return self.trace().jaxpr

    def lower(self):
        if self._lowered is None:
            self._lowered = self.trace().lower()
        return self._lowered

    def compile(self):
        """The compiled executable (cached): GSPMD accounting reads its
        HLO text, the memory pass its ``memory_analysis()``."""
        if self._compiled is None:
            self._compiled = self.lower().compile()
        return self._compiled

    def compiled_text(self) -> str:
        """Post-SPMD optimized HLO text (compiles on first call)."""
        if self._compiled_text is None:
            self._compiled_text = self.compile().as_text()
        return self._compiled_text

    def __repr__(self):
        return f"ExecutableHandle({self.name!r})"


_EXECUTABLE_REGISTRY: Dict[str, ExecutableHandle] = {}


def register_executable(name: str, jit_fn, abstract_args,
                        meta: Optional[Dict[str, Any]] = None
                        ) -> ExecutableHandle:
    """Register (or replace) an analyzable executable under ``name``."""
    h = ExecutableHandle(name, jit_fn, abstract_args, meta)
    _EXECUTABLE_REGISTRY[name] = h
    return h


def get_executable(name: str) -> ExecutableHandle:
    return _EXECUTABLE_REGISTRY[name]


def iter_executables(prefix: str = "") -> List[ExecutableHandle]:
    return [h for n, h in sorted(_EXECUTABLE_REGISTRY.items())
            if n.startswith(prefix)]


def clear_executables(prefix: str = "") -> None:
    for n in [n for n in _EXECUTABLE_REGISTRY if n.startswith(prefix)]:
        del _EXECUTABLE_REGISTRY[n]
    # the trace plane's prediction cache holds a strong ref to each
    # priced handle (whose meta may close over an engine's KV pool):
    # evict alongside the registry or retiring an engine leaks its pool
    from ..obs.reconcile import clear_prediction_cache
    clear_prediction_cache(prefix)


def _select_tree(flag, new, old):
    """Per-leaf ``jnp.where(flag, new, old)`` over matching pytrees —
    the on-device skip primitive the AMP scaler (overflow) and the
    numeric sentry (anomaly verdict) share: when ``flag`` is True the
    new values pass through bitwise."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(flag, n, o), new, old)


class OpNode:
    """A graph node (reference ``OpDef``, ``operator.h:304``)."""

    __slots__ = ("id", "op_type", "impl", "inputs", "outputs", "attrs",
                 "name")

    def __init__(self, op_type: str, impl: Optional[Callable],
                 inputs: List[Tensor], attrs: Dict[str, Any], name: str):
        self.id = next(_op_ids)
        self.op_type = op_type
        self.impl = impl
        self.inputs = inputs
        self.outputs: List[Tensor] = []
        self.attrs = attrs
        self.name = name or f"{op_type}_{self.id}"

    def __repr__(self):
        return f"OpNode({self.name}, inputs={[t.name for t in self.inputs]})"


class Graph:
    """Base graph: op/tensor registry + tracing evaluator."""

    def __init__(self, name: str = "graph"):
        self.name = name
        self.ops: List[OpNode] = []
        self.cur_strategy_id: int = 0
        self.num_strategy: int = 1
        self.mesh: Optional[Mesh] = None
        # variable/optimizer state: tensor.id -> jax.Array (device resident)
        self._var_data: Dict[int, jax.Array] = {}
        self._var_tensors: Dict[int, Tensor] = {}
        self._placeholders: Dict[int, Tensor] = {}
        self._grad_accum: Dict[int, jax.Array] = {}
        self._rng_tensor: Optional[Tensor] = None
        self._rng_seed = _GRAPH_SEED_STREAM[0].randint(0, 2**31 - 1)
        self._run_counter = 0
        # axes currently traced in shard_map manual mode (explicit
        # grad-comm path): pspec sharding constraints referencing manual
        # axes are illegal inside the region and are skipped there
        self._manual_axes: Tuple[str, ...] = ()
        # MoE layers built in this graph record their dispatch bounds
        # here (nn/moe.py) for the analyzer's capacity accounting
        self._moe_meta: List[Dict[str, Any]] = []

    # -- construction -------------------------------------------------------

    def set_num_strategy(self, n: int) -> None:
        self.num_strategy = n

    def _lift_constant(self, value, dtype=None) -> Tensor:
        arr = jnp.asarray(value, dtype=canonicalize_dtype(dtype).to_jnp()
                          if dtype is not None else None)
        t = Tensor(arr.shape, arr.dtype, name="const", graph=self)
        node = OpNode("constant", None, [], {"value": arr}, t.name)
        node.outputs = [t]
        t.producer = node
        self.ops.append(node)
        return t

    def as_tensor(self, value) -> Tensor:
        if isinstance(value, Tensor):
            return value
        return self._lift_constant(value)

    def make_op(self, op_type: str, impl: Callable,
                inputs: Sequence[Any], attrs: Optional[Dict[str, Any]] = None,
                name: str = "", num_outputs: int = 1) -> Union[Tensor, List[Tensor]]:
        attrs = dict(attrs or {})
        in_tensors = [self.as_tensor(x) for x in inputs]
        node = OpNode(op_type, impl, in_tensors, attrs, name)
        # shape/dtype inference via abstract evaluation (replaces the
        # reference's per-op DoInferMeta, operator.h:423).  Unbound symbolic
        # dims get a provisional binding — recorded shapes are advisory; the
        # real shapes come from the feed arrays at trace time (shape plans).
        for t in in_tensors:
            for d in t.shape:
                if isinstance(d, SymbolicDim) and not d.is_bound:
                    d.set(16)
        in_structs = [jax.ShapeDtypeStruct(t.concrete_shape(), t.dtype.to_jnp())
                      for t in in_tensors]
        # underscore attrs are node metadata, not impl kwargs (same
        # filtering _eval_targets applies at trace time)
        call_attrs = {k: v for k, v in attrs.items()
                      if not k.startswith("_")}
        out_struct = jax.eval_shape(lambda *xs: impl(*xs, **call_attrs),
                                    *in_structs)
        flat_outs, treedef = jax.tree_util.tree_flatten(out_struct)
        outputs = []
        for i, s in enumerate(flat_outs):
            t = Tensor(s.shape, s.dtype, producer=node,
                       name=f"{node.name}:{i}" if len(flat_outs) > 1 else node.name,
                       graph=self,
                       requires_grad=any(x.requires_grad for x in in_tensors))
            outputs.append(t)
        node.outputs = outputs
        node.attrs["_treedef"] = treedef
        self.ops.append(node)
        self._post_make_op(node)
        return outputs[0] if num_outputs == 1 and len(outputs) == 1 else outputs

    def _post_make_op(self, node: OpNode) -> None:
        pass

    # -- variables / placeholders -------------------------------------------

    def add_variable(self, t: Tensor, init_fn: Callable[[], jax.Array]) -> None:
        node = OpNode("variable", None, [], {"init_fn": init_fn}, t.name)
        node.outputs = [t]
        t.producer = node
        t.graph = self
        self.ops.append(node)
        self._var_tensors[t.id] = t

    def add_placeholder(self, t: Tensor) -> None:
        node = OpNode("placeholder", None, [], {}, t.name)
        node.outputs = [t]
        t.producer = node
        t.graph = self
        self.ops.append(node)
        self._placeholders[t.id] = t

    def next_rng_tensor(self) -> Tensor:
        """The per-run RNG key tensor (auto-fed with a fresh key each run);
        stochastic ops (dropout) fold a per-op salt into it.  Replaces the
        reference's per-device RNG state (hetu/impl/random/)."""
        if self._rng_tensor is None:
            t = Tensor((2,), "uint32", name="_rng", graph=self)
            self.add_placeholder(t)
            self._rng_tensor = t
        return self._rng_tensor

    def _fresh_rng_key(self) -> np.ndarray:
        self._run_counter += 1
        return np.asarray(
            jax.random.PRNGKey(self._rng_seed + self._run_counter),
            dtype=np.uint32)

    def _materialize_var(self, t: Tensor) -> jax.Array:
        if t.id not in self._var_data:
            init_fn = t.producer.attrs["init_fn"]
            val = init_fn()
            sharding = self._sharding_for(t)
            if sharding is not None:
                val = jax.device_put(val, sharding)
            self._var_data[t.id] = val
        return self._var_data[t.id]

    def get_tensor_value(self, t: Tensor):
        if t.id in self._var_data:
            return self._var_data[t.id]
        if t.id in self._var_tensors:
            return self._materialize_var(t)
        raise ValueError(f"{t.name} has no stored value; fetch it via run()")

    def reset_variable(self, t: Tensor, value) -> None:
        sharding = self._sharding_for(t)
        val = jnp.asarray(value, dtype=t.dtype.to_jnp())
        if sharding is not None:
            val = jax.device_put(val, sharding)
        self._var_data[t.id] = val
        # external param writes (load_model / user resets) invalidate
        # any flat-optimizer fp32 master packed from the OLD values —
        # flat optimizers watch this epoch and the per-tensor log
        # (_ensure_flat_state refreshes ONLY the written params'
        # masters, so untouched bf16 params keep their fp32 precision)
        self._var_writes = getattr(self, "_var_writes", 0) + 1
        if not hasattr(self, "_var_write_log"):
            self._var_write_log = {}
        self._var_write_log[t.id] = self._var_writes

    # -- sharding -----------------------------------------------------------

    def _pspec_for(self, t: Tensor) -> Optional[PartitionSpec]:
        spec = getattr(t, "pspec", None)
        if spec is None or self.mesh is None:
            return spec
        # drop axis names the current mesh doesn't have: after a hot
        # switch to a smaller/reshaped mesh (e.g. tp or pp removed) stale
        # annotations on intermediates must degrade to replication on the
        # missing axes, exactly as the reference re-deduces ds on the new
        # topology
        names = set(self.mesh.axis_names)

        def _fix(entry):
            if entry is None:
                return None
            ent = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(n for n in ent if n in names)
            if not kept:
                return None
            return kept if len(kept) > 1 else kept[0]

        fixed = [_fix(e) for e in spec]
        if all(f == e for f, e in zip(fixed, spec)):
            return spec
        return PartitionSpec(*fixed)

    def _sharding_for(self, t: Tensor) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        spec = self._pspec_for(t)
        if spec is None:
            return None
        return NamedSharding(self.mesh, spec)

    # -- evaluation engine ---------------------------------------------------

    def _topo_from(self, targets: Sequence[Tensor]) -> List[OpNode]:
        """Reverse-DFS topo sort (reference Graph::TopoSort, graph.h:960)."""
        visited: Dict[int, bool] = {}
        order: List[OpNode] = []

        def visit(node: OpNode):
            if node.id in visited:
                return
            visited[node.id] = True
            for t in node.inputs:
                if t.producer is not None:
                    visit(t.producer)
            order.append(node)

        for t in targets:
            if t.producer is not None:
                visit(t.producer)
        return order

    def _eval_targets(self, targets: Sequence[Tensor],
                      env: Dict[int, Any],
                      out_env: Optional[Dict[int, Any]] = None) -> List[Any]:
        """Evaluate target tensors given env (tensor.id -> concrete value).

        Pure w.r.t. env: used both eagerly and under jit tracing.
        ``out_env``, when given, receives every value computed along the
        way (keyed by tensor id) so callers can cache intermediates.
        """
        base_env = dict(env)  # leaf values only (placeholders/variables)
        env = dict(env) if out_env is None else out_env
        if out_env is not None:
            out_env.update(base_env)
        for node in self._topo_from(targets):
            if all(t.id in env for t in node.outputs):
                continue
            if node.op_type == "constant":
                env[node.outputs[0].id] = node.attrs["value"]
            elif node.op_type in ("variable", "placeholder"):
                if node.outputs[0].id not in env:
                    raise ValueError(
                        f"{node.op_type} {node.name} not fed/materialized")
            elif node.op_type == "gradients":
                self._eval_gradients_node(node, env, base_env)
            else:
                args = [env[t.id] for t in node.inputs]
                attrs = {k: v for k, v in node.attrs.items()
                         if not k.startswith("_")}
                out = node.impl(*args, **attrs)
                flat = jax.tree_util.tree_leaves(out)
                for t, v in zip(node.outputs, flat):
                    spec = self._pspec_for(t)
                    if spec is not None and self.mesh is not None \
                            and not self._manual_axes:
                        v = jax.lax.with_sharding_constraint(
                            v, NamedSharding(self.mesh, spec))
                    env[t.id] = v
        return [env[t.id] for t in targets]

    def _eval_gradients_node(self, node: OpNode, env: Dict[int, Any],
                             base_env: Optional[Dict[int, Any]] = None) -> None:
        """Reverse-mode autodiff (reference Graph::Gradients, graph.cc:117).

        Implemented as jax.grad over the traced forward closure from the
        requested vars to the loss; multi-consumer grad summation and
        partial-grad reduction fall out of jax's vjp + GSPMD.  The closure
        re-evaluates the forward from *leaf* values only (base_env), so the
        differentiated variables actually flow into the loss.
        """
        loss_t: Tensor = node.attrs["loss"]
        xs: List[Tensor] = node.attrs["xs"]
        leaf_env = base_env if base_env is not None else env

        def loss_fn(var_vals: Dict[int, Any]):
            inner_env = {k: v for k, v in leaf_env.items()
                         if k not in var_vals}
            inner_env.update(var_vals)
            (loss_val,) = self._eval_targets([loss_t], inner_env)
            return jnp.sum(loss_val) if loss_val.ndim > 0 else loss_val

        var_vals = {t.id: env[t.id] for t in xs}
        grads = jax.grad(loss_fn)(var_vals)
        for t_out, t_x in zip(node.outputs, xs):
            env[t_out.id] = grads[t_x.id]

    def make_gradients(self, loss: Tensor, xs: Sequence[Tensor]) -> List[Tensor]:
        node = OpNode("gradients", None, [loss] + list(xs),
                      {"loss": loss, "xs": list(xs)}, f"grad_{loss.name}")
        outputs = []
        for x in xs:
            g = Tensor(x.shape, x.dtype, producer=node,
                       name=f"grad_{x.name}", graph=self, is_grad=True)
            if hasattr(x, "pspec"):
                g.pspec = x.pspec
            outputs.append(g)
        node.outputs = outputs
        self.ops.append(node)
        return outputs

    @property
    def trainable_variables(self) -> List[Tensor]:
        return [t for t in self._var_tensors.values() if t.trainable]


class EagerGraph(Graph):
    """Immediate execution (reference ``eager_graph.h:8``)."""

    def _post_make_op(self, node: OpNode) -> None:
        env: Dict[int, Any] = {}
        for t in node.inputs:
            env[t.id] = t.get_data() if t._data is not None else \
                self.get_tensor_value(t) if t.id in self._var_tensors else None
            if env[t.id] is None:
                env[t.id] = self._eval_with_deps(t)
        args = [env[t.id] for t in node.inputs]
        attrs = {k: v for k, v in node.attrs.items() if not k.startswith("_")}
        out = node.impl(*args, **attrs)
        flat = jax.tree_util.tree_leaves(out)
        for t, v in zip(node.outputs, flat):
            t.set_data(v)

    def _eval_with_deps(self, t: Tensor):
        env = {}
        for node in self._topo_from([t]):
            for it in node.inputs:
                if it._data is not None:
                    env[it.id] = it._data
            for vt_id in self._var_tensors:
                env[vt_id] = self._materialize_var(self._var_tensors[vt_id])
        (val,) = self._eval_targets([t], env)
        return val

    def get_tensor_value(self, t: Tensor):
        if t._data is not None:
            return t._data
        return super().get_tensor_value(t)

    def next_rng_tensor(self) -> Tensor:
        # eager: a fresh concrete key every call
        return self._lift_constant(self._fresh_rng_key())


class DefineByRunGraph(Graph):
    """Lazy trace variant (reference ``define_by_run_graph.h:9``): ops
    record symbolically like DefineAndRun, but values materialize on
    demand via :meth:`get_or_compute` (the reference's ``GetOrCompute``)
    with per-tensor caching — new ops invalidate nothing already
    computed, matching torch-like deferred execution without re-running
    the whole graph per fetch."""

    def __init__(self, name: str = "define_by_run"):
        super().__init__(name)
        self._computed: Dict[int, Any] = {}

    def get_or_compute(self, t: Tensor):
        if t.id in self._computed:
            return self._computed[t.id]
        env: Dict[int, Any] = dict(self._computed)
        for vt_id, vt in self._var_tensors.items():
            env.setdefault(vt_id, self._materialize_var(vt))
        # cache every intermediate computed for this fetch (reference
        # GetOrCompute caches per-tensor): separate fetches then reuse
        # one consistent set of values instead of re-running upstream.
        # Variable VALUES stay out of the cache — reset_variable /
        # optimizer updates must be visible to later fetches.
        full_env: Dict[int, Any] = {}
        (val,) = self._eval_targets([t], env, out_env=full_env)
        self._computed.update(
            {k: v for k, v in full_env.items()
             if k not in self._var_tensors})
        return val

    def feed(self, t: Tensor, value) -> None:
        """Bind a placeholder's value for subsequent get_or_compute."""
        self._computed[t.id] = jnp.asarray(value)

    def invalidate(self) -> None:
        """Drop cached activations (keep variables)."""
        self._computed.clear()

    def get_tensor_value(self, t: Tensor):
        if t.id in self._computed:
            return self._computed[t.id]
        if t.id in self._var_tensors:
            return super().get_tensor_value(t)
        return self.get_or_compute(t)


class DefineAndRunGraph(Graph):
    """Symbolic graph with an executable-plan pool."""

    def __init__(self, name: str = "define_and_run"):
        super().__init__(name)
        self._plan_pool: Dict[Tuple, Any] = {}
        self._abstract_pool: Dict[Tuple, Any] = {}  # plan key -> arg specs
        self._cost_cache: Dict[int, Any] = {}       # id(plan) -> cost dict
        self._shape_buckets: Optional[List[int]] = None
        self._bucket_pad_values: Dict[int, Any] = {}
        self._memory_profiler = None  # lazy (env-gated) MemoryProfiler
        # every DerivedDim ever seen in a feed/placeholder shape: stale
        # provisional overrides are cleared for ALL of them on every bind
        # pass, not only the ones the current feed_dict mentions
        self._derived_dims: Dict[int, Any] = {}
        # explicit grad-comm introspection (set at plan-build time)
        self._grad_comm_active: bool = False
        self._grad_comm_fallback: Optional[str] = None
        # plan key -> registered analysis-handle name (analysis hook)
        self._plan_names: Dict[Tuple, str] = {}
        # numeric-sentry chaos seam (resilience/sentry.py): an auto-fed
        # int32 code placeholder (0 = clean) the compiled step reads to
        # poison gradients/loss at the injection point — feed VALUE
        # only, so injections never retrace
        self._sentry_tensor: Optional[Tensor] = None
        self._sentry_next_code: int = 0
        # ZeRO-3 flat: (optimizer, xs) whose per-param working copies
        # went stale at the last update step (the flat fp32 master is
        # the authoritative storage); refreshed lazily on first read
        self._stale_flat_params: Optional[Tuple[Any, list]] = None

    def _refresh_stale_params(self) -> None:
        """Materialize ZeRO-3 flat working params from the flat master
        (bitwise the in-region gather's values), then clear the flag."""
        stale = self._stale_flat_params
        if stale is not None:
            self._stale_flat_params = None
            stale[0].materialize_flat_params(self, stale[1])

    def get_tensor_value(self, t: Tensor):
        if self._stale_flat_params is not None:
            self._refresh_stale_params()
        return super().get_tensor_value(t)

    # -- numeric sentry (resilience/sentry.py) -------------------------------

    def _sentry_code_tensor(self) -> Tensor:
        if self._sentry_tensor is None:
            t = Tensor((), "int32", name="_sentry_code", graph=self)
            self.add_placeholder(t)
            self._sentry_tensor = t
        return self._sentry_tensor

    def inject_numeric_fault(self, kind: str) -> None:
        """Arm a one-shot numeric chaos injection for the NEXT
        UPDATE-level run (FaultPlan ``grad_nan`` / ``grad_spike`` /
        ``loss_spike`` verdicts): the fed code makes the compiled step
        poison its own gradients/loss at the sentry's seam."""
        from ..resilience.sentry import INJECT_CODES
        if kind not in INJECT_CODES:
            raise ValueError(f"unknown numeric fault {kind!r}; have "
                             f"{sorted(INJECT_CODES)}")
        self._sentry_next_code = INJECT_CODES[kind]

    @staticmethod
    def _sentry_for(update_node, run_level) -> Optional[Any]:
        """The active NumericSentry for this plan, or None — ONE
        definition shared by plan build, feed marshalling and meta
        registration so the compiled program and its feeds can never
        disagree about whether the code input exists."""
        if update_node is None or run_level != RunLevel.UPDATE:
            return None
        return getattr(update_node.attrs["optimizer"], "sentry", None)

    # -- shape-plan bucketing ------------------------------------------------

    def set_shape_buckets(self, buckets, pad_values=None) -> None:
        """Bucket symbolic feed dims so varying shapes reuse compiled
        plans (reference DeduceShapePlan + shape-plan pool,
        define_and_run_graph.cc:273; SURVEY hard part #4).

        ``buckets``: sorted list of allowed sizes, or an int alignment
        (round symbolic dims up to a multiple — the data/bucket.py
        alignment convention).  Feeds are padded up to the bucket along
        every :class:`SymbolicDim` axis; ``pad_values`` maps placeholder
        Tensors to their pad fill (default 0 — use the loss ignore_index
        for label feeds so padded positions drop out of the loss).
        """
        if isinstance(buckets, int):
            self._shape_buckets = buckets
        else:
            self._shape_buckets = sorted(int(b) for b in buckets)
            if not self._shape_buckets:
                raise ValueError("shape bucket list must be non-empty")
        self._bucket_pad_values = {
            (t.id if isinstance(t, Tensor) else t): v
            for t, v in (pad_values or {}).items()}

    def _bucket_dim(self, size: int) -> int:
        b = self._shape_buckets
        if isinstance(b, int):
            return ((size + b - 1) // b) * b
        for cand in b:
            if cand >= size:
                return cand
        raise ValueError(
            f"feed dim {size} exceeds the largest shape bucket {b[-1]}")

    def _bucket_feeds(self, feed_dict: Dict[Tensor, Any]
                      ) -> Dict[Tensor, Any]:
        """Pad feeds up to bucket boundaries along symbolic dims."""
        out = {}
        for t, v in feed_dict.items():
            arr = np.asarray(v) if not isinstance(v, jax.Array) else v
            pads = []
            changed = False
            for i, dim in enumerate(t.shape):
                if isinstance(dim, SymbolicDim) and i < arr.ndim:
                    tgt = self._bucket_dim(arr.shape[i])
                    pads.append((0, tgt - arr.shape[i]))
                    changed = changed or tgt != arr.shape[i]
                else:
                    pads.append((0, 0))
            if changed:
                # np.pad keeps the feed host-side: _plan_key reads feed
                # dtypes/shapes and must not force a device sync; run()
                # device_puts the padded array once afterwards
                fill = self._bucket_pad_values.get(t.id, 0)
                arr = np.pad(np.asarray(arr), pads, constant_values=fill)
            out[t] = arr
        return out

    # -- plan construction ---------------------------------------------------

    @staticmethod
    def _leaf_dims(dim):
        from .tensor import DerivedDim
        out = []
        stack = [dim]
        while stack:
            d = stack.pop()
            if isinstance(d, DerivedDim):
                stack.extend(p for p in d._parents
                             if isinstance(p, SymbolicDim))
            elif isinstance(d, SymbolicDim):
                out.append(d)
        return out

    @staticmethod
    def _derived_nodes(dim):
        """Every DerivedDim on the expression DAG rooted at ``dim``
        (including itself) — overrides must clear along the WHOLE path,
        or a nested dim evaluates through a stale intermediate."""
        from .tensor import DerivedDim
        out = []
        stack = [dim]
        while stack:
            d = stack.pop()
            if isinstance(d, DerivedDim):
                out.append(d)
                stack.extend(p for p in d._parents
                             if isinstance(p, SymbolicDim))
        return out

    def _bind_symbolic_dims(self, feed_dict: Dict[Tensor, Any]) -> None:
        from .tensor import DerivedDim
        # two passes: leaf symbols bind from feeds first, then DERIVED
        # dims (IntSymbol arithmetic, e.g. seq // cp) are CHECKED against
        # their computed value — a mismatched feed must raise, not
        # silently override the expression.  The check only fires when
        # every leaf was bound by THIS feed pass (stale advisory
        # bindings from make_op's provisional set(16) must not reject
        # valid feeds) and shape buckets are off (independent padding
        # legitimately breaks arithmetic relations between dims).
        derived = []
        fresh: set = set()
        for t, v in feed_dict.items():
            v_shape = np.shape(v)
            if len(v_shape) != len(t.shape):
                raise ValueError(
                    f"feed for {t.name} has rank {len(v_shape)}, "
                    f"expected {len(t.shape)} ({t.shape})")
            for dim, d in zip(t.shape, v_shape):
                if isinstance(dim, DerivedDim):
                    derived.append((t, dim, d))
                elif isinstance(dim, SymbolicDim):
                    dim.set(d)
                    fresh.add(id(dim))
                elif int(dim) != d:
                    raise ValueError(
                        f"feed for {t.name} has shape {v_shape}, "
                        f"expected {t.shape}")
        # register derived dims reachable from this feed AND from every
        # placeholder, then clear provisional overrides on ALL of them: a
        # stale override from an earlier run (unbound leaves/bucketing)
        # must not shadow a re-evaluation after this pass rebinds leaves
        for t in itertools.chain(feed_dict.keys(),
                                 self._placeholders.values()):
            for dim in t.shape:
                if isinstance(dim, DerivedDim):
                    for node in self._derived_nodes(dim):
                        self._derived_dims[id(node)] = node
        for node in self._derived_dims.values():
            node.clear_override()
        seen: Dict[int, int] = {}
        for t, dim, d in derived:
            prev = seen.get(id(dim))
            if prev is not None and prev != d:
                raise ValueError(
                    f"conflicting feeds for derived dim {dim.name}: "
                    f"{prev} vs {d} (tensor {t.name})")
            seen[id(dim)] = d
            enforce = (self._shape_buckets is None
                       and all(id(l) in fresh
                               for l in self._leaf_dims(dim))
                       and dim.is_bound)
            if enforce:
                if dim.get() != d:
                    raise ValueError(
                        f"feed for {t.name} gives derived dim {dim.name} "
                        f"= {d}, but its expression evaluates to "
                        f"{dim.get()}")
            else:
                dim.set(d)  # provisional (unbound leaves / bucketing)

    def _plan_key(self, fetches, feed_dict, num_micro_batches, run_level,
                  update_node):
        feed_sig = tuple(sorted(
            (t.id, tuple(np.shape(v)), str(np.asarray(v).dtype))
            for t, v in feed_dict.items()))
        fetch_sig = tuple(t.id for t in fetches)
        return (self.cur_strategy_id, fetch_sig, feed_sig,
                num_micro_batches, run_level,
                update_node.id if update_node is not None else None,
                # remat/offload contexts are baked into the traced plan
                getattr(self, "_recompute_policy", None),
                getattr(self, "_offload", False))

    def _split_micro_batches(self, feeds: Dict[int, Any], n: int):
        """Stack feed arrays into [n, batch/n, ...] micro-batch form
        (reference NDArray::split at executable_graph.cc:1828) — the
        leading dim is consumed by the executor's ``lax.scan`` so the
        fwd+bwd graph is traced ONCE regardless of n (the reference loops
        micro-batches at runtime, executable_graph.cc:1424; a trace-time
        Python loop would duplicate the whole XLA program n times).
        Scalars (0-d feeds) are replicated; the rng key feed is folded
        with the micro-batch index so stochastic ops differ per
        micro-batch."""
        rng_id = self._rng_tensor.id if self._rng_tensor is not None else None
        if n == 1:
            return feeds
        out = {}
        for tid, v in feeds.items():
            if tid == rng_id:
                out[tid] = jnp.stack(
                    [jax.random.fold_in(v, i) for i in range(n)])
                continue
            if np.ndim(v) == 0:
                out[tid] = jnp.broadcast_to(jnp.asarray(v), (n,))
                continue
            b = v.shape[0]
            assert b % n == 0, f"batch {b} not divisible by {n} micro-batches"
            out[tid] = v.reshape(n, b // n, *v.shape[1:])
        return out

    def _plan_explicit_grad_comm(self, opt, fetches: List[Tensor],
                                 feed_tensors: List[Tensor],
                                 num_micro_batches: int,
                                 loss_t: Optional[Tensor] = None,
                                 sentry_active: bool = False):
        """Decide whether the explicit coalesced grad-comm path applies
        and build its shard_map specs.  Returns (plan, None) or
        (None, reason).

        The path runs fwd+bwd in shard_map MANUAL mode over the dp axis
        (so gradients stay local until the optimizer's bucketed
        collectives sync them).  It requires a pure-dp mesh, ZeRO<=2
        (params replicated over dp at rest), and every non-scalar fetch
        annotated with a pspec; anything else falls back to the implicit
        GSPMD per-tensor sync.
        """
        dpa = opt.dp_axis
        mesh = self.mesh
        if mesh is None:
            return None, "no mesh on the graph"
        if tuple(mesh.axis_names) != (dpa,):
            return None, (f"mesh axes {tuple(mesh.axis_names)} != "
                          f"({dpa!r},): explicit path needs a pure-dp mesh")
        if mesh.shape[dpa] <= 1:
            return None, "dp axis has size 1 (nothing to sync)"
        if opt.zero >= 3 and not getattr(opt, "flat_state", False):
            # per-param ZeRO-3 rides GSPMD (partitioner-inserted
            # gathers); the FLAT layout owns its gathers explicitly
            # (param_gather buckets), so flat zero-3 stays on this path
            return None, "zero-3 (FSDP) keeps params dp-sharded at rest"

        def _refs_dp(spec) -> bool:
            if spec is None:
                return False
            for e in spec:
                ents = e if isinstance(e, tuple) else (e,)
                if dpa in ents:
                    return True
            return False

        for t in self._var_tensors.values():
            if _refs_dp(self._pspec_for(t)):
                return None, f"variable {t.name} is sharded over {dpa!r}"
        # grad sync uses the data-parallel MEAN convention (torch-DDP
        # semantics): correct for mean-normalized losses (this repo's
        # convention), 1/dp-scaled for sum-reduced ones.  Mean-ness is
        # not structurally decidable for composed losses, so — like
        # torch DDP — the convention is documented (optimizer docstring,
        # DESIGN.md §7) and only the unambiguous top-level reduce_sum is
        # caught here as a best-effort guard.
        loss_id = loss_t.id if loss_t is not None else None
        if loss_t is not None and loss_t.producer is not None \
                and loss_t.producer.op_type == "reduce_sum":
            return None, (f"loss {loss_t.name} is sum-reduced; the "
                          f"explicit path's dp-mean grad sync assumes "
                          f"a mean-normalized loss")
        fetch_specs = []
        for t in fetches:
            if len(t.shape) == 0:
                # only the loss has known (mean) reduction semantics
                # under manual dp; pmean of an arbitrary scalar (a sum,
                # max, count...) would silently change its value
                if loss_id is not None and t.id != loss_id:
                    return None, (f"scalar fetch {t.name} is not the "
                                  f"loss (unknown reduction semantics "
                                  f"under manual dp)")
                fetch_specs.append(PartitionSpec())
            else:
                spec = self._pspec_for(t)
                # the spec must actually shard over dp: a replicated
                # annotation on a dp-dependent value would let each rank
                # return its own local shard as "the" result
                if spec is None or not _refs_dp(spec):
                    return None, (f"non-scalar fetch {t.name} has no "
                                  f"{dpa!r}-sharded pspec (manual region "
                                  f"cannot place it)")
                fetch_specs.append(spec)
        feed_specs = {}
        tensors = list(feed_tensors)
        if self._rng_tensor is not None and \
                all(t.id != self._rng_tensor.id for t in tensors):
            tensors.append(self._rng_tensor)
        if sentry_active:
            st = self._sentry_code_tensor()
            if all(t.id != st.id for t in tensors):
                tensors.append(st)
        M = num_micro_batches
        for t in tensors:
            base = self._pspec_for(t) or PartitionSpec()
            if t.ndim == 0:
                feed_specs[t.id] = PartitionSpec()  # (M,) replicated stack
            elif M > 1:
                feed_specs[t.id] = PartitionSpec(None, *base)
            else:
                feed_specs[t.id] = base
        return {"axis": dpa, "feed_specs": feed_specs,
                "fetch_specs": fetch_specs}, None

    def _build_executable(self, fetches: List[Tensor],
                          feed_tensors: List[Tensor],
                          num_micro_batches: int,
                          run_level: RunLevel,
                          update_node: Optional[OpNode]):
        """Trace the DAG into a pure jitted step function.

        Signature: step(var_state, opt_state, grad_accum, feeds)
                   -> (fetch_vals, new_var_state, new_opt_state, new_grad_accum)
        var/opt/grad_accum are donated (device-resident, updated in place) —
        the analogue of the reference's fused param/grad buffers
        (executable_graph.h:292-303).
        """
        graph = self
        # activation recompute / host offload (reference recompute +
        # activation_cpu_offload graph passes -> XLA remat policies)
        from .recompute import offload_policy, resolve_policy
        remat_policy = resolve_policy(getattr(self, "_recompute_policy", None))
        if getattr(self, "_offload", False):
            off = offload_policy()
            remat_policy = off if off is not None else (
                remat_policy or jax.checkpoint_policies.nothing_saveable)
        scaler = update_node.attrs.get("grad_scaler") \
            if update_node is not None else None
        if scaler is not None and not scaler.enabled:
            scaler = None

        # explicit coalesced/quantized gradient sync (optimizer
        # grad_comm): the fwd+bwd (incl. the micro-batch scan) runs in a
        # shard_map manual region over the dp axis, so gradients stay
        # LOCAL until the optimizer's bucketed collective syncs them —
        # once per step, not once per micro-batch or per parameter.
        # numeric sentry (resilience/sentry.py): fused finite/spike
        # verdict + on-device update skip, UPDATE-level plans only
        sentry = self._sentry_for(update_node, run_level)
        sentry_tid = None
        loss_fetch_idx = None
        if sentry is not None:
            loss_t_sentry = update_node.attrs["grad_node"].attrs["loss"]
            loss_fetch_idx = next(
                (i for i, f in enumerate(fetches)
                 if isinstance(f, Tensor) and f.id == loss_t_sentry.id),
                None)
            if loss_fetch_idx is None:
                raise ValueError(
                    "numeric sentry needs the loss among the fetches "
                    "(its spike/finite verdict reads the merged loss)")
            sentry_tid = self._sentry_code_tensor().id

        explicit = None
        flat_mode = False
        gc_state = (False, None)      # (active, fallback_reason) per plan
        if update_node is not None:
            opt_gc = update_node.attrs["optimizer"]
            if getattr(opt_gc, "grad_comm", None) is not None:
                if scaler is not None:
                    why = "dynamic loss scaler active"
                    explicit = None
                else:
                    explicit, why = self._plan_explicit_grad_comm(
                        opt_gc, fetches, feed_tensors, num_micro_batches,
                        loss_t=update_node.attrs["grad_node"]
                        .attrs["loss"],
                        sentry_active=sentry is not None)
                gc_state = (explicit is not None,
                            None if explicit else why)
                # reduce-scatter-only ZeRO-2: the update runs on the
                # locally-owned flat chunk INSIDE the manual region, so
                # the full gradient never materializes.  GRAD-level runs
                # keep the all-reduce sync — persistent accumulation
                # stores full (replicated) gradients.
                flat_mode = bool(explicit is not None
                                 and getattr(opt_gc, "flat_state", False)
                                 and run_level == RunLevel.UPDATE)

        def step(var_state, opt_state, grad_accum, feeds_mb):
            scale = opt_state["_scaler"]["scale"] if scaler is not None \
                else None

            # feeds_mb: list of per-micro-batch dicts
            def fwd_bwd(mb_feeds, vstate):
                env = {**vstate, **mb_feeds}
                if update_node is not None:
                    grad_node = update_node.attrs["grad_node"]
                    xs = grad_node.attrs["xs"]
                    loss_t = grad_node.attrs["loss"]

                    def loss_fn(vv):
                        inner = {**env, **vv}
                        (lv,) = graph._eval_targets([loss_t], inner)
                        lv = jnp.sum(lv) if lv.ndim > 0 else lv
                        if scaler is not None:
                            lv = scaler.scale_loss(lv, {"scale": scale})
                        return lv

                    if remat_policy is not None:
                        loss_fn = jax.checkpoint(loss_fn, policy=remat_policy)
                    var_vals = {t.id: env[t.id] for t in xs}
                    loss_val, grads = jax.value_and_grad(loss_fn)(var_vals)
                    if scaler is not None:
                        loss_val = scaler.unscale_loss(
                            loss_val, {"scale": scale})
                        grads = scaler.unscale_grads(
                            grads, {"scale": scale})
                    # evaluate non-loss fetches too
                    other = [f for f in fetches if f.id != loss_t.id]
                    other_vals = graph._eval_targets(other, env) if other else []
                    fetch_vals = []
                    oi = 0
                    for f in fetches:
                        if f.id == loss_t.id:
                            fetch_vals.append(loss_val)
                        else:
                            fetch_vals.append(other_vals[oi])
                            oi += 1
                    return fetch_vals, grads
                fetch_vals = graph._eval_targets(fetches, env)
                return fetch_vals, None

            # micro-batch loop as a runtime lax.scan over the stacked
            # [M, ...] feeds (reference ComputeFunc loop,
            # executable_graph.cc:1424): one traced fwd+bwd body for any
            # M, instead of unrolling M copies of the program.
            # Scalar fetches average over micro-batches; non-scalar
            # fetches return the last micro-batch's value.
            M = num_micro_batches

            def _merge_fetches(carry_fv, fv):
                return [c + f if f.ndim == 0 else f
                        for c, f in zip(carry_fv, fv)]

            def _zeros_of(sds):
                return jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), sds)

            if update_node is None:
                if M == 1:
                    fetch_vals, _ = fwd_bwd(feeds_mb, var_state)
                    return fetch_vals, var_state, opt_state, grad_accum

                def body(carry_fv, mb):
                    fv, _ = fwd_bwd(mb, var_state)
                    return _merge_fetches(carry_fv, fv), None

                first = jax.tree_util.tree_map(lambda v: v[0], feeds_mb)
                fv_sds, _ = jax.eval_shape(fwd_bwd, first, var_state)
                fetch_vals, _ = lax.scan(body, _zeros_of(fv_sds), feeds_mb)
                out = [v / M if v.ndim == 0 else v for v in fetch_vals]
                return out, var_state, opt_state, grad_accum

            def compute_grads(vstate, fmb):
                # grad accumulation across micro-batches; returns the
                # merged fetch values and the 1/M-normalized accumulated
                # grads (LOCAL grads inside a manual region)
                if M == 1:
                    fetch_vals, acc_grads = fwd_bwd(fmb, vstate)
                else:
                    def body(carry, mb):
                        carry_fv, carry_g = carry
                        fv, g = fwd_bwd(mb, vstate)
                        new_g = {k: carry_g[k] + g[k] for k in g}
                        return (_merge_fetches(carry_fv, fv), new_g), None

                    first = jax.tree_util.tree_map(lambda v: v[0], fmb)
                    fv_sds, g_sds = jax.eval_shape(fwd_bwd, first, vstate)
                    (fetch_vals, acc_grads), _ = lax.scan(
                        body, (_zeros_of(fv_sds), _zeros_of(g_sds)), fmb)
                acc_grads = {k: g / M for k, g in acc_grads.items()}
                fetch_vals = [v / M if v.ndim == 0 else v
                              for v in fetch_vals]
                return fetch_vals, acc_grads

            if explicit is not None and flat_mode:
                # flat ZeRO-2 fast path: fwd+bwd, reduce-scatter, the
                # local-chunk optimizer update AND the param all-gather
                # all happen inside ONE manual region — the gradients
                # cross the wire exactly once (scattered), the updated
                # params exactly once (weight dtype).
                dpa = explicit["axis"]
                opt_flat = update_node.attrs["optimizer"]
                # sentry state never enters the manual region: its
                # scalars update OUTSIDE from the psum-reduced signals
                # the region returns
                opt_region = {k: v for k, v in opt_state.items()
                              if k != "_sentry"}

                def flat_phase(vstate, fmb, fstate, gaccum):
                    graph._manual_axes = (dpa,)
                    try:
                        if opt_flat.zero >= 3:
                            # ZeRO-3: working params exist only as 1/dp
                            # master chunks at rest — gather each bucket
                            # just-in-time in the weight dtype
                            # (param_gather) before the fwd+bwd reads it
                            vstate = {**vstate,
                                      **opt_flat._flat_gather_params(
                                          fstate,
                                          update_node.attrs["xs"], dpa)}
                        fv, acc = compute_grads(vstate, fmb)
                        if gaccum:
                            # persistent GRAD-level grads arrive already
                            # mean-synced and replicated; the dp-mean of
                            # (local + replicated) preserves them exactly
                            acc = {k: acc[k] + gaccum[k] for k in acc}
                        if sentry is not None:
                            # the chaos seam: poison the accumulated
                            # gradients per the fed code (1.0 when clean)
                            code_l = jnp.reshape(fmb[sentry_tid],
                                                 (-1,))[0]
                            acc = sentry.inject_grads(acc, code_l)
                        new_vars, new_fstate, sqn = \
                            opt_flat._flat_sync_and_update(
                                vstate, fstate, acc,
                                update_node.attrs["xs"], dpa,
                                want_sq_norm=sentry is not None)
                    finally:
                        graph._manual_axes = ()
                    fv = [lax.pmean(v, dpa) if v.ndim == 0 else v
                          for v in fv]
                    if sentry is not None:
                        # sqn is psum-reduced (replicated by reduction),
                        # so it may leave the region un-linted
                        return fv, new_vars, new_fstate, sqn
                    return fv, new_vars, new_fstate

                from ..parallel import comm as _comm
                fspecs = opt_flat._flat_state_pspecs(opt_region)
                # the step counter never leaves the manual region (see
                # _flat_sync_and_update); it increments out here where
                # its replication is structural
                out_fspecs = {k: v for k, v in fspecs.items()
                              if k != "step"}
                gac_specs = {k: PartitionSpec() for k in grad_accum}
                out_specs = (explicit["fetch_specs"], PartitionSpec(),
                             out_fspecs)
                if sentry is not None:
                    out_specs = out_specs + (PartitionSpec(),)
                flat_fn = _comm.shard_map(
                    flat_phase, graph.mesh,
                    in_specs=(PartitionSpec(), explicit["feed_specs"],
                              fspecs, gac_specs),
                    out_specs=out_specs)
                outs = flat_fn(var_state, feeds_mb, opt_region,
                               grad_accum)
                if sentry is not None:
                    fetch_vals, new_vars, new_opt, grad_sq = outs
                else:
                    fetch_vals, new_vars, new_opt = outs
                new_opt = dict(new_opt)
                if sentry is not None:
                    code = jnp.reshape(feeds_mb[sentry_tid], (-1,))[0]
                    fetch_vals = list(fetch_vals)
                    fetch_vals[loss_fetch_idx] = sentry.inject_loss(
                        fetch_vals[loss_fetch_idx], code)
                    ok, new_sstate = sentry.update(
                        fetch_vals[loss_fetch_idx], grad_sq,
                        opt_state["_sentry"])
                    # anomalous verdict: select the OLD params, flat
                    # buffers and step counter — a skipped step leaves
                    # bitwise-zero residue
                    old_core = {k: v for k, v in opt_region.items()
                                if k != "step"}
                    new_vars = _select_tree(ok, new_vars, var_state)
                    new_opt = _select_tree(ok, new_opt, old_core)
                    new_opt["step"] = opt_state["step"] + \
                        jnp.where(ok, 1, 0).astype(jnp.int32)
                    new_opt["_sentry"] = new_sstate
                else:
                    new_opt["step"] = opt_state["step"] + 1
                new_accum = {k: jnp.zeros_like(v)
                             for k, v in grad_accum.items()} \
                    if grad_accum else {}
                return fetch_vals, new_vars, new_opt, new_accum

            if explicit is not None:
                dpa = explicit["axis"]
                opt_sync = update_node.attrs["optimizer"]

                def grad_phase(vstate, fmb):
                    graph._manual_axes = (dpa,)
                    try:
                        fv, acc = compute_grads(vstate, fmb)
                        # micro-batch-accumulated grads sync ONCE per
                        # step through fused (quantized) buckets
                        acc = opt_sync.sync_gradients(acc, dpa)
                    finally:
                        graph._manual_axes = ()
                    fv = [lax.pmean(v, dpa) if v.ndim == 0 else v
                          for v in fv]
                    return fv, acc

                from ..parallel import comm as _comm
                sync_fn = _comm.shard_map(
                    grad_phase, graph.mesh,
                    in_specs=(PartitionSpec(), explicit["feed_specs"]),
                    out_specs=(explicit["fetch_specs"], PartitionSpec()))
                fetch_vals, acc_grads = sync_fn(var_state, feeds_mb)
            else:
                fetch_vals, acc_grads = compute_grads(var_state, feeds_mb)

            # fold in persistent accumulation (RunLevel.GRAD across runs)
            if grad_accum:
                acc_grads = {k: acc_grads[k] + grad_accum.get(k, 0.0)
                             for k in acc_grads}

            if run_level == RunLevel.GRAD:
                return fetch_vals, var_state, opt_state, acc_grads

            # UPDATE: apply optimizer
            opt = update_node.attrs["optimizer"]
            opt_core = {k: v for k, v in opt_state.items()
                        if k not in ("_scaler", "_sentry")}
            if sentry is not None:
                # the chaos seam: poison the (accumulated, synced)
                # gradients per the fed code (multiply by 1.0 = bitwise
                # identity on a clean step)
                code = jnp.reshape(feeds_mb[sentry_tid], (-1,))[0]
                acc_grads = sentry.inject_grads(acc_grads, code)
            new_vars, new_opt = opt._apply_updates(
                var_state, opt_core, acc_grads, update_node.attrs["xs"])
            if scaler is not None:
                # skip the update (params AND optimizer state) on overflow,
                # then grow/backoff the scale (reference update_scale op)
                from .amp import check_finite
                finite = check_finite(acc_grads)
                new_vars = _select_tree(finite, new_vars, var_state)
                new_opt = _select_tree(finite, new_opt, opt_core)
            if sentry is not None:
                fetch_vals = list(fetch_vals)
                fetch_vals[loss_fetch_idx] = sentry.inject_loss(
                    fetch_vals[loss_fetch_idx], code)
                # the same fp32 sum-of-squares the global-norm clip
                # reads (Optimizer._grad_sq_norm; XLA CSE dedupes)
                grad_sq = opt._grad_sq_norm(acc_grads,
                                            update_node.attrs["xs"])
                ok, new_sstate = sentry.update(
                    fetch_vals[loss_fetch_idx], grad_sq,
                    opt_state["_sentry"])
                # anomalous verdict: keep OLD params, optimizer state
                # and step counter — bitwise-zero residue on skip
                new_vars = _select_tree(ok, new_vars, var_state)
                new_opt = _select_tree(ok, new_opt, opt_core)
                new_opt["_sentry"] = new_sstate
            if scaler is not None:
                new_opt["_scaler"] = scaler.update_state(
                    opt_state["_scaler"], finite)
            new_accum = {k: jnp.zeros_like(v) for k, v in grad_accum.items()} \
                if grad_accum else {}
            return fetch_vals, new_vars, new_opt, new_accum

        jit_step = jax.jit(step, donate_argnums=(0, 1, 2))
        return jit_step, gc_state, flat_mode

    # -- analysis hook -------------------------------------------------------

    def _collect_pspec_edges(self) -> List[Dict[str, Any]]:
        """Producer -> consumer pspec edges of this graph, for the
        per-edge attribution pass (hetu_tpu/analysis/edges).

        Every tensor carrying a pspec annotation is a constraint site
        (``_eval_targets`` applies ``with_sharding_constraint`` there);
        the edge runs from its nearest *annotated* dataflow ancestor to
        it, and ``dstates.deduce_pspec_transition`` names the collective
        GSPMD will insert for the transition.  Identity edges (the
        annotation merely restates the inherited layout) are dropped.
        """
        edges: List[Dict[str, Any]] = []
        if self.mesh is None:
            return edges
        mesh_axes = {str(a): int(s) for a, s in self.mesh.shape.items()}
        if max(mesh_axes.values(), default=1) <= 1:
            return edges
        from ..parallel.dstates import _spec_pairs, deduce_pspec_transition

        def _ancestor(t, limit: int = 128):
            """Nearest annotated tensor on the main dataflow chain."""
            for _ in range(limit):
                node = t.producer
                if node is None or not node.inputs:
                    return None
                t = node.inputs[0]
                if self._pspec_for(t) is not None:
                    return t
            return None

        for node in self.ops:
            for out in node.outputs:
                dst_spec = self._pspec_for(out)
                if dst_spec is None or node.op_type in ("variable",
                                                        "placeholder"):
                    continue    # leaf annotations constrain inputs only
                src_t = _ancestor(out)
                src_spec = self._pspec_for(src_t) \
                    if src_t is not None else None
                try:
                    src_shape = tuple(src_t.concrete_shape()) \
                        if src_t is not None else tuple(out.concrete_shape())
                    dst_shape = tuple(out.concrete_shape())
                    kind = deduce_pspec_transition(
                        src_spec, src_shape, dst_spec, dst_shape,
                        mesh_axes)
                except (ValueError, TypeError):
                    continue
                if kind == "identity":
                    continue
                nbytes = int(np.prod(dst_shape, dtype=np.int64)
                             * np.dtype(out.dtype.to_jnp()).itemsize)
                # the axes the transition MOVES (placement changed) —
                # spectator axes keep their dim and never communicate
                changed = {a for _d, a in
                           _spec_pairs(src_spec) ^ _spec_pairs(dst_spec)}
                edges.append({
                    "kind": kind,
                    "tensor": out.name,
                    "producer": src_t.name if src_t is not None
                    else node.inputs[0].name if node.inputs else "",
                    "consumer": node.attrs.get("_edge_tag") or node.name,
                    "src_spec": str(src_spec),
                    "dst_spec": str(dst_spec),
                    "axes": tuple(sorted(changed)),
                    "payload_bytes": nbytes,
                })
        return edges

    def _arg_memory_facts(self, abstract_pool, mesh_axes, update_node):
        """(divisors, kinds): pytrees mirroring the plan's abstract arg
        tuple ``(var_state, opt_state, grad_accum, feeds)``, carrying per
        leaf how many ways it is sharded (product of mesh axis sizes in
        its pspec) and what buffer class it is — the registered facts the
        static memory pass (analysis/memory) prices resident HBM from."""
        var_state, opt_state, grad_accum, feeds = abstract_pool

        from ..parallel.dstates import pspec_shard_divisor

        def _div(pspec) -> int:
            return pspec_shard_divisor(pspec, mesh_axes)

        def _tensor_div(tid) -> int:
            t = self._var_tensors.get(tid) or self._placeholders.get(tid)
            return _div(self._pspec_for(t)) if t is not None else 1

        opt = update_node.attrs["optimizer"] if update_node is not None \
            else None
        dp = int(mesh_axes.get(opt.dp_axis, 1)) if opt is not None else 1
        opt_shardings = getattr(opt, "_shardings", {}) if opt is not None \
            else {}

        def _slot_div(tid) -> int:
            # per-param slots ride the sharding the optimizer actually
            # device_put them with (the param's own pspec, plus ZeRO's
            # dp dim-0 shard when enabled) — recorded in _shardings
            sh = opt_shardings.get(tid)
            if sh is not None and getattr(sh, "spec", None) is not None:
                return _div(sh.spec)
            return _tensor_div(tid) if isinstance(tid, int) else 1

        def _opt_entry(name, sub):
            if isinstance(name, str) and name.startswith("flat_"):
                # flat buffers are sharded P(dp) in equal rank chunks
                return _mirror(sub, lambda _l, _k: dp), \
                    _mirror(sub, lambda _l, _k: "opt-state")
            div = _mirror(sub, lambda _l, k: _slot_div(k))
            return div, _mirror(sub, lambda _l, _k: "opt-state")

        def _mirror(obj, fn, key=None):
            if isinstance(obj, dict):
                return {k: _mirror(v, fn, k) for k, v in obj.items()}
            if isinstance(obj, tuple) and hasattr(obj, "_fields"):
                # NamedTuple states (optax-style, e.g. FactoredState)
                # construct positionally, not from one iterable
                return type(obj)(*(_mirror(v, fn, key) for v in obj))
            if isinstance(obj, (list, tuple)):
                return type(obj)(_mirror(v, fn, key) for v in obj)
            return fn(obj, key)

        var_div = {k: _tensor_div(k) for k in var_state}
        var_kind = {k: "param" for k in var_state}
        opt_div, opt_kind = {}, {}
        for name, sub in (opt_state or {}).items():
            opt_div[name], opt_kind[name] = _opt_entry(name, sub)
        accum_div = _mirror(grad_accum or {},
                            lambda _l, k: _tensor_div(k)
                            if isinstance(k, int) else 1)
        accum_kind = _mirror(grad_accum or {}, lambda _l, _k: "grad")
        feed_div = _mirror(feeds or {},
                           lambda _l, k: _tensor_div(k)
                           if isinstance(k, int) else 1)
        feed_kind = _mirror(feeds or {}, lambda _l, _k: "feed")
        return (var_div, opt_div, accum_div, feed_div), \
            (var_kind, opt_kind, accum_kind, feed_kind)

    def _register_plan_for_analysis(self, key, jit_step, gc_state,
                                    update_node, real_fetches,
                                    num_micro_batches,
                                    flat_mode: bool = False) -> None:
        """Expose this plan to the static analyzer (hetu_tpu/analysis):
        register an ExecutableHandle with the abstract arg specs plus the
        graph-level facts a jaxpr cannot carry — param shardings, mesh
        axes, and (when the explicit path is active) the grad-comm plan
        the dstates predictor can be run against."""
        name = self._plan_names.get(key)
        if name is not None and name in _EXECUTABLE_REGISTRY:
            return
        if name is None:
            # registry membership is re-checked (not just _plan_names):
            # after clear_executables() a cached plan must re-register
            # under its original name on its next run, or it would
            # silently vanish from analysis while still executing
            name = f"{self.name}/plan{len(self._plan_names)}"
            self._plan_names[key] = name
        mesh_axes = {str(a): int(s) for a, s in self.mesh.shape.items()} \
            if self.mesh is not None else {}
        params = []
        for t in self._var_tensors.values():
            params.append({"name": t.name,
                           "shape": tuple(t.concrete_shape()),
                           "dtype": np.dtype(t.dtype.to_jnp()).name,
                           "pspec": self._pspec_for(t),
                           "trainable": bool(t.trainable)})
        meta: Dict[str, Any] = {
            "kind": "train_step" if update_node is not None else "forward",
            "fetches": [getattr(f, "name", str(f)) for f in real_fetches],
            "num_micro_batches": num_micro_batches,
            "mesh_axes": mesh_axes,
            "params": params,
            "grad_comm_active": gc_state[0],
            # explicit path predicts EVERY collective -> strict reshard
            # gate; otherwise GSPMD owns the grad sync and no implicit-
            # reshard claim is made (allowed_gspmd None disables it)
            "allowed_gspmd": {} if gc_state[0] else None,
            # per-edge attribution (analysis/edges): the graph's
            # producer -> consumer pspec transitions, plus the facts the
            # edge synthesizers need (scalar fetch reductions, MoE
            # dispatch bounds)
            "pspec_edges": self._collect_pspec_edges(),
            "scalar_fetches": sum(
                1 for f in real_fetches
                if isinstance(f, Tensor) and len(f.shape) == 0),
            "moe": [dict(m) for m in getattr(self, "_moe_meta", ())],
            # step-time cost fact (analysis/cost overlap model): the
            # explicit coalesced grad sync is bucketed exactly so the
            # latency-hiding scheduler can run it behind backward
            # compute — its grad_comm/param_comm edges may hide under
            # the roofline.  Implicit GSPMD sync makes no such claim.
            "comm_overlap": bool(gc_state[0]),
        }
        # static memory model facts (analysis/memory): per-argument
        # sharding divisors + buffer kinds, mirroring the abstract arg
        # tree (var_state, opt_state, grad_accum, feeds).  Advisory:
        # an unmirrorable state container must degrade the memory pass
        # to its (shape, dtype) fallback, never break plan registration
        try:
            divisors, kinds = self._arg_memory_facts(
                self._abstract_pool[key], mesh_axes, update_node)
            meta["arg_divisors"] = divisors
            meta["arg_kinds"] = kinds
        except Exception:
            pass
        if update_node is not None:
            opt = update_node.attrs["optimizer"]
            meta["dp_axis"] = opt.dp_axis
            sentry_meta = self._sentry_for(update_node, key[4])
            if sentry_meta is not None:
                # registration meta: the thresholds the fused verdict
                # enforces + the fact the step carries the packed
                # verdict in its outputs (analysis/bench introspection)
                meta["sentry"] = sentry_meta.meta()
            # recorded for every train step (implicit-sync plans too):
            # the replicated-state-under-shard rule needs to know whether
            # the optimizer shards its state down by dp
            meta["zero"] = int(opt.zero)
            meta["flat_state"] = bool(flat_mode)
            if gc_state[0] and flat_mode:
                # reduce-scatter-only sync: the updated params leave the
                # manual region fully gathered, so the per-param
                # all-gather allowance is ZERO — any GSPMD regather is a
                # regression the implicit-reshard rule must flag.
                # Optimizer-declared in-region collectives (Adafactor's
                # factored-stat psums) are EXPLICIT lowered emissions,
                # accounted through grad_comm's opt_extra below, so the
                # GSPMD-insert claim stays exactly zero
                meta["allowed_gspmd"] = {}
            elif gc_state[0] and opt.zero in (1, 2):
                # ZeRO-1/2 keeps optimizer state dp-sharded but params
                # replicated at rest: GSPMD re-materializes each updated
                # param from its sharded update — one predictable
                # all_gather per dp-sharded state param (the flat_state
                # reduce-scatter-only sync removes these)
                meta["allowed_gspmd"] = {"all_gather": len(opt._shardings)}
            elif gc_state[0] and opt.zero >= 3:
                # FSDP: params sharded at rest, forward gathers them —
                # count depends on layer structure; no strict claim
                meta["allowed_gspmd"] = None
            if gc_state[0]:
                # entries in SYNC order (optim.flat_state.sync_order —
                # the one ordering every flat-geometry consumer shares),
                # so bucket planning in the predictor sees exactly the
                # runtime geometry
                from ..optim.flat_state import sync_order
                xs = sync_order(update_node.attrs["xs"])
                entries = [(t.name, tuple(t.concrete_shape()),
                            np.dtype(t.dtype.to_jnp()).name) for t in xs]
                meta["grad_comm"] = {
                    "entries": entries,
                    "dp_axis": opt.dp_axis,
                    "transport": opt.grad_comm,
                    "bucket_mb": opt.bucket_mb,
                    "device_num": mesh_axes.get(opt.dp_axis, 1),
                    "zero": opt.zero,
                    "flat": bool(flat_mode),
                    # the flat sentry's global grad-norm psum shares the
                    # clip's collective shape (same reduction whether
                    # clipping fires or not), so the predictor counts it
                    # under "clip"
                    "clip": opt.max_grad_norm is not None
                    or bool(flat_mode
                            and self._sentry_for(update_node, key[4])
                            is not None),
                    # each scalar fetch is pmean'd inside the manual
                    # region (one explicit all_reduce apiece)
                    "scalar_fetches": meta["scalar_fetches"],
                    # optimizer-declared in-region collectives beyond
                    # the grad/param chains (Adafactor's factored-stat
                    # psums) — folded into the predictor's "extra"
                    "opt_extra": dict(opt._flat_comm_extra())
                    if flat_mode else {},
                }
        register_executable(name, jit_step, self._abstract_pool[key], meta)

    def analysis_handles(self) -> List[ExecutableHandle]:
        """Handles of every plan this graph has registered."""
        return [get_executable(n) for n in self._plan_names.values()
                if n in _EXECUTABLE_REGISTRY]

    # -- hot switch ----------------------------------------------------------

    def cost_analysis(self):
        """XLA cost analysis of the last executed step program (flops,
        bytes accessed, ...): metrics from INSIDE the compiled program,
        complementing the eager-replay OpProfiler (reference op-level
        TimeCost + CUDAProfiler counters, hetu/graph/profiler.h:30-66).

        Returns the XLA cost dict (keys like "flops",
        "bytes accessed") or None when no step has run yet."""
        jit_step = getattr(self, "_last_plan", None)
        key = getattr(self, "_last_plan_key", None)
        spec = self._abstract_pool.get(key)
        if jit_step is None or spec is None:
            return None
        if id(jit_step) in self._cost_cache:       # invariant per plan
            return self._cost_cache[id(jit_step)]
        compiled = jit_step.lower(*spec).compile()
        costs = compiled.cost_analysis()
        # jax returns either a dict or a 1-element list of dicts
        if isinstance(costs, (list, tuple)):
            costs = costs[0] if costs else {}
        out = dict(costs) if costs else None
        self._cost_cache[id(jit_step)] = out
        return out

    def switch_strategy(self, new_mesh, pspec_overrides=None, optimizer=None,
                        mode=None, dtype=None):
        """Hot-switch params/optimizer states/grads to a new mesh and/or
        new per-param shardings, activating a fresh strategy id (reference
        DefineAndRunGraph plan-change -> SwitchExecGraph::SwitchParams,
        define_and_run_graph.cc:1073-1129).  Returns a SwitchProfile."""
        from ..parallel.switch import SwitchExecGraph, SwitchMode
        # ZeRO-3 flat keeps working params stale between update steps;
        # the switch migrates _var_data, so materialize first (bitwise
        # vs the in-region gather — the continuation stays exact)
        self._refresh_stale_params()
        if mode is None:
            mode = SwitchMode.ORIGIN_PARAM if optimizer is None \
                else SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER
        tr = get_tracer()
        sp = tr.begin("switch_strategy", track="train",
                      from_strategy=self.cur_strategy_id) if tr.enabled \
            else None
        try:
            sw = SwitchExecGraph(self, new_mesh, pspec_overrides, mode,
                                 dtype)
            prof = sw.switch(optimizer)
            self.cur_strategy_id += 1
            self.num_strategy = max(self.num_strategy,
                                    self.cur_strategy_id + 1)
            if sp is not None:
                tr.end(sp, to_strategy=self.cur_strategy_id,
                       **prof.as_dict())
            return prof
        finally:
            if sp is not None:
                tr.end(sp)      # idempotent: only fires if we raised

    # -- run ----------------------------------------------------------------

    def run(self, loss_or_fetches, fetches=None, feed_dict=None,
            num_micro_batches: int = 1, cur_strategy_id: Optional[int] = None,
            run_level: Union[str, RunLevel, None] = None,
            save_checkpoint: bool = False):
        """Execute the graph (reference DefineAndRunGraph::Run,
        define_and_run_graph.cc:912).

        Accepts either ``run(fetches, feed_dict=...)`` or the reference's
        ``run(loss, fetches, feed_dict, num_micro_batches, ...)`` signature.
        """
        if fetches is None:
            fetches = loss_or_fetches
        if not isinstance(fetches, (list, tuple)):
            fetches = [fetches]
        fetches = list(fetches)
        feed_dict = dict(feed_dict or {})
        if run_level is None:
            run_level = _run_level_ctx._current  # ambient ht.run_level(...)
        if isinstance(run_level, str):
            run_level = RunLevel(run_level)
        if cur_strategy_id is not None:
            self.cur_strategy_id = cur_strategy_id

        if run_level == RunLevel.TOPO:
            return self._topo_from([f for f in fetches if isinstance(f, Tensor)])

        if self._shape_buckets is not None:
            feed_dict = self._bucket_feeds(feed_dict)
        self._bind_symbolic_dims(feed_dict)

        # find update node among fetches (optimizer.minimize output);
        # remember its positions so returned values align with fetches
        update_node = None
        real_fetches = []
        update_positions = []
        for i, f in enumerate(fetches):
            if isinstance(f, Tensor) and f.producer is not None \
                    and f.producer.op_type == "update":
                update_node = f.producer
                update_positions.append(i)
            else:
                real_fetches.append(f)
        if run_level in (RunLevel.COMPUTE_ONLY, RunLevel.ALLOC):
            update_node = None

        # materialize variables (ALLOC)
        for t in self._var_tensors.values():
            self._materialize_var(t)
        if run_level == RunLevel.ALLOC:
            return []

        key = self._plan_key(real_fetches, feed_dict, num_micro_batches,
                             run_level, update_node)
        if key not in self._plan_pool:
            feed_tensors = list(feed_dict.keys())
            self._plan_pool[key] = self._build_executable(
                real_fetches, feed_tensors, num_micro_batches, run_level,
                update_node)
        jit_step, gc_state, flat_mode = self._plan_pool[key]
        # introspection tracks the plan actually EXECUTED this run, not
        # the last grad-comm-requesting build
        self._grad_comm_active, self._grad_comm_fallback = gc_state
        self._last_plan = jit_step  # for cost_analysis()
        self._last_plan_key = key

        # trace plane (hetu_tpu/obs): per-step phase spans on the
        # "train" track — feed marshalling, the executable call, state
        # commit — nested under one step span.  NULL tracer: all guards
        # read False and nothing below allocates.  The try/finally
        # closes the step span even when the body raises (ending the
        # outermost span pops-and-discards any open children), so a
        # caught-and-retried failing step never corrupts the
        # per-thread nesting stack.
        tr = get_tracer()
        step_sp = tr.begin(
            "train_step" if update_node is not None else "forward",
            track="train", run_level=run_level.value,
            strategy=self.cur_strategy_id) if tr.enabled else None
        try:
            return self._run_plan(tr, key, jit_step, gc_state, flat_mode,
                                  update_node, real_fetches,
                                  update_positions, feed_dict,
                                  num_micro_batches)
        finally:
            if step_sp is not None:
                tr.end(step_sp)

    def _run_plan(self, tr, key, jit_step, gc_state, flat_mode,
                  update_node, real_fetches, update_positions, feed_dict,
                  num_micro_batches):
        """The per-run tail of :meth:`run`: feed marshalling, state
        assembly, registration, the executable call, and state commit —
        split out so the step span wraps it in one try/finally."""
        feed_sp = tr.begin("feed", track="train") if tr.enabled else None
        feeds = {}
        for t, v in feed_dict.items():
            arr = jnp.asarray(v, dtype=t.dtype.to_jnp())
            sharding = self._sharding_for(t)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            feeds[t.id] = arr
        if self._rng_tensor is not None:
            feeds[self._rng_tensor.id] = jnp.asarray(self._fresh_rng_key())
        run_level = key[4]
        sentry = self._sentry_for(update_node, run_level)
        if sentry is not None:
            # the one-shot chaos code (0 = clean): a VALUE, never a
            # shape — injections can never retrace the plan
            feeds[self._sentry_code_tensor().id] = jnp.asarray(
                self._sentry_next_code, jnp.int32)
            self._sentry_next_code = 0
        feeds_mb = self._split_micro_batches(feeds, num_micro_batches)
        if feed_sp is not None:
            tr.end(feed_sp, n_feeds=len(feed_dict),
                   micro_batches=num_micro_batches)

        # ZeRO-3 flat leaves per-param working copies stale between
        # update steps (the flat master is authoritative); any OTHER
        # plan about to read parameter values must refresh them first
        stale = getattr(self, "_stale_flat_params", None)
        if stale is not None and not (
                flat_mode and update_node is not None
                and update_node.attrs["optimizer"] is stale[0]):
            self._refresh_stale_params()

        var_state = dict(self._var_data)
        opt_state = {}
        scaler = None
        zero3_flat = False
        if update_node is not None:
            opt = update_node.attrs["optimizer"]
            if flat_mode:
                # flat dp-sharded buffers matching the reduce-scatter
                # geometry (optim/flat_state.py); grafts restored
                # per-param checkpoints on the way
                opt_state = dict(opt._ensure_flat_state(
                    var_state, update_node.attrs["xs"], self))
                zero3_flat = opt.zero >= 3
                if zero3_flat:
                    # params at rest = the 1/dp flat master chunks; the
                    # full working copies never enter the step (the
                    # region re-gathers them per bucket, param_gather)
                    for t in update_node.attrs["xs"]:
                        var_state.pop(t.id, None)
            else:
                opt_state = dict(opt._ensure_state(
                    var_state, update_node.attrs["xs"], self))
            scaler = update_node.attrs.get("grad_scaler")
            if scaler is not None and not scaler.enabled:
                scaler = None
            if scaler is not None:
                opt_state["_scaler"] = scaler.init_state()
            if sentry is not None:
                opt_state["_sentry"] = sentry.init_state()
        grad_accum = dict(self._grad_accum)

        if key not in self._abstract_pool:
            # arg specs for cost_analysis(); shapes are invariant per plan
            # key, so this traversal runs once per compiled plan
            self._abstract_pool[key] = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    np.shape(a), np.asarray(a).dtype)
                if not hasattr(a, "aval") else
                jax.ShapeDtypeStruct(a.shape, a.dtype),
                (var_state, opt_state, grad_accum, feeds_mb))
        self._register_plan_for_analysis(key, jit_step, gc_state,
                                         update_node, real_fetches,
                                         num_micro_batches,
                                         flat_mode=flat_mode)
        exec_sp = None
        if tr.enabled:
            # the span reconciliation joins on: exec= is the registered
            # plan name; grad-comm/optimizer work happens INSIDE the
            # executable, attributed here via the plan's comm meta (the
            # per-bucket comm_tag plane names each collective in the
            # lowered program itself)
            plan_name = self._plan_names.get(key, self.name)
            attrs: Dict[str, Any] = {"exec": plan_name,
                                     "micro_batches": num_micro_batches}
            if update_node is not None:
                opt_tr = update_node.attrs["optimizer"]
                # explicit coalesced path: name the transport the
                # comm_tag'd buckets ride; otherwise GSPMD owns the sync
                attrs["grad_comm"] = getattr(opt_tr, "grad_comm", None) \
                    if gc_state[0] else "gspmd"
                attrs["zero"] = int(getattr(opt_tr, "zero", 0))
                attrs["flat_state"] = bool(flat_mode)
            from ..obs.reconcile import predicted_span_attrs
            attrs.update(predicted_span_attrs(plan_name))
            exec_sp = tr.begin("executable", track="train", **attrs)
        fetch_vals, new_vars, new_opt, new_accum = jit_step(
            var_state, opt_state, grad_accum, feeds_mb)
        if exec_sp is not None:
            # the jit call returns async futures: only block for an
            # honest wall time when the step is actually being traced
            jax.block_until_ready(fetch_vals)
            tr.end(exec_sp)

        commit_sp = tr.begin("commit", track="train") if tr.enabled \
            else None
        if zero3_flat:
            # the step returns no trainables (they live only in the flat
            # master now): keep the existing dp-sharded working copies —
            # STALE until _refresh_stale_params materializes from master
            merged = dict(self._var_data)
            merged.update(new_vars)
            self._var_data = merged
            self._stale_flat_params = (update_node.attrs["optimizer"],
                                       list(update_node.attrs["xs"]))
        else:
            self._var_data = dict(new_vars)
        if update_node is not None:
            new_opt = dict(new_opt)
            if scaler is not None and "_scaler" in new_opt:
                scaler.store_state(new_opt.pop("_scaler"))
            if sentry is not None and "_sentry" in new_opt:
                # the verdict rode the step outputs; stash it for the
                # trainer's policy ladder (no extra device fetch)
                sentry.store_state(new_opt.pop("_sentry"))
            update_node.attrs["optimizer"]._store_state(new_opt)
        self._grad_accum = dict(new_accum)
        # per-step memory snapshot when HETU_MEMORY_PROFILE is set
        # (reference executable_graph.cc:1738 memory profile levels; the
        # SPMD micro-batch loop is one compiled program, so the runtime
        # granularity here is the step — the MPMD runtime snapshots per
        # micro-batch)
        if self._memory_profiler is None:
            from ..utils.profiler import MemoryProfiler
            self._memory_profiler = MemoryProfiler()
        if self._memory_profiler.enabled:
            self._memory_profiler.snapshot("step")
        if commit_sp is not None:
            tr.end(commit_sp)
        # restore fetch arity: update-op positions yield None
        out = list(fetch_vals)
        for i in update_positions:
            out.insert(i, None)
        return out


# ---------------------------------------------------------------------------
# graph context management (python/hetu/__init__.py:124 ht.graph())
# ---------------------------------------------------------------------------

_graph_stack: List[Graph] = []
_default_graphs: Dict[str, Graph] = {}


def get_default_graph() -> Graph:
    if _graph_stack:
        return _graph_stack[-1]
    if "eager" not in _default_graphs:
        _default_graphs["eager"] = EagerGraph("default_eager")
    return _default_graphs["eager"]


class graph:
    """``with ht.graph("define_and_run", num_strategy=N):`` context."""

    def __init__(self, kind: Union[str, Graph] = "define_and_run",
                 create_new: bool = False, prefix: str = "default",
                 num_strategy: int = -1, mesh: Optional[Mesh] = None):
        if isinstance(kind, Graph):
            self.g = kind
        else:
            cache_key = f"{prefix}_{kind}"
            if create_new or cache_key not in _default_graphs:
                if kind == "define_and_run":
                    g = DefineAndRunGraph(cache_key)
                elif kind == "define_by_run":
                    g = DefineByRunGraph(cache_key)
                else:
                    g = EagerGraph(cache_key)
                if create_new:
                    self.g = g
                else:
                    _default_graphs[cache_key] = g
                    self.g = g
            else:
                self.g = _default_graphs[cache_key]
        if num_strategy >= 1:
            self.g.set_num_strategy(num_strategy)
        if mesh is not None:
            self.g.mesh = mesh

    def __enter__(self) -> Graph:
        _graph_stack.append(self.g)
        return self.g

    def __exit__(self, *exc):
        _graph_stack.pop()


class run_level:
    """Context setting the ambient run level (ht.run_level(...)); consulted
    by ``DefineAndRunGraph.run`` when no explicit run_level is passed."""
    _current = RunLevel.UPDATE

    def __init__(self, level: Union[str, RunLevel]):
        self.level = RunLevel(level) if isinstance(level, str) else level

    def __enter__(self):
        self.prev = run_level._current
        run_level._current = self.level
        return self

    def __exit__(self, *exc):
        run_level._current = self.prev


_run_level_ctx = run_level
