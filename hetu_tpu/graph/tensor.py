"""Symbolic Tensor handles for the graph frontend.

TPU-native analogue of the reference's ``TensorDef`` (``hetu/graph/tensor.h:20``):
a graph-level handle carrying shape (possibly symbolic dims), dtype, producer
op, a ``DistributedStatesHierarchy`` sharding annotation (``tensor.h:255``)
and trainable/grad flags.  Unlike the reference there is no storage here —
concrete values are ``jax.Array``s owned by the executing graph; under jit
the Tensor is just a node id in the traced plan.

Symbolic dims: the reference threads ``IntSymbol`` shapes through ops for
variable sequence lengths (``hetu/core/symbol.h``).  XLA wants static shapes,
so symbolic dims here are named placeholders resolved per shape-plan bucket
(see ``DefineAndRunGraph.run``), mirroring Hetu's shape-plan pool.
"""
from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..core.dtype import DataType, canonicalize_dtype
from ..parallel.dstates import (DistributedStates, DistributedStatesHierarchy,
                                DistributedStatesUnion)

_tensor_ids = itertools.count()


class SymbolicDim:
    """A named symbolic dimension (reference IntSymbol, core/symbol.h).

    Carries an optional current binding so eager execution works; under
    define-and-run the binding comes from the feed shapes at run time.

    Arithmetic composes symbols into a lazily-evaluated DAG (the
    reference's IntSymbol operator overloads): ``seq // cp * heads``
    yields a :class:`DerivedDim` that re-evaluates from its parents at
    every ``get()`` — rebinding a leaf propagates to every derived dim.
    """

    __slots__ = ("name", "_value")

    def __init__(self, name: str, value: Optional[int] = None):
        self.name = name
        self._value = value

    def set(self, value: int) -> None:
        self._value = int(value)

    def get(self) -> int:
        if self._value is None:
            raise ValueError(f"symbolic dim {self.name!r} is unbound")
        return self._value

    @property
    def is_bound(self) -> bool:
        return self._value is not None

    def __repr__(self) -> str:
        return f"Sym({self.name}={self._value})"

    # -- IntSymbol arithmetic DAG (core/symbol.h operator overloads) -------

    def _derive(self, op: str, fn, other, swapped: bool = False):
        if not isinstance(other, (int, SymbolicDim)):
            return NotImplemented
        a, b = (other, self) if swapped else (self, other)
        return DerivedDim(op, fn, (a, b))

    def __add__(self, o):
        return self._derive("+", lambda a, b: a + b, o)

    def __radd__(self, o):
        return self._derive("+", lambda a, b: a + b, o, swapped=True)

    def __sub__(self, o):
        return self._derive("-", lambda a, b: a - b, o)

    def __rsub__(self, o):
        return self._derive("-", lambda a, b: a - b, o, swapped=True)

    def __mul__(self, o):
        return self._derive("*", lambda a, b: a * b, o)

    def __rmul__(self, o):
        return self._derive("*", lambda a, b: a * b, o, swapped=True)

    def __floordiv__(self, o):
        return self._derive("//", lambda a, b: a // b, o)

    def __rfloordiv__(self, o):
        return self._derive("//", lambda a, b: a // b, o, swapped=True)

    def __mod__(self, o):
        return self._derive("%", lambda a, b: a % b, o)

    def __rmod__(self, o):
        return self._derive("%", lambda a, b: a % b, o, swapped=True)


class DerivedDim(SymbolicDim):
    """A dim computed from other dims (the IntSymbol expression DAG).

    ``get()`` evaluates from the parents every time, so rebinding a leaf
    symbol is visible everywhere; an explicit ``set()`` installs a
    provisional override (the shape-bucket pools bind unbound dims
    provisionally, graph.py) which the next ``set``/parent rebinding via
    ``clear_override`` controls.
    """

    __slots__ = ("_fn", "_parents")

    def __init__(self, op: str, fn, parents):
        names = [p.name if isinstance(p, SymbolicDim) else str(p)
                 for p in parents]
        super().__init__(f"({names[0]}{op}{names[1]})", None)
        self._fn = fn
        self._parents = tuple(parents)

    @staticmethod
    def _val(p) -> Optional[int]:
        if isinstance(p, SymbolicDim):
            return p.get() if p.is_bound else None
        return int(p)

    def get(self) -> int:
        if self._value is not None:       # provisional override
            return self._value
        vals = [self._val(p) for p in self._parents]
        if any(v is None for v in vals):
            raise ValueError(f"symbolic dim {self.name!r} is unbound "
                             f"(parent unbound)")
        return int(self._fn(*vals))

    @property
    def is_bound(self) -> bool:
        if self._value is not None:
            return True
        return all(self._val(p) is not None for p in self._parents)

    def clear_override(self) -> None:
        self._value = None

    def __repr__(self) -> str:
        try:
            return f"Sym({self.name}={self.get()})"
        except ValueError:
            return f"Sym({self.name}=?)"


DimLike = Union[int, SymbolicDim]


def concrete_shape(shape: Sequence[DimLike]) -> Tuple[int, ...]:
    return tuple(d.get() if isinstance(d, SymbolicDim) else int(d)
                 for d in shape)


def has_symbolic(shape: Sequence[DimLike]) -> bool:
    return any(isinstance(d, SymbolicDim) for d in shape)


class Tensor:
    """Graph-level tensor handle."""

    def __init__(self, shape: Sequence[DimLike], dtype: Any = None,
                 producer: Optional["OpNode"] = None,
                 name: str = "", graph: Optional[Any] = None,
                 trainable: bool = False,
                 requires_grad: bool = False,
                 is_grad: bool = False):
        self.id = next(_tensor_ids)
        self.shape = tuple(shape)
        self.dtype: DataType = canonicalize_dtype(dtype)
        self.producer = producer
        self.name = name or f"tensor_{self.id}"
        self.graph = graph
        self.trainable = trainable
        self.requires_grad = requires_grad or trainable
        self.is_grad = is_grad
        self.ds_hierarchy: Optional[DistributedStatesHierarchy] = None
        # set for variables/placeholders by the owning graph
        self._data: Optional[jnp.ndarray] = None

    # -- sharding annotation ------------------------------------------------

    @property
    def ds_union(self) -> Optional[DistributedStatesUnion]:
        if self.ds_hierarchy is None or self.ds_hierarchy.size() == 0:
            return None
        g = self.graph
        sid = getattr(g, "cur_strategy_id", 0) if g is not None else 0
        sid = min(sid, self.ds_hierarchy.size() - 1)
        return self.ds_hierarchy.get(sid)

    @property
    def distributed_states(self) -> Optional[DistributedStates]:
        u = self.ds_union
        return u.get_default_ds() if u is not None else None

    def set_ds_hierarchy(self, ds_hierarchy) -> None:
        if isinstance(ds_hierarchy, DistributedStatesHierarchy):
            self.ds_hierarchy = ds_hierarchy
        elif isinstance(ds_hierarchy, DistributedStatesUnion):
            self.ds_hierarchy = DistributedStatesHierarchy([ds_hierarchy])
        elif isinstance(ds_hierarchy, DistributedStates):
            self.ds_hierarchy = DistributedStatesHierarchy(
                [DistributedStatesUnion([ds_hierarchy])])
        elif isinstance(ds_hierarchy, (list, tuple)):
            unions = [u if isinstance(u, DistributedStatesUnion)
                      else DistributedStatesUnion([u]) for u in ds_hierarchy]
            self.ds_hierarchy = DistributedStatesHierarchy(unions)
        else:
            raise TypeError(f"bad ds annotation: {ds_hierarchy!r}")

    # -- shape helpers ------------------------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def is_symbolic(self) -> bool:
        return has_symbolic(self.shape)

    def concrete_shape(self) -> Tuple[int, ...]:
        return concrete_shape(self.shape)

    def numel(self) -> int:
        return int(np.prod(self.concrete_shape())) if self.shape else 1

    @property
    def global_shape(self) -> Tuple[int, ...]:
        return self.concrete_shape()

    def local_shape_for(self, device_index: int) -> Tuple[int, ...]:
        ds = self.distributed_states
        if ds is None:
            return self.concrete_shape()
        return ds.local_shape(self.concrete_shape())

    # -- value access (eager / after run) -----------------------------------

    def numpy(self) -> np.ndarray:
        data = self.get_data()
        return np.asarray(data)

    def get_data(self):
        if self._data is not None:
            return self._data
        if self.graph is not None:
            return self.graph.get_tensor_value(self)
        raise ValueError(f"{self.name} has no concrete value")

    def set_data(self, value) -> None:
        self._data = value

    # -- operator overloads -> ops module -----------------------------------

    def _ops(self):
        from .. import ops
        return ops

    def __add__(self, other):
        return self._ops().add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return self._ops().sub(self, other)

    def __rsub__(self, other):
        return self._ops().sub(other, self)

    def __mul__(self, other):
        return self._ops().mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._ops().div(self, other)

    def __rtruediv__(self, other):
        return self._ops().div(other, self)

    def __neg__(self):
        return self._ops().neg(self)

    def __pow__(self, e):
        return self._ops().pow(self, e)

    def __matmul__(self, other):
        return self._ops().matmul(self, other)

    def __getitem__(self, idx):
        return self._ops().getitem(self, idx)

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self._ops().reshape(self, shape)

    def transpose(self, *perm):
        if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
            perm = tuple(perm[0])
        return self._ops().transpose(self, perm or None)

    def sum(self, axis=None, keepdims=False):
        return self._ops().reduce_sum(self, axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._ops().reduce_mean(self, axis, keepdims)

    def to(self, dtype):
        return self._ops().cast(self, dtype)

    def __repr__(self) -> str:
        ds = self.distributed_states
        dss = f", ds={ds}" if ds is not None else ""
        return (f"Tensor(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype.value}{dss})")

    def __hash__(self):
        return self.id

    def __eq__(self, other):
        return isinstance(other, Tensor) and other.id == self.id
