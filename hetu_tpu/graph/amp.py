"""AMP: autocast dtype context + dynamic-loss-scaling GradScaler.

TPU-native re-expression of the reference's AMP stack
(``hetu/graph/autocast/*``: dtype context stack consulted per op;
``GradScaler`` with inf-check via the ``CheckFinite`` kernel and the
``update_scale`` op, ``hetu/impl/kernel/CheckFinite.cu``).

* :class:`autocast` — a graph-construction context: ops created inside it
  record a compute dtype; matmul-class ops cast their floating inputs down
  (bf16/fp16 ride the MXU), numerically-sensitive ops (losses, softmax,
  norms) cast up to fp32.  The cast is folded into the op's impl at trace
  time so XLA fuses it into the surrounding computation.
* :class:`GradScaler` — dynamic loss scaling for fp16: scales the loss,
  unscales grads, skips the update when any grad is non-finite, and grows /
  backs off the scale (reference ``update_scale`` semantics).  On TPU bf16
  autocast normally needs no scaler; it exists for fp16 parity.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.dtype import canonicalize_dtype

# Ops whose inputs are cast DOWN to the autocast dtype (MXU-bound).
_LOW_PRECISION_OPS = frozenset({
    "matmul", "batch_matmul", "linear", "einsum", "conv2d",
    "fused_lm_cross_entropy",
    "attention", "parallel_attention", "flash_attention",
})
# Ops whose floating inputs are cast UP to fp32 (numerically sensitive).
_FULL_PRECISION_OPS = frozenset({
    "softmax_cross_entropy", "nll_loss", "mse_loss", "kl_div",
    "bce", "vocab_parallel_cross_entropy",
    "log_softmax", "layer_norm", "rms_norm", "batch_norm",
})

_autocast_stack: List[Any] = []


class autocast:
    """``with ht.autocast(ht.bfloat16):`` (reference
    ``python/hetu/__init__.py:141``)."""

    def __init__(self, dtype="bfloat16", enabled: bool = True):
        self.dtype = canonicalize_dtype(dtype)
        self.enabled = enabled

    def __enter__(self):
        _autocast_stack.append(self if self.enabled else None)
        return self

    def __exit__(self, *exc):
        _autocast_stack.pop()


def current_autocast() -> Optional[autocast]:
    return _autocast_stack[-1] if _autocast_stack else None


def _cast_floats(args, dtype):
    out = []
    for a in args:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != dtype:
            out.append(a.astype(dtype))
        else:
            out.append(a)
    return out


def wrap_impl(op_type: str, impl):
    """Fold the ambient autocast policy into an op impl (consulted by the
    op factory at graph-construction time, like the reference's per-op
    dtype deduction under AutoCast)."""
    ac = current_autocast()
    if ac is None:
        return impl
    if op_type in _LOW_PRECISION_OPS:
        lo = ac.dtype.to_jnp()

        def low(*args, **kw):
            return impl(*_cast_floats(args, lo), **kw)
        return low
    if op_type in _FULL_PRECISION_OPS:
        def full(*args, **kw):
            return impl(*_cast_floats(args, jnp.float32), **kw)
        return full
    return impl


# ---------------------------------------------------------------------------
# GradScaler
# ---------------------------------------------------------------------------

def check_finite(grads) -> jax.Array:
    """True iff every leaf of ``grads`` is finite (reference CheckFinite
    kernel: writes a flag consumed by update_scale)."""
    leaves = jax.tree_util.tree_leaves(grads)
    ok = jnp.bool_(True)
    for g in leaves:
        if jnp.issubdtype(g.dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


class GradScaler:
    """Dynamic loss scaling (reference ``hetu/graph/autocast/grad_scaler.*``).

    State lives with the optimizer state so the scale update compiles into
    the same XLA step program as the parameter update.
    """

    def __init__(self, init_scale: float = 2.0 ** 16,
                 growth_factor: float = 2.0, backoff_factor: float = 0.5,
                 growth_interval: int = 2000, enabled: bool = True):
        self.init_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.enabled = enabled
        self._host_state: Optional[Dict[str, Any]] = None

    # state pytree: {"scale": f32[], "good_steps": i32[]}
    def init_state(self) -> Dict[str, jax.Array]:
        if self._host_state is None:
            self._host_state = {
                "scale": jnp.float32(self.init_scale),
                "good_steps": jnp.int32(0),
            }
        return self._host_state

    def store_state(self, state: Dict[str, jax.Array]) -> None:
        self._host_state = state

    @property
    def scale(self) -> float:
        return float(self.init_state()["scale"])

    def scale_loss(self, loss, state):
        if not self.enabled:
            return loss
        # scale in fp32: casting the scale into an fp16 loss would overflow
        # (default 2**16 > fp16 max)
        return loss.astype(jnp.float32) * state["scale"]

    def unscale_loss(self, loss, state):
        if not self.enabled:
            return loss
        return loss.astype(jnp.float32) / state["scale"]

    def unscale_grads(self, grads, state):
        if not self.enabled:
            return grads
        inv = (1.0 / state["scale"])
        return jax.tree_util.tree_map(
            lambda g: (g * inv.astype(g.dtype))
            if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)

    def update_state(self, state, finite) -> Dict[str, jax.Array]:
        """The ``update_scale`` op: grow after `growth_interval` consecutive
        finite steps, back off immediately on overflow."""
        if not self.enabled:
            return state
        good = jnp.where(finite, state["good_steps"] + 1, 0)
        grow = good >= self.growth_interval
        scale = jnp.where(
            finite,
            jnp.where(grow, state["scale"] * self.growth_factor,
                      state["scale"]),
            state["scale"] * self.backoff_factor)
        good = jnp.where(grow, 0, good)
        return {"scale": scale.astype(jnp.float32),
                "good_steps": good.astype(jnp.int32)}
