from .tensor import Tensor, SymbolicDim
from .graph import (Graph, EagerGraph, DefineAndRunGraph, DefineByRunGraph, OpNode, RunLevel,
                    graph, run_level, get_default_graph,
                    ExecutableHandle, register_executable, get_executable,
                    iter_executables, clear_executables)
from .ctor import (placeholder, parameter, variable, parallel_placeholder,
                   parallel_parameter, Initializer, ConstantInitializer,
                   UniformInitializer, NormalInitializer,
                   TruncatedNormalInitializer, XavierUniformInitializer,
                   XavierNormalInitializer, HeUniformInitializer,
                   HeNormalInitializer, ProvidedInitializer)

__all__ = [
    "Tensor", "SymbolicDim", "Graph", "EagerGraph", "DefineAndRunGraph", "DefineByRunGraph",
    "OpNode", "RunLevel", "graph", "run_level", "get_default_graph",
    "ExecutableHandle", "register_executable", "get_executable",
    "iter_executables", "clear_executables",
    "placeholder", "parameter", "variable", "parallel_placeholder",
    "parallel_parameter", "Initializer", "ConstantInitializer",
    "UniformInitializer", "NormalInitializer", "TruncatedNormalInitializer",
    "XavierUniformInitializer", "XavierNormalInitializer",
    "HeUniformInitializer", "HeNormalInitializer", "ProvidedInitializer",
]
