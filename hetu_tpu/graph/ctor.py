"""Tensor constructors: placeholders, parameters, variables.

Mirrors the reference's tensor ctors incl. ``parallel_placeholder`` /
``parallel_parameter`` (``python/hetu/_binding/graph/tensor_ctor.cc:144``)
and the initializer hierarchy (``hetu/graph/init/initializer.h``).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..core.dtype import canonicalize_dtype
from .graph import Graph, get_default_graph
from .tensor import Tensor

_seed_counter = [0]


def _next_key(seed: Optional[int] = None) -> jax.Array:
    if seed is None:
        _seed_counter[0] += 1
        seed = _seed_counter[0]
    return jax.random.PRNGKey(seed)


# -- initializers (reference Initializer hierarchy) -------------------------

class Initializer:
    def __call__(self, shape, dtype) -> jax.Array:
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class UniformInitializer(Initializer):
    def __init__(self, lr: Union[float, Sequence[float]] = 0.1, seed=None):
        self.range = (-lr, lr) if np.isscalar(lr) else tuple(lr)
        self.seed = seed

    def __call__(self, shape, dtype):
        return jax.random.uniform(_next_key(self.seed), shape, jnp.float32,
                                  self.range[0], self.range[1]).astype(dtype)


class NormalInitializer(Initializer):
    def __init__(self, mean: float = 0.0, stddev: float = 0.01, seed=None):
        self.mean, self.stddev, self.seed = mean, stddev, seed

    def __call__(self, shape, dtype):
        return (self.mean + self.stddev * jax.random.normal(
            _next_key(self.seed), shape, jnp.float32)).astype(dtype)


class TruncatedNormalInitializer(NormalInitializer):
    def __call__(self, shape, dtype):
        return (self.mean + self.stddev * jax.random.truncated_normal(
            _next_key(self.seed), -2.0, 2.0, shape, jnp.float32)).astype(dtype)


class XavierUniformInitializer(Initializer):
    def __init__(self, gain: float = 1.0, seed=None):
        self.gain, self.seed = gain, seed

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * float(np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(_next_key(self.seed), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class XavierNormalInitializer(Initializer):
    def __init__(self, gain: float = 1.0, seed=None):
        self.gain, self.seed = gain, seed

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fans(shape)
        std = self.gain * float(np.sqrt(2.0 / (fan_in + fan_out)))
        return (std * jax.random.normal(_next_key(self.seed), shape,
                                        jnp.float32)).astype(dtype)


class HeUniformInitializer(Initializer):
    def __init__(self, seed=None):
        self.seed = seed

    def __call__(self, shape, dtype):
        fan_in, _ = _fans(shape)
        limit = float(np.sqrt(6.0 / fan_in))
        return jax.random.uniform(_next_key(self.seed), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class HeNormalInitializer(Initializer):
    def __init__(self, seed=None):
        self.seed = seed

    def __call__(self, shape, dtype):
        fan_in, _ = _fans(shape)
        std = float(np.sqrt(2.0 / fan_in))
        return (std * jax.random.normal(_next_key(self.seed), shape,
                                        jnp.float32)).astype(dtype)


class ProvidedInitializer(Initializer):
    def __init__(self, data):
        self.data = data

    def __call__(self, shape, dtype):
        arr = jnp.asarray(self.data, dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"provided data shape {arr.shape} != {shape}"
        return arr


def _fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


# -- constructors -----------------------------------------------------------

def placeholder(dtype=None, shape: Sequence = (), name: str = "",
                graph: Optional[Graph] = None) -> Tensor:
    g = graph or get_default_graph()
    t = Tensor(shape, dtype, name=name or "placeholder", graph=g)
    g.add_placeholder(t)
    return t


def parameter(init: Union[Initializer, Any], shape: Sequence = None,
              dtype=None, name: str = "", trainable: bool = True,
              requires_grad: Optional[bool] = None,
              graph: Optional[Graph] = None) -> Tensor:
    g = graph or get_default_graph()
    if not isinstance(init, Initializer):
        data = np.asarray(init)
        shape = data.shape if shape is None else shape
        init = ProvidedInitializer(data)
    dt = canonicalize_dtype(dtype)
    if requires_grad is None:
        requires_grad = trainable
    t = Tensor(shape, dt, name=name or "param", graph=g,
               trainable=trainable, requires_grad=requires_grad)
    jdt = dt.to_jnp()
    g.add_variable(t, lambda init=init, shape=tuple(
        int(s) for s in shape), jdt=jdt: init(shape, jdt))
    return t


variable = parameter


def parallel_placeholder(dtype, global_shape: Sequence, ds_hierarchy=None,
                         pspec: Optional[PartitionSpec] = None,
                         name: str = "", graph: Optional[Graph] = None) -> Tensor:
    """Placeholder with sharding annotation (tensor_ctor.cc:144)."""
    t = placeholder(dtype, global_shape, name, graph)
    if ds_hierarchy is not None:
        t.set_ds_hierarchy(ds_hierarchy)
    if pspec is not None:
        t.pspec = pspec
    return t


def parallel_parameter(init: Union[Initializer, Any], global_shape: Sequence,
                       ds_hierarchy=None, pspec: Optional[PartitionSpec] = None,
                       dtype=None, name: str = "", trainable: bool = True,
                       graph: Optional[Graph] = None) -> Tensor:
    """Parameter with sharding annotation: initialized at global shape and
    device_put with its NamedSharding, so each device materializes only its
    shard (XLA handles the scatter — the analogue of deferred sharded init)."""
    t = parameter(init, global_shape, dtype, name, trainable, graph=graph)
    if ds_hierarchy is not None:
        t.set_ds_hierarchy(ds_hierarchy)
    if pspec is not None:
        t.pspec = pspec
    return t
