"""Static per-executable peak-HBM model + XLA cross-check.

The collectives side of the analyzer (PR 3/5) statically explains 100%
of what a program *communicates*; this module does the same for what it
*holds*.  Every prediction is computed from facts the registry already
carries — no execution, no profiling:

* **resident state** — every argument leaf of the lowered program,
  sharded down by its registered divisor (param pspecs from the graph,
  the flat optimizer buffers' ``P(dp)`` layout, feed pspecs), classified
  as ``param`` / ``opt-state`` / ``grad`` / ``feed`` / ``kv-page``
  (serving-pool page arrays, recognized through the pool snapshot hook).
* **activation liveness** — a last-use interval walk over the closed
  jaxpr (:func:`liveness_walk`): buffers allocate at their defining eqn
  and free after their last consumer; scan body temporaries peak once
  (not × trips) and the final carry aliases the running carry buffer;
  remat regions need no special casing because the walk runs on the
  *post-AD* jaxpr, where rematerialization has already replaced the
  saved-activation intervals it eliminates.
* **donation-aware outputs** — donated input leaves are matched to
  output leaves by (shape, dtype); only the unmatched output bytes cost
  new HBM (XLA writes the rest in place, exactly what its alias table
  reports).

The sum is a :class:`MemoryReport`: peak bytes, a per-kind breakdown,
and an attribution table of the top contributors with file:line
provenance for activations.

**XLA cross-check** (:func:`xla_memory_stats` + ``MemoryReport.xla``):
the same compiled executable the GSPMD accounting already builds exposes
``compiled.memory_analysis()`` — argument/output/temp/alias bytes.  The
mapping is component-wise: resident ↔ ``argument``, unmatched outputs ↔
``output − alias``, activation peak ↔ ``temp``.  Two documented,
platform-only adjustments apply to the *comparable* number
(``cmp_peak_bytes``), never to the native prediction the planner and
the baseline use:

* CPU has no native bf16/f16 — XLA upcasts narrow-float intermediates
  to f32 buffers, so the cross-check counts them at 4 bytes;
* sub-64KB programs are alignment/fragmentation-dominated, so the gate
  tolerance has a small absolute floor.

Why XLA can still differ (DESIGN.md §14): fusion eliminates most
elementwise intermediates (the walk materializes only
:data:`MATERIALIZE_PRIMS` outputs), but XLA *keeps* a bounded set of
small long-lived fusible values (attention probabilities, norm
statistics) instead of recomputing them in their far-away backward
consumers — modeled by the capped residual pool
(:data:`RESIDUAL_FAR_EQNS` / :data:`RESIDUAL_SMALL_BYTES` /
:data:`RESIDUAL_POOL_CAP`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: primitives whose outputs always materialize as real buffers (XLA
#: cannot fuse them away): contractions, data movement, collectives,
#: control-flow containers, reductions.  Everything else is assumed
#: fused into its consumer.
MATERIALIZE_PRIMS = frozenset({
    "dot_general", "conv_general_dilated", "scatter", "scatter-add",
    "scatter_add", "gather", "concatenate", "sort", "top_k", "cumsum",
    "psum", "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "ppermute", "pmax", "pmin", "rng_bit_generator", "threefry2x32",
    "scan", "while", "cond", "custom_vjp_call", "custom_jvp_call",
    "pjit", "remat", "remat2", "checkpoint", "shard_map",
    "dynamic_update_slice", "pad", "rev", "dynamic_slice",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "argmax", "argmin", "reduce_and", "reduce_or", "add_any",
    "select_and_scatter_add", "reduce_window",
})

#: primitives XLA runs in place when the operand dies at the eqn: the
#: output reuses the input buffer (same-size collectives, DUS/scatter).
INPLACE_PRIMS = frozenset({
    "dynamic_update_slice", "scatter", "scatter_add", "scatter-add",
    "psum", "pmax", "pmin", "ppermute", "all_to_all",
})

#: residual-pool model: a *fusible* value consumed more than
#: RESIDUAL_FAR_EQNS equations after its definition and no larger than
#: RESIDUAL_SMALL_BYTES (post-sharding) is a candidate XLA materializes
#: rather than recomputes; the pool's live total is capped at
#: RESIDUAL_POOL_CAP x the materialized live set (XLA keeps *some* of
#: them, never all — calibrated once against the frozen gate families).
RESIDUAL_FAR_EQNS = 8
RESIDUAL_SMALL_BYTES = 8192
RESIDUAL_POOL_CAP = 0.3

#: CPU cross-check only: XLA's CPU backend has no native bf16/f16 and
#: materializes intermediates as f32.
NARROW_FLOAT_WIDTH = {"bfloat16": 4, "float16": 4}

#: absolute tolerance floor for the XLA cross-check: below this,
#: buffer-assignment alignment and fragmentation dominate.
XLA_ABS_TOLERANCE = 1 << 16


# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MemoryBuffer:
    """One attributed HBM contributor."""
    kind: str                 # param|opt-state|grad|feed|kv-page|
    #                           activation|output|input
    name: str                 # param name / arg path / primitive
    nbytes: int               # per-device bytes (sharding applied)
    source: str = ""          # file:line provenance (activations)
    detail: str = ""          # shape/dtype slug

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class MemoryReport:
    """Static peak-HBM prediction for one executable."""
    name: str = ""
    peak_bytes: int = 0            # native dtype widths (the TPU truth)
    cmp_peak_bytes: int = 0        # platform-comparable (CPU upcast)
    resident_bytes: int = 0
    activation_peak_bytes: int = 0
    output_extra_bytes: int = 0    # outputs no donated input absorbs
    by_kind: Dict[str, int] = dataclasses.field(default_factory=dict)
    buffers: List[MemoryBuffer] = dataclasses.field(default_factory=list)
    # XLA cross-check: argument/output/temp/alias/total bytes from
    # compiled.memory_analysis(), or None when not compiled
    xla: Optional[Dict[str, int]] = None

    def top(self, k: int = 10) -> List[MemoryBuffer]:
        return sorted(self.buffers, key=lambda b: -b.nbytes)[:k]

    def dominant_kind(self) -> str:
        if not self.by_kind:
            return "?"
        return max(self.by_kind.items(), key=lambda kv: kv[1])[0]

    @property
    def xla_total(self) -> Optional[int]:
        if self.xla is None:
            return None
        return (self.xla["argument"] + self.xla["output"]
                + self.xla["temp"] - self.xla["alias"])

    def xla_delta(self) -> Optional[float]:
        """Relative delta of the comparable prediction vs XLA's total
        (signed; None when the executable was not compiled)."""
        tot = self.xla_total
        if tot is None or tot <= 0:
            return None
        return (self.cmp_peak_bytes - tot) / tot

    def xla_within(self, rel: float = 0.1,
                   abs_floor: int = XLA_ABS_TOLERANCE) -> Optional[bool]:
        tot = self.xla_total
        if tot is None:
            return None
        return abs(self.cmp_peak_bytes - tot) <= max(rel * tot, abs_floor)

    def to_dict(self, buffers: bool = False) -> dict:
        d: Dict[str, Any] = {
            "peak_bytes": int(self.peak_bytes),
            "by_kind": {k: int(v) for k, v in sorted(self.by_kind.items())},
        }
        if self.xla is not None:
            d["xla_total_bytes"] = int(self.xla_total)
            delta = self.xla_delta()
            d["xla_delta_pct"] = round(100.0 * delta, 1) \
                if delta is not None else None
        if buffers:
            d["top_buffers"] = [b.to_dict() for b in self.top(10)]
        return d

    def summary(self) -> str:
        parts = [f"peak {_fmt_bytes(self.peak_bytes)}"]
        for k, v in sorted(self.by_kind.items(), key=lambda kv: -kv[1]):
            if v:
                parts.append(f"{k} {_fmt_bytes(v)}")
        s = ", ".join(parts)
        d = self.xla_delta()
        if d is not None:
            s += f" (xla {_fmt_bytes(self.xla_total)}, {d:+.1%})"
        return s


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


# ---------------------------------------------------------------------------
# activation liveness walk
# ---------------------------------------------------------------------------


def _as_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr


def _aval_bytes(aval, upcast: bool) -> int:
    try:
        dt = np.dtype(aval.dtype)
        item = NARROW_FLOAT_WIDTH.get(dt.name, dt.itemsize) if upcast \
            else dt.itemsize
        return int(np.prod(aval.shape, dtype=np.int64) * item)
    except Exception:
        return 0


def _source_of(eqn) -> str:
    si = getattr(eqn, "source_info", None)
    if si is None:
        return ""
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(si)
        if fr is not None:
            import os
            return f"{os.path.basename(fr.file_name)}:{fr.start_line}"
    except Exception:
        pass
    return ""


@dataclasses.dataclass
class _LivePeak:
    """Result of one (sub-)jaxpr liveness walk."""
    peak: float = 0.0
    # materialized buffers live at the peak instant: (bytes, prim, src)
    at_peak: List[Tuple[float, str, str]] = dataclasses.field(
        default_factory=list)


def liveness_walk(jaxpr, scale: float = 1.0, upcast: bool = False,
                  param_shapes: frozenset = frozenset(),
                  param_scale: Optional[float] = None) -> _LivePeak:
    """Peak transient (activation/temp) bytes of a closed jaxpr.

    ``scale`` divides global aval bytes down to per-device (GSPMD batch
    sharding over dp); inside ``shard_map`` regions avals are already
    per-device block shapes, so the scale resets to 1.  ``param_shapes``
    marks shapes whose intermediates (weight gradients, optimizer math)
    are *replicated* over dp unless ZeRO shards them — their scale is
    ``param_scale``.

    Rules (module docstring): only :data:`MATERIALIZE_PRIMS` outputs
    allocate; :data:`INPLACE_PRIMS` reuse a dying operand's buffer;
    jaxpr outvars cost nothing here (they land in donated/output
    buffers, accounted by the resident/output components); a scan's
    final carry aliases the running carry; small far-consumed fusible
    values feed a capped residual pool.
    """
    if param_scale is None:
        param_scale = scale
    j = _as_jaxpr(jaxpr)
    eqns = j.eqns
    last_use: Dict[int, int] = {}
    invars = {id(v) for v in j.invars}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if hasattr(v, "count"):
                last_use[id(v)] = i
    held = {id(v) for v in j.outvars if hasattr(v, "count")}
    live = 0.0
    resid = 0.0
    out = _LivePeak()
    var_bytes: Dict[int, float] = {}
    resid_bytes: Dict[int, float] = {}
    live_desc: Dict[int, Tuple[float, str, str]] = {}
    for i, eqn in enumerate(eqns):
        pname = eqn.primitive.name
        sub_scale = 1.0 if pname == "shard_map" else scale
        sub_pscale = 1.0 if pname == "shard_map" else param_scale
        transient = _LivePeak()
        for sub in _sub_jaxprs(eqn):
            t = liveness_walk(sub, sub_scale, upcast, param_shapes,
                              sub_pscale)
            if t.peak > transient.peak:
                transient = t
        inplace = pname in INPLACE_PRIMS
        dying = [id(v) for v in {id(x): x for x in eqn.invars}.values()
                 if hasattr(v, "count") and last_use.get(id(v)) == i
                 and id(v) not in invars and id(v) not in held]
        if inplace:
            for v in dying:
                live -= var_bytes.pop(v, 0.0)
                resid -= resid_bytes.pop(v, 0.0)
                live_desc.pop(v, None)
        skip = set()
        if pname == "scan":
            # the final carry aliases the running carry buffer (updated
            # in place across trips) — only stacked ys are new memory
            nc = int(eqn.params.get("num_carry", 0))
            skip = {id(ov) for ov in eqn.outvars[:nc]
                    if hasattr(ov, "count")}
        out_b = 0.0
        mat = pname in MATERIALIZE_PRIMS
        src = None
        for ov in eqn.outvars:
            if not hasattr(ov, "count"):
                continue
            if id(ov) in held or id(ov) in skip:
                var_bytes[id(ov)] = 0.0
                continue
            sc = scale
            if tuple(getattr(ov.aval, "shape", ())) in param_shapes:
                sc = param_scale
            b = _aval_bytes(ov.aval, upcast) * sc
            if mat:
                var_bytes[id(ov)] = b
                out_b += b
                if b:
                    if src is None:
                        src = _source_of(eqn)
                    live_desc[id(ov)] = (
                        b, pname,
                        src or str(getattr(ov.aval, "shape", "")))
            elif last_use.get(id(ov), i) - i > RESIDUAL_FAR_EQNS \
                    and b <= RESIDUAL_SMALL_BYTES:
                resid_bytes[id(ov)] = b
                resid += b
                var_bytes[id(ov)] = 0.0
            else:
                var_bytes[id(ov)] = 0.0
        live += out_b
        here = live + min(resid, RESIDUAL_POOL_CAP * live) + transient.peak
        if here > out.peak:
            out.peak = here
            out.at_peak = sorted(live_desc.values(),
                                 key=lambda t: -t[0])[:8] \
                + transient.at_peak[:4]
        if not inplace:
            for v in dying:
                live -= var_bytes.pop(v, 0.0)
                resid -= resid_bytes.pop(v, 0.0)
                live_desc.pop(v, None)
    return out


def has_remat_region(jaxpr, _depth: int = 0) -> bool:
    """Whether any remat/checkpoint region appears in the jaxpr tree
    (the ``remat-opportunity`` rule's 'already covered' probe)."""
    if _depth > 8:
        return False
    j = _as_jaxpr(jaxpr)
    for eqn in j.eqns:
        name = eqn.primitive.name
        if name in ("remat", "remat2", "checkpoint"):
            return True
        if name == "pjit" and eqn.params.get("name") == "checkpoint":
            return True
        for sub in _sub_jaxprs(eqn):
            if has_remat_region(sub, _depth + 1):
                return True
    return False


# ---------------------------------------------------------------------------
# resident-state + output accounting
# ---------------------------------------------------------------------------


def _leaf_bytes(leaf) -> int:
    try:
        return int(np.prod(leaf.shape, dtype=np.int64)
                   * np.dtype(leaf.dtype).itemsize)
    except Exception:
        return 0


def _kv_page_shapes(serving) -> set:
    """Page-array shapes of the serving pool (kv-page classification).

    Read from the pool's live arrays (``page_array_shapes``), not its
    constructor attrs: the MLA latent layout stores a compressed
    ``[.., 1, latent_dim]`` stream (k) next to a rope/scale sidecar (v)
    whose shapes differ from ``(num_pages, page_size, kv_heads,
    head_dim)`` — and from each other."""
    shapes = set()
    pool = (serving or {}).get("pool")
    if pool is not None:
        try:
            k_shapes, v_shapes = pool.page_array_shapes()
            for s in (*k_shapes, *v_shapes):
                shapes.add(tuple(int(d) for d in s))
        except AttributeError:      # foreign pool object: attr fallback
            shapes.add((int(pool.num_pages), int(pool.page_size),
                        int(pool.kv_heads), int(pool.head_dim)))
    return shapes


def classify_args(handle) -> List[MemoryBuffer]:
    """Per-argument resident buffers of a lowered executable.

    Divisors (how many ways each leaf is sharded) come from the
    registered ``arg_divisors`` tree when present (the graph writes it
    from param/optimizer/feed pspecs); otherwise leaves matching a
    registered param's (shape, dtype) use that param's pspec divisor and
    everything else counts replicated.  Kinds ride the parallel
    ``arg_kinds`` tree, kv-page arrays are recognized by the pool's page
    shape, and flat optimizer buffers by the grad-comm flat layout.
    """
    import jax

    meta = handle.meta
    lowered = handle.lower()
    flat, _ = jax.tree_util.tree_flatten_with_path(lowered.args_info)
    divisors = meta.get("arg_divisors")
    kinds = meta.get("arg_kinds")
    div_leaves = jax.tree_util.tree_leaves(divisors) \
        if divisors is not None else None
    kind_leaves = jax.tree_util.tree_leaves(kinds) \
        if kinds is not None else None
    if div_leaves is not None and len(div_leaves) != len(flat):
        div_leaves = None           # registration drifted: fall back
    if kind_leaves is not None and len(kind_leaves) != len(flat):
        kind_leaves = None

    mesh_axes = {str(a): int(s)
                 for a, s in (meta.get("mesh_axes") or {}).items()}

    from ..parallel.dstates import pspec_shard_divisor

    def _pspec_divisor(pspec) -> int:
        return pspec_shard_divisor(pspec, mesh_axes)

    # fallback maps: (shape, dtype) -> (divisor, name) from params meta
    param_by_sig: Dict[Tuple, List[Tuple[int, str]]] = {}
    for p in meta.get("params", ()):
        sig = (tuple(p["shape"]), str(p["dtype"]))
        param_by_sig.setdefault(sig, []).append(
            (_pspec_divisor(p.get("pspec")), p["name"]))

    serving = meta.get("serving")
    if callable(serving):
        try:
            serving = serving()
        except Exception:
            serving = None
    page_shapes = _kv_page_shapes(serving)

    gc = meta.get("grad_comm") or {}
    flat_sizes: set = set()
    if gc.get("flat"):
        try:
            from ..optim.flat_state import FlatStateLayout
            lay = FlatStateLayout(
                [(n, tuple(s), d) for n, s, d in gc["entries"]],
                gc["device_num"], bucket_mb=gc["bucket_mb"])
            flat_sizes = {(int(s),) for s in lay.padded_sizes}
        except Exception:
            flat_sizes = set()
    dp = mesh_axes.get(meta.get("dp_axis") or "dp", 1)

    out: List[MemoryBuffer] = []
    for idx, (path, leaf) in enumerate(flat):
        if not hasattr(leaf, "shape"):
            continue
        nb = _leaf_bytes(leaf)
        sig = (tuple(leaf.shape), np.dtype(leaf.dtype).name)
        div = None
        kind = None
        name = jax.tree_util.keystr(path)
        if div_leaves is not None:
            try:
                div = int(div_leaves[idx])
            except (TypeError, ValueError):
                div = None
        if kind_leaves is not None and isinstance(kind_leaves[idx], str):
            kind = kind_leaves[idx]
        if tuple(leaf.shape) in page_shapes:
            kind = "kv-page"
            div = div or 1
        elif tuple(leaf.shape) in flat_sizes \
                and np.dtype(leaf.dtype).name == "float32":
            kind = kind or "opt-state"
            div = div if div is not None else dp
        if div is None or kind is None:
            cands = param_by_sig.get(sig)
            if cands:
                d, pname = cands[0]
                if len(cands) > 1:
                    param_by_sig[sig] = cands[1:]
                div = div if div is not None else d
                kind = kind or "param"
                name = pname
        out.append(MemoryBuffer(
            kind=kind or "input", name=name,
            nbytes=int(np.ceil(nb / max(div or 1, 1))),
            detail=f"{sig[1]}{list(sig[0])}"))
    return out


def parse_input_output_aliases(hlo_text: str) -> List[Tuple[int, int]]:
    """``(output_index, parameter_number)`` pairs from a compiled HLO's
    ``input_output_alias`` directive — XLA's actual alias table, used to
    de-false-positive ``donation-miss`` (a shape-matched output that XLA
    already aliased to some *other* donated input is not reusable)."""
    import re
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return []
    # the directive nests braces ({output index} / param shape-index
    # {}), so find its end by depth, not by regex
    i = start + len(key)
    depth = 1
    while i < len(hlo_text) and depth:
        c = hlo_text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        i += 1
    body = hlo_text[start + len(key):i - 1]
    # entries look like: {0}: (3, {}, may-alias) — {output index}:
    # (param number, param shape-index, kind)
    return [(int(om) if om else 0, int(pm))
            for om, pm in re.findall(r"\{(\d*)\}\s*:\s*\((\d+)", body)]


def output_accounting(handle, arg_buffers: Sequence[MemoryBuffer]
                      ) -> Tuple[int, int]:
    """(output_extra_bytes, donated_alias_bytes): outputs not absorbed
    by a donated input, and the bytes that are (the static counterpart
    of XLA's ``alias_size_in_bytes``).

    Outputs inherit the sharding divisor of the same-signature input
    (a train step's outputs mirror its state arguments); outputs with
    no matching input count replicated.
    """
    import jax

    lowered = handle.lower()
    try:
        out_avals = handle.jaxpr.out_avals
    except Exception:
        return 0, 0
    flat, _ = jax.tree_util.tree_flatten_with_path(lowered.args_info)
    shaped = [leaf for _p, leaf in flat if hasattr(leaf, "shape")]
    div_by_sig: Dict[Tuple, int] = {}
    donated: Dict[Tuple, int] = {}
    for leaf, buf in zip(shaped, arg_buffers):
        sig = (tuple(leaf.shape), np.dtype(leaf.dtype).name)
        if sig not in div_by_sig:
            div_by_sig[sig] = max(
                1, int(round(_leaf_bytes(leaf) / max(buf.nbytes, 1))))
        if getattr(leaf, "donated", False):
            donated[sig] = donated.get(sig, 0) + 1
    extra = 0
    alias = 0
    for o in jax.tree_util.tree_leaves(out_avals):
        if not hasattr(o, "shape"):
            continue
        sig = (tuple(o.shape), np.dtype(o.dtype).name)
        nb = _leaf_bytes(o)
        div = div_by_sig.get(sig, 1)
        if donated.get(sig, 0) > 0:
            donated[sig] -= 1
            alias += int(np.ceil(nb / div))
        else:
            extra += int(np.ceil(nb / div))
    return extra, alias


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def xla_memory_stats(handle) -> Optional[Dict[str, int]]:
    """argument/output/temp/alias bytes from the compiled executable's
    own ``memory_analysis()`` (None when unavailable)."""
    try:
        ma = handle.compile().memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None
    try:
        return {
            "argument": int(ma.argument_size_in_bytes),
            "output": int(ma.output_size_in_bytes),
            "temp": int(ma.temp_size_in_bytes),
            "alias": int(ma.alias_size_in_bytes),
        }
    except AttributeError:
        return None


def predict_memory(handle, xla: bool = False) -> MemoryReport:
    """The static peak-HBM model for one registered executable.

    ``peak = resident(args, sharded by registered divisors)
           + activation liveness peak (jaxpr walk)
           + outputs no donated input absorbs``

    With ``xla=True`` the compiled executable's ``memory_analysis()``
    is attached for the cross-check (compiles on first call — the gate
    already pays this for GSPMD accounting).
    """
    meta = handle.meta
    mesh_axes = {str(a): int(s)
                 for a, s in (meta.get("mesh_axes") or {}).items()}
    dp = mesh_axes.get(meta.get("dp_axis") or "dp", 1)
    gc = meta.get("grad_comm") or {}
    # graph registration records zero/flat_state for EVERY train plan
    # (implicit-sync ones carry no grad_comm entry); same precedence as
    # the replicated-state-under-shard rule so the two passes agree
    zero = int(meta.get("zero", gc.get("zero", 0)) or 0)
    flat = bool(meta.get("flat_state", gc.get("flat", False)))

    rep = MemoryReport(name=handle.name)
    arg_buffers = classify_args(handle)
    rep.buffers.extend(arg_buffers)
    rep.resident_bytes = sum(b.nbytes for b in arg_buffers)

    rep.output_extra_bytes, _alias = output_accounting(handle, arg_buffers)
    if rep.output_extra_bytes:
        rep.buffers.append(MemoryBuffer(
            kind="output", name="un-donated outputs",
            nbytes=rep.output_extra_bytes,
            detail="outputs with no donated input to alias"))

    param_shapes = frozenset(tuple(p["shape"])
                             for p in meta.get("params", ()))
    # weight-gradient / optimizer intermediates are replicated over dp
    # (they have no batch dim) unless ZeRO shards the update
    pscale = 1.0 / max(dp, 1) if (zero >= 1 or flat) else 1.0
    scale = 1.0 / max(dp, 1)
    jaxpr = handle.jaxpr
    native = liveness_walk(jaxpr, scale=scale, upcast=False,
                           param_shapes=param_shapes, param_scale=pscale)
    rep.activation_peak_bytes = int(native.peak)
    for b, prim, src in native.at_peak:
        rep.buffers.append(MemoryBuffer(
            kind="activation", name=prim, nbytes=int(b),
            source=src if ":" in src else "", detail=src))
    if flat and zero >= 3:
        # ZeRO-3's just-in-time param gather: the per-bucket gathered
        # weight-dtype buffers AND their unpacked per-param views stay
        # live through fwd+bwd — at FULL size, not dp-sharded (the
        # liveness walk prices param-shaped intermediates at 1/dp,
        # right for weight grads but not for the gathered copies).
        # Transient, so by_kind keeps them out of the at-rest "param"
        # class the replicated-state-under-shard rule polices.  Bucket
        # padding is ignored (<= dp*block elems per bucket).
        gath = 2 * sum(
            int(np.prod(s) if s else 1) * np.dtype(d).itemsize
            for _, s, d in gc.get("entries", ()))
        if gath:
            rep.activation_peak_bytes += gath
            rep.buffers.append(MemoryBuffer(
                kind="activation", name="param_gather", nbytes=gath,
                detail="just-in-time gathered params + unpacked views "
                       "(full size, transient)"))
    rep.peak_bytes = (rep.resident_bytes + rep.activation_peak_bytes
                      + rep.output_extra_bytes)

    # platform-comparable peak: CPU upcasts narrow-float intermediates
    import jax
    upcast = jax.default_backend() == "cpu"
    if upcast:
        cmp_walk = liveness_walk(jaxpr, scale=scale, upcast=True,
                                 param_shapes=param_shapes,
                                 param_scale=pscale)
        gath = sum(b.nbytes for b in rep.buffers
                   if b.name == "param_gather")
        rep.cmp_peak_bytes = (rep.resident_bytes + int(cmp_walk.peak)
                              + gath + rep.output_extra_bytes)
    else:
        rep.cmp_peak_bytes = rep.peak_bytes

    by_kind: Dict[str, int] = {}
    for b in rep.buffers:
        by_kind[b.kind] = by_kind.get(b.kind, 0) + b.nbytes
    rep.by_kind = by_kind

    if xla:
        rep.xla = xla_memory_stats(handle)
    return rep
