"""``python -m hetu_tpu.analysis`` entry point (see cli.py)."""
import sys

from .cli import main

sys.exit(main())
