"""Serving-protocol verifier: lifecycle state machines + an exhaustive
interleaving explorer over the typed event stream (DESIGN.md §23).

Three state machines own the protocol invariants every serving
guarantee rests on:

``PageMachine``
    free → allocated → cached → host-staged → free; the trash page is
    immutable; refcount conservation (every share has an unshare,
    terminal refcounts zero).
``RequestMachine``
    queued → running → preempted/handoff-staged → adopted →
    finished | shed; no double-adopt of one staging epoch; no
    post-finish writes; finished and shed are mutually terminal.
``FenceMachine``
    per-replica fencing epochs are monotone; no completion and no
    adoption is accepted under a stale epoch.

:func:`replay` runs all three over any normalized event stream
(``analysis.events``) and returns :class:`Violation` records with
file:line-style provenance into the source plane plus the per-subject
event subtrace (what ``--explain`` prints).  The same predicates back
the runtime invariant checkers: ``PagedKVPool.check_invariants``,
``PrefixCache.check_invariants`` and ``fault.check_cluster_invariants``
all delegate to the ``*_problems`` snapshot functions here — one
implementation, asserted at runtime AND replayed over traces.

:func:`explore` is a bounded model checker for the control plane: a
small abstract model of the cluster (replicas, pools, prefix sharing,
host tier, disaggregated handoffs, fencing, chaos verdicts, drains)
executes EVERY interleaving of the nondeterministic choices the
scheduler/router/chaos/autoscaler make, asserting the state machines
in every reachable state.  Small bounds suffice for this bug class:
the known interaction bugs (phantom reclaim pages, drain-vs-inflight
handoff) all manifest with 2 replicas, ≤4 requests and ≤8 pages —
they are ordering bugs, not scale bugs.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import events as ev
from .events import Event

# rule names (registered in analysis.rules; shared so the explorer and
# the mutation tests name the same vocabulary)
RULE_PAGE = "page-lifecycle-violation"
RULE_REQUEST = "request-lifecycle-violation"
RULE_FENCE = "fence-regression"
RULE_REFCOUNT = "refcount-leak"


@dataclass
class Violation:
    """One protocol violation: which rule, which subject, what broke,
    where in the source plane, and the subject's event subtrace."""
    rule: str
    subject: str
    message: str
    provenance: str = ""
    subtrace: List[Event] = field(default_factory=list)

    def format_subtrace(self, limit: int = 8) -> str:
        lines = [f"  {e.step:>5}  {e.kind:<14} {e.key} "
                 f"[{e.provenance}]"
                 + (f" epoch={e.epoch}" if e.epoch is not None else "")
                 for e in self.subtrace[-limit:]]
        return "violating event subtrace (last "\
            f"{min(limit, len(self.subtrace))} of "\
            f"{len(self.subtrace)} events for {self.subject}):\n"\
            + "\n".join(lines)


# -- snapshot predicates (the ONE implementation the runtime checkers
# -- and the machines share) --------------------------------------------------

def page_partition_problems(num_pages: int, free_list, allocated,
                            cached, trash: int = ev.TRASH_PAGE
                            ) -> List[str]:
    """Allocator bookkeeping invariants: free/allocated/cached PARTITION
    the usable pages (pairwise disjoint, nothing leaked or invented),
    trash page never issued, cached refcounts non-negative.  Message
    strings are the contract (tests pin them)."""
    problems: List[str] = []
    free = set(free_list)
    allocated = set(allocated)
    cached_map = dict(cached)
    cached_set = set(cached_map)
    if len(free) != len(list(free_list)):
        problems.append("free list holds duplicates")
    if free & allocated:
        problems.append("page both free and allocated")
    if free & cached_set:
        problems.append("page both free and cached")
    if allocated & cached_set:
        problems.append("page both allocated and cached")
    if free | allocated | cached_set != set(range(1, num_pages)):
        problems.append("pages leaked or invented")
    if trash in free or trash in allocated:
        problems.append("reserved trash page was issued")
    if trash in cached_set:
        problems.append("trash page entered the cache")
    if any(rc < 0 for rc in cached_map.values()):
        problems.append("negative cached-page refcount")
    return problems


_ROOT = -1                        # prefix_cache.ROOT


def cache_index_problems(cache, pool) -> List[str]:
    """Prefix-cache bookkeeping invariants (the logic formerly inlined
    in ``PrefixCache.check_invariants``, messages preserved): index and
    id map agree, refcounts non-negative, parent refcounts dominate
    children's, child counts exact, per-page refcounts mirror the
    pool's cached partition, attached references accounted."""
    problems: List[str] = []
    if len(cache._index) != len(cache._by_id):
        problems.append("cache index and id map disagree")
    per_page_refs: Dict[int, int] = {}
    children: Dict[int, int] = {}
    for e in cache._index.values():
        if cache._by_id.get(e.eid) is not e:
            problems.append(f"entry {e.eid} missing from the id map")
        if e.refs < 0:
            problems.append(f"negative refcount on entry {e.eid}")
        per_page_refs[e.page] = e.refs
        if e.parent != _ROOT:
            parent = cache._by_id.get(e.parent)
            if parent is None:
                problems.append(f"entry {e.eid} orphaned: parent "
                                f"{e.parent} evicted")
                continue
            if parent.depth != e.depth - 1:
                problems.append(f"entry {e.eid} at depth {e.depth} "
                                f"does not extend its parent at depth "
                                f"{parent.depth}")
            if parent.refs < e.refs:
                problems.append("child page outlives its parent's "
                                "sharers")
            children[e.parent] = children.get(e.parent, 0) + 1
    for e in cache._index.values():
        if e.children != children.get(e.eid, 0):
            problems.append(f"entry {e.eid} claims {e.children} "
                            f"children, counted "
                            f"{children.get(e.eid, 0)}")
    # the pool's cached partition and the index agree page-for-page
    if per_page_refs != dict(pool._cached):
        problems.append("cache index and pool cached-page partition "
                        "diverged")
    attached_refs: Dict[int, int] = {}
    for entries in cache._attached.values():
        for e in entries:
            attached_refs[e.eid] = attached_refs.get(e.eid, 0) + 1
    for e in cache._index.values():
        if e.refs != attached_refs.get(e.eid, 0):
            problems.append(f"entry {e.eid} refcount {e.refs} != "
                            f"attached references")
    return problems


def cluster_problems(cluster) -> List[str]:
    """Cluster request-accounting invariants (the logic formerly
    inlined in ``fault.check_cluster_invariants``, messages preserved):
    every request lives in exactly one home (backlog / live / finished
    / shed), finished and shed are disjoint, token budgets hold."""
    problems: List[str] = []
    backlog_ids = {rid for _, rid, _ in cluster._backlog}
    placed_ids = {creq.req_id
                  for (creq, _stage, _epoch) in cluster._placed.values()}
    handoff_ids = {h["creq"].req_id for h in cluster._pending_handoffs
                   if not h.get("redelivery")}
    finished_ids = set(cluster.finished)
    shed_ids = set(cluster.shed)
    if finished_ids & shed_ids:
        problems.append(f"requests both finished and shed: "
                        f"{finished_ids & shed_ids}")
    for rid, creq in cluster.requests.items():
        homes = [rid in backlog_ids,
                 rid in finished_ids,
                 rid in shed_ids,
                 rid in placed_ids or rid in handoff_ids]
        if sum(bool(h) for h in homes) != 1:
            problems.append(
                f"request {rid} accounting broken: backlog={homes[0]} "
                f"finished={homes[1]} shed={homes[2]} live={homes[3]} "
                f"(stage={creq.stage!r}, "
                f"pending={creq.handoff_pending})")
        if len(creq.out_tokens) > creq.max_new_tokens:
            problems.append(f"request {rid} overran its budget "
                            f"(duplicated tokens?)")
    return problems


# -- lifecycle state machines -------------------------------------------------

_FREE, _ALLOCATED, _CACHED = "free", "allocated", "cached"


class _MachineBase:
    """Shared violation plumbing: first violation per subject poisons
    the subject (state force-syncs to the event's implied post-state),
    so one corrupted transition reports exactly once instead of
    cascading — the mutation tests pin this exactly-once contract."""

    def __init__(self):
        self.violations: List[Violation] = []
        self._poisoned: Set[Any] = set()
        self._trace: Dict[Any, List[Event]] = {}

    def _note(self, e: Event) -> None:
        self._trace.setdefault(e.key, []).append(e)

    def _violate(self, rule: str, e: Event, message: str) -> None:
        if e.key in self._poisoned:
            return
        self._poisoned.add(e.key)
        self.violations.append(Violation(
            rule=rule, subject=str(e.key), message=message,
            provenance=e.provenance,
            subtrace=list(self._trace.get(e.key, ()))))


class PageMachine(_MachineBase):
    """free → allocated → cached → host-staged → free, trash immutable,
    refcount conservation.  Pages materialize lazily: the first event
    naming a page seeds it FREE (pool logs are complete from
    construction, so the first touch is always an alloc)."""

    def __init__(self):
        super().__init__()
        self.state: Dict[str, str] = {}
        self.sharers: Dict[str, int] = {}
        self.host: Set[Any] = set()
        self.pages_seen: Set[str] = set()

    def _st(self, key: str) -> str:
        return self.state.get(key, _FREE)

    def apply(self, e: Event) -> None:
        k = e.kind
        if k in (ev.PAGE_ALLOC, ev.PAGE_FREE, ev.PAGE_CACHE,
                 ev.PAGE_SHARE, ev.PAGE_UNSHARE, ev.PAGE_UNCACHE):
            self._note(e)
            self.pages_seen.add(e.key)
            if e.attrs.get("page") == ev.TRASH_PAGE:
                self._violate(RULE_PAGE, e,
                              f"{k} touched the reserved trash page — "
                              f"it is immutable and never issued")
                return
        if k == ev.POOL_RESET:
            self.state.clear()
            self.sharers.clear()
            return
        if k == ev.PAGE_ALLOC:
            if self._st(e.key) != _FREE:
                self._violate(RULE_PAGE, e,
                              f"alloc of page {e.key} while "
                              f"{self._st(e.key)} — only a free page "
                              f"may be issued")
            self.state[e.key] = _ALLOCATED
        elif k == ev.PAGE_FREE:
            if self._st(e.key) != _ALLOCATED:
                self._violate(RULE_PAGE, e,
                              f"free of page {e.key} while "
                              f"{self._st(e.key)} — only an allocated "
                              f"page returns to the free list")
            self.state[e.key] = _FREE
        elif k == ev.PAGE_CACHE:
            if self._st(e.key) != _ALLOCATED:
                self._violate(RULE_PAGE, e,
                              f"cache of page {e.key} while "
                              f"{self._st(e.key)} — only an allocated "
                              f"page enters the cache")
            self.state[e.key] = _CACHED
            self.sharers[e.key] = 0
        elif k == ev.PAGE_SHARE:
            if self._st(e.key) != _CACHED:
                self._violate(RULE_PAGE, e,
                              f"share of page {e.key} while "
                              f"{self._st(e.key)} — only a cached page "
                              f"is shareable")
                self.state[e.key] = _CACHED
                self.sharers.setdefault(e.key, 0)
            self.sharers[e.key] = self.sharers.get(e.key, 0) + 1
        elif k == ev.PAGE_UNSHARE:
            if self._st(e.key) != _CACHED \
                    or self.sharers.get(e.key, 0) < 1:
                self._violate(RULE_REFCOUNT, e,
                              f"unshare of page {e.key} without a "
                              f"matching share — the refcount went "
                              f"negative")
                self.sharers[e.key] = 0
            else:
                self.sharers[e.key] -= 1
        elif k == ev.PAGE_UNCACHE:
            if self._st(e.key) != _CACHED:
                self._violate(RULE_PAGE, e,
                              f"uncache of page {e.key} while "
                              f"{self._st(e.key)}")
            elif self.sharers.get(e.key, 0) != 0:
                self._violate(RULE_REFCOUNT, e,
                              f"uncache of page {e.key} with "
                              f"{self.sharers[e.key]} live sharers — "
                              f"a share was never unshared")
            self.state[e.key] = _FREE
            self.sharers.pop(e.key, None)
        elif k == ev.HOST_STAGE:
            self._note(e)
            page = e.attrs.get("page")
            pkey = None if page is None else f"p{int(page)}"
            if pkey is not None and self._st(pkey) != _CACHED:
                self._violate(RULE_PAGE, e,
                              f"host-stage of page {pkey} while "
                              f"{self._st(pkey)} — only a cached page "
                              f"is staged to host (evict path)")
            self.host.add(e.key)
        elif k == ev.HOST_REFETCH:
            self._note(e)
            if e.key not in self.host:
                self._violate(RULE_PAGE, e,
                              f"host-refetch of {e.key} that was never "
                              f"staged to host")
            self.host.discard(e.key)
        elif k == ev.WIRE_EXTRACT:
            for pg in e.attrs.get("pages", ()):
                pkey = f"p{int(pg)}"
                self._note(Event(kind=k, key=pkey, step=e.step,
                                 attrs=e.attrs,
                                 provenance=e.provenance))
                if int(pg) == ev.TRASH_PAGE:
                    continue          # padding slot reads are benign
                if self._st(pkey) == _FREE and pkey in self.pages_seen:
                    self._violate(RULE_PAGE, Event(
                        kind=k, key=pkey, step=e.step, attrs=e.attrs,
                        provenance=e.provenance),
                        f"wire extract read page {pkey} while free — "
                        f"staging a reclaimed page ships garbage KV")

    def finish(self, skip: Optional[Set[str]] = None) -> None:
        """Terminal refcount conservation: every share was unshared."""
        skip = skip or set()
        for key, n in sorted(self.sharers.items()):
            if n > 0 and key not in self._poisoned and key not in skip:
                self._poisoned.add(key)
                self.violations.append(Violation(
                    rule=RULE_REFCOUNT, subject=str(key),
                    message=f"page {key} ends the trace with "
                            f"{n} live sharers — a share was never "
                            f"unshared (terminal refcounts must be "
                            f"zero)",
                    provenance="terminal",
                    subtrace=list(self._trace.get(key, ()))))

    def consistency_problems(self, num_pages: Optional[int] = None
                             ) -> List[str]:
        """The machine's state projected through the SAME snapshot
        predicate the live pool asserts."""
        free, allocated, cached = set(), set(), {}
        for key, st in self.state.items():
            pg = int(key[1:])
            if st == _FREE:
                free.add(pg)
            elif st == _ALLOCATED:
                allocated.add(pg)
            else:
                cached[pg] = self.sharers.get(key, 0)
        if num_pages is None:
            return page_partition_problems(
                max(free | allocated | set(cached), default=0) + 1,
                free | (set(range(1, max(free | allocated
                                         | set(cached), default=0) + 1))
                        - allocated - set(cached)),
                allocated, cached)
        tracked = free | allocated | set(cached)
        free |= set(range(1, num_pages)) - tracked
        return page_partition_problems(num_pages, free, allocated,
                                       cached)


_QUEUED, _RUNNING, _PREEMPTED = "queued", "running", "preempted"
_STAGED, _FINISHED, _SHED = "handoff-staged", "finished", "shed"


class RequestMachine(_MachineBase):
    """queued → running → preempted/handoff-staged → adopted →
    finished | shed.  Keys are namespaced (``req:<id>`` engine-local,
    ``creq:<id>`` cluster) so the two id spaces never collide.  The
    tap is a bounded window, so an unknown request's first write is
    trusted (like the rewind lint's first-sight rule); terminal-state
    violations (post-finish writes, double adopt, shed-after-finish)
    never false-positive under truncation."""

    def __init__(self):
        super().__init__()
        self.state: Dict[str, str] = {}
        self.adopted: Set[Tuple[str, Any]] = set()

    def _terminal(self, key) -> Optional[str]:
        st = self.state.get(key)
        return st if st in (_FINISHED, _SHED) else None

    def apply(self, e: Event) -> None:
        k = e.kind
        if k not in (ev.REQ_QUEUED, ev.REQ_ADMIT, ev.REQ_WRITE,
                     ev.REQ_PREEMPT, ev.REQ_REWIND, ev.REQ_STAGE,
                     ev.REQ_ADOPT, ev.REQ_FINISH, ev.REQ_SHED):
            return
        self._note(e)
        term = self._terminal(e.key)
        if k == ev.REQ_QUEUED:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} re-queued after "
                              f"{term} — terminal states are terminal")
            self.state[e.key] = _QUEUED
        elif k == ev.REQ_ADMIT:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} admitted after {term}")
            self.state[e.key] = _RUNNING
        elif k == ev.REQ_WRITE:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} wrote KV at tap step "
                              f"{e.attrs.get('tap_step', '?')} AFTER "
                              f"{term} — post-finish writes corrupt "
                              f"pages the pool already reissued")
            self.state.setdefault(e.key, _RUNNING)
        elif k == ev.REQ_PREEMPT:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} preempted after {term}")
            self.state[e.key] = _PREEMPTED
        elif k == ev.REQ_REWIND:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} rewound after {term}")
        elif k == ev.REQ_STAGE:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} handoff-staged after "
                              f"{term}")
            self.state[e.key] = _STAGED
        elif k == ev.REQ_ADOPT:
            akey = (e.key, e.epoch)
            if akey in self.adopted:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} adopted TWICE under "
                              f"staging epoch {e.epoch} — the "
                              f"(request id, epoch) dedup failed and "
                              f"tokens will double-deliver")
            elif term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} adopted after {term}")
            self.adopted.add(akey)
            self.state[e.key] = _RUNNING
        elif k == ev.REQ_FINISH:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} finished after {term} "
                              f"— a completion delivered twice")
            self.state[e.key] = _FINISHED
        elif k == ev.REQ_SHED:
            if term:
                self._violate(RULE_REQUEST, e,
                              f"request {e.key} shed after {term} — "
                              f"shed and finished are mutually "
                              f"terminal")
            self.state[e.key] = _SHED


class FenceMachine(_MachineBase):
    """Per-replica fencing epochs are monotone; no stale-epoch
    completion or adoption is accepted.  Keys are replica indices."""

    def __init__(self):
        super().__init__()
        self.epoch: Dict[Any, int] = {}

    def apply(self, e: Event) -> None:
        k = e.kind
        if k == ev.FENCE_BUMP:
            self._note(e)
            rep = e.key
            new = e.epoch
            if new is None:
                new = self.epoch.get(rep, 0) + 1
            if new <= self.epoch.get(rep, -1):
                self._violate(RULE_FENCE, e,
                              f"fence epoch of replica {rep} moved "
                              f"{self.epoch[rep]} -> {new} — epochs "
                              f"are monotone; a regressed fence "
                              f"un-quarantines a zombie")
            self.epoch[rep] = new if new is not None else \
                self.epoch.get(rep, 0) + 1
        elif k == ev.FENCE_COMPLETE:
            self._note(e)
            rep = e.attrs.get("replica", e.key)
            cur = self.epoch.get(rep)
            if cur is not None and e.epoch is not None \
                    and e.epoch != cur:
                self._violate(RULE_FENCE, e,
                              f"completion accepted on replica {rep} "
                              f"under epoch {e.epoch} but the fence is "
                              f"at {cur} — a fenced (stale) completion "
                              f"must be dropped, never accepted")
            if cur is None and e.epoch is not None:
                self.epoch[rep] = e.epoch
        elif k == ev.FENCE_STALE_DROP:
            self._note(e)
        elif k == ev.REQ_ADOPT:
            rep = e.attrs.get("dst")
            fe = e.attrs.get("fence_epoch")
            if rep is None or fe is None:
                return
            key = f"r{rep}"
            self._note(Event(kind=k, key=key, step=e.step,
                             epoch=e.epoch, attrs=e.attrs,
                             provenance=e.provenance))
            cur = self.epoch.get(key)
            if cur is not None and fe != cur:
                self._violate(RULE_FENCE, Event(
                    kind=k, key=key, step=e.step, epoch=e.epoch,
                    attrs=e.attrs, provenance=e.provenance),
                    f"adoption on replica {key} stamped fence epoch "
                    f"{fe} but the fence is at {cur} — the landing "
                    f"rode a stale epoch past the death sweep")
            if cur is None:
                self.epoch[key] = fe


def replay(events: Iterable[Event],
           strict_terminal: bool = True,
           terminal_skip: Optional[Set[str]] = None
           ) -> List[Violation]:
    """Run all three lifecycle machines over one normalized stream and
    return every violation, provenance-stamped, in stream order."""
    pages, reqs, fences = PageMachine(), RequestMachine(), FenceMachine()
    for e in events:
        pages.apply(e)
        reqs.apply(e)
        fences.apply(e)
    if strict_terminal:
        pages.finish(skip=terminal_skip)
    return pages.violations + reqs.violations + fences.violations


def machine_summary(events: Sequence[Event]) -> Dict[str, Any]:
    """Coverage summary for the report's ``protocol`` section."""
    pages, reqs, fences = PageMachine(), RequestMachine(), FenceMachine()
    for e in events:
        pages.apply(e)
        reqs.apply(e)
        fences.apply(e)
    return {"pages": len(pages.pages_seen),
            "requests": len(reqs.state),
            "replicas": len(fences.epoch)}


# -- the bounded interleaving explorer ---------------------------------------

@dataclass
class ExploreConfig:
    """Bounds for the exhaustive model check.  Defaults exhaust in
    seconds and still cover every known interaction-bug shape (ordering
    bugs need two replicas and a handful of requests/pages, not
    scale).  ``max_depth`` is a recursion safety net far above the
    longest possible action sequence; ``max_interleavings`` caps the
    DISTINCT STATES expanded (the path count itself is recovered by
    memoized counting and may legitimately be astronomically larger)."""
    n_replicas: int = 2
    n_requests: int = 2
    pages_per_replica: int = 2
    tokens_per_request: int = 2
    prefix_families: int = 1     # distinct shared-prefix chain hashes
    max_crashes: int = 1
    max_chaos: int = 1           # wire drops
    max_sheds: int = 1
    max_preempts: int = 1
    max_evicts: int = 2          # host-tier stagings
    max_drains: int = 1          # autoscaler scale-down attempts
    # symmetry reduction: replicas are interchangeable, so letting
    # chaos kill ONE fixed replica and the autoscaler drain the OTHER
    # covers the same interaction shapes at a fraction of the states
    crash_targets: Tuple[int, ...] = (0,)
    drain_targets: Tuple[int, ...] = (1,)
    max_depth: int = 64
    max_interleavings: int = 400_000


@dataclass
class ExploreResult:
    interleavings: int
    states: int
    max_depth: int
    events_checked: int
    violations: List[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations


class _Model:
    """Abstract control-plane model.  State is plain dicts/tuples with
    an explicit :meth:`clone`; every action emits protocol events that
    feed the three machines incrementally.  ``bug`` re-introduces a
    specific interaction bug so tests can assert the explorer FINDS
    this bug class:

    ``double_adopt``    skip the (req, epoch) idempotency dedup
    ``stale_accept``    accept a fenced (zombie) completion
    ``drain_inflight``  drain-idle check ignores in-flight handoffs
                        (the real autoscaler bug this PR fixes)
    ``free_shared``     preemption frees shared pages instead of
                        unsharing them
    """

    def __init__(self, cfg: ExploreConfig, bug: Optional[str] = None):
        self.cfg = cfg
        self.bug = bug
        self.reps = {r: {"alive": True, "fence": 0, "draining": False}
                     for r in range(cfg.n_replicas)}
        # page key -> state per replica pool: "free"/"alloc"/"cached"
        self.pages = {r: {p: _FREE
                          for p in range(1, cfg.pages_per_replica + 1)}
                      for r in range(cfg.n_replicas)}
        self.sharers = {r: {} for r in range(cfg.n_replicas)}
        # cached chain hash -> page, per replica (one shared prefix)
        self.cached_hash = {r: {} for r in range(cfg.n_replicas)}
        self.host = {r: set() for r in range(cfg.n_replicas)}
        self.reqs = {q: {"state": _QUEUED, "rep": None, "pages": (),
                         "shared": (), "done": 0, "epoch": None}
                     for q in range(cfg.n_requests)}
        self.handoffs: List[Dict[str, Any]] = []
        self.injected: Set[Tuple[int, int]] = set()
        self.stage_seq = 0
        self.crashes = 0
        self.chaos = 0
        self.sheds = 0
        self.preempts = 0
        self.evicts = 0
        self.drains = 0
        self.zombie_finishes: List[Tuple[int, int, int]] = []

    def clone(self) -> "_Model":
        m = _Model.__new__(_Model)
        m.cfg, m.bug = self.cfg, self.bug
        m.reps = {r: dict(v) for r, v in self.reps.items()}
        m.pages = {r: dict(v) for r, v in self.pages.items()}
        m.sharers = {r: dict(v) for r, v in self.sharers.items()}
        m.cached_hash = {r: dict(v)
                         for r, v in self.cached_hash.items()}
        m.host = {r: set(v) for r, v in self.host.items()}
        m.reqs = {q: dict(v) for q, v in self.reqs.items()}
        m.handoffs = [dict(h) for h in self.handoffs]
        m.injected = set(self.injected)
        m.stage_seq = self.stage_seq
        m.crashes, m.chaos = self.crashes, self.chaos
        m.sheds, m.preempts = self.sheds, self.preempts
        m.evicts, m.drains = self.evicts, self.drains
        m.zombie_finishes = list(self.zombie_finishes)
        return m

    def fingerprint(self) -> Tuple:
        """Complete state identity: two models with equal fingerprints
        have identical enabled-action sets and identical subtrees, so
        the explorer may share their subtree path counts.  Every field
        that gates an action (including the capped counters) MUST be
        here or the memoized counts go wrong."""
        return (
            tuple(sorted((r, v["alive"], v["fence"], v["draining"])
                         for r, v in self.reps.items())),
            tuple((r, tuple(sorted(self.pages[r].items())),
                   tuple(sorted(self.sharers[r].items())),
                   tuple(sorted(self.cached_hash[r].items())),
                   tuple(sorted(self.host[r])))
                  for r in sorted(self.pages)),
            tuple((q, v["state"], v["rep"], v["pages"], v["shared"],
                   v["done"], v["epoch"], v.get("zombie_rep"),
                   v.get("zombie_pages"), v.get("zombie_shared"))
                  for q, v in sorted(self.reqs.items())),
            tuple(sorted((h["req"], h["epoch"], h["state"], h["src"],
                          h.get("dst"), h.get("dst_pages") or (),
                          h.get("stale_fence"))
                         for h in self.handoffs)),
            tuple(sorted(self.injected)),
            tuple(sorted(self.zombie_finishes)),
            (self.stage_seq, self.crashes, self.chaos, self.sheds,
             self.preempts, self.evicts, self.drains),
        )

    # -- page helpers (emit pool-plane events) ---------------------------

    def _pkey(self, rep: int, pg: int) -> str:
        return f"r{rep}:p{pg}"

    def _alloc(self, rep: int, n: int, emit) -> Optional[List[int]]:
        free = [p for p, st in sorted(self.pages[rep].items())
                if st == _FREE]
        if len(free) < n:
            return None
        got = free[:n]
        for pg in got:
            self.pages[rep][pg] = _ALLOCATED
            emit(ev.PAGE_ALLOC, self._pkey(rep, pg), page=pg)
        return got

    def _free(self, rep: int, pages, emit) -> None:
        for pg in pages:
            self.pages[rep][pg] = _FREE
            emit(ev.PAGE_FREE, self._pkey(rep, pg), page=pg)

    def _unshare(self, rep: int, pages, emit) -> None:
        for pg in pages:
            if self.bug == "free_shared":
                # the seeded bug: shared prefix pages go back to the
                # free list while the cache index still serves them
                self.pages[rep][pg] = _FREE
                emit(ev.PAGE_FREE, self._pkey(rep, pg), page=pg)
                continue
            self.sharers[rep][pg] -= 1
            emit(ev.PAGE_UNSHARE, self._pkey(rep, pg), page=pg)

    def _drain_busy(self, r: int) -> bool:
        """The autoscaler's drain-idle check.  The FIXED check counts a
        chaos-delayed in-flight handoff whose reserved destination is
        this replica as work; ``bug='drain_inflight'`` reproduces the
        pre-fix check that missed it."""
        busy = any(rv["rep"] == r and rv["state"] == _RUNNING
                   for rv in self.reqs.values())
        if self.bug != "drain_inflight":
            busy = busy or any(h["state"] == "inflight"
                               and h["dst"] == r
                               for h in self.handoffs)
        return busy

    # -- enabled actions --------------------------------------------------

    def actions(self) -> List[Tuple]:
        cfg = self.cfg
        acts: List[Tuple] = []
        live = [r for r, v in self.reps.items()
                if v["alive"] and not v["draining"]]
        for q, v in self.reqs.items():
            if v["state"] in (_QUEUED, _PREEMPTED):
                for r in live:
                    if any(st == _FREE
                           for st in self.pages[r].values()):
                        acts.append(("admit", q, r))
                if self.sheds < cfg.max_sheds:
                    acts.append(("shed", q))
            elif v["state"] == _RUNNING:
                acts.append(("work", q))
                if self.preempts < cfg.max_preempts:
                    acts.append(("preempt", q))
                if v["done"] == 0 and not self.handoffs \
                        and v["epoch"] is not None:
                    acts.append(("stage", q))
        for r in self.reps:
            v = self.reps[r]
            if v["alive"]:
                if self.evicts < cfg.max_evicts:
                    for h, pg in sorted(self.cached_hash[r].items()):
                        if self.sharers[r].get(pg, 0) == 0:
                            acts.append(("evict", r, h))
                for h in sorted(self.host[r]):
                    if any(st == _FREE
                           for st in self.pages[r].values()):
                        acts.append(("refetch", r, h))
                if self.crashes < cfg.max_crashes \
                        and r in cfg.crash_targets:
                    acts.append(("crash", r))
                if not v["draining"] \
                        and r in cfg.drain_targets \
                        and self.drains < cfg.max_drains \
                        and sum(1 for x in self.reps.values()
                                if x["alive"]
                                and not x["draining"]) > 1:
                    acts.append(("drain", r))
            if v["draining"] and not self._drain_busy(r):
                # gated on the idle check so a busy drain is never a
                # no-op transition (it would blow up the tree);
                # bug='drain_inflight' weakens the check itself
                acts.append(("finish_drain", r))
            if not v["alive"]:
                acts.append(("readmit", r))
        for i, h in enumerate(self.handoffs):
            if h["state"] == "staged":
                for r in live:
                    if r != h["src"] and any(
                            st == _FREE
                            for st in self.pages[r].values()):
                        acts.append(("send", i, r))
            elif h["state"] == "inflight":
                acts.append(("land", i))
                if self.chaos < cfg.max_chaos:
                    acts.append(("drop_wire", i))
            elif h["state"] == "landed":
                if self.chaos < cfg.max_chaos:
                    acts.append(("dup_deliver", i))
        for zi, (q, r, epoch) in enumerate(self.zombie_finishes):
            acts.append(("zombie_finish", zi))
        return acts

    # -- apply one action, emitting events --------------------------------

    def apply(self, act: Tuple, emit) -> None:
        name = act[0]
        if name == "admit":
            _, q, r = act
            v = self.reqs[q]
            got = self._alloc(r, 1, emit)
            if got is None:
                return
            shared = ()
            hkey = f"h{q % self.cfg.prefix_families}"
            pg = self.cached_hash[r].get(hkey)
            if pg is not None:
                self.sharers[r][pg] = self.sharers[r].get(pg, 0) + 1
                emit(ev.PAGE_SHARE, self._pkey(r, pg), page=pg)
                shared = (pg,)
            v.update(state=_RUNNING, rep=r, pages=tuple(got),
                     shared=shared, epoch=self.reps[r]["fence"])
            emit(ev.REQ_ADMIT, f"req:{q}")
        elif name == "work":
            _, q = act
            v = self.reqs[q]
            r = v["rep"]
            emit(ev.REQ_WRITE, f"req:{q}", pos=v["done"], qlen=1,
                 ctx_len=v["done"] + 1)
            v["done"] += 1
            if v["done"] < self.cfg.tokens_per_request:
                return
            if v["epoch"] != self.reps[r]["fence"] \
                    and not (self.bug == "stale_accept"):
                # placement from a fenced epoch: drop, requeue
                emit(ev.FENCE_STALE_DROP, f"r{r}",
                     epoch=v["epoch"])
                self._finish_pages(q, cache=False, emit=emit)
                v.update(state=_QUEUED, rep=None, done=0, epoch=None)
                return
            emit(ev.FENCE_COMPLETE, f"r{r}", epoch=v["epoch"],
                 replica=f"r{r}")
            self._finish_pages(q, cache=True, emit=emit)
            v["state"] = _FINISHED
            emit(ev.REQ_FINISH, f"req:{q}")
        elif name == "preempt":
            _, q = act
            self.preempts += 1
            v = self.reqs[q]
            r = v["rep"]
            emit(ev.REQ_PREEMPT, f"req:{q}")
            self._free(r, v["pages"], emit)
            self._unshare(r, v["shared"], emit)
            v.update(state=_PREEMPTED, rep=None, pages=(), shared=(),
                     done=0, epoch=None)
        elif name == "shed":
            _, q = act
            self.sheds += 1
            self.reqs[q]["state"] = _SHED
            emit(ev.REQ_SHED, f"req:{q}")
        elif name == "evict":
            _, r, h = act
            self.evicts += 1
            pg = self.cached_hash[r].pop(h)
            emit(ev.HOST_STAGE, f"hh:{r}:{h}", page=None,
                 model_page=pg)
            self.host[r].add(h)
            self.pages[r][pg] = _FREE
            self.sharers[r].pop(pg, None)
            emit(ev.PAGE_UNCACHE, self._pkey(r, pg), page=pg)
        elif name == "refetch":
            _, r, h = act
            got = self._alloc(r, 1, emit)
            if got is None:
                return
            emit(ev.WIRE_INJECT, f"host->r{r}", epoch=0)
            self.host[r].discard(h)
            emit(ev.HOST_REFETCH, f"hh:{r}:{h}")
            self.pages[r][got[0]] = _CACHED
            self.sharers[r][got[0]] = 0
            self.cached_hash[r][h] = got[0]
            emit(ev.PAGE_CACHE, self._pkey(r, got[0]), page=got[0])
        elif name == "stage":
            _, q = act
            v = self.reqs[q]
            r = v["rep"]
            self.stage_seq += 1
            emit(ev.WIRE_EXTRACT, f"r{r}",
                 pages=tuple())       # model pages are per-replica keys
            emit(ev.REQ_STAGE, f"req:{q}", epoch=self.stage_seq)
            self._free(r, v["pages"], emit)
            self._unshare(r, v["shared"], emit)
            self.handoffs.append({"req": q, "epoch": self.stage_seq,
                                  "src": r, "state": "staged",
                                  "dst": None, "dst_pages": None})
            v.update(state=_STAGED, rep=None, pages=(), shared=())
        elif name == "send":
            _, i, r = act
            h = self.handoffs[i]
            got = self._alloc(r, 1, emit)
            if got is None:
                return
            h.update(state="inflight", dst=r, dst_pages=tuple(got))
        elif name == "drop_wire":
            _, i = act
            self.chaos += 1
            h = self.handoffs[i]
            emit(ev.CHAOS_INJECT, "chaos:drop")
            self._free(h["dst"], h["dst_pages"], emit)
            h.update(state="staged", dst=None, dst_pages=None)
        elif name == "land":
            _, i = act
            h = self.handoffs[i]
            q, r = h["req"], h["dst"]
            key = (q, h["epoch"])
            if key in self.injected and self.bug != "double_adopt":
                self._free(r, h["dst_pages"], emit)
                h.update(state="done", dst=None, dst_pages=None)
                return
            if not self.reps[r]["alive"]:
                # destination fenced while in flight: restage
                self._free(r, h["dst_pages"], emit)
                self.stage_seq += 1
                h.update(state="staged", dst=None, dst_pages=None,
                         epoch=self.stage_seq)
                return
            fence = self.reps[r]["fence"]
            emit(ev.WIRE_INJECT, f"r{h['src']}->r{r}",
                 epoch=h["epoch"])
            emit(ev.REQ_ADOPT, f"req:{q}", epoch=h["epoch"], dst=r,
                 fence_epoch=h["stale_fence"]
                 if "stale_fence" in h else fence)
            self.injected.add(key)
            self.reqs[q].update(state=_RUNNING, rep=r,
                                pages=h["dst_pages"], shared=(),
                                epoch=fence if "stale_fence" not in h
                                else h["stale_fence"])
            h.update(state="landed", dst_pages=None)
        elif name == "dup_deliver":
            _, i = act
            self.chaos += 1
            h = self.handoffs[i]
            emit(ev.CHAOS_INJECT, "chaos:dup")
            if self.bug == "double_adopt":
                q = h["req"]
                live = [r for r, v in self.reps.items() if v["alive"]]
                r = live[0]
                emit(ev.REQ_ADOPT, f"req:{q}", epoch=h["epoch"],
                     dst=r, fence_epoch=self.reps[r]["fence"])
            h["state"] = "done"
        elif name == "crash":
            _, r = act
            self.crashes += 1
            v = self.reps[r]
            v["alive"] = False
            v["draining"] = False
            v["fence"] += 1
            emit(ev.CHAOS_INJECT, "chaos:crash")
            emit(ev.FENCE_BUMP, f"r{r}", epoch=v["fence"])
            for q, rv in self.reqs.items():
                if rv["rep"] == r and rv["state"] == _RUNNING:
                    # re-route: the zombie copy may still complete and
                    # must be dropped by the fence, never accepted
                    self.zombie_finishes.append((q, r, v["fence"] - 1))
                    emit(ev.REQ_QUEUED, f"req:{q}")
                    rv.update(state=_QUEUED, rep=None, done=0,
                              epoch=None)
                    # pages stay leaked in the dead pool until readmit
                    rv["zombie_pages"] = rv["pages"]
                    rv["zombie_shared"] = rv["shared"]
                    rv["zombie_rep"] = r
                    rv.update(pages=(), shared=())
        elif name == "zombie_finish":
            _, zi = act
            q, r, epoch = self.zombie_finishes.pop(zi)
            if self.bug == "stale_accept":
                emit(ev.FENCE_COMPLETE, f"r{r}", epoch=epoch,
                     replica=f"r{r}")
            else:
                emit(ev.FENCE_STALE_DROP, f"r{r}", epoch=epoch)
        elif name == "readmit":
            _, r = act
            v = self.reps[r]
            # abort_all: the zombie's leaked pages return to the pool
            for q, rv in self.reqs.items():
                if rv.get("zombie_rep") == r:
                    self._free(r, rv.pop("zombie_pages", ()), emit)
                    self._unshare(r, rv.pop("zombie_shared", ()),
                                  emit)
                    rv.pop("zombie_rep", None)
            v["alive"] = True
        elif name == "drain":
            _, r = act
            self.drains += 1
            self.reps[r]["draining"] = True
            emit(ev.CHAOS_INJECT, "chaos:drain")
        elif name == "finish_drain":
            _, r = act
            v = self.reps[r]
            if self._drain_busy(r):
                return
            v["draining"] = False
            v["alive"] = False
            v["fence"] += 1
            emit(ev.FENCE_BUMP, f"r{r}", epoch=v["fence"])
            if self.bug == "drain_inflight":
                # the bug: the in-flight handoff still lands on the
                # fenced replica, stamped with the pre-drain epoch
                for h in self.handoffs:
                    if h["state"] == "inflight" and h["dst"] == r:
                        h["stale_fence"] = v["fence"] - 1
                        self.reps[r]["alive"] = True  # lands anyway

    def _finish_pages(self, q: int, cache: bool, emit) -> None:
        v = self.reqs[q]
        r = v["rep"]
        pages = list(v["pages"])
        hkey = f"h{q % self.cfg.prefix_families}"
        if cache and pages and hkey not in self.cached_hash[r] \
                and hkey not in self.host[r]:
            pg = pages.pop(0)
            self.pages[r][pg] = _CACHED
            self.sharers[r][pg] = 0
            self.cached_hash[r][hkey] = pg
            emit(ev.PAGE_CACHE, self._pkey(r, pg), page=pg)
        self._free(r, pages, emit)
        self._unshare(r, v["shared"], emit)
        v.update(pages=(), shared=())

    def done(self) -> bool:
        return all(v["state"] in (_FINISHED, _SHED)
                   for v in self.reqs.values())

    def terminal_skip(self) -> Set[str]:
        """Pages living in a dead (quarantined) pool at trace end are
        exempt from the terminal-refcount check — the pool is fenced,
        not leaked; readmission reclaims it."""
        skip: Set[str] = set()
        for r, v in self.reps.items():
            if not v["alive"]:
                skip.update(self._pkey(r, p) for p in self.pages[r])
        return skip


def _machines_from_model(model: "_Model"
                         ) -> Tuple[PageMachine, RequestMachine,
                                    FenceMachine]:
    """Seed the three lifecycle machines with a model state's exact
    protocol view.  Sound because the machines' view is a projection of
    the model's fingerprint: equal fingerprints give equal machine
    seeds, so a transition's verdict depends only on (state, action) —
    the fact that lets :func:`explore` check every transition of the
    state DAG exactly once instead of once per path through it."""
    pages = PageMachine()
    for r, pool in model.pages.items():
        for pg, st in pool.items():
            key = model._pkey(r, pg)
            pages.state[key] = st
            pages.pages_seen.add(key)
        for pg, n in model.sharers[r].items():
            pages.sharers[model._pkey(r, pg)] = n
        for h in model.host[r]:
            pages.host.add(f"hh:{r}:{h}")
    reqs = RequestMachine()
    for q, v in model.reqs.items():
        reqs.state[f"req:{q}"] = v["state"]
    for q, epoch in model.injected:
        reqs.adopted.add((f"req:{q}", epoch))
    fences = FenceMachine()
    for r, v in model.reps.items():
        fences.epoch[f"r{r}"] = v["fence"]
    return pages, reqs, fences


class _StopSearch(Exception):
    pass


def explore(cfg: Optional[ExploreConfig] = None,
            bug: Optional[str] = None,
            stop_at_first: bool = True) -> ExploreResult:
    """Exhaustive model check of the bounded control plane.

    The reachable state graph is a DAG (every potentially-cyclic action
    — crash/readmit, preempt/readmit, evict/refetch, drain — increments
    a capped counter that is part of the state fingerprint), so the
    explorer walks it once: every reachable state is expanded once and
    every transition's emitted events are checked by lifecycle machines
    seeded from the parent state (:func:`_machines_from_model`);
    terminal states additionally get the refcount-conservation check.
    The number of INTERLEAVINGS (root-to-leaf paths — what a naive
    per-path DFS would enumerate one by one) is recovered exactly by
    memoized path counting over the same DAG, so the reported
    ``interleavings`` is the true exhaustive count even when it is
    orders of magnitude beyond what per-path enumeration could visit.
    On the clean model (``bug=None``) zero violations is the
    contract."""
    cfg = cfg or ExploreConfig()
    root = _Model(cfg, bug=bug)
    stats = {"max_depth": 0, "checked": 0, "transitions": 0,
             "cutoffs": 0}
    memo: Dict[Tuple, int] = {}        # fingerprint -> leaf-path count
    seen: Set[Tuple] = set()
    found: List[Violation] = []

    def check(parent: "_Model", events: List[Event],
              terminal: Optional["_Model"] = None) -> None:
        pages, reqs, fences = _machines_from_model(parent)
        for e in events:
            stats["checked"] += 1
            pages.apply(e)
            reqs.apply(e)
            fences.apply(e)
        if terminal is not None:
            pages.finish(skip=terminal.terminal_skip())
        vs = pages.violations + reqs.violations + fences.violations
        if vs:
            found.extend(vs)
            if stop_at_first:
                raise _StopSearch

    def dfs(model: "_Model", depth: int) -> int:
        stats["max_depth"] = max(stats["max_depth"], depth)
        fp = model.fingerprint()
        hit = memo.get(fp)
        if hit is not None:
            return hit
        seen.add(fp)
        if len(seen) > cfg.max_interleavings:
            raise _StopSearch         # state-count safety net
        acts = model.actions()
        if not acts or model.done():
            check(model, [], terminal=model if model.done() else None)
            memo[fp] = 1
            return 1
        if depth >= cfg.max_depth:
            # pure recursion safety net (paths are bounded by the
            # action caps, far below max_depth); NOT memoized so the
            # counts stay exact if it ever triggers
            stats["cutoffs"] += 1
            return 1
        total = 0
        for act in acts:
            child = model.clone()
            events: List[Event] = []

            def emit(kind, key, epoch=None, _act=act, _d=depth,
                     **attrs):
                events.append(Event(
                    kind=kind, key=key, step=len(events), epoch=epoch,
                    attrs=attrs,
                    provenance=f"explore:{_act[0]}@d{_d}",
                    seq=len(events) + 1))

            child.apply(act, emit)
            stats["transitions"] += 1
            check(model, events)
            total += dfs(child, depth + 1)
        memo[fp] = total
        return total

    try:
        n_paths = dfs(root, 0)
    except _StopSearch:
        n_paths = 0                   # aborted at first violation
    # dedupe violations (shared subjects across sibling transitions)
    uniq: Dict[Tuple[str, str, str], Violation] = {}
    for v in found:
        uniq.setdefault((v.rule, v.subject, v.message), v)
    return ExploreResult(interleavings=n_paths,
                         states=len(seen),
                         max_depth=stats["max_depth"],
                         events_checked=stats["checked"],
                         violations=list(uniq.values()))


def fuzz_trace(seed: int = 0, n_events: int = 300,
               cfg: Optional[ExploreConfig] = None,
               bug: Optional[str] = None) -> List[Event]:
    """One seeded random walk through the model: a reproducible
    ~``n_events``-event chaos trace (admissions, preemptions, handoffs,
    crashes, drains, host-tier churn).  The clean walk replays with
    zero violations; the mutation tests corrupt single events in it."""
    cfg = cfg or ExploreConfig(max_crashes=2, max_chaos=2,
                               max_sheds=2, max_preempts=3,
                               n_requests=4,
                               pages_per_replica=4,
                               max_depth=10 ** 9)
    rng = random.Random(seed)
    model = _Model(cfg, bug=bug)
    out: List[Event] = []

    def emit(kind, key, epoch=None, **attrs):
        out.append(Event(kind=kind, key=key, step=len(out),
                         epoch=epoch, attrs=attrs,
                         provenance=f"fuzz[{len(out)}]",
                         seq=len(out)))

    guard = 0
    while len(out) < n_events and guard < 50 * n_events:
        guard += 1
        if model.done():
            # recycle: admit a FRESH batch of request ids so a long
            # trace keeps exercising the full lifecycle (terminal
            # states are terminal — a finished id never re-queues),
            # and reset the chaos budgets for the new era.  All
            # handoffs are settled at this point (an active one keeps
            # its request non-terminal), so clearing them re-arms the
            # staging path
            base = max(model.reqs) + 1
            for j in range(model.cfg.n_requests):
                q = base + j
                model.reqs[q] = {"state": _QUEUED, "rep": None,
                                 "pages": (), "shared": (),
                                 "done": 0, "epoch": None}
                emit(ev.REQ_QUEUED, f"req:{q}")
            model.handoffs = []
            model.preempts = model.sheds = 0
            model.crashes = model.chaos = 0
            model.evicts = model.drains = 0
            continue
        acts = model.actions()
        if not acts:
            # mid-era starvation (chaos budgets spent, pools pinned):
            # refresh the budgets and retry; a walk that is still
            # starved is genuinely wedged, so stop
            model.preempts = model.sheds = 0
            model.crashes = model.chaos = 0
            model.evicts = model.drains = 0
            acts = model.actions()
            if not acts:
                break
        model.apply(acts[rng.randrange(len(acts))], emit)
    # settle: exhaust the chaos budgets (progress actions only) and run
    # the last era to completion so every in-flight share closes —
    # terminal refcount conservation must hold on the clean walk
    model.crashes = cfg.max_crashes
    model.chaos = cfg.max_chaos
    model.preempts = cfg.max_preempts
    model.sheds = cfg.max_sheds
    model.drains = cfg.max_drains
    guard = 0
    while not model.done() and guard < 50 * n_events:
        guard += 1
        acts = model.actions()
        if not acts:
            model.evicts = 0      # un-pin a saturated pool
            acts = model.actions()
            if not acts:
                break
        model.apply(acts[rng.randrange(len(acts))], emit)
    # close the trace: revive dead pools so terminal refcounts settle
    for r, v in model.reps.items():
        if not v["alive"]:
            model.apply(("readmit", r), emit)
    skip = model.terminal_skip()
    assert not skip
    return out
