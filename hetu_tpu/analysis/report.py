"""Analysis data model: collective inventory, lint findings, baselines.

The analyzer (``hetu_tpu.analysis``) walks the closed jaxpr / lowered
StableHLO of registered executables and produces an
:class:`AnalysisReport` — one :class:`ExecutableReport` per executable,
each holding a **collective inventory** (every communication op the
traced program performs, with payload/wire accounting and source
attribution) and the **lint findings** the rule engine raised.

Baselines (``ANALYSIS_BASELINE.json``) freeze the per-executable
collective counts/bytes and the accepted findings;
:meth:`AnalysisReport.check_against_baseline` is the CI gate — counts
may not grow, bytes may not grow beyond a tolerance, and no finding may
appear whose key is not already recorded.  Finding keys deliberately
exclude source lines (they shift with unrelated edits); they are
``executable::rule::subject`` with ``subject`` a stable slug (a param
name, a collective kind, an argument index).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

BASELINE_VERSION = 1


@dataclasses.dataclass
class CollectiveRecord:
    """One communication op in a traced program.

    ``count`` folds in enclosing loop trip counts (a psum inside a
    ``lax.scan`` of length M executes M times per step); ``payload_bytes``
    and ``wire_bytes`` are PER EXECUTION — totals multiply by ``count``.
    ``scope`` is the jax name-stack at the emission site (the
    ``comm.comm_tag`` attribution tags land here); ``source`` is the user
    frame ``file:line`` from eqn provenance.
    """
    kind: str                 # all_reduce | all_gather | all_to_all | ...
    axes: Tuple[str, ...]
    dtype: str
    payload_bytes: int
    wire_bytes: float
    count: int = 1
    scope: str = ""
    source: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Finding:
    """One lint-rule violation."""
    rule: str
    subject: str              # stable slug: param name, kind, arg index...
    message: str
    executable: str = ""
    source: str = ""
    severity: str = "warn"
    # suggested remediation, printed by the CLI's --explain mode (a pspec
    # change, a donation, a narrower transport, a capacity factor...).
    # NOT part of the baseline key: hints may improve without re-freezing.
    hint: str = ""

    @property
    def key(self) -> str:
        """Baseline identity — stable across unrelated source motion."""
        return f"{self.executable}::{self.rule}::{self.subject}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        src = f" [{self.source}]" if self.source else ""
        return f"{self.rule}({self.subject}): {self.message}{src}"


@dataclasses.dataclass
class ExecutableReport:
    """Analysis result for one executable."""
    name: str
    records: List[CollectiveRecord] = dataclasses.field(default_factory=list)
    findings: List[Finding] = dataclasses.field(default_factory=list)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def collective_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records:
            out[r.kind] = out.get(r.kind, 0) + r.count
        return out

    @property
    def total_payload_bytes(self) -> int:
        return sum(r.payload_bytes * r.count for r in self.records)

    @property
    def total_wire_bytes(self) -> float:
        return sum(r.wire_bytes * r.count for r in self.records)

    def to_dict(self, records: bool = True) -> dict:
        d = {"collectives": self.collective_counts(),
             "payload_bytes": self.total_payload_bytes,
             "wire_bytes": round(self.total_wire_bytes, 1),
             "findings": sorted(f.key for f in self.findings)}
        # per-edge attribution results (present when the executable
        # registers an edge claim): coverage is gated (may not drop),
        # GSPMD-inserted counts are gated like explicit counts (may not
        # grow) — the edge pass explains them, the baseline pins them.
        if "edge_coverage" in self.meta:
            d["edge_coverage"] = dict(self.meta["edge_coverage"])
        if "gspmd_collectives" in self.meta:
            d["gspmd_collectives"] = dict(self.meta["gspmd_collectives"])
        # static peak-HBM prediction (analysis/memory): the baseline pins
        # peak_bytes (gated with the byte tolerance); the per-kind
        # breakdown and the XLA cross-check delta ride along as the
        # reviewable evidence for a re-freeze
        if "memory" in self.meta:
            d["memory"] = self.meta["memory"].to_dict()
        # static step-time prediction (analysis/cost): the baseline pins
        # flops / hbm_bytes (byte tolerance) and step_time_us; the
        # roofline verdict and XLA cross-check deltas ride along as the
        # reviewable evidence for a re-freeze
        if "cost" in self.meta:
            d["cost"] = self.meta["cost"].to_dict()
        # serving-protocol coverage (analysis/events + protocol): the
        # baseline pins the normalized event-stream size, the observed
        # kind vocabulary and the lifecycle-violation count (0 on a
        # clean tree) so an executable cannot silently stop emitting
        # protocol events — a lost stream turns the lifecycle rules
        # vacuously green, which is the regression class this pins
        if "protocol" in self.meta:
            d["protocol"] = dict(self.meta["protocol"])
        # cross-rank schedule coverage (analysis/schedule): the baseline
        # pins rank/op counts, the op-kind inventory, the verifier's
        # violation count (0 on a clean tree) and the rule vocabulary
        # available at freeze time — a vanished rule or a collapsed
        # schedule turns the hang-freedom verdict vacuously green
        if "schedule" in self.meta:
            d["schedule"] = dict(self.meta["schedule"])
        if records:
            d["records"] = [r.to_dict() for r in self.records]
        return d


class AnalysisReport:
    """Reports for a set of executables + the baseline gate."""

    def __init__(self):
        self.executables: Dict[str, ExecutableReport] = {}

    def add(self, rep: ExecutableReport) -> ExecutableReport:
        self.executables[rep.name] = rep
        return rep

    @property
    def findings(self) -> List[Finding]:
        return [f for rep in self.executables.values()
                for f in rep.findings]

    def to_dict(self, records: bool = False) -> dict:
        return {"version": BASELINE_VERSION,
                "executables": {name: rep.to_dict(records=records)
                                for name, rep in
                                sorted(self.executables.items())}}

    def to_json(self, records: bool = False) -> str:
        return json.dumps(self.to_dict(records=records), indent=1,
                          sort_keys=True)

    def summary(self) -> str:
        lines = []
        for name, rep in sorted(self.executables.items()):
            counts = rep.collective_counts()
            cov = rep.meta.get("edge_coverage")
            cov_s = ""
            if cov:
                pct = 100.0 * cov["explained"] / cov["total"] \
                    if cov["total"] else 100.0
                cov_s = (f", edges explain {cov['explained']}/"
                         f"{cov['total']} ({pct:.0f}%)")
            prot = rep.meta.get("protocol")
            prot_s = ""
            if prot:
                prot_s = (f", {prot['events']} protocol events/"
                          f"{prot['violations']} violations")
            lines.append(
                f"{name}: {sum(counts.values())} collectives {counts}, "
                f"{rep.total_payload_bytes} payload B, "
                f"{rep.total_wire_bytes:.0f} wire B/rank, "
                f"{len(rep.findings)} findings{cov_s}{prot_s}")
            for f in rep.findings:
                lines.append(f"  - {f}")
        return "\n".join(lines)

    # -- baseline gate -------------------------------------------------------

    def check_against_baseline(self, baseline: Optional[dict],
                               tolerance: float = 0.1) -> List[str]:
        """Regression check against a baseline dict.

        Fails (returns messages) when: an executable is missing from the
        baseline, a collective count grew, payload/wire bytes grew more
        than ``tolerance`` (relative), or a finding key not recorded in
        the baseline appeared.  Improvements (fewer collectives / bytes /
        findings) pass — re-freeze them with ``--update-baseline``.
        """
        problems: List[str] = []
        if not baseline:
            return [f"no baseline for {name} (run --update-baseline)"
                    for name in sorted(self.executables)]
        base_exes = baseline.get("executables", {})
        for name, rep in sorted(self.executables.items()):
            base = base_exes.get(name)
            if base is None:
                problems.append(f"{name}: not in baseline "
                                f"(run --update-baseline)")
                continue
            want = base.get("collectives", {})
            got = rep.collective_counts()
            for kind in sorted(set(want) | set(got)):
                w, g = int(want.get(kind, 0)), int(got.get(kind, 0))
                if g > w:
                    problems.append(
                        f"{name}: {kind} count regressed {w} -> {g}")
            # GSPMD-inserted counts (edge pass): may not grow either —
            # a new implicit reshard must re-freeze the baseline even
            # when a generous edge budget would absorb it.  A report
            # that LOST its GSPMD accounting (edge claim dropped, or
            # analysis ran uncompiled) fails too: silently stopping to
            # measure is the regression class this gate exists for.
            want_g = base.get("gspmd_collectives", {})
            got_g = rep.meta.get("gspmd_collectives")
            if "gspmd_collectives" in base:
                if got_g is None:
                    problems.append(
                        f"{name}: baseline records GSPMD accounting but "
                        f"the report has none (edge claim lost, or "
                        f"--no-compile?)")
                else:
                    for kind in sorted(set(want_g) | set(got_g)):
                        w = int(want_g.get(kind, 0))
                        g = int(got_g.get(kind, 0))
                        if g > w:
                            problems.append(
                                f"{name}: GSPMD-inserted {kind} "
                                f"regressed {w} -> {g}")
            # edge coverage may not drop below the frozen ratio, and an
            # executable may not silently stop making its edge claim
            want_c = base.get("edge_coverage")
            got_c = rep.meta.get("edge_coverage")
            if want_c and got_c is None:
                problems.append(
                    f"{name}: baseline records edge coverage "
                    f"{want_c['explained']}/{want_c['total']} but the "
                    f"executable no longer makes an edge claim")
            elif want_c and got_c:
                w_un = int(want_c["total"]) - int(want_c["explained"])
                g_un = int(got_c["total"]) - int(got_c["explained"])
                if g_un > w_un:
                    problems.append(
                        f"{name}: unexplained collectives regressed "
                        f"{w_un} -> {g_un} (edge coverage "
                        f"{got_c['explained']}/{got_c['total']})")
            # static peak-HBM: may not grow beyond the byte tolerance,
            # and an executable may not silently lose its memory
            # accounting (same philosophy as the GSPMD counts above —
            # stopping to measure IS the regression)
            want_m = base.get("memory")
            got_m = rep.meta.get("memory")
            if want_m:
                if got_m is None:
                    problems.append(
                        f"{name}: baseline records peak-HBM accounting "
                        f"but the report has none (memory pass failed?)")
                else:
                    b = float(want_m.get("peak_bytes", 0))
                    g = float(got_m.peak_bytes)
                    if g > b * (1.0 + tolerance) and g - b > 1:
                        problems.append(
                            f"{name}: predicted peak HBM regressed "
                            f"{b:.0f} -> {g:.0f} B "
                            f"(> {tolerance:.0%} tolerance; dominant "
                            f"class {got_m.dominant_kind()})")
            # static step-time: FLOPs / HBM bytes / predicted step time
            # may not grow beyond the tolerance, and an executable may
            # not silently lose its cost accounting (same philosophy as
            # the memory gate: stopping to measure IS the regression)
            want_t = base.get("cost")
            got_t = rep.meta.get("cost")
            if want_t:
                if got_t is None:
                    problems.append(
                        f"{name}: baseline records step-time accounting "
                        f"but the report has none (cost pass failed?)")
                else:
                    for field, bkey in (("flops", "flops"),
                                        ("hbm_bytes", "hbm_bytes"),
                                        ("step_time_us", "step_time_us")):
                        b = float(want_t.get(bkey, 0))
                        g = float(getattr(
                            got_t, field, None) if field != "step_time_us"
                            else got_t.step_time_s * 1e6)
                        if g > b * (1.0 + tolerance) and g - b > 1:
                            problems.append(
                                f"{name}: predicted {field} regressed "
                                f"{b:.0f} -> {g:.0f} "
                                f"(> {tolerance:.0%} tolerance; "
                                f"{got_t.bound}-bound)")
            # serving-protocol coverage: violations may not grow (the
            # tree is clean — any lifecycle violation is a regression),
            # the observed event-kind vocabulary may not lose kinds
            # (an adapter silently dropping a plane un-checks it), and
            # the stream may not shrink beyond the tolerance (stopping
            # to measure IS the regression, as with the gates above)
            want_p = base.get("protocol")
            got_p = rep.meta.get("protocol")
            if want_p:
                if got_p is None:
                    problems.append(
                        f"{name}: baseline records protocol coverage "
                        f"but the report has none (event stream lost?)")
                else:
                    w_v = int(want_p.get("violations", 0))
                    g_v = int(got_p.get("violations", 0))
                    if g_v > w_v:
                        problems.append(
                            f"{name}: protocol violations regressed "
                            f"{w_v} -> {g_v}")
                    missing = sorted(set(want_p.get("kinds", {}))
                                     - set(got_p.get("kinds", {})))
                    if missing:
                        problems.append(
                            f"{name}: protocol event kinds vanished "
                            f"from the stream: {missing} (adapter or "
                            f"producer lost?)")
                    w_e = float(want_p.get("events", 0))
                    g_e = float(got_p.get("events", 0))
                    if g_e < w_e * (1.0 - tolerance) and w_e - g_e > 1:
                        problems.append(
                            f"{name}: protocol event stream shrank "
                            f"{w_e:.0f} -> {g_e:.0f} events "
                            f"(> {tolerance:.0%} tolerance — protocol "
                            f"coverage drop)")
            # cross-rank schedule coverage: violations may not grow (a
            # clean tree verifies hang-free — any divergence is a
            # regression), no rule pinned at freeze time may vanish
            # from the registry (a vanished rule un-checks its
            # invariant), and the extracted schedule may not collapse
            # (ranks drop to zero / ops shrink beyond the tolerance —
            # stopping to extract IS the regression)
            want_s = base.get("schedule")
            got_s = rep.meta.get("schedule")
            if want_s:
                if got_s is None:
                    problems.append(
                        f"{name}: baseline records schedule coverage "
                        f"but the report has none (extraction lost?)")
                else:
                    w_v = int(want_s.get("violations", 0))
                    g_v = int(got_s.get("violations", 0))
                    if g_v > w_v:
                        problems.append(
                            f"{name}: schedule violations regressed "
                            f"{w_v} -> {g_v} "
                            f"({got_s.get('violation_rules')})")
                    from .rules import RULES as _rules
                    gone = sorted(set(want_s.get("rules_available", ()))
                                  - set(_rules))
                    if gone:
                        problems.append(
                            f"{name}: schedule rules vanished from the "
                            f"registry: {gone}")
                    if int(want_s.get("ranks", 0)) > 0 \
                            and int(got_s.get("ranks", 0)) == 0:
                        problems.append(
                            f"{name}: schedule extraction collapsed "
                            f"({want_s.get('ranks')} ranks -> 0)")
                    w_o = float(want_s.get("ops", 0))
                    g_o = float(got_s.get("ops", 0))
                    if g_o < w_o * (1.0 - tolerance) and w_o - g_o > 1:
                        problems.append(
                            f"{name}: schedule op inventory shrank "
                            f"{w_o:.0f} -> {g_o:.0f} ops "
                            f"(> {tolerance:.0%} tolerance — schedule "
                            f"coverage drop)")
            for field, value in (("payload_bytes", rep.total_payload_bytes),
                                 ("wire_bytes", rep.total_wire_bytes)):
                b = float(base.get(field, 0))
                if value > b * (1.0 + tolerance) and value - b > 1:
                    problems.append(
                        f"{name}: {field} regressed {b:.0f} -> "
                        f"{value:.0f} (> {tolerance:.0%} tolerance)")
            known = set(base.get("findings", ()))
            for f in rep.findings:
                if f.key not in known:
                    problems.append(f"{name}: new finding {f}")
        for name in sorted(set(base_exes) - set(self.executables)):
            problems.append(
                f"{name}: in baseline but not analyzed (stale baseline? "
                f"run --update-baseline)")
        return problems


def load_baseline(path: str) -> Optional[dict]:
    import os
    if not os.path.exists(path):
        return None
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')}, "
            f"analyzer speaks {BASELINE_VERSION}")
    return data


def save_baseline(path: str, report: AnalysisReport) -> None:
    with open(path, "w") as f:
        f.write(report.to_json(records=False) + "\n")
