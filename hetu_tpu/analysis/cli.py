"""``python -m hetu_tpu.analysis`` — the lint-graph CI gate.

Builds the canonical executables (a GPT-2-small-shaped train step on a
pure-dp mesh with the explicit int8 grad sync, and the serving
prefill/decode executables of a small continuous-batching engine — both
scaled down so the gate runs on CPU in CI), analyzes every one, and:

* ``--check`` (default): compare against ``ANALYSIS_BASELINE.json`` —
  exit 1 when a collective count grows, payload/wire bytes grow beyond
  ``--tolerance``, a new lint finding appears, or the grad-comm
  emission no longer matches the DistributedStates prediction.
* ``--update-baseline``: re-freeze the baseline after an INTENTIONAL
  perf change (review the printed diff before committing it).
* ``--json``: dump the full report (with per-collective records) to
  stdout instead of the summary.

The model shapes are deliberately frozen: the baseline pins exact
collective counts, so any change to the lowering path (a new implicit
reshard, a lost donation, a widened transport) trips the gate even when
tests still pass numerically.
"""
from __future__ import annotations

import argparse
import os
import sys

BASELINE_DEFAULT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ANALYSIS_BASELINE.json")


def _force_cpu_mesh() -> None:
    """The gate needs >= 8 devices; CPU CI gets them virtually.  Must
    run before jax initializes a backend (import is fine, first device
    query is not)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if os.environ["JAX_PLATFORMS"] == "cpu":
        jax.config.update("jax_platforms", "cpu")


def build_gate_executables():
    """Build + register the gate's executables; returns their names.

    Deterministic by construction: fixed seeds, fixed shapes, fixed
    request schedule — the baseline pins the exact collective counts.
    """
    import numpy as np
    import jax
    from jax.sharding import PartitionSpec as P

    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.graph.graph import DefineAndRunGraph, clear_executables
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel, llama_config
    from hetu_tpu.parallel import create_mesh
    from hetu_tpu.serving import Engine

    clear_executables("gate_")
    devices = jax.devices()[:8]

    # -- train step: GPT-2-small-shaped (12-head/768-wide ratios scaled
    # to CI size), dp=8, ZeRO-2, explicit int8 grad sync over FLAT
    # dp-sharded optimizer state (reduce-scatter-only: one RS chain +
    # one bf16 param all-gather per bucket, ZERO grad all-gathers) -----
    ht.set_seed(0)
    mesh = create_mesh({"dp": 8}, devices)
    cfg = llama_config(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, max_seq_len=32, sp=False,
                       dtype="bfloat16")
    g = DefineAndRunGraph("gate_train")
    g.mesh = mesh
    with ht.graph(g):
        ids = ht.parallel_placeholder("int32", (8, 32),
                                      pspec=P("dp", None), name="ids")
        labels = ht.parallel_placeholder("int32", (8, 32),
                                         pspec=P("dp", None), name="labels")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="int8",
                                       flat_state=True).minimize(loss)
        rng = np.random.RandomState(0)
        IDS = rng.randint(0, 256, (8, 32)).astype(np.int32)
        g.run(loss, [loss, train_op], {ids: IDS,
                                       labels: np.roll(IDS, -1, axis=1)})
        assert g._grad_comm_active, g._grad_comm_fallback

    # -- serving: prefill + decode over the paged pool -----------------
    ht.set_seed(1)
    scfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, max_seq_len=64)
    with ht.graph("eager", create_new=True):
        smodel = GPTLMHeadModel(scfg)
        smodel.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in
                 smodel.state_dict().items()}
    clock = [0.0]
    eng = Engine(state, scfg, num_pages=16, page_size=8, max_batch=4,
                 name="gate_serving", time_fn=lambda: clock[0])
    eng.add_request([1, 2, 3, 4, 5], max_new_tokens=4)
    eng.add_request([7, 8, 9], max_new_tokens=4)
    while eng.has_work:
        eng.step()
        clock[0] += 1.0
    eng.pool.check_invariants()
    return ["gate_train/plan0"] + sorted(
        f"gate_serving/{k}-{b}" for k, b in eng._compiled)


def run_gate(baseline_path: str = BASELINE_DEFAULT,
             tolerance: float = 0.1, update: bool = False,
             as_json: bool = False, compile: bool = True,
             out=sys.stdout) -> int:
    """Build, analyze, gate.  Returns the process exit code."""
    from . import (AnalysisReport, analyze_handle, get_executable,
                   load_baseline, save_baseline, verify_grad_comm)

    names = build_gate_executables()
    report = AnalysisReport()
    problems = []
    for name in names:
        handle = get_executable(name)
        report.add(analyze_handle(handle, compile=compile))
        if handle.meta.get("grad_comm"):
            # PR-1 grad-comm emission assertions, via the general pass
            try:
                verify_grad_comm(handle)
            except AssertionError as e:
                problems.append(f"{name}: grad-comm emission drifted "
                                f"from the DS prediction: {e}")
    if as_json:
        print(report.to_json(records=True), file=out)
    else:
        print(report.summary(), file=out)
    if update:
        save_baseline(baseline_path, report)
        print(f"baseline written to {baseline_path}", file=out)
        return 0
    baseline = load_baseline(baseline_path)
    problems += report.check_against_baseline(baseline,
                                              tolerance=tolerance)
    if problems:
        print("\nLINT-GRAPH GATE FAILED:", file=out)
        for p in problems:
            print(f"  ! {p}", file=out)
        print(f"\n(intentional change? review and re-freeze with "
              f"`python -m hetu_tpu.analysis --update-baseline`)",
              file=out)
        return 1
    print("\nlint-graph gate OK (baseline "
          f"{os.path.basename(baseline_path)})", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis",
        description="jaxpr/HLO sharding & collectives linter + CI gate")
    ap.add_argument("--check", action="store_true",
                    help="gate against the baseline (default action)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-freeze ANALYSIS_BASELINE.json")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help=f"baseline path (default {BASELINE_DEFAULT})")
    ap.add_argument("--tolerance", type=float, default=0.1,
                    help="relative byte-regression tolerance (default 0.1;"
                         " collective COUNTS are always exact)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip post-SPMD compilation (disables the "
                         "implicit-reshard rule)")
    args = ap.parse_args(argv)
    _force_cpu_mesh()
    return run_gate(baseline_path=args.baseline,
                    tolerance=args.tolerance,
                    update=args.update_baseline,
                    as_json=args.json,
                    compile=not args.no_compile)


if __name__ == "__main__":
    sys.exit(main())
