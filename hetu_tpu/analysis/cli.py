"""``python -m hetu_tpu.analysis`` — the lint-graph CI gate.

Builds the canonical executables — five gated families, all scaled down
so the gate runs on CPU in CI:

* ``gate_train``   — GPT-2-small-shaped train step, pure-dp mesh,
  ZeRO-2 + flat state + explicit int8 grad sync, PLUS the same model
  under ZeRO-3 params-sharded-at-rest (``gate_train@zero3``): the flat
  masters keep the only parameter copy, the forward all-gathers each
  bucket just-in-time (priced ``param_gather`` edges), and the memory
  section pins the at-rest saving;
* ``gate_serving`` — the unified ragged prefill+decode step of a small
  continuous-batching engine over the paged KV pool (ONE executable;
  the v1 bucketed prefill/decode grid is gone), PLUS a disaggregated
  2-replica serving cluster whose prefill and decode engines register
  under distinct per-replica names (``gate_serving@r{i}/unified``) and
  whose prefill→decode KV-page handoffs must carry priced edge claims
  (``kv-handoff-unpriced``);
* ``gate_tp``      — a TP/SP train graph (dp=2 x tp=4, Megatron-SP
  layers from ``nn/parallel.py``), implicit GSPMD sync;
* ``gate_pipe``    — a pipeline run, both ways: MPMD per-stage programs
  (``models/gpt_mpmd.py`` on dp=2 x tp=2 submeshes) and the SPMD
  collective-permute pipeline (``parallel/pipeline.py`` ppermute hop
  chain inside the tick scan);
* ``gate_moe``     — a dropless-MoE train step (``nn/moe.py`` +
  ``ops/moe_dispatch.py`` blocked group-GEMM) with the explicit int8
  sync.

Every family registers a per-edge claim, so the per-edge attribution
pass (``analysis/edges.py``) must explain 100% of what each program
emits; then:

* ``--check`` (default): compare against ``ANALYSIS_BASELINE.json`` —
  exit 1 when a collective count grows, payload/wire bytes grow beyond
  ``--tolerance``, edge coverage drops, a new lint finding appears, or
  the grad-comm emission no longer matches the DistributedStates
  prediction.  Exit 2 when the baseline file is missing entirely.
* ``--update-baseline``: re-freeze the baseline after an INTENTIONAL
  perf change (review the printed diff before committing it).
* ``--format json`` (or legacy ``--json``): dump the full report (with
  per-collective records and edge coverage) to stdout for CI artifacts.
* ``--explain``: after the summary, print each finding's offending
  edge/record plus a concrete remediation hint (pspec change, donation,
  narrower transport, capacity factor).
* ``--memory``: print the static peak-HBM section per executable
  (predicted peak, per-kind breakdown, XLA cross-check delta; with
  ``--explain``, the top-contributor attribution table).  The numbers
  are always computed and gated — the flag only controls the text
  section; ``--format json`` always carries them.
* ``--cost``: print the static step-time section per executable
  (FLOP/HBM roofline verdict, comm time, XLA ``cost_analysis()``
  deltas; with ``--explain``, the top-contributor attribution table).
  Same contract as ``--memory``: always computed and gated, the flag
  only controls the text section, ``--format json`` always carries the
  ``cost`` dict.
* ``--protocol``: print the serving-protocol verifier section per
  executable (normalized event-stream size, observed kind vocabulary,
  lifecycle-machine coverage, violation count — DESIGN.md §23).  Like
  ``--memory``/``--cost`` the numbers are always computed and gated
  (the baseline pins per-executable protocol coverage); the flag only
  controls the text section, ``--format json`` always carries the
  ``protocol`` dict.  Lifecycle findings carry the violating event
  subtrace, printed by ``--explain``.
* ``--schedule``: print the cross-rank schedule verifier section per
  executable (per-rank symbolic op inventory, collective/p2p/switch
  plane sizes, hang-freedom verdict — DESIGN.md §25).  Same contract
  again: always computed and gated (the baseline pins per-executable
  schedule coverage and the rule vocabulary); the flag only controls
  the text section, ``--format json`` always carries the ``schedule``
  dict.  Schedule findings carry the divergent per-rank subtraces side
  by side, printed by ``--explain``.
* ``--hbm-budget``: device HBM budget in GiB for the ``oom-risk`` rule
  (default: the rule's v5p budget).

The memory gate (on by default, with ``--tolerance``): per-executable
predicted peak bytes are pinned in the baseline and may not grow; and
every compiled executable's prediction must stay within ±10% of XLA's
own ``compiled.memory_analysis()`` totals — a drifting memory model is
itself a gate failure, so the planner numbers stay honest.

The step-time gate works the same way: per-executable predicted FLOPs
/ HBM bytes / step time are pinned in the baseline and may not grow
beyond the tolerance, and every compiled executable's comparable FLOP
and bytes-accessed totals must stay within ±10% (absolute floors for
toy-scale programs) of XLA's own ``compiled.cost_analysis()`` — the
same numbers ``planner.cost_model.calibrate_layer_time`` feeds the DP
solver, so the planner search runs on cross-checked physics.

Exit codes (stable, documented for CI): **0** clean, **1** findings or
baseline regressions, **2** baseline missing (run ``--update-baseline``
to create it — the missing-baseline check runs *before* the expensive
build, so a misconfigured CI path fails fast).

The model shapes are deliberately frozen: the baseline pins exact
collective counts, so any change to the lowering path (a new implicit
reshard, a lost donation, a widened transport) trips the gate even when
tests still pass numerically.
"""
from __future__ import annotations

import argparse
import os
import sys

BASELINE_DEFAULT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "ANALYSIS_BASELINE.json")


def _force_cpu_mesh() -> None:
    """The gate needs >= 8 devices; CPU CI gets them virtually.  Must
    run before jax initializes a backend (import is fine, first device
    query is not)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if os.environ["JAX_PLATFORMS"] == "cpu":
        jax.config.update("jax_platforms", "cpu")


def build_gate_executables():
    """Build + register the gate's executables; returns their names.

    Deterministic by construction: fixed seeds, fixed shapes, fixed
    request schedule — the baseline pins the exact collective counts.
    """
    import numpy as np
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    import hetu_tpu as ht
    from hetu_tpu import optim, ops
    from hetu_tpu.graph.graph import (DefineAndRunGraph, clear_executables,
                                      register_executable)
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel, llama_config
    from hetu_tpu.parallel import create_mesh
    from hetu_tpu.serving import Engine

    clear_executables("gate_")
    devices = jax.devices()[:8]
    names = []

    # -- train step: GPT-2-small-shaped (12-head/768-wide ratios scaled
    # to CI size), dp=8, ZeRO-2, explicit int8 grad sync over FLAT
    # dp-sharded optimizer state (reduce-scatter-only: one RS chain +
    # one bf16 param all-gather per bucket, ZERO grad all-gathers) -----
    ht.set_seed(0)
    mesh = create_mesh({"dp": 8}, devices)
    cfg = llama_config(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, max_seq_len=32, sp=False,
                       dtype="bfloat16")
    g = DefineAndRunGraph("gate_train")
    g.mesh = mesh
    with ht.graph(g):
        ids = ht.parallel_placeholder("int32", (8, 32),
                                      pspec=P("dp", None), name="ids")
        labels = ht.parallel_placeholder("int32", (8, 32),
                                         pspec=P("dp", None), name="labels")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="int8",
                                       flat_state=True).minimize(loss)
        rng = np.random.RandomState(0)
        IDS = rng.randint(0, 256, (8, 32)).astype(np.int32)
        g.run(loss, [loss, train_op], {ids: IDS,
                                       labels: np.roll(IDS, -1, axis=1)})
        assert g._grad_comm_active, g._grad_comm_fallback
    names.append("gate_train/plan0")

    # -- ZeRO-3 train step: the SAME model and shapes with the params
    # sharded at rest — the flat fp32 masters hold the only copy, the
    # forward all-gathers each bucket just-in-time (tagged
    # param_gather), and after the chunk-local update only the 1/dp
    # shard remains.  The baseline pins the new priced edge family and
    # the memory section's at-rest param bytes (zero vs gate_train's
    # replicated set) --------------------------------------------------
    ht.set_seed(0)
    g3 = DefineAndRunGraph("gate_train@zero3")
    g3.mesh = create_mesh({"dp": 8}, devices)
    with ht.graph(g3):
        ids = ht.parallel_placeholder("int32", (8, 32),
                                      pspec=P("dp", None), name="ids")
        labels = ht.parallel_placeholder("int32", (8, 32),
                                         pspec=P("dp", None), name="labels")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-2, zero=3, grad_comm="int8",
                                       flat_state=True).minimize(loss)
        rng = np.random.RandomState(0)
        IDS = rng.randint(0, 256, (8, 32)).astype(np.int32)
        g3.run(loss, [loss, train_op], {ids: IDS,
                                        labels: np.roll(IDS, -1, axis=1)})
        assert g3._grad_comm_active, g3._grad_comm_fallback
    names.append("gate_train@zero3/plan0")

    # -- TP/SP train graph: dp=2 x tp=4, Megatron-SP parallel layers,
    # implicit GSPMD sync — every GSPMD-inserted collective must be
    # explained by the graph's pspec edges ----------------------------
    ht.set_seed(4)
    tp_mesh = create_mesh({"dp": 2, "tp": 4}, devices)
    tp_cfg = llama_config(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=32, sp=True,
                          dtype="bfloat16")
    gt = DefineAndRunGraph("gate_tp")
    gt.mesh = tp_mesh
    with ht.graph(gt):
        ids = ht.parallel_placeholder("int32", (8, 32),
                                      pspec=P("dp", None), name="ids")
        labels = ht.parallel_placeholder("int32", (8, 32),
                                         pspec=P("dp", None), name="labels")
        model = GPTLMHeadModel(tp_cfg)
        loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
        rng = np.random.RandomState(4)
        IDS = rng.randint(0, 256, (8, 32)).astype(np.int32)
        gt.run(loss, [loss, train_op], {ids: IDS,
                                        labels: np.roll(IDS, -1, axis=1)})
    names.append("gate_tp/plan0")

    # -- pipeline, MPMD: per-stage programs on dp=2 x tp=2 submeshes,
    # declared stage edges (models/gpt_mpmd.stage_comm_edges) ---------
    from hetu_tpu.models.gpt_mpmd import MPMDGPT
    devs = np.array(devices).reshape(2, 2, 2)
    pipe_cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                         num_heads=4, max_seq_len=16, dropout=0.0,
                         activation="gelu", norm="layernorm",
                         position="learned", sp=False)
    mpmd = MPMDGPT(pipe_cfg, stage_layers=[[1, 1]],
                   meshes=[[Mesh(devs[0], ("dp", "tp")),
                            Mesh(devs[1], ("dp", "tp"))]], seed=5)
    names += mpmd.register_analysis("gate_pipe_mpmd", batch=4, seq=16)

    # -- pipeline, SPMD: the collective-permute pipeline — ppermute hop
    # chain (M + S - 1 hops) inside the tick scan, tagged pipeline/hop
    from hetu_tpu.parallel.pipeline import pipeline_spmd
    pp_mesh = create_mesh({"pp": 4}, devices[:4])
    S, d, M, B = 4, 16, 2, 8

    def _stage_fn(p, v):
        import jax.numpy as jnp
        return jnp.tanh(v @ p["w"][0])

    pp_fn = jax.jit(lambda pr, x: pipeline_spmd(_stage_fn, pr, x, M,
                                                pp_mesh))
    pp_params = {"w": jax.ShapeDtypeStruct((S, 1, d, d), np.float32)}
    register_executable(
        "gate_pipe_spmd/fwd", pp_fn,
        (pp_params, jax.ShapeDtypeStruct((B, d), np.float32)),
        {"kind": "forward", "mesh_axes": {"pp": 4}, "params": [],
         "scalar_fetches": 0,
         "pipeline": {
             "pp_axis": "pp", "hops": M + S - 1,
             "payload_bytes": (B // M) * d * 4,
             "extra_edges": [
                 {"kind": "all_reduce", "tensor": "out_collect",
                  "producer": "last stage",
                  "consumer": "out broadcast + aux micro-batch mean",
                  "axes": ("pp",), "count": 2, "tag": "pipeline",
                  "payload_bytes": B * d * 4}]}})
    names.append("gate_pipe_spmd/fwd")

    # -- dropless-MoE train step: capacity-free blocked group-GEMM
    # (every assignment computes), explicit int8 sync -----------------
    from hetu_tpu.nn.moe import make_moe_layer
    ht.set_seed(6)
    moe_mesh = create_mesh({"dp": 8}, devices)
    gm = DefineAndRunGraph("gate_moe")
    gm.mesh = moe_mesh
    with ht.graph(gm):
        x = ht.parallel_placeholder("float32", (16, 32),
                                    pspec=P("dp", None), name="x")
        moe = make_moe_layer(32, 64, num_experts=4, gate_type="topk",
                             k=2, dispatch_mode="dropless", name="moe")
        out, aux = moe(x)
        loss = ops.reduce_mean(out ** 2) + 0.01 * aux
        train_op = optim.AdamOptimizer(lr=1e-2, zero=1,
                                       grad_comm="int8").minimize(loss)
        rng = np.random.RandomState(6)
        gm.run(loss, [loss, train_op],
               {x: rng.randn(16, 32).astype(np.float32)})
        assert gm._grad_comm_active, gm._grad_comm_fallback
    names.append("gate_moe/plan0")

    # -- serving: ONE unified ragged prefill+decode executable ---------
    ht.set_seed(1)
    scfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, max_seq_len=64)
    with ht.graph("eager", create_new=True):
        smodel = GPTLMHeadModel(scfg)
        smodel.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in
                 smodel.state_dict().items()}
    clock = [0.0]
    eng = Engine(state, scfg, num_pages=16, page_size=8, max_batch=4,
                 chunk_size=4, name="gate_serving",
                 time_fn=lambda: clock[0])
    eng.add_request([1, 2, 3, 4, 5], max_new_tokens=4)
    eng.add_request([7, 8, 9], max_new_tokens=4)
    while eng.has_work:
        eng.step()
        clock[0] += 1.0
    eng.pool.check_invariants(force=True)
    assert eng.compile_count == 1, "the bucket grid came back"
    names += sorted(f"gate_serving/{k}" for k in eng._compiled)

    # -- MLA latent serving: the SAME checkpoint converted to the
    # weight-absorbed latent-KV schema (models.gpt.mla_state_from) on a
    # latent-layout pool — the standing pool lints (trash-page-write,
    # cow-page-write via the shared-header cache hit below) now audit
    # compressed pages, and analysis/memory classifies the asymmetric
    # latent k/v page shapes as kv-page operands ---------------------
    from hetu_tpu.models.gpt import mla_state_from
    mstate, mcfg = mla_state_from(state, scfg, kv_latent_dim=12)
    mclock = [0.0]
    meng = Engine(mstate, mcfg, num_pages=16, page_size=8, max_batch=4,
                  chunk_size=4, name="gate_serving@mla",
                  time_fn=lambda: mclock[0])
    header = list(range(1, 10))          # one full cached page at ps=8
    meng.add_request(header + [11, 12], max_new_tokens=4)
    while meng.has_work:
        meng.step()
        mclock[0] += 1.0
    meng.add_request(header + [21, 22], max_new_tokens=4)
    while meng.has_work:
        meng.step()
        mclock[0] += 1.0
    meng.pool.check_invariants(force=True)
    assert meng.pool.is_latent, "MLA gate engine built a full-head pool"
    assert meng.compile_count == 1, \
        "the latent path retraced the unified executable"
    assert meng.counters["prefix_cache_hits"].value >= 1, \
        "MLA gate trace never hit the prefix cache — the cow-page " \
        "lint would be vacuous over latent pages"
    names.append("gate_serving@mla/unified")

    # -- speculative serving: the SAME model behind a spec-mode engine
    # (truncated 1-layer self-draft, k=3) — the unified executable
    # grows the on-device verify/accept head and registers under
    # gate_serving@spec/unified; the spec-rewind-leak rule audits the
    # trace's tap (rewinds asserted non-vacuous so the rule has real
    # records to chew), and the draft programs join the compile pin ---
    from hetu_tpu.models import draft_state_from
    from hetu_tpu.serving import SpecConfig
    dstate, dcfg = draft_state_from(state, scfg, 1)
    spclock = [0.0]
    speng = Engine(state, scfg, num_pages=16, page_size=8, max_batch=4,
                   chunk_size=8, name="gate_serving@spec",
                   time_fn=lambda: spclock[0],
                   spec=SpecConfig(dstate, dcfg, k=3))
    speng.add_request([1, 2, 3, 4, 5], max_new_tokens=6)
    speng.add_request([7, 8, 9], max_new_tokens=6)
    while speng.has_work:
        speng.step()
        spclock[0] += 1.0
    speng.pool.check_invariants(force=True)
    assert speng.compile_count == 4, \
        "spec engine = unified + draft prefill/propose/insert, pinned"
    prop = speng.counters["spec_proposed"].value
    acc = speng.counters["spec_accepted"].value
    assert prop > 0, "spec gate trace never speculated"
    assert acc < prop, \
        "spec gate trace never rewound — the rewind lint is vacuous"
    names.append("gate_serving@spec/unified")

    # -- serving cluster: a disaggregated 2-replica fleet (1 prefill +
    # 1 decode) over the SAME model — each replica's unified executable
    # registers under its own name (gate_serving@r{i}/unified), the
    # prefill→decode KV-page handoff must carry a priced edge claim
    # (kv-handoff-unpriced audits the records the decode replica's
    # meta exposes), and both replicas share ONE compiled program -----
    from hetu_tpu.serving import EngineCluster
    cclock = [0.0]
    cl = EngineCluster(state, scfg, num_replicas=2,
                       mode="disaggregated", num_prefill=1,
                       name="gate_serving", num_pages=16, page_size=8,
                       max_batch=4, chunk_size=4,
                       time_fn=lambda: cclock[0], ttl=3600.0)
    cl.add_request([1, 2, 3, 4, 5, 6, 7, 8, 9], max_new_tokens=3)
    cl.add_request([1, 2, 3, 4, 5, 6, 7, 8, 11], max_new_tokens=3)
    guard = 0
    while cl.has_work:
        cl.step()
        cclock[0] += 1.0
        guard += 1
        assert guard < 200, "cluster gate trace did not drain"
    assert len(cl.transport.records) == 2, "prefill->decode handoff gone"
    assert all(r["predicted_s"] > 0 for r in cl.transport.records), \
        "handoff lost its alpha-beta pricing"
    for r in cl.replicas:
        r.engine.pool.check_invariants(force=True)
    cl.close()

    # -- SLO traffic plane: an engine with the host-RAM tier for cold
    # prefix-cache pages — a warmed cache is forcibly swept to host,
    # then a same-header request refetches through the priced
    # device↔host path (host-offload-unpriced audits the records the
    # host_offload meta exposes; both directions asserted non-vacuous
    # so the rule has real evicts AND refetches to chew) --------------
    hclock = [0.0]
    heng = Engine(state, scfg, num_pages=16, page_size=8, max_batch=4,
                  chunk_size=4, name="gate_serving@slo",
                  time_fn=lambda: hclock[0], prefix_cache=True,
                  host_tier=True)
    header = list(range(1, 18))          # two full cached pages at ps=8
    heng.add_request(header + [21, 22], max_new_tokens=4,
                     slo_class="interactive")
    while heng.has_work:
        heng.step()
        hclock[0] += 1.0
    heng.prefix_cache.evict(16)          # cold sweep -> host staging
    heng.add_request(header + [31, 32], max_new_tokens=4,
                     slo_class="batch")
    while heng.has_work:
        heng.step()
        hclock[0] += 1.0
    heng.pool.check_invariants(force=True)
    heng.prefix_cache.check_invariants()
    assert heng.host_tier.evictions >= 2, \
        "host-tier gate trace evicted nothing — the rule is vacuous"
    assert heng.host_tier.hits >= 2, \
        "host-tier gate trace never refetched — the refetch half of " \
        "the rule is vacuous"
    assert all(r["predicted_s"] > 0 for r in heng.host_tier.records), \
        "host-tier move lost its alpha-beta pricing"
    names.append("gate_serving@slo/unified")
    return names + [f"gate_serving@r{i}/unified" for i in range(2)]


def explain_report(report, out=sys.stdout, memory: bool = False,
                   cost: bool = False) -> None:
    """--explain: per finding, the offending edge/record and a concrete
    remediation hint; per executable, the predicted edge list (and, with
    --memory / --cost, the peak-HBM / step-time attribution tables)."""
    for name, rep in sorted(report.executables.items()):
        cov = rep.meta.get("edge_coverage")
        edges = rep.meta.get("edges")
        print(f"\n=== {name} ===", file=out)
        if cov:
            print(f"  edge coverage: {cov['explained']}/{cov['total']} "
                  f"collectives explained", file=out)
        if edges is not None:
            print(f"  predicted edges ({len(edges)}):", file=out)
            for e in edges:
                print(f"    . {e.describe()}", file=out)
        mem = rep.meta.get("memory")
        if memory and mem is not None:
            print(f"  peak-HBM attribution (top contributors):", file=out)
            for b in mem.top(10):
                src = f"  [{b.source}]" if b.source else ""
                print(f"    . {b.kind:10s} {b.nbytes:>12d} B  "
                      f"{b.name} {b.detail}{src}", file=out)
        co = rep.meta.get("cost")
        if cost and co is not None:
            print(f"  step-time attribution (top contributors):",
                  file=out)
            for e in co.top(10):
                src = f"  [{e.source}]" if e.source else ""
                print(f"    . {e.prim:18s} "
                      f"{int((e.flops + e.transcendentals) * e.count):>12d}"
                      f" FLOP {int(e.bytes * e.count):>10d} B"
                      f"  {e.detail}{src}", file=out)
            for c in sorted(co.comm, key=lambda c: -c.total_s)[:6]:
                ov = " (overlapped)" if c.overlapped else ""
                print(f"    . comm {c.kind:13s} {c.payload_bytes:>10d} B"
                      f" x{c.count} over {c.group} chips -> "
                      f"{c.total_s * 1e6:.1f}us{ov}", file=out)
        if not rep.findings:
            print("  no findings", file=out)
            continue
        for f in rep.findings:
            print(f"  ! {f}", file=out)
            if not f.hint:
                continue
            if "\n" in f.hint:
                # lifecycle findings carry the violating event subtrace
                # (protocol.Violation.format_subtrace) — print it as a
                # block, not jammed onto one "fix:" line
                for ln in f.hint.splitlines():
                    print(f"    {ln}", file=out)
            else:
                print(f"    fix: {f.hint}", file=out)


def memory_section(report, out=sys.stdout) -> None:
    """--memory: the static peak-HBM model per executable — predicted
    peak, per-kind breakdown, and the XLA cross-check delta."""
    print("\nstatic peak-HBM model (analysis/memory):", file=out)
    for name, rep in sorted(report.executables.items()):
        mem = rep.meta.get("memory")
        if mem is None:
            print(f"  {name}: (memory pass unavailable)", file=out)
            continue
        print(f"  {name}: {mem.summary()}", file=out)


def cost_section(report, out=sys.stdout) -> None:
    """--cost: the static step-time model per executable — FLOP/HBM
    roofline verdict, comm time, and the XLA cost_analysis deltas."""
    print("\nstatic step-time model (analysis/cost):", file=out)
    for name, rep in sorted(report.executables.items()):
        co = rep.meta.get("cost")
        if co is None:
            print(f"  {name}: (cost pass unavailable)", file=out)
            continue
        print(f"  {name}: {co.summary()}", file=out)


def protocol_section(report, out=sys.stdout) -> None:
    """--protocol: the serving-protocol verifier per executable — the
    normalized event stream's size and kind vocabulary, the lifecycle
    machines' coverage, and the violation count (DESIGN.md §23)."""
    print("\nserving-protocol verifier (analysis/protocol):", file=out)
    for name, rep in sorted(report.executables.items()):
        p = rep.meta.get("protocol")
        if p is None:
            print(f"  {name}: (protocol pass unavailable)", file=out)
            continue
        m = p.get("machines", {})
        lost = f", LOST hooks {p['lost_hooks']}" \
            if p.get("lost_hooks") else ""
        print(f"  {name}: {p['events']} events / "
              f"{len(p.get('kinds', {}))} kinds, machines saw "
              f"{m.get('pages', 0)} pages / {m.get('requests', 0)} "
              f"requests / {m.get('replicas', 0)} replicas, "
              f"{p['violations']} violations{lost}", file=out)
        if p.get("kinds"):
            ks = ", ".join(f"{k} x{v}"
                           for k, v in sorted(p["kinds"].items()))
            print(f"    kinds: {ks}", file=out)


def schedule_section(report, out=sys.stdout) -> None:
    """--schedule: the cross-rank schedule verifier per executable —
    rank count, op inventory, plane sizes and the hang-freedom verdict
    (DESIGN.md §25).  Divergent per-rank subtraces ride --explain: each
    schedule finding's hint is the side-by-side window around the
    divergence point on every implicated rank."""
    print("\ncross-rank schedule verifier (analysis/schedule):",
          file=out)
    for name, rep in sorted(report.executables.items()):
        s = rep.meta.get("schedule")
        if s is None:
            print(f"  {name}: (schedule pass unavailable)", file=out)
            continue
        if not s.get("ranks"):
            print(f"  {name}: no multi-rank claim", file=out)
            continue
        verdict = "hang-free" if not s["violations"] \
            else f"{s['violations']} VIOLATION(S) {s['violation_rules']}"
        print(f"  {name}: {s['ranks']} ranks x {s['ops']} ops "
              f"({s['collectives']} collective, {s['p2p']} p2p, "
              f"{s['switch']} switch) — {verdict}", file=out)
        if s.get("kinds"):
            ks = ", ".join(f"{k} x{v}"
                           for k, v in sorted(s["kinds"].items()))
            print(f"    kinds: {ks}", file=out)


def run_gate(baseline_path: str = BASELINE_DEFAULT,
             tolerance: float = 0.1, update: bool = False,
             as_json: bool = False, compile: bool = True,
             explain: bool = False, memory: bool = False,
             cost: bool = False, protocol: bool = False,
             schedule: bool = False,
             hbm_budget_gib: float = None, out=sys.stdout) -> int:
    """Build, analyze, gate.  Returns the process exit code
    (0 clean / 1 findings / 2 baseline missing)."""
    from . import (AnalysisReport, analyze_handle, get_executable,
                   load_baseline, save_baseline, verify_grad_comm)

    baseline = None
    if not update:
        # fail fast BEFORE the expensive build: a missing baseline is a
        # CI configuration error, not a lint finding
        baseline = load_baseline(baseline_path)
        if baseline is None:
            print(f"no baseline at {baseline_path} — run "
                  f"`python -m hetu_tpu.analysis --update-baseline` "
                  f"and commit the result", file=out)
            return 2

    # rule options: the peak-memory-regression rule reads the frozen
    # per-executable peaks straight from the baseline, so the rule and
    # the baseline gate agree on what "regressed" means
    options = {"memory_tolerance": tolerance,
               "step_time_tolerance": tolerance}
    if baseline is not None:
        options["baseline_peak_bytes"] = {
            name: ex["memory"]["peak_bytes"]
            for name, ex in baseline.get("executables", {}).items()
            if "memory" in ex}
        # predicted-step-regression reads the frozen per-executable
        # step times the same way (baseline pins microseconds)
        options["baseline_step_time_s"] = {
            name: float(ex["cost"]["step_time_us"]) * 1e-6
            for name, ex in baseline.get("executables", {}).items()
            if "cost" in ex}
    if hbm_budget_gib is not None:
        options["hbm_budget_bytes"] = float(hbm_budget_gib) * (1 << 30)

    names = build_gate_executables()
    report = AnalysisReport()
    problems = []
    for name in names:
        handle = get_executable(name)
        rep = report.add(analyze_handle(handle, compile=compile,
                                        options=options))
        if handle.meta.get("grad_comm"):
            # PR-1 grad-comm emission assertions, via the general pass
            try:
                verify_grad_comm(handle)
            except AssertionError as e:
                problems.append(f"{name}: grad-comm emission drifted "
                                f"from the DS prediction: {e}")
        # XLA cross-check: the static model must stay within ±10% of
        # compiled.memory_analysis() (abs floor for tiny programs) —
        # a drifting memory model fails the gate even when the baseline
        # peak is unchanged, and LOSING the cross-check (memory pass or
        # memory_analysis gone) fails it too
        if compile:
            mem = rep.meta.get("memory")
            if mem is None:
                problems.append(f"{name}: static memory pass produced "
                                f"no report (walk failure?)")
            elif mem.xla is None:
                problems.append(f"{name}: compiled.memory_analysis() "
                                f"unavailable — XLA cross-check lost")
            elif not mem.xla_within(rel=0.1):
                problems.append(
                    f"{name}: static peak {mem.cmp_peak_bytes} B drifted "
                    f"{mem.xla_delta():+.1%} from XLA's "
                    f"{mem.xla_total} B (±10% cross-check)")
            # step-time cross-check, same stance: FLOP and
            # bytes-accessed totals within ±10% of cost_analysis()
            # (absolute floors for toy-scale programs), and LOSING the
            # accounting is itself a gate failure
            co = rep.meta.get("cost")
            if co is None:
                problems.append(f"{name}: static cost pass produced "
                                f"no report (walk failure?)")
            elif co.xla is None:
                problems.append(f"{name}: compiled.cost_analysis() "
                                f"unavailable — XLA cross-check lost")
            elif not co.xla_within(rel=0.1):
                fd, bd = co.xla_flops_delta(), co.xla_bytes_delta()
                problems.append(
                    f"{name}: static cost drifted from XLA's "
                    f"cost_analysis (flops "
                    f"{fd:+.1%}, bytes "
                    f"{bd:+.1%}; ±10% cross-check)"
                    if fd is not None and bd is not None else
                    f"{name}: static cost cross-check unavailable")
    if as_json:
        print(report.to_json(records=True), file=out)
    else:
        print(report.summary(), file=out)
        if memory:
            memory_section(report, out=out)
        if cost:
            cost_section(report, out=out)
        if protocol:
            protocol_section(report, out=out)
        if schedule:
            schedule_section(report, out=out)
    if explain:
        explain_report(report, out=out, memory=memory, cost=cost)
    if update:
        save_baseline(baseline_path, report)
        print(f"baseline written to {baseline_path}", file=out)
        return 0
    problems += report.check_against_baseline(baseline,
                                              tolerance=tolerance)
    if problems:
        print("\nLINT-GRAPH GATE FAILED:", file=out)
        for p in problems:
            print(f"  ! {p}", file=out)
        print(f"\n(intentional change? review and re-freeze with "
              f"`python -m hetu_tpu.analysis --update-baseline`)",
              file=out)
        return 1
    print("\nlint-graph gate OK (baseline "
          f"{os.path.basename(baseline_path)})", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis",
        description="jaxpr/HLO sharding & collectives linter + CI gate "
                    "(exit 0 clean / 1 findings / 2 baseline missing)")
    ap.add_argument("--check", action="store_true",
                    help="gate against the baseline (default action)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-freeze ANALYSIS_BASELINE.json")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help=f"baseline path (default {BASELINE_DEFAULT})")
    ap.add_argument("--tolerance", type=float, default=0.1,
                    help="relative byte-regression tolerance (default 0.1;"
                         " collective COUNTS are always exact)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    dest="fmt",
                    help="report output format (json: full report with "
                         "records + edge coverage, for CI artifacts)")
    ap.add_argument("--json", action="store_true",
                    help="legacy alias for --format json")
    ap.add_argument("--explain", action="store_true",
                    help="print each finding's offending edge plus a "
                         "suggested remediation (pspec change, donation,"
                         " narrower transport, capacity factor)")
    ap.add_argument("--memory", action="store_true",
                    help="print the static peak-HBM section (predicted "
                         "peak, per-kind breakdown, XLA cross-check "
                         "delta; with --explain, the attribution table)")
    ap.add_argument("--cost", action="store_true",
                    help="print the static step-time section (FLOP/HBM "
                         "roofline verdict, comm time, XLA cost_analysis"
                         " deltas; with --explain, the attribution "
                         "table)")
    ap.add_argument("--protocol", action="store_true",
                    help="print the serving-protocol verifier section "
                         "(event stream size, kind vocabulary, machine "
                         "coverage, lifecycle violations; --explain "
                         "prints each violation's event subtrace)")
    ap.add_argument("--schedule", action="store_true",
                    help="print the cross-rank schedule verifier "
                         "section (per-rank op inventory, hang-freedom "
                         "verdict; --explain prints each divergence's "
                         "per-rank subtraces side by side)")
    ap.add_argument("--hbm-budget", type=float, default=None,
                    metavar="GIB",
                    help="device HBM budget in GiB for the oom-risk "
                         "rule (default: the rule's v5p budget)")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip post-SPMD compilation (disables GSPMD "
                         "accounting: implicit-reshard, the GSPMD half "
                         "of unexplained-collective, and the XLA "
                         "memory cross-check)")
    args = ap.parse_args(argv)
    _force_cpu_mesh()
    return run_gate(baseline_path=args.baseline,
                    tolerance=args.tolerance,
                    update=args.update_baseline,
                    as_json=args.json or args.fmt == "json",
                    compile=not args.no_compile,
                    explain=args.explain,
                    memory=args.memory,
                    cost=args.cost,
                    protocol=args.protocol,
                    schedule=args.schedule,
                    hbm_budget_gib=args.hbm_budget)


if __name__ == "__main__":
    sys.exit(main())
