"""One typed event stream for the serving protocol (DESIGN.md §23).

Every record plane the serving stack produces — the unified-step tap,
``PagedKVPool`` allocator ops, ``PrefixCache`` sharing, page-transport
extract/inject, ``HostTier`` stage/refetch, cluster fencing / adoption
/ shedding, speculative rewinds, chaos instants — historically carried
its own private dict shape, and every trace lint re-parsed its own
plane.  This module is the single normalization point: adapters turn
each raw plane into :class:`Event` records with a canonical ``kind``
vocabulary, and :func:`collect_events` merges an executable's planes
into ONE ordered stream (ordered by the process-global protocol
sequence every producer stamps at record time — see
``serving.kv_pool.protocol_seq``).  The lifecycle state machines
(``analysis.protocol``) and every trace-replay rule (``analysis.rules``)
consume ONLY this stream, so a new subsystem plugs into the verifier by
emitting events, not by teaching each rule a new dict shape.

Event kinds are plain strings (``"page.alloc"``, ``"req.adopt"``,
``"fence.bump"``, ...) so producers in ``hetu_tpu.serving`` can log
them without importing the analysis package (no import cycle); the
canonical vocabulary lives here as constants.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

import numpy as np

# Mirrors serving.kv_pool.TRASH_PAGE (kept as a literal so this module
# stays import-light; the pool asserts the value at construction).
TRASH_PAGE = 0

# -- canonical event vocabulary ----------------------------------------------

# page plane (allocator + host tier + wire)
PAGE_ALLOC = "page.alloc"
PAGE_FREE = "page.free"
PAGE_CACHE = "page.cache"
PAGE_SHARE = "page.share"
PAGE_UNSHARE = "page.unshare"
PAGE_UNCACHE = "page.uncache"
PAGE_WRITE = "page.write"        # KV scatter into a page (from the tap)
POOL_RESET = "pool.reset"
HOST_STAGE = "host.stage"        # cold page staged to host RAM
HOST_REFETCH = "host.refetch"    # staged page injected back on device

# request plane
REQ_QUEUED = "req.queued"
REQ_ADMIT = "req.admit"
REQ_WRITE = "req.write"          # one packed row's KV write claim
REQ_PREEMPT = "req.preempt"      # recompute-style eviction (kv_drop)
REQ_REWIND = "req.rewind"        # speculative verify rejection
REQ_STAGE = "req.stage"          # disaggregated handoff staged
REQ_ADOPT = "req.adopt"          # mid-flight adoption on a replica
REQ_FINISH = "req.finish"
REQ_SHED = "req.shed"

# fence plane
FENCE_BUMP = "fence.bump"
FENCE_COMPLETE = "fence.complete"
FENCE_STALE_DROP = "fence.stale_drop"

# wire plane
WIRE_EXTRACT = "wire.extract"
WIRE_INJECT = "wire.inject"

# fault plane
CHAOS_INJECT = "chaos.inject"

ALL_KINDS = (
    PAGE_ALLOC, PAGE_FREE, PAGE_CACHE, PAGE_SHARE, PAGE_UNSHARE,
    PAGE_UNCACHE, PAGE_WRITE, POOL_RESET, HOST_STAGE, HOST_REFETCH,
    REQ_QUEUED, REQ_ADMIT, REQ_WRITE, REQ_PREEMPT, REQ_REWIND,
    REQ_STAGE, REQ_ADOPT, REQ_FINISH, REQ_SHED,
    FENCE_BUMP, FENCE_COMPLETE, FENCE_STALE_DROP,
    WIRE_EXTRACT, WIRE_INJECT, CHAOS_INJECT,
)


@dataclass(frozen=True)
class Event:
    """One protocol event.

    ``kind``
        Canonical vocabulary entry (one of :data:`ALL_KINDS`).
    ``key``
        The protocol subject: a page id (``"p3"``), a request id
        (``"req:7"`` / ``"creq:7"`` — engine-local and cluster request
        id spaces are distinct and kept apart), a replica index
        (``"r1"``), or a host-store chain hash.
    ``step``
        Position in the normalized stream (assigned by
        :func:`normalize`; -1 before normalization).
    ``epoch``
        Fence/staging epoch when the plane carries one, else ``None``.
    ``attrs``
        Plane-specific payload (raw record, row/pos/qlen, refcount
        snapshot, ...).
    ``provenance``
        file:line-style pointer into the SOURCE plane
        (``"tap[3].rows[1]"``, ``"pool[42]"``) so a violation names the
        exact record that broke the protocol.
    ``seq``
        Process-global protocol ordinal stamped at record time; the
        merge key across planes (-1 = unknown, keeps stream-local
        order).
    """
    kind: str
    key: Any
    step: int = -1
    epoch: Optional[int] = None
    attrs: Mapping[str, Any] = field(default_factory=dict)
    provenance: str = ""
    seq: int = -1

    def with_step(self, step: int) -> "Event":
        object.__setattr__(self, "step", step)
        return self


def _ev(kind, key, seq=-1, epoch=None, provenance="", **attrs) -> Event:
    return Event(kind=kind, key=key, seq=seq, epoch=epoch,
                 attrs=attrs, provenance=provenance)


# -- adapters: one per record plane ------------------------------------------

def events_from_pool_log(log: Iterable, source: str = "pool"
                         ) -> List[Event]:
    """``PagedKVPool.event_log`` entries ``(seq, op, pages)`` →
    page-plane events (one per page; ``reset`` stays a single event)."""
    out: List[Event] = []
    op_kind = {"alloc": PAGE_ALLOC, "free": PAGE_FREE,
               "cache": PAGE_CACHE, "share": PAGE_SHARE,
               "unshare": PAGE_UNSHARE, "uncache": PAGE_UNCACHE}
    for i, entry in enumerate(log or ()):
        seq, op, pages = entry
        prov = f"{source}[{i}]"
        if op == "reset":
            out.append(_ev(POOL_RESET, source, seq=seq, provenance=prov))
            continue
        kind = op_kind.get(op)
        if kind is None:
            continue
        if isinstance(pages, (int, np.integer)):
            pages = (pages,)
        for pg in pages:
            out.append(_ev(kind, f"p{int(pg)}", seq=seq,
                           provenance=prov, page=int(pg)))
    return out


def _page_write_events(step: int, row, pos: int, qlen: int, pt,
                       page_size: int, refs, seq: int, src: str
                       ) -> List[Event]:
    """Expand one packed row's write plan into per-page-span
    :data:`PAGE_WRITE` events (consecutive tokens hitting the same page
    collapse into one event; ``t0``/``pos0`` locate the first token of
    the span for message parity with the historical per-token scan)."""
    out: List[Event] = []
    last_pg = None
    for t in range(int(qlen)):
        pg = int(pt[int(row), (int(pos) + t) // page_size])
        if pg == last_pg:
            continue
        last_pg = pg
        rc = None
        if refs is not None and pg in refs:
            rc = int(refs[pg])
        out.append(_ev(PAGE_WRITE, f"p{pg}", seq=seq,
                       provenance=f"tap[{step}].rows[{int(row)}]",
                       page=pg, row=int(row), pos0=int(pos) + t,
                       tap_step=step, refcount=rc, src=src))
    return out


def events_from_tap(tap: Iterable[Mapping], page_size: int = 1
                    ) -> List[Event]:
    """The engine's unified-step tap → request-plane write/preempt/
    rewind events plus per-page :data:`PAGE_WRITE` events.  Order is
    the tap's own order (the deque is append-ordered); each record's
    stamped ``seq`` rides onto every event it expands to, so the
    cross-plane merge keeps writes where they happened."""
    out: List[Event] = []
    ps = max(int(page_size), 1)
    for step, rec in enumerate(tap or ()):
        kind = rec.get("kind")
        seq = int(rec.get("seq", -1))
        if kind == "kv_drop":
            out.append(_ev(REQ_PREEMPT, f"req:{int(rec['req'])}",
                           seq=seq, provenance=f"tap[{step}]",
                           tap_step=step))
            continue
        if kind == "spec_rewind":
            out.append(_ev(REQ_REWIND, f"req:{int(rec['req'])}",
                           seq=seq, provenance=f"tap[{step}]",
                           tap_step=step,
                           valid_upto=int(rec["valid_upto"]),
                           written_upto=int(rec.get("written_upto", 0))))
            continue
        if kind == "unified":
            refs = rec.get("refcounts") or None
            pt = rec.get("page_tables")
            pt = None if pt is None else np.asarray(pt)
            exempt = bool(rec.get("rewind_exempt"))
            for r, pos, qlen, ctx_len in rec.get("reads", ()):
                out.append(_ev(
                    REQ_WRITE, f"req:{int(r)}", seq=seq,
                    provenance=f"tap[{step}]", tap_step=step,
                    pos=int(pos), qlen=int(qlen), ctx_len=int(ctx_len),
                    rewind_exempt=exempt))
            if pt is not None:
                for row, pos, qlen in rec.get("rows", ()):
                    out.extend(_page_write_events(
                        step, row, int(pos), int(qlen), pt, ps, refs,
                        seq, "unified"))
            continue
        if kind == "prefill":
            for pg in rec.get("pages", ()):
                out.append(_ev(PAGE_WRITE, f"p{int(pg)}", seq=seq,
                               provenance=f"tap[{step}]",
                               page=int(pg), tap_step=step,
                               refcount=None, src="prefill"))
            continue
        # legacy decode record: one write per live row at its cursor
        pt = np.asarray(rec.get("page_tables"))
        pos = np.asarray(rec.get("pos"))
        n_live = int(rec.get("n_live", 0))
        for i in range(min(n_live, pt.shape[0] if pt.ndim else 0)):
            pg = int(pt[i, int(pos[i]) // ps])
            out.append(_ev(PAGE_WRITE, f"p{pg}", seq=seq,
                           provenance=f"tap[{step}].row[{i}]",
                           page=pg, row=i, pos0=int(pos[i]),
                           tap_step=step, refcount=None, src="decode"))
    return out


def events_from_handoff_records(records: Iterable[Mapping]
                                ) -> List[Event]:
    """Transport ``inject`` records (the priced cross-replica /
    host↔device wire) → :data:`WIRE_INJECT` events; the raw record
    rides in ``attrs['record']`` for the pricing rules."""
    out: List[Event] = []
    for i, rec in enumerate(records or ()):
        epoch = rec.get("epoch")
        if isinstance(epoch, bool) or not isinstance(epoch, int):
            epoch = None
        out.append(_ev(
            WIRE_INJECT,
            f"r{rec.get('src', '?')}->r{rec.get('dst', '?')}",
            seq=int(rec.get("seq", -1)), epoch=epoch,
            provenance=f"kv_handoff[{i}]", record=dict(rec), index=i,
            pages=rec.get("dst_pages")))
    return out


def events_from_extract_log(log: Iterable, source: str = "wire"
                            ) -> List[Event]:
    """Transport ``extract_log`` entries ``(seq, pages)`` →
    :data:`WIRE_EXTRACT` events (a read of live pages into the host
    staging buffer — the pages must be allocated or cached)."""
    out: List[Event] = []
    for i, entry in enumerate(log or ()):
        seq, pages = entry
        out.append(_ev(WIRE_EXTRACT, source, seq=int(seq),
                       provenance=f"{source}.extract[{i}]",
                       pages=tuple(int(p) for p in pages)))
    return out


def events_from_host_records(records: Iterable[Mapping]
                             ) -> List[Event]:
    """``HostTier.records`` (dir evict|refetch) → host-plane events
    keyed by the layout-salted chain hash."""
    out: List[Event] = []
    for i, rec in enumerate(records or ()):
        kind = HOST_STAGE if rec.get("dir") == "evict" else HOST_REFETCH
        out.append(_ev(kind, f"h{rec.get('chain_hash', '?')}",
                       seq=int(rec.get("seq", -1)),
                       provenance=f"host_offload[{i}]",
                       record=dict(rec), index=i,
                       page=rec.get("page")))
    return out


def events_from_adoptions(records: Iterable[Mapping]) -> List[Event]:
    """Cluster ``_adoptions`` entries → :data:`REQ_ADOPT` events in the
    CLUSTER request-id namespace (``creq:<id>``), carrying the staging
    epoch and the destination's fence epoch at adoption time."""
    out: List[Event] = []
    for i, rec in enumerate(records or ()):
        epoch = rec.get("epoch")
        if isinstance(epoch, bool) or not isinstance(epoch, int):
            epoch = None
        out.append(_ev(
            REQ_ADOPT, f"creq:{rec.get('req_id', '?')}",
            seq=int(rec.get("seq", -1)), epoch=epoch,
            provenance=f"adoptions[{i}]", record=dict(rec), index=i,
            dst=rec.get("dst"), fence_epoch=rec.get("fence_epoch")))
    return out


def events_from_protocol_log(log: Iterable[Mapping],
                             source: str = "protocol") -> List[Event]:
    """Generic adapter for the ``protocol_log`` lists the engine and
    cluster append to: each entry is ``{"ev": <kind>, "key": <subject>,
    "seq": <ordinal>, ...attrs}`` with ``ev`` already canonical."""
    out: List[Event] = []
    for i, rec in enumerate(log or ()):
        attrs = {k: v for k, v in rec.items()
                 if k not in ("ev", "key", "seq", "epoch")}
        epoch = rec.get("epoch")
        if isinstance(epoch, bool) or not isinstance(epoch, int):
            epoch = None
        out.append(Event(kind=rec["ev"], key=rec.get("key"),
                         seq=int(rec.get("seq", -1)), epoch=epoch,
                         attrs=attrs, provenance=f"{source}[{i}]"))
    return out


def events_from_chaos(injected: Iterable[Mapping]) -> List[Event]:
    """``ChaosController.injected`` audit entries → chaos instants."""
    out: List[Event] = []
    for i, rec in enumerate(injected or ()):
        out.append(_ev(CHAOS_INJECT,
                       f"chaos:{rec.get('kind', '?')}",
                       seq=int(rec.get("seq", -1)),
                       provenance=f"chaos[{i}]", record=dict(rec)))
    return out


# -- the merged stream --------------------------------------------------------

def normalize(*streams: List[Event]) -> List[Event]:
    """Merge per-plane event lists into ONE ordered stream.  Each
    stream is internally ordered; across streams the process-global
    ``seq`` stamped at record time is the merge key.  Events without a
    seq (hand-built traces, pre-protocol records) inherit their
    stream-local predecessor's seq, so they stay put relative to their
    neighbours.  Stream ``step`` ordinals are assigned here."""
    tagged: List[Tuple[int, int, int, Event]] = []
    for si, stream in enumerate(streams):
        last = -1
        for j, e in enumerate(stream or ()):
            seq = e.seq if e.seq >= 0 else last
            last = seq
            tagged.append((seq, si, j, e))
    tagged.sort(key=lambda t: (t[0], t[1], t[2]))
    out = []
    for step, (_, _, _, e) in enumerate(tagged):
        out.append(e.with_step(step))
    return out


def _resolve(meta, key):
    """Resolve a meta record hook exactly like the rules do: ``None``
    + lost=True when the hook raised (the accounting itself is lost)."""
    records = (meta or {}).get(key)
    if callable(records):
        try:
            records = records()
        except Exception:
            return None, True
    return records, False


def collect_events(ctx) -> Tuple[List[Event], List[str]]:
    """Build an executable's full normalized protocol stream from its
    analysis context: pool event log + unified tap (``ctx.serving``),
    engine/cluster protocol logs, transport extract log, and the
    ``kv_handoff`` / ``adoptions`` / ``host_offload`` meta hooks.
    Returns ``(events, lost_hooks)`` where ``lost_hooks`` names meta
    hooks that raised.  Memoized on the context object — the four
    lifecycle rules and the report section share one build."""
    cached = getattr(ctx, "_protocol_events", None)
    if cached is not None:
        return cached
    streams: List[List[Event]] = []
    lost: List[str] = []
    serving = getattr(ctx, "serving", None) or {}
    pool = serving.get("pool")
    pool_log = serving.get("pool_log")
    if pool_log is None and pool is not None:
        pool_log = getattr(pool, "event_log", None)
    if pool_log:
        streams.append(events_from_pool_log(pool_log))
    ps = serving.get("page_size") or getattr(pool, "page_size", 1) or 1
    if serving.get("tap"):
        streams.append(events_from_tap(serving["tap"], page_size=ps))
    if serving.get("protocol"):
        streams.append(events_from_protocol_log(serving["protocol"],
                                                source="engine"))
    if serving.get("extract_log"):
        streams.append(events_from_extract_log(serving["extract_log"]))
    meta = getattr(ctx, "meta", None) or {}
    for key, adapter in (("kv_handoff", events_from_handoff_records),
                         ("host_offload", events_from_host_records),
                         ("adoptions", events_from_adoptions)):
        if key not in meta:
            continue
        records, hook_lost = _resolve(meta, key)
        if hook_lost:
            lost.append(key)
            continue
        if records:
            streams.append(adapter(records))
    if "extract_log" in meta:
        # the transport's extract log, attached only to the replica
        # whose pool the extracts read (page ids are pool-local)
        records, hook_lost = _resolve(meta, "extract_log")
        if hook_lost:
            lost.append("extract_log")
        elif records:
            streams.append(events_from_extract_log(records))
    if "protocol" in meta:
        records, hook_lost = _resolve(meta, "protocol")
        if hook_lost:
            lost.append("protocol")
        elif records:
            streams.append(events_from_protocol_log(records,
                                                    source="cluster"))
    if "chaos" in meta:
        records, hook_lost = _resolve(meta, "chaos")
        if hook_lost:
            lost.append("chaos")
        elif records:
            streams.append(events_from_chaos(records))
    events = normalize(*streams)
    result = (events, lost)
    try:
        ctx._protocol_events = result
    except Exception:
        pass
    return result


def kind_counts(events: Iterable[Event]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for e in events:
        out[e.kind] = out.get(e.kind, 0) + 1
    return dict(sorted(out.items()))
