"""Closed-jaxpr traversal: collective inventory + structural probes.

The static half of the analyzer: given the jaxpr of a compiled plan
(``jax.stages.Lowered``/``Traced`` expose it without running anything),
recursively walk every sub-jaxpr — ``shard_map`` manual regions, scan
bodies, pjit/remat calls, cond branches — and pull out:

* :func:`collect_collectives` — every communication primitive, with
  payload/wire-byte accounting (the :mod:`hetu_tpu.parallel.comm` ring
  conventions), the mesh-axis sizes resolved from the enclosing
  ``shard_map``'s mesh, loop trip counts folded into ``count``, and
  source attribution from eqn provenance (user frame + jax name stack,
  which carries the ``comm.comm_tag`` tags).
* :func:`compute_dtype_histogram` — what dtype the FLOP-heavy ops
  (dot_general/conv) run in, for the wide-collective rule.
* :func:`unreduced_scalar_outputs` — scalar outputs of manual-mode
  regions whose def-chain contains no cross-replica reduction (each rank
  would return its own local value as "the" result).
* :func:`donation_candidates` — large un-donated inputs whose
  shape/dtype reappears among the outputs (a buffer the caller could
  donate).

GSPMD-inserted collectives (implicit resharding from sharding
constraints) do NOT appear in the jaxpr — they only exist after SPMD
partitioning.  Rules that need them diff compiled-HLO counts against the
jaxpr inventory (``rules.implicit-reshard``).
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..parallel.comm import ring_wire_bytes
from .report import CollectiveRecord

#: primitive name -> canonical collective kind (comm.py vocabulary)
COLLECTIVE_PRIMS = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "ppermute": "ppermute",
    "pbroadcast": "all_reduce",
}

#: cross-replica reduction prims (for the unreduced-scalar probe)
REDUCTION_PRIMS = {"psum", "pmax", "pmin", "reduce_scatter", "psum_scatter"}

#: FLOP-dominant compute prims (for the dtype histogram)
COMPUTE_PRIMS = {"dot_general", "conv_general_dilated"}


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Every sub-jaxpr a primitive carries (Jaxpr or ClosedJaxpr)."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):               # raw Jaxpr
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr                    # ClosedJaxpr


def _as_jaxpr(jaxpr):
    return jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, dtype=np.int64)
                   * np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def _axis_names(params: dict) -> Tuple[str, ...]:
    ax = params.get("axes", params.get("axis_name", ()))
    if isinstance(ax, str):
        return (ax,)
    return tuple(str(a) for a in ax)


def _name_stack_of(eqn) -> str:
    """The eqn's jax name-stack alone (no traceback walk)."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return ""
    try:
        return str(si.name_stack)
    except Exception:
        return ""


def _source_of(eqn) -> Tuple[str, str]:
    """(scope, file:line) from eqn provenance."""
    scope = _name_stack_of(eqn)
    src = ""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return scope, src
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(si)
        if fr is not None:
            import os
            src = f"{os.path.basename(fr.file_name)}:{fr.start_line}"
    except Exception:
        pass
    return scope, src


def iter_eqns(jaxpr, _trip: int = 1, _axis_sizes: Optional[Dict[str, int]]
              = None, _scope: str = ""
              ) -> Iterator[Tuple[Any, int, Dict[str, int], str]]:
    """Yield ``(eqn, trip_count, axis_sizes, scope_prefix)`` over the
    whole jaxpr tree.

    ``trip_count`` multiplies enclosing ``scan``/``while`` iterations
    (unbounded whiles count as 1 with the loop noted by the caller via
    the eqn itself); ``axis_sizes`` maps manual mesh axes in scope to
    their sizes, resolved from enclosing ``shard_map`` meshes.

    ``scope_prefix`` carries the name-stack of the enclosing *container*
    eqns: jax traces scan/pjit/cond bodies in a fresh name-stack frame,
    so a ``comm_tag`` entered AROUND a ``lax.scan`` lands on the scan
    eqn but NOT on the collectives inside its body — without the prefix
    a pipeline loop's ppermutes would show up untagged.  Callers join
    ``scope_prefix`` with the eqn's own name-stack for full attribution.
    """
    axis_sizes = dict(_axis_sizes or {})
    for eqn in _as_jaxpr(jaxpr).eqns:
        yield eqn, _trip, axis_sizes, _scope
        sub_trip = _trip
        sub_axes = axis_sizes
        if eqn.primitive.name == "scan":
            sub_trip = _trip * int(eqn.params.get("length", 1))
        elif eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                sub_axes = dict(axis_sizes)
                shape = getattr(mesh, "shape", {})
                items = shape.items() if hasattr(shape, "items") else \
                    zip(getattr(mesh, "axis_names", ()), shape)
                for name, size in items:
                    sub_axes[str(name)] = int(size)
        subs = list(_sub_jaxprs(eqn))
        if subs:
            # scope computed only for container eqns (name-stack read,
            # no traceback walk) — per-eqn cost would dominate the walk
            sub_scope = _join_scope(_scope, _name_stack_of(eqn))
            for sub in subs:
                yield from iter_eqns(sub, sub_trip, sub_axes, sub_scope)


def _join_scope(prefix: str, scope: str) -> str:
    """Compose an enclosing container's scope with an inner name-stack
    (skipping duplication when the inner stack already carries it)."""
    if not prefix:
        return scope
    if not scope or scope == prefix or scope.startswith(prefix + "/"):
        return scope or prefix
    return f"{prefix}/{scope}"


def collect_collectives(jaxpr) -> List[CollectiveRecord]:
    """The collective inventory of a closed jaxpr (see module doc)."""
    records: List[CollectiveRecord] = []
    for eqn, trip, axis_sizes, prefix in iter_eqns(jaxpr):
        kind = COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if kind is None:
            continue
        axes = _axis_names(eqn.params)
        n = 1
        for a in axes:
            n *= axis_sizes.get(a, 1)
        groups = eqn.params.get("axis_index_groups")
        if groups:
            n = max(len(g) for g in groups)
        # psum is variadic: one record per eqn, bytes summed over operands
        op_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars
                       if hasattr(v, "aval"))
        if kind == "all_gather":
            payload = op_bytes * n   # comm.py convention: gathered size
        else:
            payload = op_bytes
        dtype = "unknown"
        for v in eqn.invars:
            if hasattr(v, "aval") and hasattr(v.aval, "dtype"):
                dtype = np.dtype(v.aval.dtype).name
                break
        scope, src = _source_of(eqn)
        # container-scope propagation: a comm_tag entered around the
        # enclosing scan/pjit lands on the container eqn, not the body
        # eqns — join it in so loop collectives keep their attribution
        # (ppermute hop chains inside the pipeline tick scan).
        scope = _join_scope(prefix, scope)
        try:
            wire = ring_wire_bytes(kind, payload, n)
        except ValueError:
            wire = 0.0
        records.append(CollectiveRecord(
            kind=kind, axes=axes, dtype=dtype, payload_bytes=int(payload),
            wire_bytes=wire, count=trip, scope=scope, source=src))
    return records


def compute_dtype_histogram(jaxpr) -> Dict[str, int]:
    """dtype name -> count of FLOP-dominant eqns producing it."""
    out: Dict[str, int] = {}
    for eqn, trip, _, _prefix in iter_eqns(jaxpr):
        if eqn.primitive.name in COMPUTE_PRIMS and eqn.outvars:
            aval = getattr(eqn.outvars[0], "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                name = np.dtype(aval.dtype).name
                out[name] = out.get(name, 0) + trip
    return out


def _contains_reduction(jaxpr, _depth: int = 0) -> bool:
    if _depth > 8:
        return False
    for eqn in _as_jaxpr(jaxpr).eqns:
        if eqn.primitive.name in REDUCTION_PRIMS:
            return True
        for sub in _sub_jaxprs(eqn):
            if _contains_reduction(sub, _depth + 1):
                return True
    return False


def unreduced_scalar_outputs(jaxpr) -> List[Tuple[str, str, str]]:
    """Scalar outputs of manual (shard_map) regions with no reduction on
    their def-chain: ``(var_name, scope, source)`` per offender.

    Each rank would return its own local value as "the" region result —
    the classic silently-wrong local mean.  Container eqns (scan, pjit,
    remat, cond) on the chain count as reduced when ANY reduction lives
    inside them (conservative: no false positives from merged carries).
    """
    offenders: List[Tuple[str, str, str]] = []
    for eqn, _trip, axis_sizes, _prefix in iter_eqns(jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        sizes = [int(s) for s in getattr(mesh, "shape", {}).values()] \
            if hasattr(getattr(mesh, "shape", None), "values") else []
        if sizes and max(sizes, default=1) <= 1:
            continue                        # single-device region
        region = _as_jaxpr(eqn.params["jaxpr"])
        produced = {}
        for ieqn in region.eqns:
            for ov in ieqn.outvars:
                produced[id(ov)] = ieqn
        region_invars = {id(v) for v in region.invars}
        for ov in region.outvars:
            aval = getattr(ov, "aval", None)
            if aval is None or getattr(aval, "shape", None) != ():
                continue
            if id(ov) in region_invars or not hasattr(ov, "count"):
                continue                    # pass-through / literal
            # BFS back through the def-chain looking for a reduction
            stack, seen, reduced = [ov], set(), False
            while stack and not reduced:
                v = stack.pop()
                if id(v) in seen or id(v) in region_invars:
                    continue
                seen.add(id(v))
                ieqn = produced.get(id(v))
                if ieqn is None:
                    continue
                if ieqn.primitive.name in REDUCTION_PRIMS:
                    reduced = True
                    break
                subs = list(_sub_jaxprs(ieqn))
                if subs and any(_contains_reduction(s) for s in subs):
                    reduced = True
                    break
                stack.extend(iv for iv in ieqn.invars
                             if hasattr(iv, "count"))
            if not reduced:
                producer = produced.get(id(ov))
                scope, src = _source_of(producer) if producer is not None \
                    else ("", "")
                offenders.append((str(ov), scope, src))
    return offenders


def donation_candidates(args_info, out_avals,
                        min_bytes: int = 1 << 20,
                        alias_pairs: Optional[List[Tuple[int, int]]] = None
                        ) -> List[Tuple[str, int]]:
    """Un-donated input buffers that could have been donated.

    ``args_info`` is ``jax.stages.Lowered.args_info`` (leaves carry
    ``.shape``/``.dtype``/``.donated``); an input leaf of at least
    ``min_bytes`` whose (shape, dtype) matches an output aval is a
    candidate — XLA could reuse its buffer in place.  Returns one
    ``(arg_path, total_bytes)`` per offending top-level argument.

    ``alias_pairs`` — ``(output_index, parameter_number)`` pairs from the
    compiled HLO's ``input_output_alias`` table
    (:func:`hetu_tpu.analysis.memory.parse_input_output_aliases`).  When
    given, output slots XLA *already* aliased are retired by exact index
    instead of the shape/dtype guess: a shape-matched output that is in
    fact absorbed by a different donated input stops producing a
    false-positive candidate.
    """
    import jax

    def _nbytes(x) -> int:
        try:
            return int(np.prod(x.shape, dtype=np.int64)
                       * np.dtype(x.dtype).itemsize)
        except Exception:
            return 0

    out_shapes: Dict[Tuple, int] = {}
    out_leaves = [o for o in jax.tree_util.tree_leaves(out_avals)
                  if hasattr(o, "shape")]
    aliased_outs = {oi for oi, _p in (alias_pairs or ())}
    for oi, o in enumerate(out_leaves):
        if oi in aliased_outs:
            continue        # XLA already writes this output in place
        key = (tuple(o.shape), np.dtype(o.dtype).name)
        out_shapes[key] = out_shapes.get(key, 0) + 1
    flat, _ = jax.tree_util.tree_flatten_with_path(args_info)
    # donated inputs claim their matching output slots FIRST: a second
    # same-shaped input has nothing left to alias and is not a
    # candidate (e.g. decode's tokens aliases the greedy output; pos,
    # the same [B] int32, cannot).  With the compiled alias table the
    # absorbed slots are already retired by index above, so only
    # donations the compiler DROPPED still consume a slot here —
    # honored ones (their parameter number appears in the table) must
    # not retire twice, which would hide a real candidate.
    honored_params = {p for _oi, p in (alias_pairs or ())}
    param_idx = -1
    for _path, leaf in flat:
        if not hasattr(leaf, "shape"):
            continue
        param_idx += 1
        if not getattr(leaf, "donated", False):
            continue
        if alias_pairs is not None and param_idx in honored_params:
            continue    # absorbed: its output already retired by index
        key = (tuple(leaf.shape), np.dtype(leaf.dtype).name)
        if out_shapes.get(key, 0) > 0:
            out_shapes[key] -= 1
    by_arg: Dict[str, int] = {}
    for path, leaf in flat:
        if getattr(leaf, "donated", False) or not hasattr(leaf, "shape"):
            continue
        nb = _nbytes(leaf)
        key = (tuple(leaf.shape), np.dtype(leaf.dtype).name)
        if nb >= min_bytes and out_shapes.get(key, 0) > 0:
            # args_info mirrors (args, kwargs): path[0] selects the
            # tuple, path[1] the argument — one finding per argument,
            # not per leaf (a pytree arg is donated as a unit)
            arg = jax.tree_util.keystr(path[:2]) or "arg"
            by_arg[arg] = by_arg.get(arg, 0) + nb
    return sorted(by_arg.items())
