"""Per-edge communication attribution: DS transitions -> expected comms.

Hetu's core contract is that ``DistributedStates`` annotations *fully
determine* the communication a program performs — v2 deduces and inserts
every comm op from producer -> consumer state transitions
(``SubstituteCommOp``, ``executable_graph.cc:1006``).  This module checks
that contract for WHOLE executables: it assembles the complete expected
collective set from the registered producer -> consumer pspec edges
(``dstates.deduce_pspec_transition`` over the graph's sharding
annotations), the coalesced grad-comm plan, MoE dispatch bounds, and
pipeline hop chains, then matches it against what the program actually
emits.  Every emitted collective is either *explained* by a predicted
edge or reported as ``unexplained-collective`` (rules.py) with source
provenance.  This replaces the lowered-vs-compiled HLO diff
(``implicit-reshard``) as the implicit-reshard detector for every
executable that registers edges.

Matching semantics (DESIGN.md §11):

* **Explicit collectives** (present in the jaxpr: shard_map manual
  regions, ppermute chains, grad-comm buckets) are matched 1:1-ish
  against edges by *(kind, comm-tag)* — tagged edges must find their tag
  in the record's name-stack scope; untagged records fall back to any
  kind-compatible edge.  A record no edge explains is a finding with the
  eqn's ``file:line`` provenance.
* **GSPMD-inserted collectives** (compiled-HLO counts minus the lowered
  program's explicit counts) never carry provenance — they only exist
  after SPMD partitioning.  Per kind, the inserted count must fit the
  *edge budget*: the sum of ``count`` over edges whose deduced kind
  covers that collective (including autodiff duals for train steps — the
  transpose of an all-gather is a reduce-scatter, the dual of a
  weight-slice ``scatter`` is a gradient all-reduce), times a bounded
  fan-out factor (one DS transition lowers to a handful of HLO ops
  across fwd+bwd, not dozens).  Executables that still declare a strict
  ``allowed_gspmd`` claim (the explicit grad-comm train step: zero
  tolerated inserts) keep exact counting.
* Exact collective *counts* stay pinned by ``ANALYSIS_BASELINE.json`` —
  the edge pass owns *attribution and coverage*, the baseline owns
  count regressions; together a new collective must both fit an edge
  and re-freeze the baseline to land.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.dstates import count_hlo_collectives

#: forward coverage: which emitted collective kinds one deduced edge kind
#: explains.  GSPMD lowers generic reshards to all-to-all / all-gather /
#: collective-permute chains depending on tiling, hence the wide rows.
FWD_COVERS: Dict[str, Tuple[str, ...]] = {
    "all_reduce":     ("all_reduce",),
    "all_gather":     ("all_gather", "ppermute"),
    "reduce_scatter": ("reduce_scatter", "ppermute"),
    "all_to_all":     ("all_to_all", "ppermute"),
    "ppermute":       ("ppermute",),
    "broadcast":      ("all_reduce",),
    "reduce":         ("all_reduce",),
    "scatter":        (),              # a local slice: no forward comm
    "reshard":        ("all_to_all", "all_gather", "reduce_scatter",
                       "ppermute"),
    "identity":       (),
}

#: additional coverage in TRAIN executables: the autodiff dual of each
#: transition (transpose of gather is scatter-add; the dual of a
#: weight-slice is a partial-grad reduction).
BWD_COVERS: Dict[str, Tuple[str, ...]] = {
    "all_reduce":     ("all_reduce",),
    "all_gather":     ("reduce_scatter", "all_reduce"),
    "reduce_scatter": ("all_gather",),
    "all_to_all":     ("all_to_all",),
    "ppermute":       ("ppermute",),
    "broadcast":      ("all_reduce",),
    "reduce":         ("all_reduce",),
    "scatter":        ("all_gather", "all_reduce", "ppermute"),
    "reshard":        ("all_to_all", "all_gather", "reduce_scatter",
                       "all_reduce", "ppermute"),
    "identity":       (),
}


@dataclasses.dataclass
class CommEdge:
    """One predicted producer -> consumer communication edge."""
    kind: str                     # deduced collective ('identity' possible)
    tensor: str = ""              # tensor / bucket the edge moves
    producer: str = ""            # producing op / layer
    consumer: str = ""            # consuming annotation site
    src_spec: str = ""            # printable source pspec / DS
    dst_spec: str = ""            # printable destination pspec / DS
    axes: Tuple[str, ...] = ()
    payload_bytes: int = 0
    count: int = 1                # trip/bucket multiplier
    tag: str = ""                 # comm_tag path expected on the record
    origin: str = "graph"         # graph|declared|grad_comm|param_comm|
                                  # fetch|grad_sync|moe|pipeline
    hint: str = ""                # remediation if this edge misbehaves

    def covers(self, rec_kind: str, train: bool) -> bool:
        if rec_kind in FWD_COVERS.get(self.kind, ()):
            return True
        return train and rec_kind in BWD_COVERS.get(self.kind, ())

    def describe(self) -> str:
        via = f" via {self.tag!r}" if self.tag else ""
        return (f"{self.producer or self.tensor or '?'} -> "
                f"{self.consumer or '?'}: {self.src_spec or 'replicated'}"
                f" -> {self.dst_spec or 'replicated'} ({self.kind}"
                f"{via}, {self.payload_bytes} B x{self.count})")


@dataclasses.dataclass
class EdgeMatch:
    """Result of matching an executable's emissions against its edges."""
    explained: List[Tuple[Any, CommEdge]] = dataclasses.field(
        default_factory=list)          # (CollectiveRecord, edge)
    #: records explained by RE-claiming a param_gather edge past its
    #: count — the ZeRO-3 weight gather replayed inside a fused forward
    #: scope (lazy materialization re-emits it per fused region)
    replayed: List[Tuple[Any, CommEdge]] = dataclasses.field(
        default_factory=list)
    unexplained_records: List[Any] = dataclasses.field(default_factory=list)
    gspmd_explained: Dict[str, Tuple[int, List[CommEdge]]] = \
        dataclasses.field(default_factory=dict)    # kind -> (count, edges)
    gspmd_unexplained: Dict[str, Tuple[int, int]] = \
        dataclasses.field(default_factory=dict)    # kind -> (excess, budget)
    gspmd_counts: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def total(self) -> int:
        return (len(self.explained) + len(self.replayed)
                + len(self.unexplained_records)
                + sum(n for n, _ in self.gspmd_explained.values())
                + sum(e for e, _ in self.gspmd_unexplained.values()))

    @property
    def explained_count(self) -> int:
        return (len(self.explained) + len(self.replayed)
                + sum(n for n, _ in self.gspmd_explained.values()))

    def coverage(self) -> Dict[str, int]:
        return {"explained": self.explained_count, "total": self.total}


# ---------------------------------------------------------------------------
# edge assembly from registration meta
# ---------------------------------------------------------------------------

EDGE_META_KEYS = ("pspec_edges", "declared_edges", "grad_comm", "pipeline",
                  "moe")


def makes_edge_claim(meta: Dict[str, Any]) -> bool:
    """Whether a registered executable predicts its communication per
    edge (at least one edge-bearing meta key present — an empty
    ``pspec_edges`` list IS a claim: "this program does no unpredicted
    communication")."""
    return any(k in meta for k in EDGE_META_KEYS)


def _edge_from_dict(d: Dict[str, Any], origin: str) -> CommEdge:
    return CommEdge(
        kind=d.get("kind", "reshard"),
        tensor=str(d.get("tensor", "")),
        producer=str(d.get("producer", "")),
        consumer=str(d.get("consumer", "")),
        src_spec=str(d.get("src_spec", "")),
        dst_spec=str(d.get("dst_spec", "")),
        axes=tuple(d.get("axes", ())),
        payload_bytes=int(d.get("payload_bytes", 0)),
        count=int(d.get("count", 1)),
        tag=str(d.get("tag", "")),
        origin=str(d.get("origin", origin)),
        hint=str(d.get("hint", "")))


def grad_comm_edges(gc: Dict[str, Any]) -> List[CommEdge]:
    """Edges for the explicit coalesced gradient sync: one edge per
    predicted collective of ``dstates.predict_update_step_collectives``,
    tagged the way ``comm.py`` tags the emission sites (``grad_comm`` /
    ``scales`` sidecars / the flat path's ``param_comm`` regather / the
    ZeRO-3 just-in-time ``param_gather``)."""
    from ..parallel.dstates import predict_update_step_collectives
    entries = [(name, tuple(shape), dtype)
               for name, shape, dtype in gc["entries"]]
    flat = bool(gc.get("flat", False))
    transport = gc["transport"]
    preds, extra = predict_update_step_collectives(
        entries, gc["device_num"], transport=transport,
        bucket_mb=gc["bucket_mb"], scalar_fetches=gc["scalar_fetches"],
        flat=flat, clip=gc.get("clip", False),
        zero=int(gc.get("zero", 2) or 2),
        opt_extra=gc.get("opt_extra"))
    edges: List[CommEdge] = []
    for p in preds:
        quantized = transport in ("bf16", "int8")
        if flat and p.get("tag") == "param_gather":
            tag, origin = "param_gather", "param_gather"
            desc = ("working params gathered just-in-time from the "
                    "flat master (ZeRO-3, weight dtype)")
        elif flat and p["kind"] == "all_gather":
            tag, origin = "param_comm", "param_comm"
            desc = "updated params regathered in the weight dtype"
        elif quantized and p["dtype"] == "float32":
            tag, origin = "scales", "grad_comm"
            desc = "quantized-transport absmax sidecar (fp32 by design)"
        else:
            tag, origin = "grad_comm", "grad_comm"
            desc = f"bucketed {transport} gradient sync"
        edges.append(CommEdge(
            kind=p["kind"], tensor="grad_bucket", producer="optimizer",
            consumer=desc, src_spec="partial(dp)" if origin == "grad_comm"
            else "P(dp)", dst_spec="P(dp)" if p["kind"] != "all_gather"
            else "replicated", axes=(gc.get("dp_axis", "dp"),),
            payload_bytes=int(p["payload_bytes"]), tag=tag, origin=origin))
    for kind, n in (extra or {}).items():
        edges.append(CommEdge(
            kind=kind, tensor="scalar_fetch", producer="loss/clip",
            consumer="pmean of scalar fetches + flat global-norm clip + "
                     "optimizer-declared in-region reductions "
                     "(Adafactor factored stats)",
            src_spec="partial(dp)", dst_spec="replicated",
            axes=(gc.get("dp_axis", "dp"),), payload_bytes=4, count=n,
            origin="fetch"))
    return edges


def predict_edges(meta: Dict[str, Any], mesh_axes: Dict[str, int],
                  train: bool) -> Optional[List[CommEdge]]:
    """The complete expected collective set of one registered
    executable, or None when it makes no edge claim."""
    if not makes_edge_claim(meta):
        return None
    edges: List[CommEdge] = []
    for d in meta.get("pspec_edges") or ():
        edges.append(_edge_from_dict(d, "graph"))
    for d in meta.get("declared_edges") or ():
        edges.append(_edge_from_dict(d, "declared"))
    if meta.get("grad_comm"):
        edges.extend(grad_comm_edges(meta["grad_comm"]))
    else:
        # scalar fetches of a sharded program are reduced to replicated
        # at the fetch boundary (partial -> duplicate: all_reduce)
        n_scalar = int(meta.get("scalar_fetches", 0) or 0)
        multi = any(int(s) > 1 for s in mesh_axes.values())
        if n_scalar and multi:
            edges.append(CommEdge(
                kind="all_reduce", tensor="scalar_fetch",
                producer="loss", consumer="fetch boundary",
                src_spec="partial", dst_spec="replicated",
                axes=tuple(mesh_axes), payload_bytes=4, count=n_scalar,
                origin="fetch"))
        if train and multi:
            # implicit GSPMD grad sync: params replicated over dp,
            # batch sharded -> per-param partial grads psum over dp
            n_params = sum(1 for p in meta.get("params", ())
                           if p.get("trainable", True)) or 1
            dpa = meta.get("dp_axis", "dp")
            edges.append(CommEdge(
                kind="all_reduce", tensor="gradients",
                producer="backward", consumer="implicit GSPMD grad sync",
                src_spec=f"partial({dpa})", dst_spec="replicated",
                axes=(dpa,), count=n_params, origin="grad_sync",
                hint="switch to the explicit path (grad_comm=) for "
                     "coalesced, narrowable gradient collectives"))
    for m in meta.get("moe") or ():
        if m.get("ep_axis"):
            itemsize = np.dtype(m.get("dtype", "float32")).itemsize
            payload = int(m.get("num_experts", 1)) \
                * int(m.get("capacity") or 1) \
                * int(m.get("embed_dim", 1)) * itemsize
            ep = str(m["ep_axis"])
            name = m.get("name", "moe")
            for which in ("dispatch", "combine"):
                edges.append(CommEdge(
                    kind="reshard", tensor=f"{name}.{which}",
                    producer="moe gate",
                    consumer=f"expert-parallel {which} all-to-all",
                    src_spec="P(dp)", dst_spec=f"P({ep})",
                    axes=(ep,), payload_bytes=payload, origin="moe",
                    hint="bytes bounded by capacity_factor "
                         f"{m.get('capacity_factor')}"))
            # the combine einsum contracts the ep-sharded expert dim:
            # its output is partial over ep (DS: partial -> duplicate =
            # all_reduce) whenever tokens are not co-sharded on ep
            edges.append(CommEdge(
                kind="all_reduce", tensor=f"{name}.combine_reduce",
                producer="combine einsum",
                consumer="partial-over-ep expert outputs",
                src_spec=f"partial({ep})", dst_spec="replicated",
                axes=(ep,), payload_bytes=payload, count=2,
                origin="moe"))
    pl = meta.get("pipeline")
    if pl:
        hops = int(pl.get("hops", 0) or 0)
        if hops:
            edges.append(CommEdge(
                kind="ppermute", tensor="stage_boundary",
                producer="pipeline tick", consumer="next stage",
                src_spec=f"P({pl.get('pp_axis', 'pp')})@stage s",
                dst_spec="stage s+1",
                axes=(str(pl.get("pp_axis", "pp")),),
                payload_bytes=int(pl.get("payload_bytes", 0)),
                count=hops, tag="pipeline", origin="pipeline"))
        for d in pl.get("extra_edges") or ():
            edges.append(_edge_from_dict(d, "pipeline"))
    return edges


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------


def _scope_segments(scope: str) -> List[str]:
    return [s for s in scope.split("/") if s]


def _tag_in_scope(tag: str, scope: str) -> bool:
    """Edge tag segments appear in the record's name-stack path, in
    order (``grad_comm`` matches ``.../grad_comm/bucket0/...``)."""
    if not tag:
        return False
    want = _scope_segments(tag)
    got = _scope_segments(scope)
    i = 0
    for seg in got:
        if i < len(want) and seg == want[i]:
            i += 1
    return i == len(want)


def match_edges(records, lowered_text: str, compiled_text: str,
                edges: List[CommEdge], train: bool,
                allowed_gspmd: Optional[Dict[str, int]] = None,
                budget_factor: int = 4) -> EdgeMatch:
    """Match an executable's emitted collectives against its predicted
    edge set (module docstring for the semantics)."""
    m = EdgeMatch()

    # -- explicit records (jaxpr inventory) ---------------------------------
    tagged = [e for e in edges if e.tag]
    untagged = [e for e in edges if not e.tag]
    # each edge may explain at most `count` records: an unbounded
    # kind-only match would let one edge absorb every rogue collective
    # of that kind and never fire
    used: Dict[int, int] = {}

    def _claim(e: CommEdge) -> bool:
        if used.get(id(e), 0) >= e.count:
            return False
        used[id(e)] = used.get(id(e), 0) + 1
        return True

    def _pick(pool, rec, need_tag):
        # exact-kind edges first, broad covers (reshard, autodiff
        # duals) second — a greedy first-fit on the broad edge could
        # starve a later record whose only cover it was
        for exact in (True, False):
            for e in pool:
                if (e.kind == rec.kind) != exact:
                    continue
                if not e.covers(rec.kind, train):
                    continue
                if need_tag and not _tag_in_scope(e.tag, rec.scope):
                    continue
                if _claim(e):
                    return e
        return None

    for rec in records:
        edge = _pick(tagged, rec, need_tag=True)       # 1: tag + kind
        if edge is None:                               # 2: untagged
            edge = _pick(untagged, rec, need_tag=False)
        if edge is not None:
            m.explained.append((rec, edge))
            continue
        # NO general third tier: a tagged edge must find its tag in
        # the record's scope — letting it absorb arbitrary same-kind
        # records would make the explicit-record half of
        # unexplained-collective vacuous (a rogue untagged ppermute in
        # a pipeline program must fire, not ride the hop edge).  One
        # bounded exception: the ZeRO-3 param_gather is re-emitted per
        # fused forward region under lazy materialization, so a record
        # whose scope DOES carry the param_gather tag may re-claim
        # that edge past its count — tracked separately as a replay,
        # never absorbing records of other tags or out-of-scope kinds.
        replay = next(
            (e for e in tagged
             if e.tag == "param_gather" and e.covers(rec.kind, train)
             and _tag_in_scope(e.tag, rec.scope)), None)
        if replay is not None:
            m.replayed.append((rec, replay))
        else:
            m.unexplained_records.append(rec)

    # -- GSPMD-inserted collectives (post-partitioning only) ----------------
    if compiled_text:
        got = count_hlo_collectives(compiled_text, include_ppermute=True)
        explicit = count_hlo_collectives(lowered_text,
                                         include_ppermute=True) \
            if lowered_text else {}
        m.gspmd_counts = {k: v - explicit.get(k, 0)
                          for k, v in got.items() if v - explicit.get(k, 0)
                          > 0}
        for kind, excess in sorted(m.gspmd_counts.items()):
            if allowed_gspmd is not None:
                # strict declared claim (explicit grad-comm train steps:
                # zero tolerated inserts) — exact, as implicit-reshard was
                budget = int(allowed_gspmd.get(kind, 0))
                covering = []
            else:
                covering = [e for e in edges if e.covers(kind, train)]
                budget = budget_factor * sum(e.count for e in covering)
            if excess <= budget:
                m.gspmd_explained[kind] = (excess, covering)
            else:
                m.gspmd_unexplained[kind] = (excess, budget)
    return m
