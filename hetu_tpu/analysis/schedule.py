"""Cross-rank collective-schedule verifier (DESIGN.md §25).

Every other pass in this package prices ONE executable — the program a
single mesh runs.  This module reasons about ALL ranks at once: it
extracts a per-rank *symbolic schedule* of communication operations —
the ordered list of collectives, p2p sends/recvs and hot-switch repack
transfers each rank issues over one training step — and verifies the
cross-rank consistency invariants that a process-local CPU harness can
never exercise but that decide whether the program hangs on a pod:

* **order**    — every rank in a communicator group issues the same
  collectives in the same order.  A rank that reaches collective #7
  while its peers sit at #6 of a different kind blocks forever.
* **group**    — the group tuples agree.  Two ranks that disagree on
  who participates in an all-reduce each wait for a member that never
  arrives.
* **payload**  — shape/dtype/reduction agree.  Mismatched payloads are
  the silent-corruption twin of the hang (and with EQuARX-style
  quantized collectives, dtype is one more way ranks can diverge).
* **pairing**  — every p2p send has a matching recv on the destination
  rank (and vice versa), per channel, by (tag, payload, dtype).
* **acyclicity** — a wait-for graph over pipeline stages x collectives
  has no cycle: the schedules are simulated under rendezvous collective
  / buffered-send / blocking-recv semantics and must run to completion.
* **repack**   — hot-switch repack transfers (``parallel/switch``)
  agree between the sending and receiving side of a dp resize.

Schedules are extracted from the SAME predictors the runtime uses:
dp grad buckets and ZeRO-2/3 ``param_gather`` chains from
``dstates.predict_update_step_collectives`` (the predictor
``optim/optimizer.py``'s flat path is verified against), communicator
groups from ``DistributedStates.get_group_indices_by_dim``, tp/cp
collectives modeled on ``parallel/ulysses`` / ``ring_attention``,
pipeline p2p from ``parallel/schedule`` task lists (via
:func:`~hetu_tpu.parallel.schedule.p2p_events`, the same projection the
MPMD runtime's executed-order tap is checked against) and
``parallel/pipeline.spmd_hop_schedule``, and switch repacks from
``parallel.switch.symbolic_repack_transfers``.

Verification gating: the deadlock simulation runs ONLY when the
pairwise checks are clean — an order/group/pairing divergence trivially
implies a hang, and reporting both would bury the root cause (and make
the seeded-bug corpus's "found by exactly its rule" contract
impossible).  Cascade suppression keeps one violation per implicated
rank set, mirroring the protocol verifier's first-violation-per-subject
poisoning.
"""
from __future__ import annotations

import dataclasses
from collections import Counter, deque
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

RULE_ORDER = "collective-order-mismatch"
RULE_GROUP = "collective-group-mismatch"
RULE_PAYLOAD = "collective-payload-mismatch"
RULE_UNPAIRED = "p2p-unpaired"
RULE_DEADLOCK = "pipeline-deadlock"
RULE_SWITCH = "switch-repack-divergence"

SCHEDULE_RULES: Tuple[str, ...] = (
    RULE_ORDER, RULE_GROUP, RULE_PAYLOAD, RULE_UNPAIRED, RULE_DEADLOCK,
    RULE_SWITCH)

COLLECTIVE_KINDS = ("all_reduce", "all_gather", "reduce_scatter",
                    "all_to_all", "ppermute")
P2P_KINDS = ("send", "recv")


@dataclasses.dataclass(frozen=True)
class CommOp:
    """One communication operation a rank issues, in program order."""
    kind: str                      # COLLECTIVE_KINDS | send | recv | copy
    group: Tuple[int, ...]         # participating ranks ((src, dst) for p2p)
    payload_bytes: int
    dtype: str = "float32"
    reduction: str = ""            # "sum" where a reduction rides the op
    tag: str = ""                  # provenance (grad_comm/bucket0, ...)
    peer: int = -1                 # p2p only: the other rank

    def describe(self) -> str:
        red = f" {self.reduction}" if self.reduction else ""
        return (f"{self.kind}{red} {self.tag or 'untagged'} "
                f"group={self.group} {self.payload_bytes}B {self.dtype}")


@dataclasses.dataclass
class ScheduleViolation:
    """One cross-rank divergence, with the per-rank subtraces that show
    it side by side (printed by the CLI's ``--schedule --explain``)."""
    rule: str
    subject: str
    message: str
    ranks: Tuple[int, ...] = ()
    subtrace: Dict[int, List[str]] = dataclasses.field(default_factory=dict)
    provenance: str = "schedule"

    def format_subtrace(self) -> str:
        blocks = []
        for r in sorted(self.subtrace):
            lines = "\n".join("    " + l for l in self.subtrace[r])
            blocks.append(f"  rank {r}:\n{lines}")
        return "\n".join(blocks)


# ---------------------------------------------------------------------------
# program specification
# ---------------------------------------------------------------------------

_DEFAULT_ENTRIES = (("w_qkv", (64, 192), "float32"),
                    ("w_mlp", (64, 256), "float32"))


@dataclasses.dataclass
class ProgramSpec:
    """Symbolic description of one multi-rank training program.

    Rank layout: ``rank = ((p * dp + d) * cp + c) * tp + t`` — pipeline
    stage outermost (MPMD submeshes are disjoint per stage), then data-,
    context-, tensor-parallel innermost, matching the gate meshes.
    """
    dp: int = 1
    tp: int = 1
    pp: int = 1
    cp: int = 1
    zero: int = 0
    flat: bool = False
    transport: str = "fp32"
    bucket_mb: float = 4.0
    clip: bool = False
    scalar_fetches: int = 1
    opt_extra: Optional[Dict[str, int]] = None
    entries: Tuple = _DEFAULT_ENTRIES
    num_micro_batches: int = 2
    per_pipe_micro: Optional[Tuple[int, ...]] = None    # MPMD Malleus
    pipeline_mode: str = "auto"        # auto | none | spmd | mpmd
    pipeline_schedule: str = "1f1b"    # 1f1b | gpipe
    cp_mode: str = "ulysses"           # ulysses | ring
    layers: int = 2
    seq: int = 128
    hidden: int = 64
    # mid-run dp resize of the flat optimizer layout: {"numel", "itemsize",
    # "new_dp"} — repack transfers appended after the step
    switch: Optional[Dict[str, int]] = None

    def __post_init__(self):
        if self.pipeline_mode == "auto":
            self.pipeline_mode = "none" if self.pp <= 1 else "mpmd"
        if self.pp <= 1:
            self.pipeline_mode = "none"

    @property
    def world(self) -> int:
        return self.pp * self.dp * self.cp * self.tp

    @property
    def block(self) -> int:
        return self.dp * self.cp * self.tp


def spec_from_meta(meta: Dict[str, Any],
                   mesh_axes: Optional[Dict[str, int]] = None
                   ) -> Optional[ProgramSpec]:
    """Derive a :class:`ProgramSpec` from an executable registration's
    meta (the same record sites the other passes consume): an explicit
    ``schedule_spec`` dict wins; otherwise a ``grad_comm`` plan (dp
    width, transport, zero, entries) and/or a ``pipeline`` record
    (stage count, hops>0 = the SPMD ppermute pipeline).  Returns None
    for executables that make no multi-rank claim (serving steps)."""
    ss = meta.get("schedule_spec")
    if ss:
        return ProgramSpec(**ss)
    mesh_axes = dict(mesh_axes or meta.get("mesh_axes") or {})
    tp = int(mesh_axes.get("tp", 1))
    cp = int(mesh_axes.get("cp", mesh_axes.get("sp", 1)))
    gc = meta.get("grad_comm")
    pl = meta.get("pipeline")
    if gc:
        entries = tuple((n, tuple(s), d) for n, s, d in gc["entries"])
        return ProgramSpec(
            dp=int(gc["device_num"]), tp=tp, cp=cp,
            zero=int(gc.get("zero", 2) or 2),
            flat=bool(gc.get("flat", False)),
            transport=gc.get("transport", "fp32"),
            bucket_mb=float(gc.get("bucket_mb", 4.0)),
            clip=bool(gc.get("clip", False)),
            scalar_fetches=int(gc.get("scalar_fetches", 1)),
            opt_extra=gc.get("opt_extra"), entries=entries)
    if pl:
        # MPMD registrations carry num_stages; the SPMD pipeline's stage
        # count is its pp mesh extent (every rank runs the same program)
        S = int(pl.get("num_stages", 0)
                or mesh_axes.get(pl.get("pp_axis", "pp"), 1))
        if S <= 1:
            return None
        hops = int(pl.get("hops", 0))
        mode = "spmd" if hops > 0 else "mpmd"
        M = max(1, hops - S + 1) if hops > 0 else 2
        dp = int(mesh_axes.get("dp", 1))
        return ProgramSpec(dp=dp, tp=tp, cp=cp, pp=S, entries=(),
                           num_micro_batches=M, pipeline_mode=mode)
    return None


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def _groups(spec: ProgramSpec):
    """(dp_group, cp_group, tp_group, pp_group) closures over global
    ranks, built on ``DistributedStates.get_group_indices_by_dim`` —
    the SAME interval/macro arithmetic the runtime's comm deduction
    uses, so the verifier's communicator groups are the deduction's."""
    from ..parallel.dstates import DistributedStates
    B = spec.block
    dims = {0: spec.dp, 1: spec.cp, 2: spec.tp}
    ds = DistributedStates(B, dict(dims), [0, 1, 2]) if B > 1 else None

    def grp(dim: int, rank: int) -> Tuple[int, ...]:
        if dims[dim] <= 1 or ds is None:
            return (rank,)
        p, local = divmod(rank, B)
        return tuple(p * B + g
                     for g in ds.get_group_indices_by_dim(dim, local))

    def pp_group(rank: int) -> Tuple[int, ...]:
        local = rank % B
        return tuple(p * B + local for p in range(spec.pp))

    return (lambda r: grp(0, r), lambda r: grp(1, r),
            lambda r: grp(2, r), pp_group)


def _grad_sections(spec: ProgramSpec, dp_group):
    """(front_ops, tail_ops) per-rank closures for the grad/param sync:
    the ZeRO-3 just-in-time ``param_gather`` chain runs at the FRONT of
    the step (before any forward math — PR 19's at-rest sharding), the
    reduce-scatter / param_comm / scalar-fetch chain at the END."""
    if spec.dp <= 1 or not spec.entries:
        return [], []
    from ..parallel.dstates import predict_update_step_collectives
    entries = [(n, tuple(s), d) for n, s, d in spec.entries]
    preds, extra = predict_update_step_collectives(
        entries, spec.dp, transport=spec.transport,
        bucket_mb=spec.bucket_mb, scalar_fetches=spec.scalar_fetches,
        flat=spec.flat, clip=spec.clip, zero=spec.zero,
        opt_extra=spec.opt_extra)
    front, tail = [], []
    bucket = 0
    for p in preds:
        tag = p.get("tag")
        if tag is None:
            tag = f"grad_comm/bucket{bucket}"
            bucket += 1
        red = "sum" if p["kind"] in ("all_reduce", "reduce_scatter") else ""
        proto = (p["kind"], int(p["payload_bytes"]), p["dtype"], red, tag)
        (front if p.get("tag") == "param_gather" else tail).append(proto)
    for kind, n in sorted((extra or {}).items()):
        for _ in range(int(n)):
            tail.append((kind, 4, "float32",
                         "sum" if kind == "all_reduce" else "",
                         "fetch/scalar"))
    return front, tail


def _compute_ops(spec: ProgramSpec, rank: int, cp_group, tp_group,
                 phase: str) -> List[CommOp]:
    """tp/cp collectives of one micro-batch's forward (or backward)
    through this rank's layer slice — Megatron-style two all-reduces
    per layer over the tp group; Ulysses head/seq all-to-all pair (plus
    the segment-id all-gather) or the ring-attention ppermute chain
    over the cp group."""
    ops: List[CommOp] = []
    act = (spec.seq // max(spec.cp, 1)) * spec.hidden * 4
    for layer in range(spec.layers):
        if spec.cp > 1:
            g = cp_group(rank)
            if spec.cp_mode == "ulysses":
                for half in ("scatter", "gather"):
                    ops.append(CommOp("all_to_all", g, act, "float32",
                                      tag=f"ulysses/l{layer}/{phase}/"
                                          f"{half}"))
                if phase == "fwd":
                    ops.append(CommOp("all_gather", g,
                                      (spec.seq // spec.cp) * 4, "int32",
                                      tag=f"ulysses/l{layer}/segids"))
            else:
                for hop in range(spec.cp - 1):
                    ops.append(CommOp("ppermute", g, act, "float32",
                                      tag=f"ring/l{layer}/{phase}/"
                                          f"hop{hop}"))
        if spec.tp > 1:
            g = tp_group(rank)
            for site in ("attn", "mlp"):
                ops.append(CommOp("all_reduce", g, act, "float32",
                                  reduction="sum",
                                  tag=f"tp/l{layer}/{phase}/{site}"))
    return ops


def _switch_ops(spec: ProgramSpec, dp_group) -> Dict[int, List[CommOp]]:
    """Hot-switch repack transfers of the flat dp-sharded optimizer
    layout under a mid-run dp resize: per dp group, the 1-D symbolic
    twin of ``SwitchPlan.transfers`` decides who sends which interval
    to whom; every member derives the SAME transfer list and emits its
    own sends/recvs (divergence here = ``switch-repack-divergence``)."""
    from ..parallel.switch import symbolic_repack_transfers
    sw = spec.switch or {}
    numel = int(sw.get("numel", 1 << 16))
    itemsize = int(sw.get("itemsize", 4))
    new_dp = max(1, int(sw.get("new_dp", max(1, spec.dp // 2))))
    out: Dict[int, List[CommOp]] = {r: [] for r in range(spec.world)}
    seen = set()
    for r in range(spec.world):
        g = dp_group(r)
        if g in seen:
            continue
        seen.add(g)
        old_ranges = _even_ranges(numel, g[:spec.dp])
        new_ranges = _even_ranges(numel, g[:new_dp])
        transfers = symbolic_repack_transfers(numel, itemsize,
                                              old_ranges, new_ranges)
        for i, (dst, src, (lo, hi), nbytes) in enumerate(transfers):
            tag = f"switch/repack/t{i}"
            if src == dst:
                out[dst].append(CommOp("copy", (dst,), nbytes, "float32",
                                       tag=tag))
                continue
            out[src].append(CommOp("send", (src, dst), nbytes, "float32",
                                   tag=tag, peer=dst))
            out[dst].append(CommOp("recv", (src, dst), nbytes, "float32",
                                   tag=tag, peer=src))
    return out


def _even_ranges(numel: int, ranks: Sequence[int]) -> Dict[int, Tuple[int, int]]:
    n = len(ranks)
    per = -(-numel // n)
    return {r: (min(i * per, numel), min((i + 1) * per, numel))
            for i, r in enumerate(ranks)}


def extract_schedules(spec: ProgramSpec) -> Dict[int, List[CommOp]]:
    """Per-rank symbolic schedule of one training step (plus the
    optional mid-run switch): ``{rank: [CommOp, ...]}`` in issue
    order."""
    from ..parallel.pipeline import spmd_hop_schedule
    from ..parallel.schedule import (generate_gpipe_schedule,
                                     generate_pipedream_flush_schedule)
    dp_group, cp_group, tp_group, pp_group = _groups(spec)
    front, tail = _grad_sections(spec, dp_group)
    sched: Dict[int, List[CommOp]] = {r: [] for r in range(spec.world)}
    act = (spec.seq // max(spec.cp, 1)) * spec.hidden * 4
    B = spec.block

    def emit_protos(rank: int, protos) -> None:
        g = dp_group(rank)
        for kind, payload, dtype, red, tag in protos:
            sched[rank].append(CommOp(kind, g, payload, dtype,
                                      reduction=red, tag=tag))

    # (1) ZeRO-3 just-in-time weight gathers, before any forward math
    for r in range(spec.world):
        emit_protos(r, front)

    # (2) forward/backward compute collectives + pipeline p2p/hops
    if spec.pipeline_mode == "mpmd":
        gen = (generate_pipedream_flush_schedule
               if spec.pipeline_schedule == "1f1b"
               else generate_gpipe_schedule)
        micro = spec.per_pipe_micro or \
            tuple([spec.num_micro_batches] * spec.dp)
        assert len(micro) == spec.dp, (micro, spec.dp)
        pipe_scheds = {d: gen(spec.pp, m) for d, m in enumerate(micro)}
        for r in range(spec.world):
            s, local = divmod(r, B)
            d = local // (spec.cp * spec.tp)
            for t in pipe_scheds[d][s]:
                m = t.micro_batch
                if t.kind == "F":
                    if s > 0:
                        peer = (s - 1) * B + local
                        sched[r].append(CommOp("recv", (peer, r), act,
                                               "float32",
                                               tag=f"pipe{d}/F{m}",
                                               peer=peer))
                    sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                             "fwd")
                    if s < spec.pp - 1:
                        peer = (s + 1) * B + local
                        sched[r].append(CommOp("send", (r, peer), act,
                                               "float32",
                                               tag=f"pipe{d}/F{m}",
                                               peer=peer))
                else:
                    if s < spec.pp - 1:
                        peer = (s + 1) * B + local
                        sched[r].append(CommOp("recv", (peer, r), act,
                                               "float32",
                                               tag=f"pipe{d}/B{m}",
                                               peer=peer))
                    sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                             "bwd")
                    if s > 0:
                        peer = (s - 1) * B + local
                        sched[r].append(CommOp("send", (r, peer), act,
                                               "float32",
                                               tag=f"pipe{d}/B{m}",
                                               peer=peer))
    elif spec.pipeline_mode == "spmd":
        # every rank runs the SAME scanned program: per-micro-batch
        # compute collectives, then the tick-loop ppermute hops and the
        # output-collect psums (parallel/pipeline.py's comm_tag sites)
        for r in range(spec.world):
            for m in range(spec.num_micro_batches):
                sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                         "fwd")
                sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                         "bwd")
            g = pp_group(r)
            for kind, tag in spmd_hop_schedule(spec.num_micro_batches,
                                               spec.pp):
                red = "sum" if kind == "all_reduce" else ""
                sched[r].append(CommOp(kind, g, act, "float32",
                                       reduction=red, tag=tag))
    else:
        for r in range(spec.world):
            for m in range(spec.num_micro_batches):
                sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                         "fwd")
                sched[r] += _compute_ops(spec, r, cp_group, tp_group,
                                         "bwd")

    # (3) gradient sync + updated-param gather + scalar fetches
    for r in range(spec.world):
        emit_protos(r, tail)

    # (4) mid-run hot-switch repack
    if spec.switch is not None:
        for r, ops in _switch_ops(spec, dp_group).items():
            sched[r] += ops
    return sched


# ---------------------------------------------------------------------------
# verification
# ---------------------------------------------------------------------------


def _fmt_window(ops: List[CommOp], center: int, radius: int = 2
                ) -> List[str]:
    lines = []
    lo = max(0, center - radius)
    hi = min(len(ops), center + radius + 1)
    for i in range(lo, hi):
        mark = ">" if i == center else " "
        lines.append(f"{mark} {i:3d}  {ops[i].describe()}")
    if center >= len(ops):
        lines.append(f"> {center:3d}  <end of schedule>")
    return lines


def _is_switch(op: CommOp) -> bool:
    return op.tag.startswith("switch/")


def _verify_p2p(schedules: Dict[int, List[CommOp]], switch: bool
                ) -> List[ScheduleViolation]:
    """Per-channel multiset pairing: sends from ``src`` to ``dst`` must
    equal recvs on ``dst`` from ``src`` by (tag, payload, dtype).
    ``switch=True`` checks the repack plane (its own rule)."""
    chans: Dict[Tuple[int, int], Dict[str, List[Tuple[int, CommOp]]]] = {}
    for r, ops in schedules.items():
        for i, o in enumerate(ops):
            if o.kind not in P2P_KINDS or _is_switch(o) != switch:
                continue
            ch = (r, o.peer) if o.kind == "send" else (o.peer, r)
            side = chans.setdefault(ch, {"send": [], "recv": []})
            side[o.kind].append((i, o))
    rule = RULE_SWITCH if switch else RULE_UNPAIRED
    out: List[ScheduleViolation] = []
    for (src, dst), side in sorted(chans.items()):
        key = lambda io: (io[1].tag, io[1].payload_bytes, io[1].dtype)
        sends = Counter(key(io) for io in side["send"])
        recvs = Counter(key(io) for io in side["recv"])
        if sends == recvs:
            continue
        extra_s = sends - recvs
        extra_r = recvs - sends
        parts = []
        for k in list(extra_s):
            parts.append(f"send {k[0]} ({k[1]}B {k[2]}) x{extra_s[k]} "
                         f"never received by rank {dst}")
        for k in list(extra_r):
            parts.append(f"recv {k[0]} ({k[1]}B {k[2]}) x{extra_r[k]} "
                         f"never sent by rank {src}")
        sub = {}
        for r, lst in ((src, side["send"]), (dst, side["recv"])):
            idx = lst[0][0] if lst else 0
            sub[r] = _fmt_window(schedules[r], idx)
        noun = "switch repack" if switch else "p2p"
        out.append(ScheduleViolation(
            rule=rule, subject=f"{'switch:' if switch else ''}"
                               f"{src}->{dst}",
            message=f"{noun} channel rank {src} -> rank {dst} diverges: "
                    + "; ".join(parts)
                    + (" — the unmatched side blocks forever on real "
                       "hardware" if not switch else
                       " — the resize leaves stale or missing shards"),
            ranks=(src, dst), subtrace=sub))
    return out


def _verify_collectives(schedules: Dict[int, List[CommOp]]
                        ) -> List[ScheduleViolation]:
    """Positional per-group alignment: project each rank's schedule to
    the ops it issues on each group; members of a group must agree at
    every position on kind (order), group tuple (membership) and
    payload/dtype/reduction (payload)."""
    streams: Dict[Tuple[int, ...], Dict[int, List[Tuple[int, CommOp]]]] = {}
    colls: Dict[int, List[Tuple[int, CommOp]]] = {}
    for r, ops in schedules.items():
        mine = [(i, o) for i, o in enumerate(ops)
                if o.kind in COLLECTIVE_KINDS and len(o.group) > 1]
        colls[r] = mine
        for i, o in mine:
            streams.setdefault(o.group, {}).setdefault(r, []).append((i, o))
    cands: List[Tuple[int, ScheduleViolation]] = []
    for G in sorted(streams, key=lambda g: (min(g), len(g))):
        per_rank = streams[G]
        broke = False
        for r in per_rank:
            if r not in G:
                i, o = per_rank[r][0]
                cands.append((i, ScheduleViolation(
                    rule=RULE_GROUP, subject=f"group{G}",
                    message=f"rank {r} issues {o.kind} ({o.tag}) on "
                            f"group {G} it is not a member of",
                    ranks=tuple(sorted(set(G) | {r})),
                    subtrace={r: _fmt_window(schedules[r], i)})))
                broke = True
        if broke:
            continue
        maxlen = max(len(v) for v in per_rank.values())
        for pos in range(maxlen):
            at = {r: (per_rank[r][pos] if pos < len(per_rank.get(r, ()))
                      else None) for r in G}
            present = {r: io for r, io in at.items() if io is not None}
            if not present:
                continue
            ref_r = min(present)
            ref_i, ref = present[ref_r]
            missing = [r for r in G if at.get(r) is None]
            if missing:
                r = missing[0]
                # same-tag op under a DIFFERENT group on the straggler:
                # a membership divergence, not a count divergence
                alt = next(((i, o) for i, o in colls.get(r, ())
                            if o.tag == ref.tag and o.group != G), None)
                sub = {ref_r: _fmt_window(schedules[ref_r], ref_i)}
                if alt is not None:
                    ai, ao = alt
                    sub[r] = _fmt_window(schedules[r], ai)
                    cands.append((ref_i, ScheduleViolation(
                        rule=RULE_GROUP, subject=f"{ref.tag}@{pos}",
                        message=f"group mismatch on {ref.kind} "
                                f"({ref.tag}): rank {ref_r} uses group "
                                f"{G}, rank {r} uses group {ao.group} — "
                                f"each side waits for members that "
                                f"never arrive",
                        ranks=(ref_r, r), subtrace=sub)))
                else:
                    sub[r] = _fmt_window(schedules[r],
                                         len(schedules[r]))
                    cands.append((ref_i, ScheduleViolation(
                        rule=RULE_ORDER, subject=f"{ref.tag}@{pos}",
                        message=f"order mismatch on group {G}: rank "
                                f"{ref_r} issues collective #{pos} "
                                f"({ref.kind} {ref.tag}) but rank {r} "
                                f"issues only {len(per_rank.get(r, ()))} "
                                f"collective(s) on this group — rank "
                                f"{ref_r} blocks forever",
                        ranks=(ref_r, r), subtrace=sub)))
                break
            kinds = {o.kind for _, o in present.values()}
            if len(kinds) > 1:
                bad = next(r for r in sorted(present)
                           if present[r][1].kind != ref.kind)
                bi, bo = present[bad]
                # a kind divergence where one side issues the other's
                # tag under a DIFFERENT group is a membership re-route
                # (group skew shifts the whole stream), not an order bug
                regroup = None
                for (ra, oa), (rb, ob) in (((ref_r, ref), (bad, bo)),
                                           ((bad, bo), (ref_r, ref))):
                    alt = next(((i, o) for i, o in colls.get(rb, ())
                                if o.tag == oa.tag and o.group != G),
                               None)
                    if alt is not None:
                        regroup = (ra, oa, rb, alt)
                        break
                if regroup is not None:
                    ra, oa, rb, (ai, ao) = regroup
                    cands.append((ref_i, ScheduleViolation(
                        rule=RULE_GROUP, subject=f"{oa.tag}@{pos}",
                        message=f"group mismatch on {oa.kind} "
                                f"({oa.tag}): rank {ra} uses group "
                                f"{oa.group}, rank {rb} uses group "
                                f"{ao.group} — each side waits for "
                                f"members that never arrive",
                        ranks=(ref_r, bad),
                        subtrace={ref_r: _fmt_window(schedules[ref_r],
                                                     ref_i),
                                  bad: _fmt_window(schedules[bad],
                                                   bi)})))
                    break
                cands.append((ref_i, ScheduleViolation(
                    rule=RULE_ORDER, subject=f"{ref.tag}@{pos}",
                    message=f"order mismatch on group {G} at position "
                            f"{pos}: rank {ref_r} issues {ref.kind} "
                            f"({ref.tag}) while rank {bad} issues "
                            f"{bo.kind} ({bo.tag}) — mismatched "
                            f"collective kinds rendezvous never "
                            f"completes",
                    ranks=(ref_r, bad),
                    subtrace={ref_r: _fmt_window(schedules[ref_r], ref_i),
                              bad: _fmt_window(schedules[bad], bi)})))
                break
            payloads = {(o.payload_bytes, o.dtype, o.reduction)
                        for _, o in present.values()}
            if len(payloads) > 1:
                bad = next(r for r in sorted(present)
                           if (present[r][1].payload_bytes,
                               present[r][1].dtype,
                               present[r][1].reduction)
                           != (ref.payload_bytes, ref.dtype,
                               ref.reduction))
                bi, bo = present[bad]
                cands.append((ref_i, ScheduleViolation(
                    rule=RULE_PAYLOAD, subject=f"{ref.tag}@{pos}",
                    message=f"payload mismatch on {ref.kind} ({ref.tag},"
                            f" group {G}): rank {ref_r} contributes "
                            f"{ref.payload_bytes}B {ref.dtype}"
                            f"{('/' + ref.reduction) if ref.reduction else ''}"
                            f" but rank {bad} contributes "
                            f"{bo.payload_bytes}B {bo.dtype}"
                            f"{('/' + bo.reduction) if bo.reduction else ''}"
                            f" — shape/dtype disagreement hangs or "
                            f"corrupts the exchange",
                    ranks=(ref_r, bad),
                    subtrace={ref_r: _fmt_window(schedules[ref_r], ref_i),
                              bad: _fmt_window(schedules[bad], bi)})))
                break
    cands.sort(key=lambda c: c[0])
    return [v for _, v in cands]


def _suppress_cascades(violations: List[ScheduleViolation]
                       ) -> List[ScheduleViolation]:
    """One violation per implicated rank set: a single divergent rank
    breaks every group it sits in; only the earliest report survives."""
    out: List[ScheduleViolation] = []
    poisoned: set = set()
    for v in violations:
        if poisoned & set(v.ranks):
            continue
        poisoned |= set(v.ranks)
        out.append(v)
    return out


def _find_deadlock(schedules: Dict[int, List[CommOp]]
                   ) -> List[ScheduleViolation]:
    """Simulate the schedules under rendezvous collectives, buffered
    (non-blocking) sends and blocking recvs — the semantics of XLA's
    async dispatch + the MPMD controller's eager ``device_put``.  A
    stall is a wait-for cycle over pipeline stages x collectives; the
    cycle (or stall set) is reported with each stuck rank's subtrace."""
    pc = {r: 0 for r in schedules}
    chans: Dict[Tuple[int, int], deque] = {}
    ranks = sorted(schedules)

    def done(r):
        return pc[r] >= len(schedules[r])

    while True:
        progressed = False
        for r in ranks:
            while not done(r):
                o = schedules[r][pc[r]]
                if o.kind == "send":
                    chans.setdefault(o.group, deque()).append(o)
                    pc[r] += 1
                    progressed = True
                    continue
                if o.kind == "copy":
                    pc[r] += 1
                    progressed = True
                    continue
                if o.kind == "recv":
                    q = chans.get(o.group)
                    if q:
                        q.popleft()
                        pc[r] += 1
                        progressed = True
                        continue
                    break
                # collective: rendezvous — every member's head op must
                # be the matching (kind, group) op
                heads = {}
                for s in o.group:
                    if done(s):
                        heads = None
                        break
                    ho = schedules[s][pc[s]]
                    if ho.kind != o.kind or ho.group != o.group:
                        heads = None
                        break
                    heads[s] = ho
                if heads is None:
                    break
                for s in o.group:
                    pc[s] += 1
                progressed = True
        if all(done(r) for r in ranks):
            return []
        if not progressed:
            break

    # stalled: build the wait-for graph and pull out a cycle
    stuck = [r for r in ranks if not done(r)]
    waits: Dict[int, List[int]] = {}
    for r in stuck:
        o = schedules[r][pc[r]]
        if o.kind == "recv":
            waits[r] = [o.peer]
        elif o.kind in COLLECTIVE_KINDS:
            waits[r] = [s for s in o.group if s != r and
                        (done(s) or schedules[s][pc[s]].kind != o.kind
                         or schedules[s][pc[s]].group != o.group)]
        else:
            waits[r] = []
    cycle = _find_cycle(waits)
    show = cycle or stuck[:6]
    sub = {r: _fmt_window(schedules[r], pc[r]) for r in show}
    arrows = " -> ".join(str(r) for r in (cycle + [cycle[0]])) \
        if cycle else ", ".join(str(r) for r in show)
    kindof = "wait-for cycle" if cycle else "stall"
    return [ScheduleViolation(
        rule=RULE_DEADLOCK, subject=f"deadlock:{arrows}",
        message=f"schedules deadlock: {kindof} over ranks {arrows} — "
                f"each rank's next operation waits on a rank that is "
                f"itself blocked ({len(stuck)} rank(s) stuck, "
                f"{sum(len(schedules[r]) - pc[r] for r in stuck)} "
                f"op(s) unexecuted)",
        ranks=tuple(show), subtrace=sub)]


def _find_cycle(waits: Dict[int, List[int]]) -> List[int]:
    color: Dict[int, int] = {}
    stack: List[int] = []

    def dfs(u) -> Optional[List[int]]:
        color[u] = 1
        stack.append(u)
        for v in waits.get(u, ()):
            if color.get(v, 0) == 1:
                return stack[stack.index(v):]
            if color.get(v, 0) == 0:
                c = dfs(v)
                if c:
                    return c
        color[u] = 2
        stack.pop()
        return None

    for u in list(waits):
        if color.get(u, 0) == 0:
            c = dfs(u)
            if c:
                return c
    return []


def verify_schedules(schedules: Dict[int, List[CommOp]]
                     ) -> List[ScheduleViolation]:
    """Run all cross-rank checks.  Pairwise consistency first; the
    deadlock simulation only over schedules the pairwise checks pass
    (any divergence already implies a hang — see module docstring)."""
    if not schedules:
        return []
    v: List[ScheduleViolation] = []
    v += _verify_p2p(schedules, switch=False)
    v += _verify_p2p(schedules, switch=True)
    v += _verify_collectives(schedules)
    v = _suppress_cascades(v)
    if not v:
        v += _find_deadlock(schedules)
    return v


# ---------------------------------------------------------------------------
# context plumbing (analysis gate)
# ---------------------------------------------------------------------------


def context_schedules(ctx) -> Dict[int, List[CommOp]]:
    """Extract (and memoize on the context) the per-rank schedules for
    one analyzed executable; ``{}`` when the registration makes no
    multi-rank claim."""
    cached = getattr(ctx, "_rank_schedules", None)
    if cached is not None:
        return cached
    spec = spec_from_meta(ctx.meta, ctx.mesh_axes)
    sched = extract_schedules(spec) if spec is not None else {}
    try:
        ctx._rank_schedules = sched
    except Exception:
        pass
    return sched


def verify_context(ctx) -> List[ScheduleViolation]:
    """Verify the context's schedules ONCE (memoized — the six schedule
    rules share one replay, like the lifecycle rules share one)."""
    cached = getattr(ctx, "_schedule_violations", None)
    if cached is not None:
        return cached
    sched = context_schedules(ctx)
    violations = verify_schedules(sched) if sched else []
    try:
        ctx._schedule_violations = violations
    except Exception:
        pass
    return violations


def schedule_summary(ctx) -> Dict[str, Any]:
    """The per-executable ``schedule`` meta/baseline section: rank
    count, op inventory by kind, plane sizes, violation verdict, and
    the rule vocabulary available at freeze time (the gate fails when a
    pinned rule later vanishes from the registry)."""
    sched = context_schedules(ctx)
    violations = verify_context(ctx)
    kinds = Counter(o.kind for ops in sched.values() for o in ops)
    n_coll = sum(c for k, c in kinds.items() if k in COLLECTIVE_KINDS)
    n_p2p = sum(c for k, c in kinds.items() if k in P2P_KINDS)
    n_switch = sum(1 for ops in sched.values() for o in ops
                   if _is_switch(o))
    return {
        "ranks": len(sched),
        "ops": int(sum(kinds.values())),
        "kinds": {k: int(v) for k, v in sorted(kinds.items())},
        "collectives": int(n_coll),
        "p2p": int(n_p2p),
        "switch": int(n_switch),
        "violations": len(violations),
        "violation_rules": sorted({v.rule for v in violations}),
        "rules_available": sorted(SCHEDULE_RULES),
    }


# ---------------------------------------------------------------------------
# strategy grid + seeded-bug corpus (bench.py schedule_lint / tier-1)
# ---------------------------------------------------------------------------


def strategy_grid() -> Iterator[Tuple[str, ProgramSpec]]:
    """The clean sweep: dp x tp x pp x cp layouts x zero in {0, 2, 3}
    x {SPMD-1F1B, MPMD} pipeline modes x with/without a mid-run dp
    resize switch.  Every spec must verify with ZERO violations."""
    shapes = [(2, 1, 1, 1), (4, 2, 1, 1), (2, 2, 1, 2), (1, 2, 2, 2),
              (2, 1, 2, 1), (2, 2, 2, 1)]
    for dp, tp, pp, cp in shapes:
        for zero in (0, 2, 3):
            flat = zero >= 2
            modes = ["spmd", "mpmd"] if pp > 1 else ["none"]
            for mode in modes:
                for with_switch in (False, True):
                    if with_switch and dp <= 1:
                        continue      # a dp resize needs dp > 1
                    per_pipe = None
                    if mode == "mpmd" and dp > 1:
                        # Malleus apportionment: uneven per-pipe counts
                        per_pipe = tuple([3] + [1] * (dp - 1))
                    spec = ProgramSpec(
                        dp=dp, tp=tp, pp=pp, cp=cp, zero=zero, flat=flat,
                        transport="int8" if zero >= 2 else "fp32",
                        pipeline_mode=mode, per_pipe_micro=per_pipe,
                        switch=({"numel": 1 << 14, "itemsize": 4,
                                 "new_dp": max(1, dp // 2)}
                                if with_switch else None))
                    label = (f"dp{dp}_tp{tp}_pp{pp}_cp{cp}_z{zero}"
                             f"_{mode}{'_switch' if with_switch else ''}")
                    yield label, spec


def _reference_spec() -> ProgramSpec:
    """The corpus substrate: 8 ranks, pp2 x dp2 x tp2, ZeRO-3 flat,
    MPMD 1F1B with uneven per-pipe micro-batches and a mid-run dp
    resize — every op plane (front gathers, tp collectives, pipeline
    p2p, grad tail, switch repack) is populated so each rule has
    something to catch."""
    return ProgramSpec(dp=2, tp=2, pp=2, cp=1, zero=3, flat=True,
                       transport="fp32", pipeline_mode="mpmd",
                       per_pipe_micro=(3, 1),
                       switch={"numel": 1 << 14, "itemsize": 4,
                               "new_dp": 1})


def _clone(schedules: Dict[int, List[CommOp]]) -> Dict[int, List[CommOp]]:
    return {r: list(ops) for r, ops in schedules.items()}


def seeded_bug_corpus() -> List[Dict[str, Any]]:
    """>= 6 injected cross-rank divergences, one per rule.  Each entry's
    mutated schedules must be flagged by EXACTLY its rule (asserted by
    the vacuity meta-test and ``bench.py schedule_lint``)."""
    base = extract_schedules(_reference_spec())
    corpus: List[Dict[str, Any]] = []

    def _mut(name, rule, note, fn):
        sch = _clone(base)
        fn(sch)
        corpus.append({"name": name, "rule": rule, "note": note,
                       "schedules": sch})

    def order_swap(sch):
        # swap two adjacent same-group collectives of different kinds
        # on one rank: positional kind divergence for its group peers
        for r in sorted(sch):
            ops = sch[r]
            for i in range(len(ops) - 1):
                a, b = ops[i], ops[i + 1]
                if (a.kind in COLLECTIVE_KINDS and b.kind in
                        COLLECTIVE_KINDS and a.group == b.group
                        and len(a.group) > 1 and a.kind != b.kind):
                    ops[i], ops[i + 1] = b, a
                    return
        raise AssertionError("no adjacent swap site in reference spec")

    def group_skew(sch):
        # one rank re-routes a dp collective onto its tp group, same
        # tag: membership divergence (each side waits forever)
        for r in sorted(sch):
            groups = {o.group for o in sch[r]
                      if o.kind in COLLECTIVE_KINDS and len(o.group) > 1}
            for i, o in enumerate(sch[r]):
                if o.kind not in COLLECTIVE_KINDS or len(o.group) <= 1:
                    continue
                alt = next((g for g in groups
                            if g != o.group and r in g), None)
                if alt is not None:
                    sch[r][i] = dataclasses.replace(o, group=alt)
                    return
        raise AssertionError("no group-skew site in reference spec")

    def payload_skew(sch):
        # EQuARX-style divergence: one rank runs a quantized collective
        # its peers run in full precision — dtype disagreement
        for r in sorted(sch):
            for i, o in enumerate(sch[r]):
                if (o.kind in COLLECTIVE_KINDS and len(o.group) > 1
                        and o.dtype == "float32"):
                    sch[r][i] = dataclasses.replace(
                        o, dtype="bfloat16",
                        payload_bytes=o.payload_bytes // 2)
                    return
        raise AssertionError("no payload-skew site in reference spec")

    def missing_recv(sch):
        for r in sorted(sch):
            for i, o in enumerate(sch[r]):
                if o.kind == "recv" and not _is_switch(o):
                    del sch[r][i]
                    return
        raise AssertionError("no pipeline recv in reference spec")

    def recv_inversion(sch):
        # a stage-0 rank waits for its backward grad BEFORE sending its
        # first forward: recv/recv wait-for cycle across the stage pair
        for r in sorted(sch):
            ops = sch[r]
            si = next((i for i, o in enumerate(ops)
                       if o.kind == "send" and not _is_switch(o)), None)
            ri = next((i for i, o in enumerate(ops)
                       if o.kind == "recv" and not _is_switch(o)), None)
            if si is not None and ri is not None and si < ri:
                op = ops.pop(ri)
                ops.insert(si, op)
                return
        raise AssertionError("no recv-inversion site in reference spec")

    def repack_skew(sch):
        # the receiving side of one repack transfer expects a different
        # source rank than the plan's sender
        for r in sorted(sch):
            for i, o in enumerate(sch[r]):
                if o.kind == "recv" and _is_switch(o):
                    other = next(s for s in sorted(sch)
                                 if s not in (r, o.peer))
                    sch[r][i] = dataclasses.replace(
                        o, peer=other, group=(other, r))
                    return
        raise AssertionError("no switch recv in reference spec")

    _mut("order_swap", RULE_ORDER,
         "adjacent collective swap on one rank", order_swap)
    _mut("group_skew", RULE_GROUP,
         "dp collective re-routed onto the tp group", group_skew)
    _mut("payload_skew", RULE_PAYLOAD,
         "one rank quantizes a collective its peers run fp32",
         payload_skew)
    _mut("missing_recv", RULE_UNPAIRED,
         "a pipeline recv dropped from one stage", missing_recv)
    _mut("recv_inversion", RULE_DEADLOCK,
         "stage waits for backward grad before first forward send",
         recv_inversion)
    _mut("repack_skew", RULE_SWITCH,
         "repack recv expects the wrong source rank", repack_skew)
    return corpus
