"""Static per-executable step-time model: FLOP/HBM roofline + comm.

The third leg of the static-analysis tripod: PR 5 explains what a
program *communicates*, PR 8 what it *holds* — this module predicts how
long it *takes*, from the same registered facts and without running
anything:

* **FLOP inventory** (:func:`cost_walk`) — a recursive walk over the
  closed jaxpr prices every primitive: ``dot_general``/``conv`` by
  contraction-dimension math (``2·|out|·|contract|``), elementwise
  arithmetic at one FLOP per output element, reductions at one FLOP per
  *input* element, transcendentals (exp/tanh/erf/...) counted in a
  separate column exactly as XLA's ``HloCostAnalysis`` does, threefry
  RNG at a measured per-element constant.  ``scan`` bodies multiply by
  the trip count, ``shard_map`` regions already carry per-device block
  shapes (scale resets to 1), and everything outside a manual region is
  divided by the mesh size — a GSPMD-partitioned program computes
  ``1/prod(mesh)`` of the global math per device.
* **HBM-traffic inventory** — the operand + result bytes of
  *materializing* primitives (contractions, data movement, collectives,
  RNG — the ops XLA cannot fuse away; slices at 2× their output,
  gather/scatter at a calibrated utilization of their big operand),
  plus a fusion model for everything else: fusible elementwise runs are
  grouped into connected components (XLA's loop fusions) that pay one
  read per unique external operand and one write per escaping output,
  with multi-consumer fusible producers duplicated into each consumer
  fusion (:data:`FUSION_DUP_CAP`) exactly as XLA's fusion pass does.
* **roofline** — compute time = FLOPs / (peak·MXU-efficiency), IO time
  = HBM bytes / bandwidth, against a :class:`~hetu_tpu.planner
  .cost_model.ChipSpec` (datasheet or measured via
  ``profile_hardware``); the executable is compute- or HBM-bound by
  whichever dominates.
* **comm time** — the per-edge collective set ``predict_edges`` already
  derives is priced through the planner's alpha-beta formulas
  (:func:`~hetu_tpu.planner.cost_model.collective_time` — ONE
  implementation for the linter and the DP solver, so they can never
  disagree).  Edge payloads are wire bytes, so EQuARX-style int8/bf16
  transports are priced at their real wire cost.  The overlap model:
  when the plan's grad-comm config is overlap-schedulable
  (``meta["comm_overlap"]``, written at registration for the explicit
  coalesced sync), grad-comm/param-comm edges hide under compute
  (``max``), everything else is exposed (added).

**XLA cross-check** (:func:`xla_cost_stats` + ``CostReport.xla``): the
compiled executable's own ``cost_analysis()`` reports flops / bytes
accessed / transcendentals for the post-optimization module.  The
comparable numbers differ from the native prediction in documented
ways (DESIGN.md §16): XLA counts a ``while``/``scan`` **body once**
(not × trips), so ``cmp_flops``/``cmp_bytes`` are computed with trip
multiplication off (conditionals need no split convention — both the
execution truth and, verified empirically, XLA's accounting charge the
per-property **max** branch); the CPU backend upcasts bf16/f16 and
brackets every narrow-float boundary with converts (comparable FLOPs
add the convert storm, comparable bytes price narrow floats at the
store-width + compute-width round trip); and the partitioner's
collective lowering materializes ring intermediates the jaxpr cannot
see (:func:`collective_traffic_adjustment`).  The native numbers —
trips multiplied, one branch, native widths, no partitioner terms —
are what the planner and the baseline use.  The gate bounds
|cmp − XLA| at ±10% per gate family (absolute floors for toy-sized
programs where constant-factor ops dominate).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..planner.cost_model import (ChipSpec, ClusterSpec, collective_time)

#: elementwise arithmetic: 1 FLOP per output element (XLA counts int
#: ops too, and select/compare chains count per op)
ELEMENTWISE_FLOP_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs",
    "sign", "floor", "ceil", "round", "clamp", "select_n", "and", "or",
    "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "eq", "ne", "lt", "le", "gt", "ge",
    "add_any", "convert_element_type", "is_finite", "nextafter",
    "integer_pow", "population_count", "clz", "exp2",
})

#: priced in XLA's separate ``transcendentals`` column, NOT flops
TRANSCENDENTAL_PRIMS = frozenset({
    "exp", "log", "log1p", "expm1", "sin", "cos", "tan", "asin", "acos",
    "atan", "atan2", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "erf", "erfc", "erf_inv", "logistic", "sqrt", "rsqrt", "cbrt",
    "pow", "digamma", "lgamma",
})

#: reductions: 1 FLOP per INPUT element (n-1 combines + epilogue)
REDUCE_FLOP_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin", "cumsum", "cummax",
    "cummin", "cumprod", "reduce_window", "select_and_scatter_add",
    "cumlogsumexp",
})

#: measured on the CPU backend (jax.random.uniform ≈ 25.5 flops/elem,
#: of which ~2 are the convert/scale epilogue the walk prices itself)
THREEFRY_FLOPS_PER_ELEM = 24.0

#: CPU-comparable only: how many convert instances the CPU backend ends
#: up executing per narrow-float operand/output element (fusion
#: duplication re-converts a value inside every consuming fusion) —
#: calibrated once against the frozen bf16 gate families, same stance
#: as memory.RESIDUAL_POOL_CAP
CPU_CONVERT_DUP = 2.0

#: XLA's instruction fusion DUPLICATES a cheap fusible producer into
#: each consumer fusion instead of materializing it; a multi-consumer
#: elementwise op therefore executes (and is counted by cost_analysis)
#: once per consumer.  Capped: duplication stops paying off for wide
#: fan-outs and XLA materializes instead.
FUSION_DUP_CAP = 4

#: shape-only ops XLA lowers to bitcasts / layout changes: free, and
#: transparent to the fusion grouping (output aliases the input)
TRANSPARENT_PRIMS = frozenset({
    "reshape", "squeeze", "expand_dims", "bitcast_convert_type",
    "stop_gradient", "copy", "real", "imag", "broadcast",
    # layout changes the consumer absorbs (dots take transposed
    # operands natively; loop fusions index through the permutation)
    "transpose",
    # shard_map replication-rewrite markers: no data moves
    "pbroadcast", "pvary",
})

#: primitives whose outputs always materialize as real HBM buffers —
#: same classification the peak-HBM pass uses (memory.MATERIALIZE_PRIMS)
#: minus the containers (recursed here, never priced as one op)
MATERIALIZE_COST_PRIMS = frozenset({
    "dot_general", "conv_general_dilated", "scatter", "scatter-add",
    "scatter_add", "gather", "concatenate", "sort", "top_k", "cumsum",
    "psum", "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "ppermute", "pmax", "pmin", "rng_bit_generator", "threefry2x32",
    "dynamic_update_slice", "dynamic_slice", "slice",
    "argmax", "argmin", "select_and_scatter_add", "reduce_window",
})
# NB: pad/rev/reduce_* are FUSIBLE — XLA's loop fusion absorbs them in
# real programs (a standalone toy pad materializes, but that regime is
# covered by the absolute cross-check floor); their FLOPs still count
# via REDUCE_FLOP_PRIMS / elementwise pricing.

#: containers: recurse into sub-jaxprs, never price the eqn itself
CONTAINER_PRIMS = frozenset({
    "scan", "while", "cond", "pjit", "remat", "remat2", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "shard_map", "closed_call", "core_call", "named_call", "custom_root",
    "custom_linear_solve",
})

#: CPU cross-check only: the CPU backend upcasts narrow floats to f32,
#: and the convert round-trip at every boundary touches the value both
#: at its 2-byte stored width and its 4-byte compute width — effective
#: ~6 bytes/element of counted traffic per boundary crossing
CMP_NARROW_WIDTH = {"bfloat16": 6, "float16": 6}

#: absolute cross-check floors: below these, CPU fusion-duplication
#: noise and constant-factor scalar ops (loop counters, rng keys,
#: layout fix-ups) dominate toy programs.  Honesty note: at CI scale
#: the FLOPS floor means the flops leg of the ±10% gate binds only for
#: families whose totals are well above 2 MFLOP (train/tp at ~30 MFLOP
#: bind for real; the 1-2 MFLOP moe/mpmd toys ride the floor) — the
#: BYTES leg binds for every family, and real-model-scale programs
#: clear the floor by orders of magnitude.
XLA_FLOPS_ABS_TOL = 2_000_000.0
XLA_BYTES_ABS_TOL = float(1 << 18)


# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CostEntry:
    """One attributed compute/traffic contributor (top-k table row)."""
    prim: str
    flops: float = 0.0
    transcendentals: float = 0.0
    bytes: float = 0.0            # HBM traffic (per device)
    count: int = 1                # enclosing trip multiplier
    source: str = ""              # file:line provenance
    detail: str = ""              # shape slug

    def time_s(self, chip: ChipSpec) -> float:
        """Roofline contribution: max of this entry's MXU and HBM time
        (transcendentals priced as flops on the vector unit)."""
        fl = (self.flops + self.transcendentals) * self.count
        by = self.bytes * self.count
        return max(fl / (chip.peak_flops * chip.mxu_efficiency),
                   by / chip.hbm_bw)

    def to_dict(self) -> dict:
        return {"prim": self.prim, "flops": float(self.flops),
                "bytes": float(self.bytes), "count": int(self.count),
                "source": self.source, "detail": self.detail}


@dataclasses.dataclass
class CommCost:
    """One predicted collective edge, priced."""
    kind: str
    payload_bytes: int = 0
    count: int = 1
    group: int = 1                # chips in the collective group
    time_s: float = 0.0           # per execution
    overlapped: bool = False      # hides under compute in the overlap model
    origin: str = ""
    tensor: str = ""

    @property
    def total_s(self) -> float:
        return self.time_s * max(self.count, 1)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CostReport:
    """Static step-time prediction for one executable (per device)."""
    name: str = ""
    # native inventory: trips multiplied, one cond branch, native widths
    flops: float = 0.0
    transcendentals: float = 0.0
    hbm_bytes: float = 0.0
    # XLA-comparable inventory: body-once, branches summed, CPU upcast
    cmp_flops: float = 0.0
    cmp_bytes: float = 0.0
    cmp_transcendentals: float = 0.0
    # roofline + comm decomposition
    compute_time_s: float = 0.0
    io_time_s: float = 0.0
    comm_time_s: float = 0.0           # total collective time
    overlapped_comm_s: float = 0.0     # hides under compute (max)
    exposed_comm_s: float = 0.0        # serial with compute (added)
    step_time_s: float = 0.0
    bound: str = "compute"             # compute|hbm|comm
    overlap: bool = False              # plan declares overlap scheduling
    chip: str = ""
    entries: List[CostEntry] = dataclasses.field(default_factory=list)
    comm: List[CommCost] = dataclasses.field(default_factory=list)
    # flops/bytes accessed/transcendentals from compiled.cost_analysis()
    xla: Optional[Dict[str, float]] = None

    def top(self, k: int = 10, chip: Optional[ChipSpec] = None
            ) -> List[CostEntry]:
        chip = chip or ChipSpec()
        return sorted(self.entries, key=lambda e: -e.time_s(chip))[:k]

    def by_prim(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self.entries:
            d = out.setdefault(e.prim, {"flops": 0.0, "bytes": 0.0})
            d["flops"] += (e.flops + e.transcendentals) * e.count
            d["bytes"] += e.bytes * e.count
        return out

    # -- XLA cross-check ---------------------------------------------------

    def xla_flops_delta(self) -> Optional[float]:
        if self.xla is None:
            return None
        want = float(self.xla.get("flops", 0.0)) \
            + float(self.xla.get("transcendentals", 0.0))
        got = self.cmp_flops + self.cmp_transcendentals
        if want <= 0:
            return None
        return (got - want) / want

    def xla_bytes_delta(self) -> Optional[float]:
        if self.xla is None:
            return None
        want = float(self.xla.get("bytes_accessed", 0.0))
        if want <= 0:
            return None
        return (self.cmp_bytes - want) / want

    def xla_within(self, rel: float = 0.1,
                   flops_floor: float = XLA_FLOPS_ABS_TOL,
                   bytes_floor: float = XLA_BYTES_ABS_TOL
                   ) -> Optional[bool]:
        """Both totals inside ±rel of XLA's (None: not compiled)."""
        if self.xla is None:
            return None
        want_f = float(self.xla.get("flops", 0.0)) \
            + float(self.xla.get("transcendentals", 0.0))
        got_f = self.cmp_flops + self.cmp_transcendentals
        ok_f = abs(got_f - want_f) <= max(rel * want_f, flops_floor)
        want_b = float(self.xla.get("bytes_accessed", 0.0))
        ok_b = abs(self.cmp_bytes - want_b) \
            <= max(rel * want_b, bytes_floor)
        return bool(ok_f and ok_b)

    def to_dict(self, entries: bool = False) -> dict:
        d: Dict[str, Any] = {
            "flops": int(self.flops),
            "transcendentals": int(self.transcendentals),
            "hbm_bytes": int(self.hbm_bytes),
            "compute_time_us": round(self.compute_time_s * 1e6, 3),
            "io_time_us": round(self.io_time_s * 1e6, 3),
            "comm_time_us": round(self.comm_time_s * 1e6, 3),
            "step_time_us": round(self.step_time_s * 1e6, 3),
            "bound": self.bound,
            "overlap": bool(self.overlap),
            "chip": self.chip,
        }
        if self.xla is not None:
            fd, bd = self.xla_flops_delta(), self.xla_bytes_delta()
            d["xla_flops"] = int(self.xla.get("flops", 0)
                                 + self.xla.get("transcendentals", 0))
            d["xla_bytes_accessed"] = int(self.xla.get(
                "bytes_accessed", 0))
            d["xla_flops_delta_pct"] = round(100.0 * fd, 1) \
                if fd is not None else None
            d["xla_bytes_delta_pct"] = round(100.0 * bd, 1) \
                if bd is not None else None
        if entries:
            d["top_entries"] = [e.to_dict() for e in self.top(10)]
            d["comm"] = [c.to_dict() for c in self.comm]
        return d

    def summary(self) -> str:
        s = (f"{_fmt_si(self.flops)}FLOP "
             f"{_fmt_si(self.hbm_bytes)}B -> "
             f"{self.step_time_s * 1e6:.1f}us "
             f"({self.bound}-bound: compute "
             f"{self.compute_time_s * 1e6:.1f}us, hbm "
             f"{self.io_time_s * 1e6:.1f}us, comm "
             f"{self.comm_time_s * 1e6:.1f}us"
             + (" overlapped" if self.overlap and self.comm_time_s
                else "") + ")")
        fd = self.xla_flops_delta()
        bd = self.xla_bytes_delta()
        if fd is not None or bd is not None:
            s += (f" (xla flops {fd:+.1%}, bytes {bd:+.1%})"
                  if fd is not None and bd is not None else " (xla n/a)")
        return s


def _fmt_si(n: float) -> str:
    n = float(n)
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000.0 or unit == "T":
            return f"{n:.1f}{unit}" if unit else f"{n:.0f}"
        n /= 1000.0
    return f"{n:.1f}T"


# ---------------------------------------------------------------------------
# the jaxpr FLOP/HBM walk
# ---------------------------------------------------------------------------


def _as_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr


def _elems(aval) -> float:
    try:
        return float(np.prod(aval.shape, dtype=np.int64))
    except Exception:
        return 0.0


def _aval_bytes(aval, upcast: bool) -> float:
    try:
        dt = np.dtype(aval.dtype)
        item = CMP_NARROW_WIDTH.get(dt.name, dt.itemsize) if upcast \
            else dt.itemsize
        return _elems(aval) * item
    except Exception:
        return 0.0


def _is_narrow_float(aval) -> bool:
    try:
        return np.dtype(aval.dtype).name in CMP_NARROW_WIDTH
    except Exception:
        return False


def _source_of(eqn) -> str:
    si = getattr(eqn, "source_info", None)
    if si is None:
        return ""
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(si)
        if fr is not None:
            import os
            return f"{os.path.basename(fr.file_name)}:{fr.start_line}"
    except Exception:
        pass
    return ""


def dot_general_flops(eqn) -> float:
    """``2 · |out| · |contracting dims|`` from the dimension numbers —
    the exact count XLA's cost analysis reports for a dot."""
    try:
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        out = eqn.outvars[0].aval
        contract = 1.0
        for d in lhs_c:
            contract *= float(lhs.shape[d])
        return 2.0 * _elems(out) * contract
    except Exception:
        return 0.0


def conv_flops(eqn) -> float:
    """``2 · |out| · kernel_spatial · in_channels / groups``."""
    try:
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        dn = eqn.params["dimension_numbers"]
        groups = float(eqn.params.get("feature_group_count", 1) or 1)
        k_spatial = 1.0
        for d in dn.rhs_spec[2:]:
            k_spatial *= float(rhs.shape[d])
        in_ch = float(rhs.shape[dn.rhs_spec[1]])
        return 2.0 * _elems(out) * k_spatial * in_ch / max(groups, 1.0)
    except Exception:
        return 0.0


def _prim_flops(eqn) -> Tuple[float, float]:
    """(flops, transcendentals) of one non-container eqn."""
    name = eqn.primitive.name
    if name == "dot_general":
        return dot_general_flops(eqn), 0.0
    if name == "conv_general_dilated":
        return conv_flops(eqn), 0.0
    out_elems = sum(_elems(ov.aval) for ov in eqn.outvars
                    if hasattr(ov, "aval"))
    if name in TRANSCENDENTAL_PRIMS:
        return 0.0, out_elems
    if name in ELEMENTWISE_FLOP_PRIMS:
        return out_elems, 0.0
    if name in REDUCE_FLOP_PRIMS:
        in_elems = sum(_elems(iv.aval) for iv in eqn.invars
                       if hasattr(iv, "aval"))
        return in_elems, 0.0
    if name in ("threefry2x32", "rng_bit_generator"):
        return THREEFRY_FLOPS_PER_ELEM * out_elems, 0.0
    if name in ("psum", "pmax", "pmin", "psum_scatter",
                "reduce_scatter"):
        return out_elems, 0.0
    return 0.0, 0.0


@dataclasses.dataclass
class _WalkTotals:
    flops: float = 0.0
    transcendentals: float = 0.0
    bytes: float = 0.0
    entries: List[CostEntry] = dataclasses.field(default_factory=list)

    def add(self, other: "_WalkTotals", mult: float = 1.0) -> None:
        self.flops += other.flops * mult
        self.transcendentals += other.transcendentals * mult
        self.bytes += other.bytes * mult
        for e in other.entries:
            self.entries.append(dataclasses.replace(
                e, count=int(max(1, round(e.count * mult)))))


def cost_walk(jaxpr, scale: float = 1.0, upcast: bool = False,
              multiply_trips: bool = True) -> _WalkTotals:
    """FLOP + HBM-traffic inventory of one (sub-)jaxpr.

    ``scale`` divides global aval costs down to per-device (GSPMD
    partitioning over the whole mesh); inside ``shard_map`` regions the
    avals are already per-device block shapes, so the scale resets to 1.
    ``multiply_trips`` toggles the native (× scan length) vs
    XLA-comparable (body once) convention.  ``cond`` charges the most
    expensive branch — both the execution truth (one branch runs) and
    XLA's convention (cost_analysis takes the per-property max over
    branch computations, verified empirically).

    The traffic model groups *fusible* eqns into connected components
    (a var produced by a fusible eqn and consumed by another fuses
    them) and prices each component once: unique external reads +
    escaping writes — the post-fusion ``bytes accessed`` convention.
    Materializing prims pay their full operand + result bytes
    (gather/scatter read the WHOLE operand, XLA's convention).
    """
    j = _as_jaxpr(jaxpr)
    out = _WalkTotals()

    # fusion components: var id -> component id for fusible-produced vars
    comp_of_var: Dict[int, int] = {}
    comp_reads: Dict[int, Dict[int, float]] = {}   # comp -> var id -> bytes
    comp_writes: Dict[int, float] = {}
    comp_src: Dict[int, str] = {}
    parent: Dict[int, int] = {}

    def find(c: int) -> int:
        while parent.get(c, c) != c:
            parent[c] = parent.get(parent[c], parent[c])
            c = parent[c]
        return c

    def union(a: int, b: int) -> int:
        ra, rb = find(a), find(b)
        if ra == rb:
            return ra
        parent[rb] = ra
        comp_reads.setdefault(ra, {}).update(comp_reads.pop(rb, {}))
        comp_writes[ra] = comp_writes.get(ra, 0.0) \
            + comp_writes.pop(rb, 0.0)
        return ra

    next_comp = [0]

    # transparent aliasing: reshape-like output vars point back at the
    # var they are a view of, so fusion grouping sees through them.
    # Built in a pre-pass so the consumer map below can attribute a
    # use THROUGH a reshape to the underlying var.
    alias: Dict[int, int] = {}
    for eqn in j.eqns:
        if _classify(eqn) == "transparent" and eqn.invars \
                and eqn.outvars and hasattr(eqn.invars[0], "count"):
            for ov in eqn.outvars:
                alias[id(ov)] = id(eqn.invars[0])

    def resolve(v) -> int:
        i = id(v)
        while i in alias:
            i = alias[i]
        return i

    # jaxpr outputs, seen through trailing reshapes/transposes: a value
    # that escapes via a transparent view still pays its fusion write
    outvar_ids = {resolve(v) for v in j.outvars if hasattr(v, "count")}

    # a fusible var consumed by a materializing/container eqn (or
    # escaping the jaxpr) forces its component to write it out.  Keyed
    # on RESOLVED ids (a use through a reshape is a use of the source)
    # and deduped per consuming eqn (x*x is ONE consumer, not two).
    consumers: Dict[int, List[str]] = {}
    for eqn in j.eqns:
        cls = _classify(eqn)
        if cls == "transparent":
            continue        # forwards its uses; not a consumer itself
        for ri in {resolve(iv) for iv in eqn.invars
                   if hasattr(iv, "count")}:
            consumers.setdefault(ri, []).append(cls)

    # repeated 1-D narrow-float unpack slices (the flat param-gather's
    # per-param dynamic_slice fan-out under ZeRO-3): when the sliced
    # values are consumed IN-program, XLA's fusion pass hoists the
    # operand's bf16<->f32 convert above the slices and duplicates the
    # FULL-buffer convert into every consuming fusion — visible in the
    # optimized HLO as one buffer-wide convert pair per unpacked param.
    # Charged per slice beyond the first on the same operand (ZeRO-2's
    # unpack escapes as plan outputs — zero consumers, zero charge).
    unpack_seen: Dict[int, int] = {}

    for eqn in j.eqns:
        name = eqn.primitive.name
        cls = _classify(eqn)
        src = None

        if cls == "transparent":
            continue        # aliased in the pre-pass: free, see-through

        if cls == "container":
            mult = 1.0
            if name == "scan" and multiply_trips:
                mult = float(eqn.params.get("length", 1) or 1)
            sub_scale = 1.0 if name == "shard_map" else scale
            subs = [cost_walk(s, sub_scale, upcast, multiply_trips)
                    for s in _sub_jaxprs(eqn)]
            if not subs:
                continue
            if name == "cond":
                # one branch executes — charge the costliest (matches
                # XLA's max-over-branches conditional accounting)
                best = max(subs, key=lambda t: (t.flops
                                                + t.transcendentals,
                                                t.bytes))
                out.add(best, mult)
            else:
                for t in subs:
                    out.add(t, mult)
            continue

        flops, trans = _prim_flops(eqn)
        flops *= scale
        trans *= scale
        if upcast:
            # CPU-comparable only: the CPU backend has no native
            # bf16/f16 and brackets every narrow-float operand read and
            # output write with a convert (~1 FLOP per element, times a
            # fusion-duplication factor — XLA's instruction fusion
            # re-converts a value inside every fusion that consumes it;
            # the convert-instruction storm visible in any bf16
            # module's optimized HLO, counted in XLA's `flops`)
            conv_elems = (
                sum(_elems(iv.aval) for iv in eqn.invars
                    if _is_narrow_float(getattr(iv, "aval", None)))
                + sum(_elems(ov.aval) for ov in eqn.outvars
                      if _is_narrow_float(getattr(ov, "aval", None))))
            flops += CPU_CONVERT_DUP * conv_elems * scale
        if cls == "materialize":
            if name in ("dynamic_slice", "slice"):
                # XLA prices slices at output read+write, NOT the full
                # operand (unlike gather, which walks the whole thing)
                nb = 2.0 * sum(_aval_bytes(ov.aval, upcast)
                               for ov in eqn.outvars
                               if hasattr(ov, "aval")) * scale
                big_av = getattr(eqn.invars[0], "aval", None) \
                    if eqn.invars and hasattr(eqn.invars[0], "count") \
                    else None
                if (upcast and big_av is not None
                        and _is_narrow_float(big_av)
                        and len(getattr(big_av, "shape", ())) == 1
                        and any(consumers.get(resolve(ov))
                                for ov in eqn.outvars
                                if hasattr(ov, "count"))):
                    key = resolve(eqn.invars[0])
                    if key in unpack_seen:
                        # both widths of the hoisted buffer convert,
                        # duplicated into this consumer's fusion
                        dup = 2.0 * CPU_CONVERT_DUP * _elems(big_av) \
                            * scale
                        flops += dup
                        nb += dup
                    unpack_seen[key] = unpack_seen.get(key, 0) + 1
            elif name in ("gather", "scatter", "scatter-add",
                          "scatter_add") and eqn.invars:
                # big operand at the calibrated fusion utilization;
                # indices/updates/outputs at full width
                big = _aval_bytes(eqn.invars[0].aval, upcast) \
                    if hasattr(eqn.invars[0], "aval") else 0.0
                rest = sum(_aval_bytes(iv.aval, upcast)
                           for iv in eqn.invars[1:]
                           if hasattr(iv, "aval"))
                outs = sum(_aval_bytes(ov.aval, upcast)
                           for ov in eqn.outvars
                           if hasattr(ov, "aval"))
                if name == "gather":
                    # XLA: operand read (utilization-weighted when
                    # fused) + indices + output written once.  A gather
                    # whose consumers all fuse is absorbed INTO the
                    # consumer loop fusion — its output never
                    # materializes (the consuming component's external
                    # read below stands in for the single pass).
                    absorbed = all(
                        c == "fusible"
                        for ov in eqn.outvars if hasattr(ov, "count")
                        for c in consumers.get(resolve(ov), ())) and any(
                        consumers.get(resolve(ov))
                        for ov in eqn.outvars if hasattr(ov, "count"))
                    nb = (SCATTER_GATHER_UTIL * big + rest
                          + (0.0 if absorbed else outs)) * scale
                else:
                    # scatter reads AND rewrites through the big
                    # operand in place (the output aliases it)
                    nb = (SCATTER_GATHER_UTIL * 2.0 * big + rest) \
                        * scale
            else:
                nb = (sum(_aval_bytes(iv.aval, upcast)
                          for iv in eqn.invars if hasattr(iv, "aval"))
                      + sum(_aval_bytes(ov.aval, upcast)
                            for ov in eqn.outvars
                            if hasattr(ov, "aval"))) * scale
            src = _source_of(eqn)
            out.flops += flops
            out.transcendentals += trans
            out.bytes += nb
            if flops or trans or nb:
                shape = ""
                if eqn.outvars and hasattr(eqn.outvars[0], "aval"):
                    shape = str(getattr(eqn.outvars[0].aval, "shape", ""))
                out.entries.append(CostEntry(
                    prim=name, flops=flops, transcendentals=trans,
                    bytes=nb, source=src, detail=shape))
            continue

        # fusible: flops count, traffic via the fusion component model.
        # Multi-consumer outputs are DUPLICATED by XLA's fusion pass
        # (recomputed inside each consumer fusion), so the op executes
        # — and cost_analysis counts it — once per consumer.
        n_cons = max((len(consumers.get(resolve(ov), ()))
                      for ov in eqn.outvars if hasattr(ov, "count")),
                     default=1)
        dup = min(FUSION_DUP_CAP, max(1, n_cons))
        flops *= dup
        trans *= dup
        out.flops += flops
        out.transcendentals += trans
        comp = next_comp[0]
        next_comp[0] += 1
        joined = comp
        for iv in eqn.invars:
            if not hasattr(iv, "count"):
                continue
            ri = resolve(iv)
            # fuse with the producer only when we are its SOLE
            # consumer — a multi-consumer fusible var is either
            # duplicated (flops above) or materialized (its producer
            # component writes it; we read it externally below)
            if ri in comp_of_var and len(consumers.get(ri, ())) <= 1:
                joined = union(joined, comp_of_var[ri])
        joined = find(joined)
        if flops or trans:
            comp_src.setdefault(joined, _source_of(eqn))
        for iv in eqn.invars:
            if not hasattr(iv, "count"):
                continue
            ri = resolve(iv)
            # external operand: a fusion read — either a var no fusible
            # eqn produced, or one produced in a DIFFERENT component
            # (the multi-consumer case above, where union was refused
            # and the producer writes it out)
            if ri not in comp_of_var or find(comp_of_var[ri]) != joined:
                comp_reads.setdefault(joined, {})[ri] = \
                    _aval_bytes(iv.aval, upcast) * scale
        for ov in eqn.outvars:
            if not hasattr(ov, "count"):
                continue
            comp_of_var[id(ov)] = joined
            ov_id = id(ov)
            esc = ov_id in outvar_ids or any(
                c != "fusible" for c in consumers.get(ov_id, ())) \
                or len(consumers.get(ov_id, ())) > 1
            if esc:                       # escaping output: fusion write
                comp_writes[joined] = comp_writes.get(joined, 0.0) \
                    + _aval_bytes(ov.aval, upcast) * scale
        if flops or trans:
            out.entries.append(CostEntry(
                prim=name, flops=flops, transcendentals=trans,
                bytes=0.0, source=comp_src.get(joined, "")))

    # settle the fusion components: one read per unique external var,
    # one write per escaping output
    roots = {find(c) for c in
             set(comp_reads) | set(comp_writes) | set(
                 comp_of_var.values())}
    fusion_bytes = 0.0
    for r in roots:
        reads = comp_reads.get(r, {})
        nb = sum(reads.values()) + comp_writes.get(r, 0.0)
        fusion_bytes += nb
        if nb:
            out.entries.append(CostEntry(
                prim="fusion", bytes=nb, source=comp_src.get(r, "")))
    out.bytes += fusion_bytes
    return out


def _classify(eqn) -> str:
    name = eqn.primitive.name
    if name in CONTAINER_PRIMS:
        return "container"
    if name in TRANSPARENT_PRIMS:
        return "transparent"
    if name in MATERIALIZE_COST_PRIMS:
        return "materialize"
    return "fusible"


# ---------------------------------------------------------------------------
# comm pricing over the predicted edge set
# ---------------------------------------------------------------------------


def price_edges(edges, mesh_axes: Dict[str, int],
                cluster: ClusterSpec,
                overlap_origins: frozenset = frozenset()
                ) -> List[CommCost]:
    """Alpha-beta time of every predicted comm edge, through the SAME
    :func:`~hetu_tpu.planner.cost_model.collective_time` formulas the
    planner's DP solver prices plans with.  Edge payloads are wire
    bytes (transport dtype already applied), so quantized transports
    cost their real narrow width."""
    out: List[CommCost] = []
    for e in edges or ():
        if e.kind in ("identity", "scatter"):
            continue
        n = 1
        for a in e.axes:
            n *= int(mesh_axes.get(str(a), 1))
        if n <= 1 and not e.axes:
            # axis-less declared edge: assume the whole mesh
            for s in mesh_axes.values():
                n *= int(s)
        t = collective_time(e.kind, float(e.payload_bytes), n, cluster)
        out.append(CommCost(
            kind=e.kind, payload_bytes=int(e.payload_bytes),
            count=int(max(e.count, 1)), group=n, time_s=float(t),
            overlapped=e.origin in overlap_origins,
            origin=e.origin, tensor=e.tensor))
    return out


#: edge origins the overlap model may hide under compute when the plan
#: declares overlap scheduling: the coalesced grad sync and its
#: sidecars/param regather are bucketed exactly so the latency-hiding
#: scheduler can run them behind the backward/update math; the ZeRO-3
#: just-in-time weight gather (param_gather) is per-bucket for the same
#: reason — bucket b+1's gather overlaps bucket b's forward compute
OVERLAPPABLE_ORIGINS = frozenset({"grad_comm", "param_comm",
                                  "param_gather"})


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


#: HLO dtype slug -> byte width (collective-traffic parsing)
_HLO_WIDTH = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
              "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
              "s8": 1, "u8": 1, "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1}

_COLLECTIVE_PRIM_NAMES = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "ppermute",
})

_HLO_COLLECTIVE_RE = None


#: how many extra buffer passes the ring lowering of one collective
#: materializes per ring step beyond the plain read+write: XLA
#: decomposes big all-gathers/all-reduces into (group−1) permute +
#: concat/accumulate rounds whose growing intermediates all count in
#: ``bytes accessed``.  Calibrated once against the frozen gate
#: families (same stance as memory.RESIDUAL_POOL_CAP); GSPMD-inserted
#: collectives decompose harder than explicit shard_map ones (the
#: partitioner adds halo/copy fix-ups around its own inserts).
RING_OVERHEAD_EXPLICIT = 1.0
RING_OVERHEAD_GSPMD = 2.0

#: fraction of a gather/scatter's LARGE operand XLA's fusion pricing
#: charges: a standalone gather reads its whole operand (toy-verified),
#: but real programs fuse the gather and HloCostAnalysis weights the
#: operand by utilization (≈ the gathered window).  One calibrated
#: blend for both regimes; indices/updates/outputs always price full.
SCATTER_GATHER_UTIL = 0.25


_HLO_KIND = {"all-reduce": "all_reduce", "all-gather": "all_gather",
             "all-to-all": "all_to_all",
             "reduce-scatter": "reduce_scatter",
             "collective-permute": "ppermute"}

_PRIM_KIND = {"psum": "all_reduce", "pmax": "all_reduce",
              "pmin": "all_reduce", "all_gather": "all_gather",
              "all_to_all": "all_to_all",
              "reduce_scatter": "reduce_scatter",
              "psum_scatter": "reduce_scatter", "ppermute": "ppermute"}


def collective_traffic_adjustment(hlo_text: str, walk_entries) -> float:
    """Extra comparable ``bytes accessed`` from the compiled module's
    collective lowering, beyond what the jaxpr walk already priced.

    Per collective kind: GSPMD-*inserted* instructions (those beyond
    the walk's explicit count) pay their read+write (the walk never saw
    them), and EVERY instruction pays the ring-lowering overhead —
    ``(group − 1)`` extra buffer passes for the permute/concat rounds
    of the decomposition, at :data:`RING_OVERHEAD_EXPLICIT` /
    :data:`RING_OVERHEAD_GSPMD`.

    Used ONLY for the XLA-*comparable* byte total: GSPMD-inserted
    collectives (implicit resharding on tp/sp meshes) materialize
    buffers the pre-partitioning jaxpr cannot see, exactly as the CPU
    bf16 upcast inserts converts the program never wrote.  Their
    *counts* are already pinned by the baseline and explained by the
    edge pass, so sizing them from the module under comparison adds no
    un-gated freedom — the walk's own (static) traffic remains the
    number the planner and the native report use.
    """
    import re
    from collections import defaultdict
    instrs = defaultdict(list)
    pat = re.compile(
        r"= *(\w+)\[([\d,]*)\][^ ]* (all-reduce|all-gather|"
        r"all-to-all|reduce-scatter|collective-permute)"
        r"(?:-start)?\(([^\n]*)")
    for m in pat.finditer(hlo_text):
        dt, sh, op, rest = m.groups()
        nb = 1
        for x in sh.split(","):
            if x:
                nb *= int(x)
        nb *= _HLO_WIDTH.get(dt, 4)
        if op == "collective-permute":
            group = 2
        else:
            group = 1
            g = re.search(r"replica_groups=\{\{([\d,]+)\}", rest)
            if g:
                group = g.group(1).count(",") + 1
            else:
                g = re.search(r"replica_groups=\[(\d+),(\d+)\]", rest)
                if g:
                    group = int(g.group(2))
        instrs[_HLO_KIND[op]].append((float(nb), group))
    explicit = defaultdict(int)
    for e in walk_entries:
        k = _PRIM_KIND.get(e.prim)
        if k:
            explicit[k] += e.count
    total = 0.0
    for k, lst in instrs.items():
        n_k = len(lst)
        fe = min(explicit.get(k, 0), n_k) / n_k if n_k else 0.0
        base2 = sum(2.0 * nb for nb, _g in lst)
        ring = sum(nb * max(0, g - 1) for nb, g in lst)
        total += (1.0 - fe) * base2 \
            + fe * RING_OVERHEAD_EXPLICIT * ring \
            + (1.0 - fe) * RING_OVERHEAD_GSPMD * ring
    return total


def xla_cost_stats(handle) -> Optional[Dict[str, float]]:
    """flops / bytes accessed / transcendentals from the compiled
    executable's own ``cost_analysis()`` (None when unavailable)."""
    try:
        ca = handle.compile().cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if ca is None:
        return None
    try:
        return {
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
            "transcendentals": float(ca.get("transcendentals", 0.0)
                                     or 0.0),
        }
    except Exception:
        return None


def predict_cost(handle, cluster: Optional[ClusterSpec] = None,
                 xla: bool = False) -> CostReport:
    """The static step-time model for one registered executable.

    ``step = max(compute_roofline, hbm_roofline, overlapped_comm)
           + exposed_comm``

    where the rooflines come from the jaxpr FLOP/HBM walk over
    ``cluster.chip`` (datasheet v5p by default; pass a
    ``profile_hardware``-calibrated cluster for measured numbers) and
    the comm terms from the predicted edge set priced through the
    planner's shared alpha-beta formulas.  With ``xla=True`` the
    compiled executable's ``cost_analysis()`` is attached for the
    cross-check (compiles on first call — the gate already pays this
    for GSPMD accounting).
    """
    from .edges import makes_edge_claim, predict_edges

    meta = handle.meta
    mesh_axes = {str(a): int(s)
                 for a, s in (meta.get("mesh_axes") or {}).items()}
    train = bool(meta.get("train", meta.get("kind") == "train_step"))
    cluster = cluster or ClusterSpec(
        num_chips=max(1, int(np.prod(list(mesh_axes.values()))
                             if mesh_axes else 1)))
    chip = cluster.chip

    gspmd_scale = 1.0
    for s in mesh_axes.values():
        gspmd_scale *= max(int(s), 1)
    scale = 1.0 / gspmd_scale

    rep = CostReport(name=handle.name, chip=chip.name)
    jaxpr = handle.jaxpr
    native = cost_walk(jaxpr, scale=scale, upcast=False,
                       multiply_trips=True)
    rep.flops = native.flops
    rep.transcendentals = native.transcendentals
    rep.hbm_bytes = native.bytes
    rep.entries = native.entries

    import jax
    upcast = jax.default_backend() == "cpu"
    cmp = cost_walk(jaxpr, scale=scale, upcast=upcast,
                    multiply_trips=False)
    rep.cmp_flops = cmp.flops
    rep.cmp_bytes = cmp.bytes
    rep.cmp_transcendentals = cmp.transcendentals

    rep.compute_time_s = (rep.flops + rep.transcendentals) \
        / (chip.peak_flops * chip.mxu_efficiency)
    rep.io_time_s = rep.hbm_bytes / chip.hbm_bw

    rep.overlap = bool(meta.get("comm_overlap", False))
    if makes_edge_claim(meta):
        edges = predict_edges(meta, mesh_axes, train)
        rep.comm = price_edges(
            edges, mesh_axes, cluster,
            overlap_origins=OVERLAPPABLE_ORIGINS if rep.overlap
            else frozenset())
    rep.comm_time_s = sum(c.total_s for c in rep.comm)
    rep.overlapped_comm_s = sum(c.total_s for c in rep.comm
                                if c.overlapped)
    rep.exposed_comm_s = rep.comm_time_s - rep.overlapped_comm_s

    roofline = max(rep.compute_time_s, rep.io_time_s)
    rep.step_time_s = max(roofline, rep.overlapped_comm_s) \
        + rep.exposed_comm_s
    if rep.exposed_comm_s > roofline:
        rep.bound = "comm"
    elif rep.io_time_s > rep.compute_time_s:
        rep.bound = "hbm"
    else:
        rep.bound = "compute"

    if xla:
        rep.xla = xla_cost_stats(handle)
        if rep.xla is not None:
            # comparable-only partitioner adjustment (docstring of
            # collective_traffic_adjustment): the GSPMD-materialized
            # collective traffic the jaxpr cannot see
            try:
                rep.cmp_bytes += collective_traffic_adjustment(
                    handle.compiled_text(), cmp.entries)
            except Exception:
                pass
    return rep
