"""Static analysis of lowered executables: sharding & collectives linter.

Hetu's core claim is that sharding annotations (``DistributedStates`` /
PartitionSpecs) *deterministically imply* the communication a program
performs.  This package makes the whole lowered program checkable
against that claim, generalizing PR 1's gradient-sync verifier to every
registered executable (train steps, the unified serving step, pipeline
stages):

* **collective inventory** — :mod:`.jaxpr_walk` walks the closed jaxpr
  of a plan and records every communication op with payload/wire bytes,
  mesh axes, dtype, loop trip counts, and source attribution (user
  frame + the jax name-stack tags :func:`hetu_tpu.parallel.comm.comm_tag`
  plants at emission sites).
* **lint rules** — :mod:`.rules` runs a rule engine over each
  executable's context (jaxpr + graph-level facts + compiled HLO +
  serving pool snapshots): replicated-large-param, implicit-reshard,
  wide-collective, donation-miss, unreduced-psum-scalar,
  trash-page-write.
* **baseline gate** — ``python -m hetu_tpu.analysis --check`` analyzes
  the canonical train + serving executables and fails when collective
  counts/bytes regress past ``ANALYSIS_BASELINE.json`` or a new finding
  appears (``--update-baseline`` re-freezes after intentional changes).

Executables register themselves: ``DefineAndRunGraph.run`` registers
every built plan, ``serving.Engine`` registers its prefill/decode
executables (``hetu_tpu.graph.register_executable`` is the public hook
for anything else).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..graph.graph import (ExecutableHandle, clear_executables,
                           get_executable, iter_executables,
                           register_executable)
from .cost import (CommCost, CostEntry, CostReport, cost_walk,
                   dot_general_flops, predict_cost, price_edges,
                   xla_cost_stats)
from .edges import (CommEdge, EdgeMatch, grad_comm_edges, makes_edge_claim,
                    match_edges, predict_edges)
from .events import ALL_KINDS, Event, collect_events, kind_counts
from .protocol import (ExploreConfig, ExploreResult, Violation, explore,
                       fuzz_trace, machine_summary, replay)
from .jaxpr_walk import (collect_collectives, compute_dtype_histogram,
                         donation_candidates, iter_eqns,
                         unreduced_scalar_outputs)
from .memory import (MemoryBuffer, MemoryReport, has_remat_region,
                     liveness_walk, parse_input_output_aliases,
                     predict_memory, xla_memory_stats)
from .report import (AnalysisReport, CollectiveRecord, ExecutableReport,
                     Finding, load_baseline, save_baseline)
from .rules import (DEFAULT_OPTIONS, RULES, SCHEDULE_RULE_OP_KINDS,
                    TRACE_RULE_EVENT_KINDS, AnalysisContext, ParamInfo,
                    _protocol_replay, rule, run_rules)
from .schedule import (SCHEDULE_RULES, CommOp, ProgramSpec,
                       ScheduleViolation, extract_schedules,
                       schedule_summary, seeded_bug_corpus,
                       spec_from_meta, strategy_grid, verify_schedules)

__all__ = [
    "AnalysisContext", "AnalysisReport", "CollectiveRecord", "CommEdge",
    "EdgeMatch", "ExecutableHandle", "ExecutableReport", "Finding",
    "ParamInfo", "RULES", "DEFAULT_OPTIONS", "analyze_handle",
    "analyze_registered", "build_context", "clear_executables",
    "collect_collectives", "get_executable", "grad_comm_edges",
    "grad_comm_prediction", "iter_executables", "makes_edge_claim",
    "match_edges", "predict_edges", "register_executable", "rule",
    "run_rules", "verify_grad_comm", "load_baseline", "save_baseline",
    "MemoryBuffer", "MemoryReport", "has_remat_region", "liveness_walk",
    "parse_input_output_aliases", "predict_memory", "xla_memory_stats",
    "predicted_cost_stats", "CommCost", "CostEntry", "CostReport",
    "cost_walk", "dot_general_flops", "predict_cost", "price_edges",
    "xla_cost_stats",
    # serving-protocol verifier (DESIGN.md §23)
    "ALL_KINDS", "Event", "ExploreConfig", "ExploreResult",
    "TRACE_RULE_EVENT_KINDS", "Violation", "collect_events", "explore",
    "fuzz_trace", "kind_counts", "machine_summary", "replay",
    # cross-rank collective-schedule verifier (DESIGN.md §25)
    "CommOp", "ProgramSpec", "SCHEDULE_RULES", "SCHEDULE_RULE_OP_KINDS",
    "ScheduleViolation", "extract_schedules", "schedule_summary",
    "seeded_bug_corpus", "spec_from_meta", "strategy_grid",
    "verify_schedules",
]


def predicted_cost_stats(handle: ExecutableHandle) -> Dict[str, Any]:
    """Static per-executable cost facts for the runtime trace plane
    (``hetu_tpu.obs.reconcile``): predicted wire bytes (the sum over the
    executable's predicted comm-edge set — ``payload_bytes x count`` per
    :class:`CommEdge`; None when the registration makes no edge claim),
    predicted peak HBM (``predict_memory`` native + comparable peaks),
    and the predicted step-time decomposition (``predict_cost``
    roofline + comm terms, seconds).  This is the join key between
    "what the analysis plane said this executable would cost" and
    "what the tracer observed it do"."""
    meta = handle.meta
    mesh_axes = dict(meta.get("mesh_axes", {}))
    train = bool(meta.get("train", meta.get("kind") == "train_step"))
    wire: Optional[int] = None
    if makes_edge_claim(meta):
        edges = predict_edges(meta, mesh_axes, train)
        wire = int(sum(e.payload_bytes * max(e.count, 1) for e in edges
                       if e.kind != "identity"))
    peak = cmp_peak = None
    try:
        mem = predict_memory(handle)
        peak, cmp_peak = int(mem.peak_bytes), int(mem.cmp_peak_bytes)
    except Exception:
        pass       # advisory, same stance as build_context's memory pass
    step = compute = io = comm = None
    flops = hbm = None
    bound = None
    try:
        cost = predict_cost(handle)
        step = float(cost.step_time_s)
        compute = float(cost.compute_time_s)
        io = float(cost.io_time_s)
        comm = float(cost.comm_time_s)
        flops = int(cost.flops)
        hbm = int(cost.hbm_bytes)
        bound = cost.bound
    except Exception:
        pass       # advisory: a broken cost pass must not break tracing
    return {"wire_bytes": wire, "peak_hbm_bytes": peak,
            "cmp_peak_bytes": cmp_peak,
            "step_time_s": step, "compute_time_s": compute,
            "io_time_s": io, "comm_time_s": comm,
            "flops": flops, "hbm_bytes": hbm, "bound": bound}


def build_context(handle: ExecutableHandle, compile: bool = False,
                  options: Optional[Dict[str, Any]] = None
                  ) -> AnalysisContext:
    """Assemble the rule-engine context for one executable: trace the
    plan (no execution), walk its jaxpr, and graft on the graph-level
    facts the registration meta carries."""
    meta = handle.meta
    jaxpr = handle.jaxpr
    lowered = handle.lower()
    params = [ParamInfo(name=p["name"], shape=tuple(p["shape"]),
                        dtype=p["dtype"], pspec=p.get("pspec"),
                        trainable=p.get("trainable", True))
              for p in meta.get("params", ())]
    serving = meta.get("serving")
    if callable(serving):
        serving = serving()
    mesh_axes = dict(meta.get("mesh_axes", {}))
    train = bool(meta.get("train", meta.get("kind") == "train_step"))
    try:
        memory = predict_memory(handle, xla=compile)
    except Exception:
        memory = None    # the memory pass is advisory: a walk failure
        #                  must not take down the collectives linter
    try:
        cost = predict_cost(handle, xla=compile)
    except Exception:
        cost = None      # same stance for the step-time pass
    ctx = AnalysisContext(
        name=handle.name,
        jaxpr=jaxpr,
        lowered_text=lowered.as_text(),
        compiled_text=handle.compiled_text() if compile else "",
        records=collect_collectives(jaxpr),
        params=params,
        mesh_axes=mesh_axes,
        dp_axis=meta.get("dp_axis", "dp"),
        args_info=lowered.args_info,
        out_avals=jaxpr.out_avals,
        allowed_gspmd=meta.get("allowed_gspmd"),
        serving=serving,
        meta=meta,
        edges=predict_edges(meta, mesh_axes, train),
        memory=memory,
        cost=cost,
        handle=handle,
        train=train,
    )
    if options:
        ctx.options = {**ctx.options, **options}
    return ctx


def analyze_handle(handle: ExecutableHandle, compile: bool = False,
                   options: Optional[Dict[str, Any]] = None,
                   rules: Optional[Sequence[str]] = None
                   ) -> ExecutableReport:
    """Analyze one executable: inventory + lint findings + (for
    edge-claiming executables) the per-edge attribution coverage."""
    ctx = build_context(handle, compile=compile, options=options)
    rep = ExecutableReport(name=handle.name, records=ctx.records,
                           meta={"kind": handle.meta.get("kind", "")})
    rep.findings = run_rules(ctx, only=rules)
    em = ctx.edge_match()
    if em is not None:
        rep.meta["edge_coverage"] = em.coverage()
        if ctx.compiled_text:
            rep.meta["gspmd_collectives"] = dict(em.gspmd_counts)
        rep.meta["edges"] = ctx.edges
        rep.meta["edge_match"] = em
    if ctx.memory is not None:
        rep.meta["memory"] = ctx.memory
    if ctx.cost is not None:
        rep.meta["cost"] = ctx.cost
    # serving-protocol coverage: every executable gets a section (train
    # gates pin an EMPTY stream — uniform baseline keys, and a train
    # plan that suddenly emits serving events is itself a finding-worthy
    # surprise the event count will surface).  The violation count here
    # is the lifecycle machines' verdict over the live trace; the
    # per-violation findings already ride in rep.findings via the four
    # lifecycle rules.
    events, lost = collect_events(ctx)
    rep.meta["protocol"] = {
        "events": len(events),
        "kinds": kind_counts(events),
        "violations": len(_protocol_replay(ctx)),
        "lost_hooks": sorted(lost),
        "machines": machine_summary(events),
    }
    # cross-rank schedule verdict: every executable gets a section
    # (uniform baseline keys; 0 ranks = this registration makes no
    # multi-rank claim).  Per-violation findings ride in rep.findings
    # via the six schedule rules, which share this pass's memoized
    # extraction + verification.
    rep.meta["schedule"] = schedule_summary(ctx)
    return rep


def analyze_registered(prefix: str = "", compile: bool = False,
                       options: Optional[Dict[str, Any]] = None,
                       rules: Optional[Sequence[str]] = None
                       ) -> AnalysisReport:
    """Analyze every registered executable whose name starts with
    ``prefix``; returns the combined :class:`AnalysisReport`."""
    report = AnalysisReport()
    for handle in iter_executables(prefix):
        report.add(analyze_handle(handle, compile=compile,
                                  options=options, rules=rules))
    return report


# ---------------------------------------------------------------------------
# grad-comm predictor, folded into the general pass (PR 1 compatibility)
# ---------------------------------------------------------------------------


def grad_comm_prediction(handle: ExecutableHandle):
    """``(prediction, extra)`` for a train-step handle whose plan runs
    the explicit coalesced grad sync — the exact collective sequence the
    lowered program must emit (``dstates.predict_update_step_collectives``
    over the registered gradient entries)."""
    gc = handle.meta.get("grad_comm")
    if not gc:
        raise ValueError(
            f"{handle.name} has no grad-comm plan registered (implicit "
            f"GSPMD sync, or not a train step)")
    from ..parallel.dstates import predict_update_step_collectives
    entries = [(name, tuple(shape), dtype)
               for name, shape, dtype in gc["entries"]]
    return predict_update_step_collectives(
        entries, gc["device_num"], transport=gc["transport"],
        bucket_mb=gc["bucket_mb"], scalar_fetches=gc["scalar_fetches"],
        flat=gc.get("flat", False), clip=gc.get("clip", False),
        zero=int(gc.get("zero", 2) or 2),
        opt_extra=gc.get("opt_extra"))


def verify_grad_comm(handle: ExecutableHandle) -> None:
    """PR 1's ``verify_grad_comm_emission`` assertion, reproduced through
    the general pass: the lowered StableHLO of the registered train step
    must contain exactly the predicted collective sequence."""
    from ..parallel.dstates import verify_grad_comm_emission
    pred, extra = grad_comm_prediction(handle)
    verify_grad_comm_emission(handle.lower().as_text(), pred, extra=extra)
