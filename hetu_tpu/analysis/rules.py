"""Lint rule engine over analyzed executables.

Every rule is a function ``rule(ctx: AnalysisContext) -> List[Finding]``
registered in :data:`RULES` via the :func:`rule` decorator; the pass
driver (``hetu_tpu.analysis.analyze_handle``) builds one
:class:`AnalysisContext` per executable and runs every enabled rule.

Rule catalog (DESIGN.md §9 for the rationale of each):

``replicated-large-param``   param above a size threshold with no
                             sharded axis, while the mesh has shardable
                             (non-dp) axes — accidental full replication.
``implicit-reshard``         compiled-HLO collective counts exceed the
                             jaxpr inventory + declared GSPMD allowance:
                             GSPMD inserted a resharding the program's
                             DistributedStates transitions don't predict.
``wide-collective``          fp32/fp64 transport above a payload
                             threshold where the surrounding compute is
                             bf16/fp16/int8 (quantized-scale sidecars,
                             tagged ``scales``, are exempt).
``donation-miss``            large input buffer whose shape/dtype
                             reappears in the outputs but is not donated.
``unreduced-psum-scalar``    scalar result of a >1-device manual region
                             with no cross-replica reduction on its
                             def-chain (each rank returns its local
                             value).
``trash-page-write``         serving: the reserved page 0 is reachable by
                             a real write — present in the pool
                             free-list/allocated set, or a live decode
                             row's page table targets it (padding rows
                             are the only legitimate trash-page writers).
``kv-handoff-unpriced``      serving cluster: a cross-replica KV-page
                             move (disaggregated prefill→decode
                             handoff) whose record carries no priced
                             edge claim — every page stream must
                             declare a CommEdge-shaped claim with its
                             payload bytes and an alpha-beta predicted
                             time, so the disaggregation design stays
                             priced before hardware exists.
``host-offload-unpriced``    serving: a host-RAM tier page move (cold
                             prefix-cache evict, or its refetch back to
                             device) whose record carries no priced
                             edge claim, or whose byte accounting
                             disagrees (edge payload vs record payload
                             vs pages x page_bytes) — the host tier is
                             wire traffic exactly like the
                             disaggregation handoff and must stay
                             priced before hardware exists.  Records
                             flagged ``host_offload_exempt`` are
                             skipped.
``unfenced-handoff``         serving cluster: a cross-replica page move
                             or a mid-flight request adoption lacking
                             an epoch/fence token — without one, a
                             revived or re-registered replica (or a
                             retried wire delivery whose ack was lost)
                             can double-deliver: two engines decode the
                             same request, duplicated tokens.  Records
                             flagged ``fence_exempt`` (a local,
                             same-pool degrade that never crosses
                             replicas) are exempt.
``unverified-restore``       a checkpoint restore read tensor bytes
                             without a digest check against a
                             generation manifest — bit rot or a torn
                             write restores garbage silently.  Every
                             restore must go through the verifying
                             generation loader
                             (resilience.load_latest_generation) or be
                             explicitly flagged ``verify_exempt``.
``cow-page-write``           serving: a unified-step KV write plan entry
                             targets a CACHED page — read-only by the
                             CoW contract whatever its sharer count
                             (the index serves it to future lookups);
                             writing it corrupts a shared KV history
                             (trash page exempt: padding's sink).
``spec-rewind-leak``         serving: after a speculative verify
                             rejected part of a burst, a later step's
                             attention window reads a rejected
                             position's STALE KV before the write plan
                             re-wrote it — the rewind contract
                             (DESIGN.md §20) silently broken
                             (``rewind_exempt`` records are skipped).
``grad-allgather-under-zero2`` a ZeRO-2 train step regathers gradients:
                             an fp32 gradient all-gather (any plan), or
                             ANY gradient all-gather in a plan that
                             declares the flat reduce-scatter-only sync
                             — the regression back to the double-wire
                             all-reduce path must fail CI.  The scale
                             sidecars of the quantized transport
                             (tagged ``scales``), the updated-param
                             gather (tagged ``param_comm``) and the
                             ZeRO-3 just-in-time weight gather (tagged
                             ``param_gather``) are exempt.  Under flat
                             ``zero>=3`` the rule also checks the
                             at-rest side: a full working parameter
                             resident in the step's argument set means
                             the params-sharded-at-rest contract is
                             broken (the memory saving silently gone).
``param-gather-unpriced``    a ``param_gather``-tagged collective (the
                             ZeRO-3 just-in-time weight gather) the
                             predicted edge set does not price: every
                             per-bucket gather must ride a
                             ``param_gather`` CommEdge with its payload
                             bytes, or the wire cost of
                             params-sharded-at-rest is invisible to the
                             planner and the step-time linter.
``unexplained-collective``   an emitted collective the per-edge
                             DS-transition attribution (analysis/edges)
                             cannot explain: an explicit record no
                             predicted edge covers, or GSPMD-inserted
                             collectives beyond the edge budget /
                             declared allowance.  Replaces
                             ``implicit-reshard`` for every executable
                             that registers edges.
``moe-capacity-overprovision`` MoE dispatch payload exceeds what the
                             layer's capacity factor predicts — the
                             dispatch/combine all-to-alls move more
                             bytes than the routing math requires
                             (dropless mode is exempt: no capacity).
``peak-memory-regression``   the static peak-HBM prediction
                             (analysis/memory) grew beyond the frozen
                             per-executable baseline + tolerance — a
                             silent memory regression (lost donation,
                             widened dtype, new long-lived buffer).
``oom-risk``                 predicted peak exceeds the configured
                             device HBM budget: the program will OOM on
                             the target chip before it runs once.  The
                             hint names the dominant buffer class and
                             its class-specific remedy.
``remat-opportunity``        saved-activation liveness dominates the
                             predicted peak, the peak is large enough
                             to matter, and no remat/checkpoint region
                             covers the program — rematerialization
                             would trade FLOPs for the dominant buffer.
``replicated-state-under-shard`` optimizer/master/gradient bytes not
                             sharded down by dp while the mesh has
                             dp > 1 — ZeRO (zero=1/2) or the flat
                             dp-sharded state would divide exactly
                             these bytes (generalizes
                             ``replicated-large-param`` from params to
                             the state that usually dwarfs them).
``page-lifecycle-violation`` serving protocol (DESIGN.md §23): a
                             page-plane event breaks the page lifecycle
                             state machine (free→allocated→cached→
                             host-staged→free, trash page immutable) —
                             double-free, alloc of a non-free page,
                             free of a cached/shared page, host-stage
                             of a page that was never cached, write to
                             a freed page...
``request-lifecycle-violation`` serving protocol: a request-plane event
                             breaks the request lifecycle (queued→
                             running→preempted/handoff-staged→adopted→
                             finished|shed) — double-adopt, adoption of
                             a request never staged, KV write or
                             re-queue after finish/shed...
``fence-regression``         serving protocol: a replica's fence epoch
                             moved BACKWARDS, or a completion/adoption
                             stamped with a stale epoch was accepted
                             past the death sweep — the exact shape
                             that double-delivers tokens after a crash.
``refcount-leak``            serving protocol: prefix-cache sharer
                             accounting broke — unshare below zero,
                             uncache with live sharers; over COMPLETE
                             traces (the explorer / fuzz gate) also
                             terminal page-conservation failures.

The four ``serving protocol`` rules replay the normalized event stream
(``analysis.events.collect_events``) through the lifecycle state
machines in ``analysis.protocol``; their findings carry the violating
event subtrace in ``hint`` (printed by the CLI's ``--explain``).
:data:`TRACE_RULE_EVENT_KINDS` maps every trace-replay rule to the
event kinds it inspects, so the vacuity meta-test can prove each rule
actually sees events of those kinds in the gate executables' traces.

Thresholds live in :data:`DEFAULT_OPTIONS` and are overridable per
context (tests seed violations with tiny thresholds).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import events as pe
from .jaxpr_walk import (compute_dtype_histogram, donation_candidates,
                         unreduced_scalar_outputs)
from .protocol import (RULE_FENCE, RULE_PAGE, RULE_REFCOUNT,
                       RULE_REQUEST, replay)
from .report import CollectiveRecord, Finding

LOW_PRECISION = {"bfloat16", "float16", "int8", "uint8", "float8_e4m3fn",
                 "float8_e5m2"}
WIDE_DTYPES = {"float32", "float64"}

DEFAULT_OPTIONS: Dict[str, Any] = {
    # replicated-large-param: min bytes before replication is suspicious
    "param_bytes_threshold": 1 << 20,
    # wide-collective: min payload for a wide transport to matter
    "wide_bytes_threshold": 1 << 20,
    # donation-miss: min buffer size worth donating
    "donation_bytes_threshold": 1 << 20,
    # unexplained-collective: how many GSPMD-inserted HLO collectives
    # ONE predicted DS-transition edge may lower to (fwd + bwd
    # transpose + a couple of partitioner splits).  Counts stay pinned
    # exactly by the baseline; this bounds attribution, not growth.
    "gspmd_budget_factor": 4,
    # moe-capacity-overprovision: tolerated payload slack over the
    # capacity-factor prediction (1.0 = exact)
    "moe_capacity_slack": 1.0,
    # oom-risk: per-device HBM budget the static peak is checked
    # against (default: v5p 95 GB x the usable fraction below)
    "hbm_budget_bytes": 95e9,
    "hbm_usable_fraction": 0.9,
    # peak-memory-regression: {executable name -> frozen peak bytes}
    # (the CLI injects this from ANALYSIS_BASELINE.json) + tolerance
    "baseline_peak_bytes": None,
    "memory_tolerance": 0.1,
    # remat-opportunity: only peaks above this matter, and only when
    # saved activations dominate by this fraction
    "remat_min_bytes": 1 << 30,
    "remat_activation_fraction": 0.5,
    # comm-bound-plan: predicted step times below this are CI-scale
    # toys where fixed collective latency always dominates a
    # microseconds-long roofline — only real workloads fire
    "comm_bound_min_step_s": 1e-3,
    # ...and exposed comm must exceed the roofline by this factor
    "comm_bound_ratio": 1.5,
    # predicted-step-regression: {executable name -> frozen step-time
    # seconds} (the CLI injects this from ANALYSIS_BASELINE.json's
    # cost.step_time_us) + tolerance
    "baseline_step_time_s": None,
    "step_time_tolerance": 0.1,
}


@dataclasses.dataclass
class ParamInfo:
    """A trainable/stateful array the executable closes over."""
    name: str
    shape: Tuple[int, ...]
    dtype: str
    pspec: Any = None          # PartitionSpec or None
    trainable: bool = True

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)
                   * np.dtype(self.dtype).itemsize)

    def sharded_axes(self) -> set:
        axes = set()
        if self.pspec is None:
            return axes
        for entry in self.pspec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                axes.add(a)
        return axes


@dataclasses.dataclass
class AnalysisContext:
    """Everything the rules may inspect for one executable."""
    name: str
    jaxpr: Any = None                       # ClosedJaxpr (traced plan)
    lowered_text: str = ""                  # StableHLO (pre-partitioning)
    compiled_text: str = ""                 # post-SPMD HLO ("" = skipped)
    records: List[CollectiveRecord] = dataclasses.field(default_factory=list)
    params: List[ParamInfo] = dataclasses.field(default_factory=list)
    mesh_axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    dp_axis: Optional[str] = "dp"           # replication intended here
    args_info: Any = None                   # Lowered.args_info
    out_avals: Any = None
    # collectives GSPMD is EXPECTED to insert (kind -> count): e.g. the
    # implicit-path gradient sync, or the scalar-loss psum of a
    # sharded-batch eval step.  None disables implicit-reshard entirely
    # (executable makes no prediction claim).
    allowed_gspmd: Optional[Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    serving: Optional[Dict[str, Any]] = None   # pool/tap snapshot
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # predicted DS-transition edges (analysis/edges.predict_edges);
    # None = the executable makes no per-edge claim
    edges: Optional[List[Any]] = None
    # static peak-HBM prediction (analysis/memory.predict_memory);
    # None when the memory pass could not run for this executable
    memory: Optional[Any] = None
    # static step-time prediction (analysis/cost.predict_cost);
    # None when the cost pass could not run for this executable
    cost: Optional[Any] = None
    # the registered ExecutableHandle (compiled-artifact access for
    # rules that consult XLA's own tables)
    handle: Optional[Any] = None
    # whether this executable differentiates (enables autodiff-dual
    # matching in the edge pass) — set once by build_context so the
    # edge predictor and the matcher share one definition
    train: bool = False
    options: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_OPTIONS))
    _edge_match: Any = dataclasses.field(default=None, repr=False)

    def opt(self, key: str):
        return self.options.get(key, DEFAULT_OPTIONS[key])

    def edge_match(self):
        """Match emitted collectives against the predicted edge set
        (cached; ``None`` when the executable makes no edge claim)."""
        if self.edges is None:
            return None
        if self._edge_match is None:
            from .edges import match_edges
            self._edge_match = match_edges(
                self.records, self.lowered_text, self.compiled_text,
                self.edges, train=self.train,
                allowed_gspmd=self.allowed_gspmd,
                budget_factor=int(self.opt("gspmd_budget_factor")))
        return self._edge_match


RuleFn = Callable[[AnalysisContext], List[Finding]]
RULES: Dict[str, RuleFn] = {}


def rule(name: str):
    def deco(fn: RuleFn) -> RuleFn:
        fn.rule_name = name
        RULES[name] = fn
        return fn
    return deco


def run_rules(ctx: AnalysisContext,
              only: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run (a subset of) the registered rules; findings carry the
    executable name and are returned most-severe-first (by rule name
    order of registration, which lists correctness rules first)."""
    findings: List[Finding] = []
    for name, fn in RULES.items():
        if only is not None and name not in only:
            continue
        for f in fn(ctx):
            f.executable = ctx.name
            f.rule = name
            findings.append(f)
    return findings


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


@rule("replicated-large-param")
def _replicated_large_param(ctx: AnalysisContext) -> List[Finding]:
    shardable = {a for a, n in ctx.mesh_axes.items()
                 if n > 1 and a != ctx.dp_axis}
    if not shardable:
        return []       # pure-dp mesh: replicated-at-rest is the design
    thr = ctx.opt("param_bytes_threshold")
    out = []
    for p in ctx.params:
        if not p.trainable or p.nbytes < thr:
            continue
        if p.sharded_axes() & shardable:
            continue
        out.append(Finding(
            rule="", subject=p.name,
            message=f"param {p.name} {p.shape} ({p.nbytes} B) is fully "
                    f"replicated; mesh has unused shardable axes "
                    f"{sorted(shardable)}",
            hint=f"shard it: pspec=P({sorted(shardable)[0]!r}, ...) on "
                 f"its largest dim (vocab/feature), or mark "
                 f"trainable=False if it is a frozen table"))
    return out


@rule("implicit-reshard")
def _implicit_reshard(ctx: AnalysisContext) -> List[Finding]:
    if ctx.edges is not None:
        # the per-edge attribution pass owns GSPMD accounting for
        # edge-claiming executables (unexplained-collective below) —
        # including the strict allowed_gspmd claim when one is declared
        return []
    if not ctx.compiled_text or ctx.allowed_gspmd is None:
        return []
    from ..parallel.dstates import count_hlo_collectives
    got = count_hlo_collectives(ctx.compiled_text)
    explicit = count_hlo_collectives(ctx.lowered_text) if ctx.lowered_text \
        else {}
    out = []
    for kind in sorted(got):
        allowed = explicit.get(kind, 0) + ctx.allowed_gspmd.get(kind, 0)
        excess = got[kind] - allowed
        if excess > 0:
            out.append(Finding(
                rule="", subject=kind,
                message=f"compiled program emits {got[kind]} {kind} "
                        f"collectives but only {allowed} are predicted "
                        f"({explicit.get(kind, 0)} explicit + "
                        f"{ctx.allowed_gspmd.get(kind, 0)} allowed): "
                        f"{excess} GSPMD-inserted reshard(s) the sharding "
                        f"annotations do not account for",
                hint="register pspec edges for this executable so the "
                     "per-edge pass can attribute the reshard, or align "
                     "the producer/consumer pspecs that force it"))
    return out


@rule("wide-collective")
def _wide_collective(ctx: AnalysisContext) -> List[Finding]:
    if ctx.jaxpr is None:
        return []
    hist = compute_dtype_histogram(ctx.jaxpr)
    if not hist:
        return []
    dominant = max(hist.items(), key=lambda kv: kv[1])[0]
    if dominant not in LOW_PRECISION:
        return []
    thr = ctx.opt("wide_bytes_threshold")
    out = []
    for r in ctx.records:
        if r.dtype not in WIDE_DTYPES or r.payload_bytes < thr:
            continue
        if "scales" in r.scope.split("/"):
            # exact comm_tag path segment, not a substring — a user
            # scope like "loss_rescales" must NOT be exempted
            continue    # quantized-transport absmax sidecar: fp32 by design
        out.append(Finding(
            rule="", subject=f"{r.kind}:{r.dtype}",
            message=f"{r.dtype} {r.kind} moves {r.payload_bytes} B over "
                    f"{'/'.join(r.axes) or '?'} while the surrounding "
                    f"compute is {dominant} — transport could be "
                    f"narrowed (grad_comm= / bf16 cast)",
            source=r.source,
            hint=f"narrow the transport: Optimizer(grad_comm='bf16'|"
                 f"'int8') for gradient syncs, or cast to {dominant} "
                 f"before the collective and back after"))
    return out


@rule("donation-miss")
def _donation_miss(ctx: AnalysisContext) -> List[Finding]:
    if ctx.args_info is None or ctx.out_avals is None:
        return []
    thr = ctx.opt("donation_bytes_threshold")
    # when the program was compiled, XLA's own input_output_alias table
    # says which output slots are ALREADY absorbed — consult it instead
    # of assuming every donated input aliases a shape-matched output
    # (the shape/dtype guess both misses aliases it can't see and
    # invents ones XLA dropped)
    alias_pairs = None
    if ctx.compiled_text:
        from .memory import parse_input_output_aliases
        # an empty table carries no information (regex miss, or a
        # program with no donations at all): keep the shape-based guess
        alias_pairs = parse_input_output_aliases(ctx.compiled_text) or None
    out = []
    for arg, nbytes in donation_candidates(ctx.args_info, ctx.out_avals,
                                           min_bytes=thr,
                                           alias_pairs=alias_pairs):
        out.append(Finding(
            rule="", subject=f"arg{arg}",
            message=f"input {arg} ({nbytes} B across its leaves) matches "
                    f"output buffers but is not donated — the executable "
                    f"holds two copies where one would do",
            hint=f"donate it: jax.jit(fn, donate_argnums=(...)) for "
                 f"input {arg} — XLA reuses the buffer in place"))
    return out


@rule("unreduced-psum-scalar")
def _unreduced_psum_scalar(ctx: AnalysisContext) -> List[Finding]:
    if ctx.jaxpr is None:
        return []
    out = []
    for var, scope, src in unreduced_scalar_outputs(ctx.jaxpr):
        out.append(Finding(
            rule="", subject=var,
            message=f"scalar output {var} of a manual-mode region has no "
                    f"psum/pmean on its def-chain: every rank returns its "
                    f"OWN local value (scope {scope or '?'})",
            source=src, severity="error",
            hint="reduce it before returning: jax.lax.pmean(x, axis) "
                 "for means, lax.psum for sums"))
    return out


@rule("grad-allgather-under-zero2")
def _grad_allgather_under_zero2(ctx: AnalysisContext) -> List[Finding]:
    gc = (ctx.meta or {}).get("grad_comm") or {}
    flat = bool(gc.get("flat", False))
    zero = int(gc.get("zero", 0) or 0)
    # in scope: any ZeRO-2+ plan, and any plan declaring the flat
    # reduce-scatter-only contract (flat zero=1 included)
    if zero < 2 and not flat:
        return []
    out = []
    for r in ctx.records:
        segs = r.scope.split("/")
        if r.kind != "all_gather" or "grad_comm" not in segs \
                or "scales" in segs:
            continue
        # fp32 gradient regather is always a ZeRO-2 bug; under the flat
        # reduce-scatter-only contract ANY gradient regather is (the
        # param gathers ride the param_comm / param_gather tags and
        # stay exempt: their scope never contains grad_comm)
        if r.dtype in WIDE_DTYPES or flat:
            out.append(Finding(
                rule="", subject=f"all_gather:{r.dtype}",
                severity="error",
                message=f"ZeRO-2 plan regathers gradients: {r.dtype} "
                        f"all_gather of {r.payload_bytes} B in scope "
                        f"{r.scope!r} pays the wire bytes the "
                        f"reduce-scatter-only sync exists to save "
                        f"(flat_state=True keeps gradients scattered)",
                source=r.source,
                hint="keep gradients scattered: Optimizer("
                     "flat_state=True) updates the locally-owned flat "
                     "chunk and regathers PARAMS (weight dtype, tag "
                     "param_comm), never gradients"))
    # the zero-3 at-rest side of the contract: params live ONLY as the
    # flat master's 1/dp chunks, so a full working parameter resident
    # in the step's argument set (matching a grad-comm entry's global
    # shape+dtype) means the memory saving is silently gone — the
    # per-bucket forward AGs (tag param_gather) are the EXPECTED shape,
    # a resident param is the new finding
    if flat and zero >= 3 and ctx.args_info is not None:
        import jax
        entry_sigs = {(tuple(int(d) for d in shape), str(dtype)): name
                      for name, shape, dtype in gc.get("entries", ())}
        try:
            var_info = ctx.args_info[0]
            leaves = jax.tree_util.tree_leaves(var_info)
        except Exception:
            leaves = []
        for leaf in leaves:
            if not hasattr(leaf, "shape"):
                continue
            sig = (tuple(int(d) for d in leaf.shape),
                   np.dtype(leaf.dtype).name)
            name = entry_sigs.get(sig)
            if name is None:
                continue
            out.append(Finding(
                rule="", subject=f"resident:{name}",
                severity="error",
                message=f"ZeRO-3 plan declares params sharded at rest "
                        f"but working parameter {name} {sig[0]} "
                        f"({sig[1]}) is resident in the step's argument "
                        f"set at full size — every rank holds the "
                        f"replica the flat master's 1/dp chunks exist "
                        f"to replace",
                hint="drop the trainable from var_state before the jit "
                     "step (the flat zero-3 path gathers it "
                     "just-in-time from flat_master, tag param_gather) "
                     "— a resident copy both wastes the HBM and risks "
                     "training from stale weights"))
    return out


@rule("param-gather-unpriced")
def _param_gather_unpriced(ctx: AnalysisContext) -> List[Finding]:
    """Every emitted ``param_gather`` collective (the ZeRO-3
    just-in-time weight gather) must be priced by a predicted
    ``param_gather`` edge carrying its payload bytes — otherwise the
    wire cost of params-sharded-at-rest is invisible to the planner,
    the step-time linter and the baseline gate."""
    recs = [r for r in ctx.records
            if "param_gather" in r.scope.split("/")]
    if not recs:
        return []
    edges = [e for e in (ctx.edges or ())
             if getattr(e, "tag", "") == "param_gather"
             and getattr(e, "payload_bytes", 0) > 0]
    budget = sum(int(getattr(e, "count", 1)) for e in edges)
    # lazy materialization replays the gather inside each fused forward
    # region: records the edge matcher attributed to a priced
    # param_gather edge (including the bounded replay tier) are priced,
    # not rogue — only an emission beyond both the budget AND the
    # attribution is unexplained wire traffic
    em = ctx.edge_match()
    attributed = set()
    if em is not None:
        for mrec, medge in list(em.explained) + list(em.replayed):
            if getattr(medge, "tag", "") == "param_gather":
                attributed.add(id(mrec))
    out: List[Finding] = []
    for i, r in enumerate(recs):
        if r.kind != "all_gather":
            out.append(Finding(
                rule="", subject=f"{r.kind}:param_gather",
                severity="error", source=r.source,
                message=f"{r.dtype} {r.kind} rides the param_gather "
                        f"tag but the ZeRO-3 weight gather is an "
                        f"all_gather by contract — a different "
                        f"collective under this tag is mis-attributed "
                        f"wire traffic",
                hint="emit the weight gather through "
                     "comm.all_gather_coalesced(..., "
                     "tag='param_gather') only"))
            continue
        if i >= budget and id(r) not in attributed:
            out.append(Finding(
                rule="", subject=f"all_gather:param_gather@{i}",
                severity="error", source=r.source,
                message=f"param_gather all_gather of "
                        f"{r.payload_bytes} B ({r.dtype}) has no "
                        f"priced edge: the predicted edge set claims "
                        f"{budget} param_gather collective(s) but the "
                        f"program emits {len(recs)}",
                hint="register the plan with grad_comm zero=3 so "
                     "grad_comm_edges prices one param_gather edge "
                     "per bucket (payload = n * chunk * weight "
                     "itemsize), or remove the rogue gather"))
    return out


@rule("unexplained-collective")
def _unexplained_collective(ctx: AnalysisContext) -> List[Finding]:
    """Per-edge attribution (analysis/edges): every emitted collective
    must be explained by a predicted DS-transition edge."""
    em = ctx.edge_match()
    if em is None:
        return []
    out: List[Finding] = []
    for r in em.unexplained_records:
        segs = [s for s in r.scope.split("/") if s]
        slug = segs[-1] if segs else "untagged"
        out.append(Finding(
            rule="", subject=f"{r.kind}:{slug}",
            message=f"{r.dtype} {r.kind} over "
                    f"{'/'.join(r.axes) or '?'} ({r.payload_bytes} B "
                    f"x{r.count}, scope {r.scope or 'untagged'}) is not "
                    f"predicted by any DS-transition edge — the program "
                    f"communicates outside its sharding contract",
            source=r.source,
            hint="predict it: annotate the producer with sharded(...) "
                 "so the edge pass sees the transition, or wrap the "
                 "emission in comm.comm_tag(...) matching a declared "
                 "edge; if the collective is wrong, fix the producer/"
                 "consumer pspecs so the transition disappears"))
    for kind, (excess, budget) in sorted(em.gspmd_unexplained.items()):
        near = [e.describe() for e in (ctx.edges or [])
                if e.kind != "identity"][:3]
        near_s = ("; nearest declared edges: " + " | ".join(near)) \
            if near else "; no edge predicts this kind at all"
        out.append(Finding(
            rule="", subject=f"gspmd:{kind}",
            message=f"GSPMD inserted {excess} {kind} collective(s) "
                    f"beyond the {budget} the predicted edges allow"
                    f"{near_s}",
            hint="a producer -> consumer pspec disagreement the "
                 "annotations do not account for: align the stale "
                 "pspec (or declare the edge) so the reshard is "
                 "predicted — or remove the mid-graph constraint that "
                 "forces it"))
    return out


@rule("moe-capacity-overprovision")
def _moe_capacity_overprovision(ctx: AnalysisContext) -> List[Finding]:
    """MoE dispatch payload must not exceed the capacity-factor
    prediction: the dispatch/combine all-to-alls are the widest
    collectives on an ICI-bound mesh, and an over-provisioned capacity
    moves (and zero-pads) bytes the routing math never fills."""
    from ..ops.moe_dispatch import capacity_tokens
    out: List[Finding] = []
    slack = float(ctx.opt("moe_capacity_slack"))
    for m in (ctx.meta or {}).get("moe") or ():
        if m.get("dispatch_mode") == "dropless":
            continue    # capacity-free: every assignment computes, no pad
        try:
            pred = capacity_tokens(int(m["tokens"]),
                                   int(m["num_experts"]),
                                   int(m.get("k", 1)),
                                   float(m["capacity_factor"]))
        except (KeyError, ValueError, TypeError):
            continue
        actual = int(m.get("capacity", pred))
        if actual <= pred * slack:
            continue
        itemsize = np.dtype(m.get("dtype", "float32")).itemsize
        per_cap = int(m["num_experts"]) * int(m.get("embed_dim", 1)) \
            * itemsize
        out.append(Finding(
            rule="", subject=m.get("name", "moe"),
            message=f"MoE layer {m.get('name', '?')} dispatches with "
                    f"capacity {actual} tokens/expert but "
                    f"capacity_factor {m['capacity_factor']} predicts "
                    f"{pred}: each dispatch/combine all-to-all moves "
                    f"{(actual - pred) * per_cap} zero-padded bytes "
                    f"per step",
            hint=f"size capacity from capacity_tokens(T, E, k, cf) "
                 f"(= {pred} here), lower capacity_factor, or switch "
                 f"to dispatch_mode='dropless' (capacity-free blocked "
                 f"group-GEMM, no padding at all)"))
    return out


def _fmt_mem(n) -> str:
    from .memory import _fmt_bytes
    return _fmt_bytes(n)


#: class-specific remedies the memory rules name for the dominant
#: buffer kind — each hint is the mechanism that divides exactly that
#: class's bytes
_KIND_REMEDY = {
    "param": "shard params over tp (pspec on the large dims) or go "
             "ZeRO-3/FSDP so only the 1/dp shard lives at rest",
    "opt-state": "Optimizer(zero=1|2) or flat_state=True dp-shards the "
                 "fp32 master/m/v — the usual biggest win",
    "grad": "Optimizer(zero=2) / flat_state=True keeps gradients "
            "reduce-scattered instead of replicated",
    "activation": "wrap blocks in jax.checkpoint (remat) to trade one "
                  "extra forward for the saved-activation set, or "
                  "shrink the micro-batch",
    "kv-page": "lower num_pages / page_size, or shard the pool over tp "
               "(kv_heads) so each device holds 1/tp of the pages",
    "feed": "shard the batch dim over dp (pspec=P('dp', ...)) so each "
            "device feeds 1/dp of the global batch",
    "output": "donate the matching input (jit donate_argnums) so the "
              "output aliases it instead of costing fresh HBM",
    "input": "donate round-tripping buffers, or shard them over the "
             "mesh so each device holds a slice",
}


@rule("peak-memory-regression")
def _peak_memory_regression(ctx: AnalysisContext) -> List[Finding]:
    """Static peak-HBM prediction vs the frozen per-executable baseline:
    growth beyond the tolerance is a silent memory regression the
    numeric tests cannot see (a lost donation, a widened dtype, a new
    long-lived buffer)."""
    base_map = ctx.opt("baseline_peak_bytes")
    if ctx.memory is None or not base_map:
        return []
    base = base_map.get(ctx.name)
    if base is None:
        return []
    tol = float(ctx.opt("memory_tolerance"))
    got = int(ctx.memory.peak_bytes)
    if got <= base * (1.0 + tol):
        return []
    dom = ctx.memory.dominant_kind()
    return [Finding(
        rule="", subject="peak",
        message=f"predicted peak HBM regressed {_fmt_mem(base)} -> "
                f"{_fmt_mem(got)} ({got / max(base, 1) - 1.0:+.1%}, "
                f"tolerance {tol:.0%}); dominant class now {dom} "
                f"({_fmt_mem(ctx.memory.by_kind.get(dom, 0))})",
        hint=f"inspect the attribution table (--memory --explain) for "
             f"the buffer that grew; if the change is intentional, "
             f"re-freeze with --update-baseline.  For {dom}: "
             f"{_KIND_REMEDY.get(dom, 'shard or donate it')}")]


@rule("oom-risk")
def _oom_risk(ctx: AnalysisContext) -> List[Finding]:
    """Predicted peak vs the device HBM budget: the program OOMs on the
    target chip before it runs once.  Static, so the verdict arrives
    without burning a pod allocation on a doomed launch."""
    if ctx.memory is None:
        return []
    budget = float(ctx.opt("hbm_budget_bytes")) \
        * float(ctx.opt("hbm_usable_fraction"))
    peak = int(ctx.memory.peak_bytes)
    if peak <= budget:
        return []
    dom = ctx.memory.dominant_kind()
    top = ctx.memory.top(3)
    top_s = "; ".join(f"{b.kind}:{b.name} {_fmt_mem(b.nbytes)}"
                      for b in top)
    return [Finding(
        rule="", subject="peak", severity="error",
        message=f"predicted peak {_fmt_mem(peak)} exceeds the "
                f"{_fmt_mem(budget)} usable-HBM budget "
                f"({peak / max(budget, 1):.2f}x) — the program will OOM "
                f"on the target chip.  Dominant class: {dom} "
                f"({_fmt_mem(ctx.memory.by_kind.get(dom, 0))}); top "
                f"buffers: {top_s}",
        hint=f"{_KIND_REMEDY.get(dom, 'shard the dominant buffers')} "
             f"(budget: hbm_budget_bytes x hbm_usable_fraction, "
             f"override via analysis options / --hbm-budget)")]


@rule("remat-opportunity")
def _remat_opportunity(ctx: AnalysisContext) -> List[Finding]:
    """Saved-activation liveness dominates the predicted peak, the peak
    is big enough to matter, and no remat/checkpoint region covers the
    program: rematerialization would trade one extra forward for
    exactly the dominant buffer class."""
    if ctx.memory is None or ctx.jaxpr is None:
        return []
    if not ctx.train:
        return []       # no backward pass: nothing holds saved
        # activations across the forward, checkpoint reclaims nothing
    peak = int(ctx.memory.peak_bytes)
    act = int(ctx.memory.activation_peak_bytes)
    if peak < int(ctx.opt("remat_min_bytes")):
        return []
    frac = float(ctx.opt("remat_activation_fraction"))
    if act < frac * peak:
        return []
    from .memory import has_remat_region
    if has_remat_region(ctx.jaxpr):
        return []       # already rematerialized: the walk priced it in
    srcs = [b.source for b in ctx.memory.top(5)
            if b.kind == "activation" and b.source]
    src_s = f" (largest at {srcs[0]})" if srcs else ""
    return [Finding(
        rule="", subject="activations",
        message=f"activation liveness {_fmt_mem(act)} is "
                f"{act / max(peak, 1):.0%} of the {_fmt_mem(peak)} "
                f"predicted peak and no remat/checkpoint region covers "
                f"the program{src_s} — rematerialization would reclaim "
                f"most of it for ~1/3 more compute",
        source=srcs[0] if srcs else "",
        hint="wrap the repeated block in jax.checkpoint (nn layers: "
             "remat=True / policy=dots_saveable) so the backward "
             "recomputes activations instead of holding them across "
             "the whole forward")]


@rule("replicated-state-under-shard")
def _replicated_state_under_shard(ctx: AnalysisContext) -> List[Finding]:
    """Optimizer/master-state bytes replicated over a dp > 1 mesh while
    nothing shards them: ZeRO-1/2 or the flat dp-sharded state would
    divide exactly these bytes by dp.  Generalizes
    ``replicated-large-param`` from params to the fp32 state that
    usually dwarfs them (Adam: master + m + v = 3x fp32)."""
    if ctx.memory is None:
        return []
    dp = int(ctx.mesh_axes.get(ctx.dp_axis or "dp", 1))
    if dp <= 1:
        return []
    meta = ctx.meta or {}
    gc = meta.get("grad_comm") or {}
    zero = int(meta.get("zero", gc.get("zero", 0)) or 0)
    flat = bool(meta.get("flat_state", gc.get("flat", False)))
    if zero >= 1 or flat:
        # the state IS dp-sharded (by contract) — but zero>=3 claims
        # MORE: the working params shard too.  Resident param bytes at
        # (or above) the full replicated size mean the claim is hollow
        # while the memory pass keeps predicting the 1/dp saving.
        if zero >= 3:
            full = sum(p.nbytes for p in ctx.params if p.trainable)
            resident = int(ctx.memory.by_kind.get("param", 0))
            if full >= int(ctx.opt("param_bytes_threshold")) \
                    and resident >= full:
                return [Finding(
                    rule="", subject="param",
                    message=f"zero={zero} declares params sharded at "
                            f"rest, yet {_fmt_mem(resident)} of param "
                            f"buffers stay resident per rank (the "
                            f"trainable set is {_fmt_mem(full)} "
                            f"replicated): the at-rest saving the "
                            f"ZeRO-3 gather pays wire bytes for never "
                            f"materializes",
                    hint=f"keep only the flat master's P(dp) chunks "
                         f"resident (1/{dp} of these bytes) and gather "
                         f"working weights just-in-time (flat_state="
                         f"True routes this through param_gather)")]
        return []
    state_bytes = int(ctx.memory.by_kind.get("opt-state", 0))
    if state_bytes < int(ctx.opt("param_bytes_threshold")):
        return []
    return [Finding(
        rule="", subject="opt-state",
        message=f"{_fmt_mem(state_bytes)} of optimizer state is "
                f"replicated on every rank of a dp={dp} mesh (zero=0, "
                f"no flat state): {_fmt_mem(state_bytes * (dp - 1) // dp)}"
                f" per device is pure redundancy ZeRO would reclaim",
        hint=f"Optimizer(zero=1) dp-shards optimizer state, zero=2 "
             f"adds gradients, flat_state=True packs it into "
             f"reduce-scatter-geometry flat buckets (1/{dp} of these "
             f"bytes per device, checkpoint-compatible)")]


@rule("comm-bound-plan")
def _comm_bound_plan(ctx: AnalysisContext) -> List[Finding]:
    """Predicted collective time exceeds the compute/HBM roofline and
    the plan declares no overlap scheduling: the chips idle on the wire
    for most of every step.  The hint names the two levers that
    actually move comm time — a narrower transport (int8/bf16 wire
    bytes) and the coalesced bucketed sync the latency-hiding scheduler
    can overlap.  Sub-millisecond predicted steps are exempt (CI-scale
    toys are latency-dominated by construction)."""
    c = ctx.cost
    if c is None:
        return []
    if c.step_time_s < float(ctx.opt("comm_bound_min_step_s")):
        return []
    roofline = max(c.compute_time_s, c.io_time_s)
    ratio = float(ctx.opt("comm_bound_ratio"))
    if c.exposed_comm_s <= ratio * max(roofline, 1e-12):
        return []
    # name the widest exposed edge for the remedy
    widest = max((e for e in c.comm if not e.overlapped),
                 key=lambda e: e.total_s, default=None)
    w = f" (widest: {widest.kind} {widest.payload_bytes} B " \
        f"x{widest.count} over {widest.group} chips, " \
        f"{widest.total_s * 1e6:.0f}us)" if widest is not None else ""
    return [Finding(
        rule="", subject="step",
        message=f"predicted step time {c.step_time_s * 1e6:.0f}us is "
                f"comm-bound: {c.exposed_comm_s * 1e6:.0f}us of exposed "
                f"collective time vs a "
                f"{roofline * 1e6:.0f}us compute/HBM roofline, and the "
                f"plan declares no overlap scheduling{w}",
        hint="narrow the transport (Optimizer(grad_comm='int8'|'bf16') "
             "prices the wire at 1/4-1/2 the fp32 bytes) and coalesce "
             "into buckets (bucket_mb=) so the latency-hiding "
             "scheduler overlaps the sync with backward compute; for "
             "activation collectives, reshard less often or move the "
             "axis to a faster link")]


@rule("predicted-step-regression")
def _predicted_step_regression(ctx: AnalysisContext) -> List[Finding]:
    """Static step-time prediction vs the frozen per-executable
    baseline: growth beyond the tolerance is a perf regression the
    numeric tests cannot see (new FLOPs, lost fusion, a widened
    transport, an extra collective) — the time-plane twin of
    ``peak-memory-regression``."""
    base_map = ctx.opt("baseline_step_time_s")
    if ctx.cost is None or not base_map:
        return []
    base = base_map.get(ctx.name)
    if base is None or base <= 0:
        return []
    tol = float(ctx.opt("step_time_tolerance"))
    got = float(ctx.cost.step_time_s)
    if got <= base * (1.0 + tol):
        return []
    return [Finding(
        rule="", subject="step",
        message=f"predicted step time regressed "
                f"{base * 1e6:.1f}us -> {got * 1e6:.1f}us "
                f"({got / base - 1.0:+.1%}, tolerance {tol:.0%}); "
                f"now {ctx.cost.bound}-bound (compute "
                f"{ctx.cost.compute_time_s * 1e6:.1f}us, hbm "
                f"{ctx.cost.io_time_s * 1e6:.1f}us, comm "
                f"{ctx.cost.comm_time_s * 1e6:.1f}us)",
        hint="inspect the attribution table (--cost --explain) for the "
             "primitive or edge that grew; if the change is "
             "intentional, re-freeze with --update-baseline")]


@rule("kv-handoff-unpriced")
def _kv_handoff_unpriced(ctx: AnalysisContext) -> List[Finding]:
    """Disaggregated serving contract: every cross-replica KV-page move
    (the prefill→decode handoff) must carry a PRICED edge claim — a
    CommEdge-shaped dict whose payload matches the pages moved, plus
    the alpha-beta predicted seconds through the shared
    ``collective_time`` formulas.  A handoff without the claim is wire
    traffic the analysis plane cannot see: the whole point of the
    CPU-honest cluster design is that the page stream is priced BEFORE
    TPU hardware exists, so an unpriced move fails CI.  Executables
    with no ``kv_handoff`` meta (everything but cluster decode
    replicas) are out of scope.  Re-based on the unified event stream:
    the adapter carries each raw record on its ``wire.inject`` event."""
    if "kv_handoff" not in (ctx.meta or {}):
        return []
    events, lost = pe.collect_events(ctx)
    if "kv_handoff" in lost:
        return [Finding(
            rule="", subject="kv_handoff", severity="error",
            message="kv_handoff record hook raised — the handoff "
                    "accounting is lost, which is itself a gate "
                    "failure")]
    out: List[Finding] = []
    for i, rec in _plane_records(events, pe.WIRE_INJECT, "kv_handoff"):
        edge = rec.get("edge") or {}
        payload = int(rec.get("payload_bytes", 0) or 0)
        problems = []
        if not edge:
            problems.append("no edge claim")
        else:
            if int(edge.get("payload_bytes", 0) or 0) != payload:
                problems.append(
                    f"edge claims {edge.get('payload_bytes')} B but the "
                    f"move carried {payload} B")
            if not edge.get("kind"):
                problems.append("edge has no collective kind")
        if payload <= 0 and int(rec.get("pages", 0) or 0) > 0:
            problems.append("pages moved with zero payload bytes")
        pred = rec.get("predicted_s")
        if pred is None or float(pred) <= 0.0:
            problems.append("no alpha-beta predicted time")
        if not problems:
            continue
        out.append(Finding(
            rule="",
            subject=f"handoff@{i}:r{rec.get('src', '?')}->"
                    f"r{rec.get('dst', '?')}",
            severity="error",
            message=f"cross-replica KV-page move #{i} "
                    f"(r{rec.get('src', '?')} -> r{rec.get('dst', '?')},"
                    f" {rec.get('pages', '?')} pages) is unpriced: "
                    + "; ".join(problems),
            hint="route the move through a PageTransport that records "
                 "a priced edge claim (LocalPageTransport prices via "
                 "planner.cost_model.collective_time — the SAME "
                 "alpha-beta formulas the planner and step-time linter "
                 "use); a handoff the analysis plane cannot price "
                 "cannot be gated before hardware"))
    return out


@rule("host-offload-unpriced")
def _host_offload_unpriced(ctx: AnalysisContext) -> List[Finding]:
    """Host-RAM tier contract (the sibling of ``kv-handoff-unpriced``
    for the device↔host edge): every cold-page evict to host staging
    and every refetch back into the pool must carry a priced edge claim
    whose byte accounting is self-consistent — edge payload == record
    payload == pages x page_bytes — plus alpha-beta predicted seconds
    through the shared ``collective_time`` formulas.  MLA-latent and
    quantized pools price at their true (smaller) ``page_bytes``, so a
    mismatch means the tier moved bytes the analysis plane cannot see.
    Executables with no ``host_offload`` meta (engines without a host
    tier) are out of scope; records flagged ``host_offload_exempt``
    are skipped.  Re-based on the unified event stream: each move rides
    in on its ``host.stage`` / ``host.refetch`` event."""
    if "host_offload" not in (ctx.meta or {}):
        return []
    events, lost = pe.collect_events(ctx)
    if "host_offload" in lost:
        return [Finding(
            rule="", subject="host_offload", severity="error",
            message="host_offload record hook raised — the host-tier "
                    "accounting is lost, which is itself a gate "
                    "failure")]
    out: List[Finding] = []
    for i, rec in _plane_records(events,
                                 (pe.HOST_STAGE, pe.HOST_REFETCH),
                                 "host_offload"):
        if rec.get("host_offload_exempt"):
            continue
        edge = rec.get("edge") or {}
        payload = int(rec.get("payload_bytes", 0) or 0)
        pages = int(rec.get("pages", 0) or 0)
        page_bytes = int(rec.get("page_bytes", 0) or 0)
        problems = []
        if not edge:
            problems.append("no edge claim")
        else:
            if int(edge.get("payload_bytes", 0) or 0) != payload:
                problems.append(
                    f"edge claims {edge.get('payload_bytes')} B but the "
                    f"move carried {payload} B")
            if not edge.get("kind"):
                problems.append("edge has no collective kind")
        if pages > 0 and page_bytes > 0 \
                and payload != pages * page_bytes:
            problems.append(
                f"{pages} pages x {page_bytes} B/page = "
                f"{pages * page_bytes} B but the record claims "
                f"{payload} B — the tier moved bytes the claim "
                f"does not cover")
        if payload <= 0 and pages > 0:
            problems.append("pages moved with zero payload bytes")
        pred = rec.get("predicted_s")
        if pred is None or float(pred) <= 0.0:
            problems.append("no alpha-beta predicted time")
        if not problems:
            continue
        out.append(Finding(
            rule="",
            subject=f"host_offload@{i}:{rec.get('dir', '?')}",
            severity="error",
            message=f"host-tier page move #{i} "
                    f"({rec.get('dir', '?')}, {pages} pages) is "
                    f"unpriced: " + "; ".join(problems),
            hint="route host-tier moves through HostTier._price (it "
                 "claims a CommEdge-shaped dict tagged host_offload "
                 "and prices via planner.cost_model.collective_time — "
                 "the SAME formulas the handoff wire uses); flag "
                 "genuinely free moves host_offload_exempt"))
    return out


def _call_meta_records(meta, key: str):
    """Resolve a meta record hook (list or callable); ``None`` signals
    the hook raised — the accounting itself is lost."""
    records = (meta or {}).get(key)
    if callable(records):
        try:
            records = records()
        except Exception:
            return None, True
    return records, False


def _plane_records(events, kinds, plane: str):
    """Pull one plane's raw records back out of the unified event
    stream: events of the given kind(s) whose adapter attached the
    record (matched by provenance prefix so e.g. the handoff wire's
    ``wire.inject`` events never mix with another plane's), yielded in
    original record order."""
    if isinstance(kinds, str):
        kinds = (kinds,)
    got = [(e.attrs["index"], e.attrs["record"]) for e in events
           if e.kind in kinds and "record" in e.attrs
           and e.provenance.startswith(plane + "[")]
    return sorted(got, key=lambda t: t[0])


@rule("unfenced-handoff")
def _unfenced_handoff(ctx: AnalysisContext) -> List[Finding]:
    """Fencing contract of the fault plane (DESIGN.md §18): every
    cross-replica KV-page move AND every mid-flight request adoption
    must carry a fence token (``epoch``).  The token is what makes
    recovery idempotent — a revived TTL-expired replica, a
    re-registered rank, or a duplicated wire delivery is dropped by the
    ``(request id, epoch)`` dedup instead of double-delivering tokens.
    A move or adoption without the token is un-fenceable traffic: under
    any of those races it duplicates work, so it fails CI.  Records
    flagged ``fence_exempt`` (the monolithic-degrade path: a local
    re-prefill that never crosses pools) are exempt; executables with
    neither ``kv_handoff`` nor ``adoptions`` meta are out of scope.
    Re-based on the unified event stream (``wire.inject`` /
    ``req.adopt`` events carry the raw records)."""
    meta = ctx.meta or {}
    if "kv_handoff" not in meta and "adoptions" not in meta:
        return []
    events, lost_hooks = pe.collect_events(ctx)
    out: List[Finding] = []
    for key, kinds, what in (
            ("kv_handoff", pe.WIRE_INJECT,
             "cross-replica KV-page move"),
            ("adoptions", pe.REQ_ADOPT,
             "mid-flight request adoption")):
        if key not in meta:
            continue
        if key in lost_hooks:
            out.append(Finding(
                rule="", subject=key, severity="error",
                message=f"{key} record hook raised — the fencing "
                        "accounting is lost, which is itself a gate "
                        "failure"))
            continue
        for i, rec in _plane_records(events, kinds, key):
            if rec.get("fence_exempt"):
                continue
            epoch = rec.get("epoch")
            if isinstance(epoch, bool) or not isinstance(epoch, int):
                out.append(Finding(
                    rule="",
                    subject=f"{key}@{i}",
                    severity="error",
                    message=f"{what} #{i} "
                            f"(req {rec.get('req_id', '?')}, "
                            f"r{rec.get('src', '?')} -> "
                            f"r{rec.get('dst', '?')}) carries no "
                            f"epoch/fence token",
                    hint="stamp the move/adoption with its staging "
                         "epoch (PageTransport.inject(epoch=) / the "
                         "cluster's _land_handoff) so a revived "
                         "replica or a duplicated delivery is dropped "
                         "by the (request id, epoch) dedup instead of "
                         "double-delivering; flag genuinely local "
                         "same-pool moves fence_exempt"))
    return out


@rule("unverified-restore")
def _unverified_restore(ctx: AnalysisContext) -> List[Finding]:
    """Verified-restore contract of the durable checkpoint plane
    (DESIGN.md §19): every checkpoint restore that reaches tensor bytes
    must first check each shard's blake2b digest against the generation
    manifest — a restore without the check loads bit rot or a torn
    write silently, poisoning the very recovery path the fault plane
    leans on.  Restore records come from
    ``utils.checkpoint.restore_records`` via a ``restores`` meta hook
    (the fault-tolerant trainer attaches its own); records flagged
    ``verify_exempt`` (a deliberate raw load — e.g. importing a foreign
    checkpoint that has no manifest) are exempt.  Executables with no
    ``restores`` meta are out of scope."""
    meta = ctx.meta or {}
    if "restores" not in meta:
        return []
    records, lost = _call_meta_records(meta, "restores")
    if lost:
        return [Finding(
            rule="", subject="restores", severity="error",
            message="restore record hook raised — the restore audit "
                    "is lost, which is itself a gate failure")]
    out: List[Finding] = []
    for i, rec in enumerate(records or ()):
        if rec.get("verify_exempt"):
            continue
        if rec.get("verified"):
            continue
        out.append(Finding(
            rule="", subject=f"restore@{i}", severity="error",
            message=f"checkpoint restore #{i} from "
                    f"{rec.get('dir', '?')} (step {rec.get('step', '?')})"
                    f" read tensor bytes with NO digest check against a "
                    f"generation manifest — bit rot or a half-written "
                    f"shard restores garbage silently",
            hint="route the restore through "
                 "resilience.load_latest_generation (blake2b per-shard "
                 "digests vs the gen-<step>/ manifest, automatic "
                 "fallback past corrupted generations), or flag a "
                 "deliberate raw load with "
                 "load_checkpoint(..., verify_exempt=True)"))
    return out


@rule("cow-page-write")
def _cow_page_write(ctx: AnalysisContext) -> List[Finding]:
    """Copy-on-write contract over the paged pool: prefix-cache pages
    are read-only, so no live row's KV write plan may resolve to ANY
    cached page.  The engine snapshots cached-page refcounts into every
    unified tap record (membership alone proves the page is read-only:
    refcount 1 = cached with zero live sharers — the index still serves
    it to future lookups); a violation means a request's scatter is
    destroying KV history the cache (and possibly other live requests,
    refcount > 1) will read.  Re-based on the unified event stream: the
    tap adapter expands each row's write plan into per-page-span
    ``page.write`` events carrying the refcount snapshot, so this rule
    is a filter over one vocabulary instead of a private tap parser."""
    if ctx.serving is None:
        return []
    from ..serving.kv_pool import TRASH_PAGE
    events, _lost = pe.collect_events(ctx)
    out: List[Finding] = []
    flagged = set()                  # one finding per (step, row)
    for e in events:
        if e.kind != pe.PAGE_WRITE or e.attrs.get("src") != "unified":
            continue
        pg = int(e.attrs["page"])
        rc = e.attrs.get("refcount")
        step, row = e.attrs.get("tap_step"), e.attrs.get("row")
        if pg == TRASH_PAGE or rc is None or (step, row) in flagged:
            continue
        flagged.add((step, row))
        out.append(Finding(
            rule="", subject=f"unified@{step}/row{row}",
            severity="error", source=e.provenance,
            message=f"unified step at tap step {step}: row "
                    f"{row}'s KV write plan (pos "
                    f"{int(e.attrs['pos0'])}) targets page {pg} "
                    f"with refcount {int(rc)} — a "
                    f"read-only prefix-cache page; the "
                    f"write corrupts KV history the cache "
                    f"(and any live sharer) reads",
            hint="copy-on-write: start the request's write "
                 "cursor at the cached boundary (pos = "
                 "shared_pages * page_size) and allocate a "
                 "fresh page for the first partial/"
                 "divergent page — shared pages may only "
                 "ever be READ"))
    return out


@rule("spec-rewind-leak")
def _spec_rewind_leak(ctx: AnalysisContext) -> List[Finding]:
    """Speculative-decoding KV-rewind honesty (DESIGN.md §20): when a
    verify burst is partially rejected, the engine rewinds ``pos`` to
    the accepted boundary and the rejected positions' KV slots go STALE
    — they hold K/V of tokens that were never committed.  The contract
    that keeps temp-0 serving bitwise is that stale slots are always
    RE-WRITTEN (by the next burst's write plan, at the same page slots)
    before any attention window can read them.  This rule replays the
    engine's tap: per request it tracks the valid-KV watermark
    (advanced by each step's contiguous writes ``[pos, pos+qlen)``,
    cut back by every ``spec_rewind`` record, reset by ``kv_drop`` —
    preemption frees the pages outright), and fires when a step's read
    extent ``ctx`` reaches past what is valid-or-just-rewritten: that
    attention is consuming rejected-draft KV, which silently corrupts
    every token after it.  Records flagged ``rewind_exempt`` are
    skipped (a deliberate replay of foreign tap data).  Re-based on the
    event stream: the tap adapter emits ``req.rewind`` / ``req.preempt``
    / ``req.write`` events in tap order, so the watermark replay is a
    fold over three event kinds instead of a private tap parser."""
    if ctx.serving is None:
        return []
    events, _lost = pe.collect_events(ctx)
    out: List[Finding] = []
    valid: Dict[int, int] = {}
    for e in events:
        if not e.provenance.startswith("tap["):
            continue
        if e.kind == pe.REQ_REWIND:
            r = int(str(e.key).rsplit(":", 1)[1])
            cut = int(e.attrs["valid_upto"])
            valid[r] = min(valid.get(r, cut), cut)
            continue
        if e.kind == pe.REQ_PREEMPT:
            valid[int(str(e.key).rsplit(":", 1)[1])] = 0
            continue
        if e.kind != pe.REQ_WRITE or e.attrs.get("rewind_exempt"):
            continue
        r = int(str(e.key).rsplit(":", 1)[1])
        step = e.attrs["tap_step"]
        pos, qlen, ctx_len = (int(e.attrs["pos"]), int(e.attrs["qlen"]),
                              int(e.attrs["ctx_len"]))
        # first sight: positions [0, pos) predate the tap window
        # (or were handed off with the request) — trust them
        v = valid.get(r, pos)
        if pos <= v:
            after = max(v, pos + qlen)
        else:
            # a write GAP: [v, pos) stays stale, writes past it
            # cannot bridge the hole
            after = v
        if ctx_len > after:
            out.append(Finding(
                rule="", subject=f"unified@{step}/req{r}",
                severity="error", source=e.provenance,
                message=f"unified step at tap step {step}: request "
                        f"{r} reads KV through position "
                        f"{ctx_len - 1} but positions "
                        f"[{after}, {ctx_len}) were never "
                        f"(re)written after the last rewind — the "
                        f"attention window is consuming "
                        f"rejected-draft KV",
                hint="rewind must land exactly on the accepted "
                     "boundary (pos = committed tokens with valid "
                     "KV) so the next verify burst's write plan "
                     "covers every stale slot before the kernel "
                     "reads it; check _commit_verify's pos "
                     "arithmetic and that ctx_lens == pos + q_len "
                     "for every packed row"))
        valid[r] = after
    return out


@rule("trash-page-write")
def _trash_page_write(ctx: AnalysisContext) -> List[Finding]:
    if ctx.serving is None:
        return []
    from ..serving.kv_pool import TRASH_PAGE
    out = []
    pool = ctx.serving.get("pool")
    if pool is not None:
        if TRASH_PAGE in getattr(pool, "_free", ()):
            out.append(Finding(
                rule="", subject="free-list", severity="error",
                message="reserved trash page 0 is on the allocator "
                        "free-list — a future alloc() will hand it to a "
                        "request and real KV writes will land in the "
                        "padding sink"))
        if TRASH_PAGE in getattr(pool, "_allocated", ()):
            out.append(Finding(
                rule="", subject="allocated", severity="error",
                message="reserved trash page 0 is marked allocated — a "
                        "live request is scatter-writing the padding "
                        "sink"))
    # tap scan, re-based on the event stream: the tap adapter expands
    # every write plan (unified rows, prefill page lists, legacy decode
    # cursors) into ``page.write`` events tagged with their source, so
    # the trash-page check is one filter over ``page == 0``
    events, _lost = pe.collect_events(ctx)
    flagged = set()              # fire-once per (src, step, row)
    for e in events:
        if e.kind != pe.PAGE_WRITE or int(e.attrs["page"]) != TRASH_PAGE:
            continue
        src = e.attrs.get("src")
        step, row = e.attrs.get("tap_step"), e.attrs.get("row")
        if (src, step, row) in flagged:
            continue
        flagged.add((src, step, row))
        if src == "unified":
            out.append(Finding(
                rule="", subject=f"unified@{step}/row{row}",
                severity="error", source=e.provenance,
                message=f"unified step at tap step {step}: "
                        f"LIVE row {row} (pos {int(e.attrs['pos0'])})"
                        f" scatter-writes page 0 outside the"
                        f" padding path — its KV history is "
                        f"being destroyed"))
        elif src == "prefill":
            out.append(Finding(
                rule="", subject=f"prefill@{step}", severity="error",
                source=e.provenance,
                message=f"prefill at tap step {step} was handed page "
                        f"0 — its prompt KV overwrites the padding "
                        f"sink"))
        elif src == "decode":
            out.append(Finding(
                rule="", subject=f"decode@{step}/row{row}",
                severity="error", source=e.provenance,
                message=f"decode at tap step {step}: LIVE row {row} "
                        f"(pos {int(e.attrs['pos0'])}) scatter-writes "
                        f"page 0 outside the padding path — its KV "
                        f"history is being destroyed"))
    return out


# ---------------------------------------------------------------------------
# serving-protocol lifecycle rules (DESIGN.md §23)
# ---------------------------------------------------------------------------


def _protocol_replay(ctx: AnalysisContext):
    """Run the three lifecycle machines over the executable's normalized
    event stream ONCE (memoized on the context — the four lifecycle
    rules share one replay, like they share one ``collect_events``).

    ``strict_terminal=False``: a live executable's trace ends mid-flight
    (requests still decoding, pages legitimately allocated), so terminal
    page-conservation is NOT enforced here — that check belongs to
    COMPLETE traces, i.e. the bounded explorer and the fuzz gate, which
    replay with ``strict_terminal=True``."""
    cached = getattr(ctx, "_protocol_violations", None)
    if cached is not None:
        return cached
    events, _lost = pe.collect_events(ctx)
    violations = replay(events, strict_terminal=False)
    try:
        ctx._protocol_violations = violations
    except Exception:
        pass
    return violations


def _lifecycle_findings(ctx: AnalysisContext,
                        rule_name: str) -> List[Finding]:
    return [Finding(rule="", subject=v.subject, severity="error",
                    source=v.provenance, message=v.message,
                    hint=v.format_subtrace())
            for v in _protocol_replay(ctx) if v.rule == rule_name]


@rule(RULE_PAGE)
def _page_lifecycle_violation(ctx: AnalysisContext) -> List[Finding]:
    """Page lifecycle (free→allocated→cached→host-staged→free, trash
    page immutable) replayed over the event stream; one finding per
    broken page, carrying the page's own event subtrace."""
    return _lifecycle_findings(ctx, RULE_PAGE)


@rule(RULE_REQUEST)
def _request_lifecycle_violation(ctx: AnalysisContext) -> List[Finding]:
    """Request lifecycle (queued→running→preempted/handoff-staged→
    adopted→finished|shed): no double-adopt, no write / re-queue after
    finish, no adoption without a stage."""
    return _lifecycle_findings(ctx, RULE_REQUEST)


@rule(RULE_FENCE)
def _fence_regression(ctx: AnalysisContext) -> List[Finding]:
    """Fence epochs are monotone per replica and no stale-epoch
    completion/adoption is ever accepted past the death sweep."""
    return _lifecycle_findings(ctx, RULE_FENCE)


@rule(RULE_REFCOUNT)
def _refcount_leak(ctx: AnalysisContext) -> List[Finding]:
    """Prefix-cache sharer conservation: unshare never dips below zero
    and no cached page is dropped while sharers still read it (terminal
    conservation over complete traces lives in the explorer/fuzz gate,
    not here — live executables end mid-flight)."""
    return _lifecycle_findings(ctx, RULE_REFCOUNT)


# Every trace-replay rule → the event kinds it inspects.  The vacuity
# meta-test (tests/test_protocol.py) walks this registry and asserts the
# registered gate executables' traces contain at least one event of a
# kind each rule inspects — a rule whose input vocabulary never occurs
# in any gate trace is vacuous and its green is meaningless.  ``None``
# marks a rule that replays a RECORD plane (meta hook) rather than the
# event stream; the meta-test skips it with that reason.
TRACE_RULE_EVENT_KINDS: Dict[str, Optional[Tuple[str, ...]]] = {
    "trash-page-write": (pe.PAGE_WRITE,),
    "kv-handoff-unpriced": (pe.WIRE_INJECT,),
    "host-offload-unpriced": (pe.HOST_STAGE, pe.HOST_REFETCH),
    "unfenced-handoff": (pe.WIRE_INJECT, pe.REQ_ADOPT),
    "cow-page-write": (pe.PAGE_WRITE,),
    "spec-rewind-leak": (pe.REQ_WRITE,),
    RULE_PAGE: (pe.PAGE_ALLOC, pe.PAGE_FREE, pe.PAGE_CACHE,
                pe.HOST_STAGE, pe.HOST_REFETCH, pe.POOL_RESET),
    RULE_REQUEST: (pe.REQ_QUEUED, pe.REQ_ADMIT, pe.REQ_FINISH,
                   pe.REQ_SHED, pe.REQ_STAGE, pe.REQ_ADOPT),
    RULE_FENCE: (pe.FENCE_BUMP, pe.FENCE_COMPLETE, pe.FENCE_STALE_DROP,
                 pe.REQ_ADOPT, pe.WIRE_INJECT),
    RULE_REFCOUNT: (pe.PAGE_SHARE, pe.PAGE_UNSHARE),
    # record-plane rule: checkpoint restore records come from the meta
    # hook, not the serving event stream
    "unverified-restore": None,
}


# ---------------------------------------------------------------------------
# cross-rank collective-schedule rules (DESIGN.md §25)
# ---------------------------------------------------------------------------

from .schedule import (COLLECTIVE_KINDS as _SCHED_COLLECTIVES,  # noqa: E402
                       P2P_KINDS as _SCHED_P2P,
                       RULE_DEADLOCK as SCHED_RULE_DEADLOCK,
                       RULE_GROUP as SCHED_RULE_GROUP,
                       RULE_ORDER as SCHED_RULE_ORDER,
                       RULE_PAYLOAD as SCHED_RULE_PAYLOAD,
                       RULE_SWITCH as SCHED_RULE_SWITCH,
                       RULE_UNPAIRED as SCHED_RULE_UNPAIRED,
                       verify_context as _schedule_replay)


def _schedule_findings(ctx: AnalysisContext,
                       rule_name: str) -> List[Finding]:
    """The six schedule rules share ONE extraction + verification pass
    (memoized on the context by ``schedule.verify_context``), exactly
    like the lifecycle rules share one protocol replay."""
    return [Finding(rule="", subject=v.subject, severity="error",
                    source=v.provenance, message=v.message,
                    hint=v.format_subtrace())
            for v in _schedule_replay(ctx) if v.rule == rule_name]


@rule(SCHED_RULE_ORDER)
def _collective_order_mismatch(ctx: AnalysisContext) -> List[Finding]:
    """Every rank in a communicator group must issue the same
    collectives in the same order — a rank whose stream diverges in
    kind or count leaves its peers blocked in a rendezvous that never
    completes."""
    return _schedule_findings(ctx, SCHED_RULE_ORDER)


@rule(SCHED_RULE_GROUP)
def _collective_group_mismatch(ctx: AnalysisContext) -> List[Finding]:
    """Group tuples must agree across the members of every collective:
    two ranks that disagree on who participates each wait for a member
    that never arrives."""
    return _schedule_findings(ctx, SCHED_RULE_GROUP)


@rule(SCHED_RULE_PAYLOAD)
def _collective_payload_mismatch(ctx: AnalysisContext) -> List[Finding]:
    """Payload bytes / dtype / reduction must agree at every aligned
    position — shape disagreement hangs, dtype disagreement (one rank
    quantizing an EQuARX-style collective its peers run full-precision)
    silently corrupts the exchange."""
    return _schedule_findings(ctx, SCHED_RULE_PAYLOAD)


@rule(SCHED_RULE_UNPAIRED)
def _p2p_unpaired(ctx: AnalysisContext) -> List[Finding]:
    """Every pipeline p2p send must pair with a recv on the destination
    rank (per channel, by tag/payload/dtype) and vice versa — the
    unmatched side blocks forever."""
    return _schedule_findings(ctx, SCHED_RULE_UNPAIRED)


@rule(SCHED_RULE_DEADLOCK)
def _pipeline_deadlock(ctx: AnalysisContext) -> List[Finding]:
    """The per-rank schedules are simulated under rendezvous-collective
    / buffered-send / blocking-recv semantics; a stall is reported with
    the wait-for cycle over pipeline stages x collectives."""
    return _schedule_findings(ctx, SCHED_RULE_DEADLOCK)


@rule(SCHED_RULE_SWITCH)
def _switch_repack_divergence(ctx: AnalysisContext) -> List[Finding]:
    """Hot-switch repack transfers (flat-state dp resize) must agree
    between the sending and receiving side — a divergent plan leaves
    stale or missing optimizer shards after the switch."""
    return _schedule_findings(ctx, SCHED_RULE_SWITCH)


# Every schedule rule → the CommOp kinds it inspects, mirroring
# TRACE_RULE_EVENT_KINDS: the vacuity meta-test
# (tests/test_schedule_verifier.py) asserts each rule (a) fires on its
# seeded-bug corpus entry and ONLY that rule fires there, (b) stays
# silent on the frozen clean strategy grid, and (c) inspects op kinds
# that actually occur in the gate schedules — a rule whose input
# vocabulary never occurs is vacuously green.
SCHEDULE_RULE_OP_KINDS: Dict[str, Tuple[str, ...]] = {
    SCHED_RULE_ORDER: _SCHED_COLLECTIVES,
    SCHED_RULE_GROUP: _SCHED_COLLECTIVES,
    SCHED_RULE_PAYLOAD: _SCHED_COLLECTIVES,
    SCHED_RULE_UNPAIRED: _SCHED_P2P,
    SCHED_RULE_DEADLOCK: _SCHED_P2P + _SCHED_COLLECTIVES,
    SCHED_RULE_SWITCH: _SCHED_P2P + ("copy",),
}
