"""Low-overhead structured span tracer (the runtime trace plane).

The analysis plane (PR 3/5/8) predicts what an executable *will* do;
this module records what actually *happened* and when: nested wall-time
spans, instant events, and retroactive complete spans, all carrying
free-form attributes, buffered in a capped ring.  Two consumers sit on
top (``obs/export.py``): Chrome trace-event JSON for Perfetto and a
JSONL journal readable with ``utils.metrics.load_jsonl``; a third
(``obs/reconcile.py``) joins spans tagged with an ``exec`` attribute
against the static per-executable predictions.

Cost model, same pattern as ``utils.metrics.NULL_INSTRUMENT``: the
module-global default tracer is a shared no-op (``NULL_TRACER``), every
emission site in the engine/train hot loops guards on ``tracer.enabled``
and every no-op method swallows its arguments — disabled tracing costs
a couple of attribute reads per *step* (asserted < 2% on the serving
microbench, BENCH_OBS.json).  A real :class:`SpanTracer` can also be
switched off in place (``tracer.enabled = False``) without losing its
buffer.

    from hetu_tpu.obs import trace, chrome_trace
    with trace() as tr:
        with tr.span("outer", track="work", phase=1):
            tr.instant("milestone", done=3)
    json.dump(chrome_trace(tr.events()), open("trace.json", "w"))

Clocks: spans stamped through :meth:`SpanTracer.now` (``time.monotonic``
unless a ``time_fn`` is injected).  Components with their own clock
(e.g. ``serving.Engine(time_fn=...)``) pass explicit ``ts`` values so
one consistent timeline survives synthetic test clocks.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Span", "SpanTracer", "PrefixedTracer", "NULL_TRACER",
           "NOOP_SPAN", "get_tracer", "install_tracer", "trace"]


class Span:
    """One finished-or-open event.  ``ph`` follows the chrome trace
    phase letters: "X" complete span, "i" instant."""

    __slots__ = ("name", "track", "ts", "dur", "ph", "attrs", "parent",
                 "_tracer")

    def __init__(self, name: str, track: str, ts: float,
                 attrs: Dict[str, Any], parent: Optional[str] = None,
                 tracer: Optional["SpanTracer"] = None, ph: str = "X"):
        self.name = name
        self.track = track
        self.ts = float(ts)
        self.dur: Optional[float] = None        # None while open / instant
        self.ph = ph
        self.attrs = attrs
        self.parent = parent                    # parent span NAME (nesting)
        self._tracer = tracer

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    @property
    def end_ts(self) -> float:
        return self.ts + (self.dur or 0.0)

    # with tracer.span(...) as sp: ...
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        if self._tracer is not None:
            self._tracer.end(self)

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, track={self.track!r}, ts={self.ts}, "
                f"dur={self.dur})")


class _NoopSpan:
    """Shared stand-in when tracing is disabled: absorbs everything."""

    __slots__ = ()
    name = track = parent = ""
    ts = 0.0
    dur: Optional[float] = None
    ph = "X"
    attrs: Dict[str, Any] = {}

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class SpanTracer:
    """Thread-safe span recorder with a capped ring buffer.

    Per-thread open-span stacks give parent/child nesting without any
    cross-thread coordination; finished events land in one shared deque
    (capacity-capped — overflow drops the OLDEST events and counts them
    in ``dropped``, so a long-running service never grows unbounded).
    """

    def __init__(self, capacity: int = 65536,
                 time_fn: Optional[Callable[[], float]] = None):
        self.enabled = True
        self.capacity = int(capacity)
        self._time = time_fn or time.monotonic
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.dropped = 0

    # -- clock ---------------------------------------------------------------

    def now(self) -> float:
        return self._time()

    # -- recording -----------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, name: str, track: Optional[str] = None,
              ts: Optional[float] = None, **attrs: Any) -> Span:
        """Open a span; nest under the current thread's innermost open
        span (inheriting its track unless one is given)."""
        if not self.enabled:
            return NOOP_SPAN
        st = self._stack()
        parent = st[-1] if st else None
        if track is None:
            track = parent.track if parent is not None \
                else threading.current_thread().name
        sp = Span(name, track, self.now() if ts is None else ts, attrs,
                  parent=parent.name if parent is not None else None,
                  tracer=self)
        st.append(sp)
        return sp

    def end(self, span: Span, ts: Optional[float] = None,
            **attrs: Any) -> None:
        """Close ``span`` and commit it to the ring.  Out-of-order ends
        pop (and discard) any spans opened after it on this thread;
        ending an already-ended span is a no-op (so a ``finally`` can
        close the outermost span unconditionally) — never raise from an
        emission site."""
        if not isinstance(span, Span) or span.dur is not None:
            return                    # NOOP_SPAN / disabled / re-ended
        st = self._stack()
        if span in st:
            while st and st.pop() is not span:
                pass
        end_ts = self.now() if ts is None else ts
        span.dur = max(0.0, end_ts - span.ts)
        if attrs:
            span.attrs.update(attrs)
        self._push(span)

    def span(self, name: str, track: Optional[str] = None,
             ts: Optional[float] = None, **attrs: Any) -> Span:
        """``with tracer.span("phase"):`` — begin() returning the
        context-managed span (its ``__exit__`` calls :meth:`end`)."""
        return self.begin(name, track=track, ts=ts, **attrs)

    def instant(self, name: str, track: Optional[str] = None,
                ts: Optional[float] = None, **attrs: Any) -> None:
        """A zero-duration point event."""
        if not self.enabled:
            return
        if track is None:
            st = self._stack()
            track = st[-1].track if st else threading.current_thread().name
        self._push(Span(name, track, self.now() if ts is None else ts,
                        attrs, ph="i"))

    def complete(self, name: str, ts: float, dur: float,
                 track: Optional[str] = None, **attrs: Any) -> None:
        """Commit a retroactive closed span (caller supplies both
        endpoints — e.g. a queue-wait interval known only at admission)."""
        if not self.enabled:
            return
        if track is None:
            track = threading.current_thread().name
        sp = Span(name, track, ts, attrs)
        sp.dur = max(0.0, float(dur))
        self._push(sp)

    def _push(self, ev: Span) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
            self._buf.append(ev)

    # -- reading -------------------------------------------------------------

    def events(self) -> List[Span]:
        """Snapshot of the committed events (insertion order)."""
        with self._lock:
            return list(self._buf)

    def open_count(self) -> int:
        """Open (un-ended) spans on the CALLING thread — 0 after a
        well-bracketed trace."""
        return len(self._stack())

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def __len__(self) -> int:
        return len(self._buf)


class _NullTracer:
    """Shared no-op tracer: the engine/train hot loops see
    ``enabled == False`` and every method swallows its arguments — the
    disabled path costs a guard, nothing else."""

    enabled = False
    capacity = 0
    dropped = 0

    def now(self) -> float:
        return 0.0

    def begin(self, name: str, track: Optional[str] = None,
              ts: Optional[float] = None, **attrs: Any) -> _NoopSpan:
        return NOOP_SPAN

    def end(self, span, ts: Optional[float] = None, **attrs: Any) -> None:
        pass

    def span(self, name: str, track: Optional[str] = None,
             ts: Optional[float] = None, **attrs: Any) -> _NoopSpan:
        return NOOP_SPAN

    def instant(self, name: str, track: Optional[str] = None,
                ts: Optional[float] = None, **attrs: Any) -> None:
        pass

    def complete(self, name: str, ts: float, dur: float,
                 track: Optional[str] = None, **attrs: Any) -> None:
        pass

    def events(self) -> List[Span]:
        return []

    def open_count(self) -> int:
        return 0

    def clear(self) -> None:
        pass

    def __len__(self) -> int:
        return 0


class PrefixedTracer:
    """A view onto another tracer that namespaces every track.

    The serving cluster hands each replica engine
    ``PrefixedTracer(shared, "r0/")`` so N engines' identically-named
    tracks (``engine``, ``scheduler``, ``req 3``) land as distinct
    ``r0/engine`` / ``r1/engine`` rows in ONE merged Perfetto trace —
    the engines need no cluster awareness and the router's own
    ``router`` track sits alongside.  Purely a pass-through otherwise:
    ``enabled`` follows the base tracer live (toggling the shared
    tracer toggles every replica view), events land in the base ring.
    """

    __slots__ = ("base", "prefix")

    def __init__(self, base, prefix: str):
        self.base = base
        self.prefix = str(prefix)

    @property
    def enabled(self) -> bool:
        return self.base.enabled

    @property
    def dropped(self) -> int:
        return self.base.dropped

    def _track(self, track: Optional[str]) -> Optional[str]:
        return None if track is None else self.prefix + track

    def now(self) -> float:
        return self.base.now()

    def begin(self, name: str, track: Optional[str] = None,
              ts: Optional[float] = None, **attrs: Any):
        return self.base.begin(name, track=self._track(track), ts=ts,
                               **attrs)

    def end(self, span, ts: Optional[float] = None, **attrs: Any) -> None:
        self.base.end(span, ts=ts, **attrs)

    def span(self, name: str, track: Optional[str] = None,
             ts: Optional[float] = None, **attrs: Any):
        return self.base.span(name, track=self._track(track), ts=ts,
                              **attrs)

    def instant(self, name: str, track: Optional[str] = None,
                ts: Optional[float] = None, **attrs: Any) -> None:
        self.base.instant(name, track=self._track(track), ts=ts, **attrs)

    def complete(self, name: str, ts: float, dur: float,
                 track: Optional[str] = None, **attrs: Any) -> None:
        self.base.complete(name, ts, dur, track=self._track(track),
                           **attrs)

    def events(self) -> List[Span]:
        return self.base.events()

    def open_count(self) -> int:
        return self.base.open_count()

    def clear(self) -> None:
        self.base.clear()

    def __len__(self) -> int:
        return len(self.base)


NULL_TRACER = _NullTracer()

# process-global default consulted by every instrumented component
# (serving.Engine, DefineAndRunGraph.run, the MPMD pipeline runtime)
# unless an explicit tracer was injected
_GLOBAL: List[Any] = [NULL_TRACER]


def get_tracer():
    """The ambient tracer (``NULL_TRACER`` unless one is installed)."""
    return _GLOBAL[0]


def install_tracer(tracer) -> Any:
    """Install ``tracer`` as the ambient tracer (None restores the
    no-op); returns the previous one so callers can restore it."""
    prev = _GLOBAL[0]
    _GLOBAL[0] = tracer if tracer is not None else NULL_TRACER
    return prev


@contextmanager
def trace(capacity: int = 65536,
          time_fn: Optional[Callable[[], float]] = None, tracer=None):
    """``with trace() as tr:`` — install a fresh :class:`SpanTracer`
    (or the one given) for the dynamic extent, restoring the previous
    ambient tracer on exit."""
    tr = tracer if tracer is not None else SpanTracer(capacity, time_fn)
    prev = install_tracer(tr)
    try:
        yield tr
    finally:
        install_tracer(prev)
