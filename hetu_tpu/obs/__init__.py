"""Runtime trace plane: span tracer, Perfetto export, reconciliation.

The observability counterpart of ``hetu_tpu/analysis`` (DESIGN.md §15):

* :mod:`.tracer` — low-overhead structured spans (monotonic clock,
  parent/child nesting, instant events, capped ring buffer,
  thread-safe) with a shared no-op ``NULL_TRACER`` so disabled tracing
  costs ~nothing in the serving/train hot loops;
* :mod:`.export` — Chrome trace-event JSON (loadable in Perfetto, one
  track per serving request / per training phase) and a JSONL journal
  readable with ``utils.metrics.load_jsonl``;
* :mod:`.reconcile` — joins observed per-executable wall time and
  device memory peaks against the analysis plane's static wire-byte and
  peak-HBM predictions.

Instrumented out of the box: ``serving.Engine`` (full per-request
lifecycle: queue wait, admission + page accounting, prefix-cache
hit/evict, prefill chunks, decode tokens, preemption, finish, plus the
scheduler's per-step packing decision), ``DefineAndRunGraph.run``
(per-step feed / executable / commit phases with grad-comm
attribution), ``switch_strategy`` and the MPMD pipeline task loop.
"""
from .export import (chrome_trace, events_to_jsonl, request_timelines,
                     timeline_summary, validate_chrome_trace,
                     write_chrome_trace, write_jsonl)
from .reconcile import (ReconcileReport, ReconcileRow, predicted_stats,
                        reconcile)
from .tracer import (NOOP_SPAN, NULL_TRACER, PrefixedTracer, Span,
                     SpanTracer, get_tracer, install_tracer, trace)

__all__ = [
    "Span", "SpanTracer", "PrefixedTracer", "NULL_TRACER", "NOOP_SPAN",
    "get_tracer", "install_tracer", "trace",
    "chrome_trace", "write_chrome_trace", "events_to_jsonl", "write_jsonl",
    "validate_chrome_trace", "request_timelines", "timeline_summary",
    "ReconcileReport", "ReconcileRow", "predicted_stats", "reconcile",
]
