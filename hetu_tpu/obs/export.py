"""Trace exporters: Chrome trace-event JSON (Perfetto) + JSONL journal.

Two formats over the same :class:`~hetu_tpu.obs.tracer.Span` stream:

* :func:`chrome_trace` — the Chrome trace-event format
  (``{"traceEvents": [...]}``) loadable directly in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``.  Each tracer
  *track* becomes a named thread row — serving runs get one track per
  request (``req N``) plus ``engine``/``scheduler`` rows, training runs
  get per-phase ``train`` / ``pipeN/stageM`` rows.  Timestamps convert
  to microseconds (the format's unit).
* :func:`write_jsonl` — a flat one-event-per-line journal readable with
  ``utils.metrics.load_jsonl`` (the repo's interchange format), for
  continuous shipping / offline joins.

Plus the serving-timeline views the examples and the gapless-timeline
CI gate share: :func:`request_timelines` / :func:`timeline_summary`.
"""
from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

from .tracer import Span

__all__ = ["chrome_trace", "write_chrome_trace", "events_to_jsonl",
           "write_jsonl", "validate_chrome_trace", "request_timelines",
           "timeline_summary"]


def _jsonable(v: Any) -> Any:
    """Attrs may carry numpy/jax scalars; coerce to plain JSON types."""
    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
    except Exception:
        pass
    try:
        return float(v)
    except Exception:
        return str(v)


def chrome_trace(events: Sequence[Span], pid: int = 0,
                 process_name: str = "hetu-tpu") -> Dict[str, Any]:
    """Render events as a chrome-trace document.

    Every emitted record (metadata included) carries ``pid``/``tid``/
    ``ts``/``ph`` so schema validation is uniform; complete spans add
    ``dur``.  Track rows keep first-appearance order via
    ``thread_sort_index`` metadata, so Perfetto shows the engine row
    above the per-request rows in arrival order.
    """
    out: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "ts": 0, "args": {"name": process_name}}]
    tids: "OrderedDict[str, int]" = OrderedDict()

    def tid_for(track: str) -> int:
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "ts": 0, "args": {"name": track}})
            out.append({"ph": "M", "name": "thread_sort_index", "pid": pid,
                        "tid": tid, "ts": 0, "args": {"sort_index": tid}})
        return tid

    for ev in sorted(events, key=lambda e: (e.ts, e.end_ts)):
        rec: Dict[str, Any] = {
            "name": ev.name, "cat": ev.track, "pid": pid,
            "tid": tid_for(ev.track), "ts": round(ev.ts * 1e6, 3),
            "args": {k: _jsonable(v) for k, v in ev.attrs.items()}}
        if ev.ph == "i":
            rec["ph"] = "i"
            rec["s"] = "t"                     # thread-scoped instant
        else:
            rec["ph"] = "X"
            rec["dur"] = round(max(ev.dur or 0.0, 0.0) * 1e6, 3)
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(events: Sequence[Span], path: str,
                       pid: int = 0) -> Dict[str, Any]:
    doc = chrome_trace(events, pid=pid)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def validate_chrome_trace(doc: Dict[str, Any]) -> None:
    """Schema check (raises AssertionError): every event has
    pid/tid/ts/ph; complete events carry a non-negative dur; instants
    carry a scope; metadata names are known."""
    assert "traceEvents" in doc and isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        for k in ("pid", "tid", "ts", "ph", "name"):
            assert k in ev, f"event missing {k!r}: {ev}"
        if ev["ph"] == "X":
            assert "dur" in ev and ev["dur"] >= 0, ev
        elif ev["ph"] == "i":
            assert ev.get("s") in ("t", "p", "g"), ev
        elif ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name",
                                  "thread_sort_index"), ev
        else:
            raise AssertionError(f"unknown phase {ev['ph']!r}")


# -- JSONL journal -----------------------------------------------------------


def events_to_jsonl(events: Sequence[Span]) -> List[Dict[str, Any]]:
    """One flat dict per event, ``step``-keyed (emission index) so the
    stream round-trips through ``utils.metrics.load_jsonl``."""
    out = []
    for i, ev in enumerate(events):
        out.append({"step": i, "name": ev.name, "track": ev.track,
                    "ph": ev.ph, "ts": ev.ts,
                    "dur": ev.dur if ev.ph == "X" else None,
                    "attrs": {k: _jsonable(v) for k, v in ev.attrs.items()}})
    return out


def write_jsonl(events: Sequence[Span], path: str) -> None:
    with open(path, "w") as f:
        for rec in events_to_jsonl(events):
            f.write(json.dumps(rec) + "\n")


# -- serving per-request timelines -------------------------------------------


def request_timelines(events: Sequence[Span]
                      ) -> Dict[int, List[Span]]:
    """Group serving events by request: every event on a ``req N``
    track (the engine stamps ``req`` in the attrs too), ordered by
    start time.  At equal timestamps instants sort before the span
    OPENING there (and stable sort keeps emission order among
    instants), so a lifecycle reads enqueue -> queued -> admit -> ...
    -> finish."""
    by_req: Dict[int, List[Span]] = {}
    for ev in events:
        rid = ev.attrs.get("req")
        if rid is None and ev.track.startswith("req "):
            try:
                rid = int(ev.track.split()[1])
            except (IndexError, ValueError):
                continue
        if rid is None:
            continue
        by_req.setdefault(int(rid), []).append(ev)
    for evs in by_req.values():
        evs.sort(key=lambda e: (e.ts, 0 if e.ph == "i" else 1, e.end_ts))
    return by_req


def timeline_summary(events: Sequence[Span]) -> str:
    """Human-readable per-request lifecycle table (the ``--trace-out``
    demo print): queue wait, prefill chunks, tokens, preemptions,
    speculative verify bursts + draft tokens accepted through them
    (the ``spec_accept`` instants of DESIGN.md §20 — a timeline shows
    the draft→verify→accept cadence directly), end-to-end latency —
    all derived from the trace, not the engine.  The ``class`` column
    is the request's SLO class, read from its ``enqueue`` instant
    (DESIGN.md §22) — ``-`` for traces predating the traffic plane."""
    lines = [f"{'req':>4} {'class':>11} {'queued_s':>9} {'chunks':>6} "
             f"{'tokens':>6} {'preempt':>7} {'verify':>6} "
             f"{'spec_acc':>8} {'e2e_s':>8}  timeline"]
    for rid, evs in sorted(request_timelines(events).items()):
        queued = sum(e.dur or 0.0 for e in evs
                     if e.ph == "X" and e.name == "queued")
        chunks = sum(1 for e in evs if e.name == "prefill_chunk")
        tokens = sum(1 for e in evs if e.name == "token")
        preempt = sum(1 for e in evs if e.name == "preempt")
        verify = sum(1 for e in evs if e.name == "verify")
        spec_acc = sum(int(e.attrs.get("n", 0)) for e in evs
                       if e.name == "spec_accept")
        slo = next((e.attrs["slo_class"] for e in evs
                    if e.name == "enqueue" and "slo_class" in e.attrs),
                   "-")
        t0 = min(e.ts for e in evs)
        t1 = max(e.end_ts for e in evs)
        path = "->".join(e.name for e in evs
                         if e.name in ("enqueue", "admit", "preempt",
                                       "finish"))
        lines.append(f"{rid:>4} {slo:>11} {queued:>9.3f} {chunks:>6} "
                     f"{tokens:>6} {preempt:>7} {verify:>6} "
                     f"{spec_acc:>8} {t1 - t0:>8.3f}  {path}")
    return "\n".join(lines)
