"""Predicted-vs-observed reconciliation: close the analysis-plane loop.

The static analysis plane predicts, per registered executable, the
collective set + wire bytes (``analysis/edges.py``) and the peak HBM
(``analysis/memory.py``) — without running anything.  The trace plane
records, per executable *call*, the observed wall time (spans whose
attrs carry ``exec=<registered name>``) and the device allocator's peak
(``utils.profiler.device_memory_stats``).  This module joins the two
into one table — the artifact ROADMAP item 5's hardware-validation
sweep freezes as evidence, runnable today on CPU with honest
expectations (the CPU sim exposes no allocator stats, so the HBM column
reads ``n/a`` instead of a fake zero-delta pass).

    with trace() as tr:
        ... run serving / training ...
        rep = reconcile(tr.events())
    print(rep.summary())

Observed peak memory is a PROCESS-wide allocator high-water mark, not
per-executable: the per-row check is therefore one-sided — a predicted
peak LARGER than the observed process peak is a real model error
(flagged), a smaller one is expected (other executables share the
device).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["predicted_stats", "predicted_span_attrs", "reconcile",
           "ReconcileRow", "ReconcileReport", "clear_prediction_cache"]

# the stats a traced executable span carries, span-attr name -> the
# predicted_stats key it projects (ONE mapping for every emission site)
_SPAN_ATTR_KEYS = (("predicted_wire_bytes", "wire_bytes"),
                   ("predicted_peak_hbm_bytes", "peak_hbm_bytes"),
                   ("predicted_step_time_s", "step_time_s"))

# predictions require tracing+lowering the executable — cached per
# registered name so the engine hot loop pays once per process; the
# entry remembers WHICH handle it priced, so a re-registered name
# (new engine, new graph plan) recomputes instead of serving stale
# numbers
_PRED_CACHE: Dict[str, Any] = {}


def clear_prediction_cache(prefix: str = "") -> None:
    """Drop cached predictions whose executable name starts with
    ``prefix``.  ``graph.clear_executables`` calls this with the same
    prefix, so retiring an engine (``unregister_analysis`` / same-name
    reconstruction) releases the handle — and the KV pool its meta
    closes over — instead of pinning it here forever."""
    for name in [n for n in _PRED_CACHE if n.startswith(prefix)]:
        del _PRED_CACHE[name]


def predicted_stats(name_or_handle) -> Dict[str, Optional[int]]:
    """Static per-executable cost facts: ``wire_bytes`` (sum over the
    predicted comm-edge set; None when the executable makes no edge
    claim), ``peak_hbm_bytes`` (native-dtype static peak) and
    ``cmp_peak_bytes`` (platform-comparable peak).  Cached by name;
    failures degrade to None fields — a broken prediction must never
    take down the traced run."""
    from ..graph.graph import get_executable
    handle = name_or_handle
    if isinstance(name_or_handle, str):
        try:
            handle = get_executable(name_or_handle)
        except KeyError:
            return {"wire_bytes": None, "peak_hbm_bytes": None,
                    "cmp_peak_bytes": None}
    cached = _PRED_CACHE.get(handle.name)
    if cached is not None and cached[0] is handle:
        return cached[1]
    from ..analysis import predicted_cost_stats
    try:
        stats = predicted_cost_stats(handle)
    except Exception:
        stats = {"wire_bytes": None, "peak_hbm_bytes": None,
                 "cmp_peak_bytes": None}
    _PRED_CACHE[handle.name] = (handle, stats)
    return stats


def predicted_span_attrs(name_or_handle) -> Dict[str, Any]:
    """:func:`predicted_stats` projected into the span-attribute
    namespace (``predicted_*`` keys, None fields dropped) — the single
    mapping both the serving engine and the train loop attach to their
    executable spans."""
    p = predicted_stats(name_or_handle)
    return {attr: p[key] for attr, key in _SPAN_ATTR_KEYS
            if p.get(key) is not None}


@dataclasses.dataclass
class ReconcileRow:
    """One executable's predicted-vs-observed join."""
    executable: str
    calls: int = 0
    total_wall_s: float = 0.0
    mean_wall_s: float = 0.0
    p90_wall_s: float = 0.0
    predicted_wire_bytes: Optional[int] = None
    predicted_peak_hbm_bytes: Optional[int] = None
    cmp_peak_bytes: Optional[int] = None
    observed_peak_hbm_bytes: int = 0          # process-wide allocator peak
    hbm_check: str = "n/a"                    # ok|over-predicted|n/a
    tokens: int = 0                           # serving spans carry tokens
    # static step-time prediction (analysis/cost roofline + comm) and
    # its decomposition; wall_ratio = observed mean wall / predicted.
    # Off-TPU the chip-spec prediction has no absolute meaning, so the
    # column reports the RATIO only — no pass/fail verdict (a CPU run
    # that "passed" an absolute-time gate would be lying)
    predicted_step_s: Optional[float] = None
    predicted_compute_s: Optional[float] = None
    predicted_comm_s: Optional[float] = None
    predicted_bound: Optional[str] = None
    wall_ratio: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class ReconcileReport:
    def __init__(self, rows: List[ReconcileRow], platform: str = "",
                 observed_peak_hbm_bytes: int = 0):
        self.rows = rows
        self.platform = platform
        self.observed_peak_hbm_bytes = observed_peak_hbm_bytes

    @property
    def families(self) -> int:
        return len(self.rows)

    def to_dict(self) -> Dict[str, Any]:
        return {"platform": self.platform,
                "observed_peak_hbm_bytes": int(self.observed_peak_hbm_bytes),
                "rows": [r.to_dict() for r in self.rows]}

    def summary(self) -> str:
        def fmt_b(v) -> str:
            if v is None:
                return "-"
            from ..analysis.memory import _fmt_bytes
            return _fmt_bytes(v)

        def fmt_ms(v) -> str:
            return "-" if v is None else f"{v * 1e3:.2f}"

        def fmt_x(v) -> str:
            return "-" if v is None else f"{v:.1f}x"

        lines = [f"{'executable':<28}{'calls':>6}{'mean_ms':>9}"
                 f"{'p90_ms':>8}{'pred_ms':>9}{'wall/pred':>10}"
                 f"{'pred_wire':>11}{'pred_peak':>11}"
                 f"{'obs_peak':>10}  hbm"]
        for r in self.rows:
            lines.append(
                f"{r.executable[:27]:<28}{r.calls:>6}"
                f"{r.mean_wall_s * 1e3:>9.2f}{r.p90_wall_s * 1e3:>8.2f}"
                f"{fmt_ms(r.predicted_step_s):>9}"
                f"{fmt_x(r.wall_ratio):>10}"
                f"{fmt_b(r.predicted_wire_bytes):>11}"
                f"{fmt_b(r.predicted_peak_hbm_bytes):>11}"
                f"{fmt_b(r.observed_peak_hbm_bytes):>10}  {r.hbm_check}")
        if not self.observed_peak_hbm_bytes:
            lines.append("(no device allocator stats on this platform — "
                         "HBM reconciliation is n/a; run on TPU for the "
                         "memory verdict)")
        if any(r.wall_ratio is not None for r in self.rows):
            lines.append("(wall/pred is a RATIO against the chip-spec "
                         "step-time model — off-TPU it has no absolute "
                         "meaning and carries no pass/fail verdict)")
        return "\n".join(lines)


def reconcile(events: Sequence, prefix: str = "",
              device=None) -> ReconcileReport:
    """Join traced executable spans against the static predictions.

    ``events``: tracer events (a :class:`SpanTracer` works too).  Spans
    are grouped by their ``exec`` attr (the registered executable name,
    optionally filtered by ``prefix``); observed wall time is the span
    durations, observed memory the live allocator peak."""
    from ..utils.profiler import device_memory_stats
    if hasattr(events, "events"):
        events = events.events()
    walls: Dict[str, List[float]] = {}
    tokens: Dict[str, int] = {}
    for ev in events:
        name = ev.attrs.get("exec")
        if name is None or ev.ph != "X" or not str(name).startswith(prefix):
            continue
        walls.setdefault(str(name), []).append(ev.dur or 0.0)
        tokens[str(name)] = tokens.get(str(name), 0) \
            + int(ev.attrs.get("tokens", 0) or 0)
    mem = device_memory_stats(device)
    peak = int(mem.get("peak_bytes_in_use", 0))
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:
        platform = "?"
    from ..utils.metrics import percentile_of
    rows: List[ReconcileRow] = []
    for name in sorted(walls):
        ws = sorted(walls[name])
        pred = predicted_stats(name)
        row = ReconcileRow(
            executable=name, calls=len(ws),
            total_wall_s=float(sum(ws)),
            mean_wall_s=float(sum(ws) / len(ws)),
            p90_wall_s=float(percentile_of(ws, 90)),
            predicted_wire_bytes=pred.get("wire_bytes"),
            predicted_peak_hbm_bytes=pred.get("peak_hbm_bytes"),
            cmp_peak_bytes=pred.get("cmp_peak_bytes"),
            observed_peak_hbm_bytes=peak,
            tokens=tokens.get(name, 0),
            predicted_step_s=pred.get("step_time_s"),
            predicted_compute_s=pred.get("compute_time_s"),
            predicted_comm_s=pred.get("comm_time_s"),
            predicted_bound=pred.get("bound"))
        if row.predicted_step_s and row.predicted_step_s > 0:
            row.wall_ratio = row.mean_wall_s / row.predicted_step_s
        if peak <= 0 or row.predicted_peak_hbm_bytes is None:
            row.hbm_check = "n/a"
        elif row.predicted_peak_hbm_bytes > peak:
            # one-sided: the static peak can never exceed what the
            # allocator actually high-watered across the whole process
            row.hbm_check = "over-predicted"
        else:
            row.hbm_check = "ok"
        rows.append(row)
    return ReconcileReport(rows, platform=platform,
                           observed_peak_hbm_bytes=peak)
