"""Fault plane: deterministic chaos injection + fenced recovery.

The robustness half of scale-out (ROADMAP items 1 and 3, DESIGN.md
§18): a seeded :class:`FaultPlan` schedules replica crashes, zombies
(heartbeat stall while the engine keeps stepping), handoff transport
drops/duplicates/delays, coordinator refusals and stragglers; a
:class:`ChaosController` injects them at the serving cluster's
instrumented seams; and the recovery machinery the harness proves out —
fencing epochs, capped-exponential retry with deadlines
(:class:`RetryPolicy`), destination-death re-staging, load shedding —
keeps every invariant: no request lost, no duplicated token, temp-0
outputs bit-for-bit equal to the fault-free run.
"""
from .backoff import RetryPolicy, unit_hash
from .chaos import ChaosController, check_cluster_invariants
from .plan import (EVENT_KINDS, NUMERIC_KINDS, TRAINING_KINDS,
                   TRANSPORT_KINDS, FaultEvent, FaultPlan)

__all__ = [
    "ChaosController", "EVENT_KINDS", "FaultEvent", "FaultPlan",
    "NUMERIC_KINDS", "RetryPolicy", "TRAINING_KINDS",
    "TRANSPORT_KINDS", "check_cluster_invariants", "unit_hash",
]
