"""Capped-exponential retry/backoff with deterministic jitter.

The recovery loops this PR replaces were bare spin retries: a
backpressured KV handoff re-tried every cluster step forever, and a
heartbeat thread died on the first coordinator error.  Both now ride
:class:`RetryPolicy` — capped exponential backoff with *deterministic*
jitter (hashed from ``(key, attempt)``, no RNG state), so two replays
of the same seeded chaos schedule retry at identical instants and the
bit-for-bit output invariant extends through every recovery path.

Deadlines are the other half: retrying forever converts an outage into
unbounded queue growth.  :meth:`RetryPolicy.deadline_for` stamps a
per-request give-up time; callers past it stop retrying and degrade
(re-route, shed with a retriable rejection, fall back to monolithic
serving) instead of spinning.
"""
from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass
from typing import Optional


def unit_hash(*keys: int) -> float:
    """Deterministic uniform in [0, 1) from integer keys — the jitter
    source.  Hash-based (blake2b), not RNG-state-based: concurrent
    retry chains can't perturb each other's jitter sequence."""
    h = hashlib.blake2b(struct.pack(f"<{len(keys)}q", *keys),
                        digest_size=8).digest()
    return struct.unpack("<Q", h)[0] / 2.0 ** 64


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff: attempt ``k`` (0-based) waits
    ``min(cap, base * multiplier**k)`` seconds, jittered ±``jitter``
    fraction deterministically by ``(key, k)``.

    ``deadline`` is the per-request retry budget in seconds (measured
    from the request's submit time); ``None`` disables the give-up path
    (the PR-11 behavior).  Time units are whatever clock the caller
    runs — the serving cluster's synthetic test clocks included.
    """

    base: float = 0.5
    cap: float = 8.0
    multiplier: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = None

    def delay(self, attempt: int, key: int = 0) -> float:
        d = min(float(self.cap),
                float(self.base) * float(self.multiplier) ** max(0, attempt))
        if self.jitter:
            u = unit_hash(int(key), int(attempt))
            d *= 1.0 + float(self.jitter) * (2.0 * u - 1.0)
        return d

    def deadline_for(self, start: float) -> Optional[float]:
        return None if self.deadline is None \
            else float(start) + float(self.deadline)

    def expired(self, start: float, now: float) -> bool:
        return self.deadline is not None \
            and now - float(start) > float(self.deadline)
