"""Deterministic, seeded chaos schedules.

A :class:`FaultPlan` is the whole experiment: a sorted list of
:class:`FaultEvent`\\ s keyed by **step index** (cluster steps for
serving, trainer steps for training) plus a map of **transport
verdicts** keyed by handoff-attempt ordinal.  Both keys are
deterministic under the repo's synthetic clocks, so the same plan
replays the same failure sequence bit-for-bit — which is what lets the
chaos tests assert temp-0 output equality against the fault-free run.

Event kinds (serving cluster seams, ``fault/chaos.py``):

``crash``        the replica process dies: serving and heartbeats stop
                 NOW; the death *verdict* lands via the coordinator TTL
                 (or immediately without one) and the cluster re-routes.
``zombie``       heartbeats stall while the engine keeps stepping — the
                 cluster must fence it: its late completions are stale.
``revive``       a zombie's heartbeats resume.  The replica stays
                 QUARANTINED (the TTL verdict is sticky) until an
                 explicit ``readmit`` — a revived replica racing its own
                 replacement is exactly the double-delivery hazard the
                 fencing epochs exist for.
``readmit``      explicit operator re-admission: the replica's stale
                 engine state is aborted, heartbeats restart, and it
                 rejoins the candidate set under the current fence
                 epoch.
``straggler``    the replica slows down for ``duration`` steps (its
                 engine skips beats); load-aware placement routes
                 around it, nothing is lost.
``coord_refuse`` the coordinator refuses every op for ``duration``
                 seconds (real time — heartbeat threads live on wall
                 clocks); surviving it is the heartbeat thread's
                 backoff-retry contract.
``worker_death`` (training) a worker rank stops heartbeating; the
                 fault-tolerant trainer re-plans on survivors and
                 restores the last snapshot.

Numeric + durability verdicts (the SILENT failures, ISSUE 14 —
injected by the fault-tolerant trainer at the sentry/checkpoint
seams, ``resilience/``):

``grad_nan``     the step's gradients go NaN (a silent compute
                 corruption); the on-device sentry must skip the
                 update with bitwise-zero residue.
``grad_spike``   the gradients blow up finite (norm past the sentry
                 threshold) — same skip contract.
``loss_spike``   the loss jumps past the relative EMA threshold; the
                 policy ladder rewinds to the last good checkpoint
                 generation.
``shard_corrupt`` bytes flip inside the newest checkpoint generation's
                 tensor shard (bit rot / torn write); the next verified
                 restore must fall back past it.
``kill_mid_write`` the checkpoint writer dies between shard files; the
                 partial generation never commits a manifest and the
                 previous generation still restores.

Transport verdicts (``FaultPlan.transport``): the N-th handoff
injection attempt (a global ordinal counted by the controller) gets
``("drop", 0)`` (the wire ate it — retry with backoff), ``("dup", 0)``
(delivered but the ack was lost — the sender re-delivers and the
``(request id, epoch)`` dedup must drop the duplicate) or
``("delay", k)`` (in flight for ``k`` clock units — the window where a
destination death forces re-staging).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: replica/worker-level event kinds
EVENT_KINDS = ("crash", "zombie", "revive", "readmit", "straggler",
               "coord_refuse", "worker_death",
               # silent-failure verdicts (numeric sentry + durable
               # checkpoint seams, resilience/ — trainer-injected)
               "grad_nan", "grad_spike", "loss_spike",
               "shard_corrupt", "kill_mid_write")
#: the subset the numeric sentry detects on-device
NUMERIC_KINDS = ("grad_nan", "grad_spike", "loss_spike")
#: training-plane kinds (injected by the fault-tolerant trainer; a
#: serving ChaosController must ignore them rather than index replicas)
TRAINING_KINDS = ("worker_death",) + NUMERIC_KINDS + (
    "shard_corrupt", "kill_mid_write")
#: handoff-wire verdict kinds
TRANSPORT_KINDS = ("drop", "dup", "delay")


@dataclass(frozen=True)
class FaultEvent:
    step: int
    kind: str
    target: int = -1
    duration: float = 0.0     # straggler steps / refuse seconds / delay
    ratio: float = 1.0        # straggler slowdown (training seam)

    def __post_init__(self):
        if self.kind not in EVENT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"have {EVENT_KINDS}")


@dataclass
class FaultPlan:
    """A deterministic chaos schedule: replica events by step +
    transport verdicts by handoff-attempt ordinal."""

    events: List[FaultEvent] = field(default_factory=list)
    transport: Dict[int, Tuple[str, float]] = field(default_factory=dict)
    seed: int = 0

    def __post_init__(self):
        self.events = sorted(self.events, key=lambda e: (e.step, e.kind,
                                                         e.target))
        for k, v in self.transport.items():
            if v[0] not in TRANSPORT_KINDS:
                raise ValueError(f"unknown transport verdict {v!r} at "
                                 f"attempt {k}")

    def due(self, step: int) -> List[FaultEvent]:
        return [e for e in self.events if e.step == int(step)]

    def transport_verdict(self, ordinal: int
                          ) -> Optional[Tuple[str, float]]:
        return self.transport.get(int(ordinal))

    @property
    def n_events(self) -> int:
        return len(self.events) + len(self.transport)

    def describe(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        for v, _ in self.transport.values():
            k = f"transport_{v}"
            out[k] = out.get(k, 0) + 1
        return out

    # -- seeded generation ---------------------------------------------------

    @classmethod
    def random(cls, seed: int, num_replicas: int, steps: int,
               n_events: int = 50,
               protect: Sequence[int] = (),
               kinds: Sequence[str] = ("crash", "zombie", "revive",
                                       "readmit", "straggler"),
               transport_kinds: Sequence[str] = TRANSPORT_KINDS,
               transport_every: int = 3) -> "FaultPlan":
        """A seeded random schedule that is always *survivable*: the
        generator tracks the simulated alive set and never crashes or
        zombifies the last live replica (``protect`` pins extra indices
        as never-faulted).  Roughly one in ``transport_every`` of the
        budgeted events becomes a transport verdict instead of a
        replica event."""
        rng = np.random.RandomState(seed)
        alive = set(range(num_replicas))
        down: Dict[int, int] = {}   # crashed/zombie -> step it went down
        events: List[FaultEvent] = []
        transport: Dict[int, Tuple[str, float]] = {}
        next_attempt = 0
        # the generated timeline is MONOTONIC in step, so the alive-set
        # tracking below replays in exactly the order the cluster will
        # apply events — the >=1-alive guarantee is exact, not a
        # generation-order approximation
        cur = 1
        readmit_steps: set = set()
        for _ in range(n_events):
            # advance within the run horizon: events past `steps` would
            # never be injected (revive/readmit ordering jumps below
            # may still exceed it — correctness beats the cap there)
            if cur < steps:
                cur += int(rng.randint(0, 2))
            if transport_kinds and rng.randint(transport_every) == 0:
                v = transport_kinds[rng.randint(len(transport_kinds))]
                dur = float(rng.randint(1, 4)) if v == "delay" else 0.0
                next_attempt += int(rng.randint(1, 5))
                transport[next_attempt] = (v, dur)
                continue
            kind = kinds[rng.randint(len(kinds))]
            if kind in ("crash", "zombie"):
                # never share a step with a readmit: the guarantee that
                # >=1 replica stays alive must hold at every point of
                # the step-sorted replay, not just between steps
                while cur in readmit_steps:
                    cur += 1
                cands = sorted(r for r in alive if r not in protect)
                if len(alive) <= 1 or not cands:
                    continue
                t = cands[rng.randint(len(cands))]
                alive.discard(t)
                down[t] = cur
                events.append(FaultEvent(cur, kind, t))
            elif kind in ("revive", "readmit"):
                if not down:
                    continue
                t = sorted(down)[rng.randint(len(down))]
                if down[t] >= cur:
                    # never the same step as the fault that downed the
                    # target: the death verdict must land first
                    cur = down[t] + 1
                if kind == "readmit":
                    del down[t]
                    alive.add(t)
                    readmit_steps.add(cur)
                events.append(FaultEvent(cur, kind, t))
            elif kind == "straggler":
                t = int(rng.randint(num_replicas))
                events.append(FaultEvent(cur, kind, t,
                                         duration=float(
                                             rng.randint(1, 6)),
                                         ratio=2.0))
        return cls(events=events, transport=transport, seed=seed)
