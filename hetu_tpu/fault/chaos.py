"""The chaos controller: injects a FaultPlan at the cluster's seams.

``EngineCluster(chaos=ChaosController(plan))`` wires the controller
into the serving loop: at the top of every cluster step the controller
applies the events due at that step (crash / zombie / revive / readmit
/ straggler / coordinator refusal), and every handoff injection attempt
asks it for a transport verdict (drop / dup / delay).  Injection is
*observable by construction*: every injected fault emits a ``fault``
instant on the ``chaos`` tracer track, and the cluster's recovery
machinery emits its own instants (``replica_dead``, ``reroute``,
``handoff_retry``, ``handoff_restaged``, ``duplicate_dropped``,
``stale_completion_dropped``, ``shed``, ``replica_readmitted``), so one
Perfetto trace shows the full fail → detect → recover chain per event.

The controller is deterministic: it owns no RNG — all randomness lives
in the seeded :class:`~hetu_tpu.fault.plan.FaultPlan` — and the
transport-attempt ordinal is a plain counter, so replaying the same
plan against the same trace injects the same faults at the same
instants.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .plan import TRAINING_KINDS, FaultEvent, FaultPlan


class ChaosController:
    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.injected: List[Dict[str, Any]] = []   # audit log
        self._attempts = 0                         # handoff ordinal
        self._applied: set = set()                 # event identity guard

    # -- cluster seam --------------------------------------------------------

    def on_step(self, cluster, step: int, now: float) -> None:
        """Apply every event due at ``step`` to the cluster."""
        for ev in self.plan.due(step):
            key = (ev.step, ev.kind, ev.target)
            if key in self._applied:
                continue
            self._applied.add(key)
            self._apply(cluster, ev, now)

    def _apply(self, cluster, ev: FaultEvent, now: float) -> None:
        tr = cluster.tracer
        if tr.enabled:
            tr.instant("fault", track="chaos", ts=now, kind=ev.kind,
                       target=ev.target, step=ev.step,
                       duration=ev.duration)
        # lazy import: fault <-> serving would cycle at module level
        from ..serving.kv_pool import protocol_seq
        self.injected.append({"step": ev.step, "kind": ev.kind,
                              "target": ev.target, "ts": now,
                              "seq": protocol_seq()})
        if ev.kind == "coord_refuse":
            if cluster.server is not None:
                cluster.server.refuse_for(float(ev.duration))
            return
        if ev.kind in TRAINING_KINDS:
            # training-plane events (worker death, numeric sentry,
            # checkpoint durability) reaching a serving cluster are a
            # plan-authoring error; ignore rather than corrupt state
            return
        r = cluster.replicas[ev.target]
        if ev.kind == "crash":
            r.kill()
            if cluster.server is None:
                # no coordinator: the stopped process is its own proof,
                # _check_health picks `not serving` up next step
                pass
        elif ev.kind == "zombie":
            # heartbeats stall, the engine keeps stepping.  With a
            # coordinator the TTL verdict lands on real time; without
            # one the synthetic-clock world gets the verdict NOW (the
            # cluster's _check_health treats `not alive` as the landed
            # verdict and fences the replica)
            r.pause_heartbeat()
            if cluster.server is None:
                r.alive = False
        elif ev.kind == "revive":
            # heartbeats return; quarantine (alive=False) is sticky
            # until an explicit readmit — asserted by the revival-race
            # tests
            r.resume_heartbeat()
        elif ev.kind == "readmit":
            cluster.readmit_replica(ev.target)
        elif ev.kind == "straggler":
            r.slow_until = cluster.steps + max(1.0, float(ev.duration))

    # -- transport seam ------------------------------------------------------

    def handoff_verdict(self) -> Tuple[str, float]:
        """The verdict for the NEXT handoff injection attempt; consumes
        one ordinal.  ``("ok", 0)`` when the plan says nothing."""
        v = self.plan.transport_verdict(self._attempts)
        self._attempts += 1
        return v if v is not None else ("ok", 0.0)


def check_cluster_invariants(cluster) -> None:
    """The chaos-fuzz safety net, asserted after EVERY step: request
    accounting is exact (each request is in exactly one of backlog /
    placed / staged-handoff / finished / shed), nothing is both finished
    and shed, no output overran its token budget, and every live pool's
    own invariants hold."""
    # one implementation: the protocol verifier's snapshot predicate
    # (analysis/protocol.py) owns the invariant logic; this wrapper
    # keeps assert-style reporting (lazy import — see _apply)
    from ..analysis.protocol import cluster_problems
    problems = cluster_problems(cluster)
    assert not problems, "; ".join(problems)
    for r in cluster.replicas:
        if r.serving and r.engine.debug:
            r.engine.pool.check_invariants()
            if r.engine.prefix_cache is not None:
                r.engine.prefix_cache.check_invariants()
