"""Serving request lifecycle + class-then-arrival admission queue."""
from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional

from .slo.classes import SLO_CLASSES, class_rank

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"


@dataclass
class Request:
    """One generation request flowing through the engine.

    ``tokens`` accumulates prompt + generated tokens; preemption resets
    only the KV state (``pages``/``pos``), so a re-prefill over
    ``tokens`` resumes the sequence with an identical continuation at
    temperature 0.
    """
    req_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0           # 0 (or >= 1) disables nucleus cut
    seed: int = 0
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0
    stream_cb: Optional[Callable] = None
    # SLO class (serving/slo/classes.py): pure POLICY — decides who
    # waits/sheds/preempts, never what a surviving request computes
    slo_class: str = "standard"

    # runtime state
    tokens: List[int] = field(default_factory=list)
    out_tokens: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    # the first shared_pages entries of ``pages`` are READ-ONLY prefix-
    # cache pages (refcounted, never in the KV write plan); the rest are
    # exclusively owned.  cached_tokens = prefill tokens skipped via the
    # cache on the most recent start (metrics / tests).
    shared_pages: int = 0
    cached_tokens: int = 0
    # speculative decoding (serving/spec.py): greedy draft proposals
    # staged for the next packed step.  Non-empty only while the engine
    # runs a spec scheduler mode AND the request is decode-ready; the
    # scheduler packs ``1 + len(spec_drafts)`` tokens as a verify row
    # (a chunk slot), and the engine clears the list after the verify
    # commits (or when the drafts are dropped: preemption, a step with
    # no free chunk slot, a page squeeze).
    spec_drafts: List[int] = field(default_factory=list)
    pos: int = 0                 # KV entries committed (next write index)
    state: str = WAITING
    # start of the CURRENT lifecycle segment (queued/running) for the
    # trace plane: the engine closes a state span over
    # [trace_t0, transition] at every admit/preempt/finish, so the
    # per-request segments tile [submit, finish] gaplessly (asserted by
    # the timeline gate in tests/test_obs.py)
    trace_t0: float = 0.0
    n_preemptions: int = 0
    peak_pages: int = 0
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    def __post_init__(self):
        if not self.tokens:
            self.tokens = list(self.prompt)
        class_rank(self.slo_class)   # validate eagerly (raises on typo)

    @property
    def rank(self) -> int:
        """Priority rank (0 = most urgent) — the leading sort key of
        every scheduler ordering decision."""
        return class_rank(self.slo_class)

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.out_tokens)

    @property
    def done(self) -> bool:
        if self.n_generated >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.out_tokens and
                self.out_tokens[-1] == self.eos_token_id)


class RequestQueue:
    """Class-ranked, arrival-time-ordered waiting queue.

    One arrival-ordered heap PER SLO class; ``pop_ready(now)`` scans
    classes in rank order and releases the first request whose
    ``arrival_time`` has passed — an interactive request that has
    arrived always pops before any standard/batch one, but a FUTURE
    interactive arrival never blocks an already-arrived lower class
    (the gate is per heap, not global).  Within a class, ties break on
    ``req_id`` (submission order), NOT insertion order, so a request
    pushed BACK (didn't fit / preempted) keeps its place ahead of
    same-arrival-time peers — no overtaking, starvation-free within
    the class.
    """

    def __init__(self):
        self._heaps = {c: [] for c in SLO_CLASSES}

    def push(self, req: Request) -> None:
        heapq.heappush(self._heaps[req.slo_class],
                       (req.arrival_time, req.req_id, req))

    def pop_ready(self, now: float) -> Optional[Request]:
        for c in SLO_CLASSES:        # rank order: interactive first
            heap = self._heaps[c]
            if heap and heap[0][0] <= now:
                return heapq.heappop(heap)[2]
        return None

    def next_arrival(self) -> Optional[float]:
        heads = [h[0][0] for h in self._heaps.values() if h]
        return min(heads) if heads else None

    def requests(self) -> Iterator[Request]:
        """All queued requests, rank-major (heap order within a class
        — NOT sorted by arrival; callers that care must sort)."""
        for c in SLO_CLASSES:
            for _, _, req in self._heaps[c]:
                yield req

    def clear(self) -> None:
        for heap in self._heaps.values():
            heap.clear()

    def depth_by_class(self) -> dict:
        """Queue depth per class — an autoscaler/router signal."""
        return {c: len(h) for c, h in self._heaps.items()}

    def __len__(self) -> int:
        return sum(len(h) for h in self._heaps.values())

    def __bool__(self) -> bool:
        return any(self._heaps.values())
