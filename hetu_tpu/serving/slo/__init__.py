"""SLO-driven traffic plane (DESIGN.md §22): priority classes over the
scheduler/router, a replica autoscaler riding the existing
register/readmit/drain lifecycle, and a host-RAM tier for cold
prefix-cache pages."""
from .autoscaler import Autoscaler
from .backlog import ClassBacklog
from .classes import CLASS_RANK, DEFAULT_TARGETS, SLO_CLASSES, class_rank
from .host_tier import HostTier

__all__ = ["Autoscaler", "ClassBacklog", "CLASS_RANK",
           "DEFAULT_TARGETS", "SLO_CLASSES", "class_rank", "HostTier"]
