"""Replica autoscaler: queue-depth + per-class TTFT signals driving the
cluster's EXISTING replica lifecycle — no second state machine.

Scale-**down** is a graceful drain: the victim replica gets its
``draining`` flag set (the router stops placing new work on it — see
:meth:`Router.candidates`), its in-flight requests finish where they
are (no recompute, no goodput dip), and only once it is empty does the
controller call :meth:`EngineCluster.kill_replica` — the same fencing
path a crash takes, so epochs, stale-completion drops and the chaos
invariants all hold without new machinery.  Scale-**up** is
:meth:`EngineCluster.readmit_replica` on a parked (previously drained
or dead) replica — the one sanctioned quarantine exit.

Signals are the router's: total backlog depth weighted toward
interactive, plus the cumulative interactive TTFT tail vs its SLO
target.  Two dampers keep a chaos-injected flap from thrashing the
fleet: a scale decision needs the signal to hold for
``hysteresis_steps`` CONSECUTIVE cluster steps, and after any action
the controller is silent for ``cooldown_steps``.

Composition with the fault plane: a replica that dies (chaos, fault
plan, operator kill) while the controller is draining it has its work
re-routed by the normal death sweep — the controller just clears its
drain intent and counts the capacity as already gone.  It never calls
``kill_replica`` on a dead replica, so a mid-drain crash can't
double-drain (asserted in tests/test_slo.py).
"""
from __future__ import annotations

from typing import Optional

from .classes import DEFAULT_TARGETS


class Autoscaler:
    """Attach via ``EngineCluster(..., autoscaler=Autoscaler(...))``;
    the cluster calls :meth:`on_step` right after its health sweep."""

    def __init__(self, min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 backlog_high: int = 8, backlog_low: int = 1,
                 ttft_target="default",
                 hysteresis_steps: int = 3, cooldown_steps: int = 20):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = None if max_replicas is None \
            else int(max_replicas)
        self.backlog_high = int(backlog_high)
        self.backlog_low = int(backlog_low)
        # "default" -> the interactive class's SLO target; None
        # disables the TTFT signal (queue depth only — synthetic-clock
        # tests, where wall-ratio targets are meaningless)
        self.ttft_target = DEFAULT_TARGETS["interactive"]["ttft_s"] \
            if ttft_target == "default" else \
            (None if ttft_target is None else float(ttft_target))
        self.hysteresis_steps = int(hysteresis_steps)
        self.cooldown_steps = int(cooldown_steps)
        # controller state
        self._over = 0           # consecutive steps of high pressure
        self._under = 0          # consecutive steps of idle fleet
        self._last_action: Optional[int] = None
        self._draining: set = set()      # replica idx with drain intent
        self._parked: list = []          # idxs WE scaled down (LIFO)
        # lifetime event counts (the cluster's counters mirror these)
        self.scale_up_events = 0
        self.scale_down_events = 0

    # -- the per-step controller ----------------------------------------------

    def on_step(self, cluster, step: int, now: float) -> None:
        self._finish_drains(cluster, now)
        # serving matters too: a replica we just fenced keeps its stale
        # alive=True until the next health sweep's verdict — it is not
        # capacity, and counting it could drain below min_replicas
        active = [r for r in cluster.replicas
                  if r.alive and r.serving and not r.draining]
        pressure, breach = self._signals(cluster, now)
        in_cooldown = self._last_action is not None \
            and step - self._last_action < self.cooldown_steps
        up = pressure >= self.backlog_high or breach
        down = pressure <= self.backlog_low and not breach
        if in_cooldown:
            self._over = self._under = 0
            return
        self._over = self._over + 1 if up else 0
        self._under = self._under + 1 if down else 0
        if self._over >= self.hysteresis_steps:
            if self._scale_up(cluster, step, now):
                self._last_action = step
            self._over = 0
        elif self._under >= self.hysteresis_steps:
            if len(active) > self.min_replicas \
                    and self._scale_down(cluster, active, step, now):
                self._last_action = step
            self._under = 0

    def _signals(self, cluster, now: float):
        # arrival-gated: a future-dated arrival is scheduled traffic,
        # not pressure — counting it would hold capacity through every
        # trough of a diurnal trace and the fleet would never scale down
        by_class = cluster._backlog.depth_by_class(now)
        # interactive waiters weigh double: one queued interactive
        # request is already a TTFT incident in the making
        pressure = sum(by_class.values()) \
            + by_class.get("interactive", 0)
        h = cluster.histograms.get("ttft_interactive")
        breach = bool(self.ttft_target is not None and h is not None
                      and h.count > 0
                      and h.percentile(90) > self.ttft_target)
        return pressure, breach

    # -- scale up: readmit a parked replica -----------------------------------

    def _scale_up(self, cluster, step: int, now: float) -> bool:
        active = sum(1 for r in cluster.replicas
                     if r.alive and r.serving and not r.draining)
        if self.max_replicas is not None and active >= self.max_replicas:
            return False
        # prefer a replica this controller drained (clean park), else
        # any dead one (capacity is capacity); never a draining one
        idx = None
        while self._parked:
            cand = self._parked.pop()
            if not cluster.replicas[cand].alive:
                idx = cand
                break
        if idx is None:
            dead = [r.idx for r in cluster.replicas
                    if not r.alive and r.idx not in self._draining]
            if not dead:
                return False
            idx = dead[0]
        cluster.readmit_replica(idx)
        self.scale_up_events += 1
        cluster.counters["scale_ups"].inc()
        tr = cluster.tracer
        if tr.enabled:
            tr.instant("scale_up", track="router", ts=now,
                       replica=idx, step=step,
                       backlog=len(cluster._backlog))
        return True

    # -- scale down: drain, then fence ----------------------------------------

    def _scale_down(self, cluster, active, step: int,
                    now: float) -> bool:
        # least-loaded victim; in a disaggregated fleet never drain the
        # last live replica of a role (the mode needs both sides)
        def last_of_role(r):
            return sum(1 for o in active if o.role == r.role) <= 1
        cands = [r for r in active
                 if not (cluster.mode == "disaggregated"
                         and last_of_role(r))]
        if not cands:
            return False
        victim = min(cands, key=lambda r: (r.outstanding_tokens(),
                                           -r.idx))
        victim.draining = True
        self._draining.add(victim.idx)
        tr = cluster.tracer
        if tr.enabled:
            tr.instant("drain", track="router", ts=now,
                       replica=victim.idx, step=step,
                       outstanding_tokens=victim.outstanding_tokens())
        return True

    def _finish_drains(self, cluster, now: float) -> None:
        for idx in list(self._draining):
            r = cluster.replicas[idx]
            if not r.alive:
                # died mid-drain (chaos/fault plan): the death sweep
                # already re-routed its work and fenced its epoch — the
                # capacity is gone, just clear the intent.  NOT a
                # second kill: that would double-drain
                self._draining.discard(idx)
                r.draining = False
                self._parked.append(idx)
                self._count_down(cluster, idx, now, reason="died")
                continue
            busy = r.engine.has_work \
                or any(k[0] == idx for k in cluster._placed)
            if not busy and any(h.get("dst") == idx
                                for h in cluster._pending_handoffs):
                # a chaos-delayed handoff is IN FLIGHT to this replica
                # (destination pinned, pages reserved): the engine looks
                # idle and nothing is placed yet, but fencing it now
                # would kill the transfer mid-air and force a restage —
                # breaking the graceful-drain contract ("in-flight
                # requests finish where they are").  Surfaced by the
                # protocol explorer (analysis/protocol.py, bug flag
                # 'drain_inflight'); defer until the handoff lands or
                # re-routes
                cluster.counters["drains_deferred_inflight"].inc()
                busy = True
            if busy:
                continue
            r.draining = False
            self._draining.discard(idx)
            self._parked.append(idx)
            cluster.kill_replica(idx)
            self._count_down(cluster, idx, now, reason="drained")

    def _count_down(self, cluster, idx: int, now: float,
                    reason: str) -> None:
        self.scale_down_events += 1
        cluster.counters["scale_downs"].inc()
        tr = cluster.tracer
        if tr.enabled:
            tr.instant("scale_down", track="router", ts=now,
                       replica=idx, reason=reason)
