"""Class-aware cluster backlog: one arrival-ordered heap per SLO class.

The cluster's front door used to be a single FIFO heap; under a mixed
priority workload FIFO is exactly the wrong policy — a burst of batch
arrivals ahead of one interactive request delays the interactive TTFT
by the whole burst.  :class:`ClassBacklog` keeps the per-class FIFO
(arrival order within a class — starvation-free, no same-class
overtaking) but serves classes rank-major: an *arrived* interactive
request always routes before an arrived batch one, and a future
arrival in a high class never gates an arrived low one (each class has
its own arrival-time head, mirroring ``serving.request.RequestQueue``).

Shedding is rank-aware in the other direction: capacity pressure
(``max_backlog``, deadlines) falls on the LOWEST class first —
:meth:`shed_candidate` names the latest-arrived entry of the
lowest-priority non-empty class, and :meth:`expired_head` scans class
heads batch-first — so backpressure sheds batch before it ever delays
(or drops) interactive.

Iteration yields the same ``(arrival_time, req_id, creq)`` triples the
old flat heap held (rank-major, arrival order within a class), so the
chaos invariant sweep and backlog introspection work unchanged.
"""
from __future__ import annotations

import heapq
from typing import Dict, Iterator, Optional

from .classes import SLO_CLASSES


class ClassBacklog:
    """Per-class min-heaps on ``(arrival_time, req_id)``."""

    def __init__(self):
        self._heaps: Dict[str, list] = {c: [] for c in SLO_CLASSES}

    def push(self, creq) -> None:
        heapq.heappush(self._heaps[creq.slo_class],
                       (creq.arrival_time, creq.req_id, creq))

    def peek_ready(self, now: float):
        """The next request to route: rank-major over classes, FIFO
        within one, gated on arrival — a future interactive never
        blocks an arrived batch."""
        for c in SLO_CLASSES:
            heap = self._heaps[c]
            if heap and heap[0][0] <= now:
                return heap[0][2]
        return None

    def remove(self, creq) -> None:
        """Drop a specific entry (a routed head, or a shed victim —
        backlogs are small and bounded, the O(n) scan is fine)."""
        heap = self._heaps[creq.slo_class]
        for i, (_arr, rid, _c) in enumerate(heap):
            if rid == creq.req_id:
                heap[i] = heap[-1]
                heap.pop()
                heapq.heapify(heap)
                return
        raise KeyError(creq.req_id)

    # -- shed policy ----------------------------------------------------------

    def shed_candidate(self):
        """Who a full backlog should displace: the latest-arrived entry
        of the lowest-priority non-empty class.  The caller sheds it
        only when the incoming request STRICTLY outranks it — same-class
        pressure keeps the old shed-the-arrival FIFO behavior."""
        for c in reversed(SLO_CLASSES):
            heap = self._heaps[c]
            if heap:
                return max(heap)[2]
        return None

    def expired_head(self, now: float, deadline: Optional[float]):
        """An arrived class head waiting past ``deadline``, lowest
        class first — when the whole fleet is backpressured, batch
        sheds before standard before interactive."""
        if deadline is None:
            return None
        for c in reversed(SLO_CLASSES):
            heap = self._heaps[c]
            if heap and heap[0][0] <= now \
                    and now - heap[0][2].submit_time > deadline:
                return heap[0][2]
        return None

    # -- introspection --------------------------------------------------------

    def depth_by_class(self,
                       now: Optional[float] = None) -> Dict[str, int]:
        """Queue depth per class; with ``now``, only ARRIVED entries
        count — a future-dated arrival is scheduled traffic, not
        pressure (the autoscaler must not hold capacity for it)."""
        if now is None:
            return {c: len(h) for c, h in self._heaps.items()}
        return {c: sum(1 for arr, _r, _q in h if arr <= now)
                for c, h in self._heaps.items()}

    def __len__(self) -> int:
        return sum(len(h) for h in self._heaps.values())

    def __bool__(self) -> bool:
        return any(self._heaps.values())

    def __iter__(self) -> Iterator:
        """Rank-major ``(arrival_time, req_id, creq)`` triples — the
        flat-heap shape the chaos invariants unpack."""
        for c in SLO_CLASSES:
            for item in sorted(self._heaps[c]):
                yield item
