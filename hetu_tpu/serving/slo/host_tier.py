"""Host-RAM tier for cold prefix-cache pages: evict to host, refetch
on a digest hit — hit / refetch / recompute instead of hit / recompute.

The paged pool's third (cached) page state generalizes here into a
real memory hierarchy, the Hetu-v1 HET hot/cold embedding split
applied to KV (SURVEY.md): when the prefix cache's LRU sweep reclaims
a refcount-0 page, the page's bytes are staged to host RAM (through
:meth:`~hetu_tpu.serving.cluster.transport.PageTransport.extract` —
the same host-staging primitive the disaggregation wire uses) keyed by
the page's layout-salted content chain hash, INSTEAD of being dropped.
A later request whose prompt chains onto a host-tier page refetches it
through :meth:`~hetu_tpu.serving.cluster.transport.PageTransport.inject`
— bit-exact, layout-checked (MLA latent and quantized pages ride the
same path; their smaller ``page_bytes`` price at true wire size) —
and the page re-enters the device cache index exactly as if it had
never left (:meth:`~hetu_tpu.serving.prefix_cache.PrefixCache.restore`).

**Every page move is priced.**  Evicts and refetches each append a
record carrying a CommEdge-shaped claim (tag ``host_offload``) plus
the alpha-beta predicted seconds through the planner's single
:func:`~hetu_tpu.planner.cost_model.collective_time` implementation —
the ``host-offload-unpriced`` analysis rule fails CI for any host-tier
page move whose record lacks the claim or whose byte accounting
disagrees, exactly like ``kv-handoff-unpriced`` does for the
cross-replica wire.

**Correctness.**  The store is hash-keyed (64-bit content chain), but
a refetch only ever extends an EXACT in-index match and re-verifies
the stored token slice against the prompt at every page, so a false
hit needs a blake2b-8 collision on top of identical page tokens —
the same odds the router's digest placement already accepts, and the
injected bytes are the evicted bytes verbatim, so temp-0 outputs stay
bit-for-bit vs a never-evicted run (asserted in tests/test_slo.py for
learned and rotary-MLA layouts, int8 pages included).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..prefix_cache import ROOT, token_chain_hashes


class HostTier:
    """LRU host-RAM store of evicted prefix-cache pages, one engine's
    pool each (staging is layout-specific).  Wire it with
    :meth:`bind`; the engine does this when constructed with
    ``host_tier=...``."""

    def __init__(self, capacity_pages: int = 256, cluster_spec=None,
                 transport=None):
        if transport is None:
            from ..cluster.transport import LocalPageTransport
            transport = LocalPageTransport(cluster_spec)
        self.transport = transport
        self.capacity_pages = int(capacity_pages)
        # chain_hash -> {"staged", "tokens", "depth"}; insertion order
        # doubles as the LRU order (move_to_end on every touch)
        self._store: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        #: priced page-move records (dir: evict|refetch), audited by
        #: the ``host-offload-unpriced`` analysis rule
        self.records: List[Dict[str, Any]] = []
        self._epoch = 0
        self.pool = None
        self.cache = None
        self._counters: Optional[Dict[str, Any]] = None
        self._gauges: Optional[Dict[str, Any]] = None
        self._tracer_fn = None
        self._time_fn = lambda: 0.0
        # lifetime counts (plain ints — survive engine metric resets)
        self.evictions = 0
        self.hits = 0
        self.refetch_bytes = 0
        self.drops = 0           # capacity evictions OF the host tier

    # -- wiring ---------------------------------------------------------------

    def bind(self, pool, cache, counters=None, gauges=None,
             tracer_fn=None, time_fn=None) -> None:
        """Attach to one engine's pool + prefix cache: installs the
        cache's ``on_evict`` hook.  ``counters``/``gauges`` are the
        engine's instrument dicts (looked up by key at use time, so
        ``reset_metrics`` swapping the instruments stays safe)."""
        self.pool = pool
        self.cache = cache
        self._counters = counters
        self._gauges = gauges
        self._tracer_fn = tracer_fn
        if time_fn is not None:
            self._time_fn = time_fn
        cache.on_evict = self._on_evict

    @property
    def host_pages(self) -> int:
        return len(self._store)

    @property
    def total_payload_bytes(self) -> int:
        return sum(r["payload_bytes"] for r in self.records)

    def predicted_s(self, direction: Optional[str] = None) -> float:
        return sum(r["predicted_s"] for r in self.records
                   if direction is None or r["dir"] == direction)

    # -- evict path (the cache's on_evict hook) -------------------------------

    def _on_evict(self, entry, h: int) -> None:
        """Stage an evicted page's bytes to host RAM, keyed by its
        layout-salted chain hash.  Called by ``PrefixCache._remove``
        while the page is still cached, so extract reads real KV."""
        staged = self.transport.extract(self.pool, [entry.page])
        self._store[h] = {"staged": staged,
                          "tokens": tuple(entry.tokens),
                          "depth": int(entry.depth)}
        self._store.move_to_end(h)
        while len(self._store) > self.capacity_pages:
            self._store.popitem(last=False)   # coldest falls off the end
            self.drops += 1
        self.evictions += 1
        rec = self._price("evict", 1, int(staged["payload_bytes"]), h)
        self.records.append(rec)
        if self._counters is not None:
            self._counters["host_evictions"].inc()
        if self._gauges is not None:
            self._gauges["host_pages"].set(len(self._store))
        tr = self._tracer_fn() if self._tracer_fn is not None else None
        if tr is not None and tr.enabled:
            tr.instant("host_evict", track="router", ts=self._time_fn(),
                       depth=int(entry.depth),
                       payload_bytes=int(staged["payload_bytes"]),
                       host_pages=len(self._store))

    # -- refetch path (engine _start, before cache acquire) -------------------

    def refetch(self, tokens) -> int:
        """Extend the device cache's exact match for ``tokens`` with
        host-tier pages: for each continuation page whose chain hash
        (and token slice) is stored, allocate a device page, inject the
        staged bytes, and :meth:`~PrefixCache.restore` it — the
        caller's subsequent ``acquire`` then attaches the deeper chain
        through the normal path.  Returns pages restored; stops at the
        first miss, verification failure, or a dry pool (recompute
        fallback — never an error).

        Restored (and matched-prefix) entries are PINNED for the
        duration: the pool ``alloc`` here can itself trigger the LRU
        sweep, which must not evict the chain mid-restore."""
        if self.cache is None or not self._store:
            return 0
        ps = self.pool.page_size
        entries = self.cache.match(tokens)
        hashes = token_chain_hashes(tokens, ps,
                                    layout=self.pool.layout_tag)
        depth0 = len(entries)
        if depth0 >= len(hashes):
            return 0
        parent = entries[-1].eid if entries else ROOT
        pinned = []

        def pin(e):
            e.refs += 1
            self.pool.share_page(e.page)
            pinned.append(e)

        for e in entries:
            pin(e)
        restored = 0
        try:
            for i in range(depth0, len(hashes)):
                item = self._store.get(hashes[i])
                if item is None:
                    break
                slice_ = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                if item["tokens"] != slice_ or item["depth"] != i:
                    break                      # hash collision guard
                got = self.pool.alloc(1)
                if got is None:
                    break                      # pool dry: recompute
                self._epoch += 1
                wire = self.transport.inject(
                    self.pool, item["staged"], got,
                    src_replica=-1, dst_replica=-1, epoch=self._epoch)
                e = self.cache.restore(parent, slice_, got[0], i)
                pin(e)
                parent = e.eid
                del self._store[hashes[i]]     # back on device: one copy
                restored += 1
                payload = int(item["staged"]["payload_bytes"])
                self.hits += 1
                self.refetch_bytes += payload
                rec = self._price("refetch", 1, payload, hashes[i],
                                  wall_s=float(wire["wall_s"]))
                self.records.append(rec)
                if self._counters is not None:
                    self._counters["host_hits"].inc()
                    self._counters["host_refetch_bytes"].inc(payload)
                if self._gauges is not None:
                    self._gauges["host_pages"].set(len(self._store))
                tr = self._tracer_fn() if self._tracer_fn is not None \
                    else None
                if tr is not None and tr.enabled:
                    tr.instant("host_refetch", track="router",
                               ts=self._time_fn(), depth=i,
                               payload_bytes=payload,
                               host_pages=len(self._store))
        finally:
            for e in pinned:
                e.refs -= 1
                self.pool.unshare_page(e.page)
        return restored

    # -- pricing --------------------------------------------------------------

    def _price(self, direction: str, n_pages: int, payload_bytes: int,
               chain_h: int, wall_s: float = 0.0) -> Dict[str, Any]:
        """The priced edge claim, shaped like the disaggregation wire's
        (``LocalPageTransport._price``) with tag ``host_offload`` —
        one vocabulary, one ``collective_time`` implementation, so the
        bench's hit-vs-recompute comparison and the lint both read the
        planner's own numbers."""
        from ...planner.cost_model import collective_time
        src, dst = (("device_pool", "host_tier")
                    if direction == "evict"
                    else ("host_tier", "device_pool"))
        edge = {"kind": "ppermute", "tensor": "kv_pages",
                "producer": src, "consumer": dst,
                "src_spec": src, "dst_spec": dst, "axes": ("host",),
                "payload_bytes": int(payload_bytes), "count": 1,
                "tag": "host_offload", "origin": "declared"}
        predicted_s = collective_time("ppermute", float(payload_bytes),
                                      2, self.transport.cluster_spec)
        from ..kv_pool import protocol_seq
        return {"dir": direction, "pages": int(n_pages),
                "payload_bytes": int(payload_bytes),
                "page_bytes": int(self.pool.page_bytes),
                "chain_hash": int(chain_h), "edge": edge,
                "predicted_s": float(predicted_s),
                "wall_s": float(wall_s), "seq": protocol_seq()}
