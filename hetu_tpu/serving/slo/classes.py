"""SLO classes: the request-priority vocabulary of the traffic plane.

Three classes, strictly rank-ordered (DESIGN.md §22):

* ``interactive`` — chat-style traffic.  Tight TTFT/TBT targets; the
  scheduler packs its prefill chunks and decode slots ahead of
  everything else, and the router's backpressure never sheds it while
  a lower class is still holding backlog space.
* ``standard`` — the default.  API traffic with ordinary latency
  expectations; ranked between the two extremes.
* ``batch`` — offline/bulk work (eval sweeps, distillation dumps).
  No latency promise: it absorbs preemption, shedding and queueing so
  the higher classes never feel the pressure.

Rank order is POLICY ONLY — it decides which request waits, sheds, or
is preempted, never what any surviving request computes.  Temperature-0
outputs therefore stay bit-for-bit identical to an unmanaged run for
every request that completes in both (the position-keyed sampler makes
token values a function of the request's own history alone; asserted
in ``tests/test_slo.py`` and gated in ``bench.py slo_bench``).

Per-class latency targets feed the autoscaler
(:class:`~hetu_tpu.serving.slo.autoscaler.Autoscaler` scales up when
interactive TTFT crosses its target) and the bench acceptance
booleans; they are defaults, overridable per cluster.
"""
from __future__ import annotations

from typing import Dict

# strict rank order: index IS the priority (lower = more urgent)
SLO_CLASSES = ("interactive", "standard", "batch")

CLASS_RANK: Dict[str, int] = {c: i for i, c in enumerate(SLO_CLASSES)}

#: per-class latency targets (seconds): TTFT = submit -> first token,
#: TBT = gap between consecutive tokens.  ``None`` = no promise.
DEFAULT_TARGETS: Dict[str, Dict[str, float]] = {
    "interactive": {"ttft_s": 0.5, "tbt_s": 0.1},
    "standard": {"ttft_s": 2.0, "tbt_s": 0.5},
    "batch": {"ttft_s": None, "tbt_s": None},
}


def class_rank(slo_class: str) -> int:
    """Priority rank of ``slo_class`` (0 = most urgent).  Raises on an
    unknown class — a typo'd class silently defaulting to batch would
    be an invisible SLO violation."""
    try:
        return CLASS_RANK[slo_class]
    except KeyError:
        raise ValueError(f"unknown slo_class {slo_class!r}; "
                         f"have {SLO_CLASSES}") from None
